// Native data plane: JPEG decode + fused decode/crop/mirror-to-float.
//
// TPU-native equivalent of the reference's host-side decode path
// (/root/reference/src/utils/decoder.h JpegDecoder + the per-instance copy
// loops in iter_thread_imbin_x-inl.hpp:269-387). The TPU does the math; this
// library keeps the *host* fast: libjpeg decode and the uint8->float CHW
// conversion are the input-pipeline hot path when feeding a chip at line
// rate. Exposed as a C ABI for ctypes (no pybind11 in this image); all entry
// points are GIL-free by construction so a Python thread pool scales.
//
// Build: make -C native   (produces libcxnetdata.so)

#include <cstdio>
#include <cstring>
#include <csetjmp>
#include <cstdlib>

#include <jpeglib.h>
#include <jerror.h>

namespace {

struct ErrorMgr {
  jpeg_error_mgr base;
  jmp_buf jump;
};

void error_exit(j_common_ptr cinfo) {
  ErrorMgr* mgr = reinterpret_cast<ErrorMgr*>(cinfo->err);
  longjmp(mgr->jump, 1);
}

}  // namespace

extern "C" {

// Decode a JPEG byte buffer to interleaved RGB (or grayscale) HWC uint8.
// Returns 0 on success; fills *w,*h,*c. out may be null to only query dims
// (two-call protocol). out_cap is the byte capacity of out.
int cxn_jpeg_decode(const unsigned char* src, long len,
                    unsigned char* out, long out_cap,
                    int* w, int* h, int* c) {
  jpeg_decompress_struct cinfo;
  ErrorMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.base);
  jerr.base.error_exit = error_exit;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<unsigned char*>(src),
               static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -2;
  }
  *w = static_cast<int>(cinfo.image_width);
  *h = static_cast<int>(cinfo.image_height);
  *c = cinfo.num_components >= 3 ? 3 : 1;
  if (out == nullptr) {
    jpeg_destroy_decompress(&cinfo);
    return 0;
  }
  cinfo.out_color_space = (*c == 3) ? JCS_RGB : JCS_GRAYSCALE;
  jpeg_start_decompress(&cinfo);
  const long row_bytes = static_cast<long>(cinfo.output_width) *
                         cinfo.output_components;
  if (row_bytes * static_cast<long>(cinfo.output_height) > out_cap) {
    jpeg_abort_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    return -3;
  }
  while (cinfo.output_scanline < cinfo.output_height) {
    unsigned char* row = out + static_cast<long>(cinfo.output_scanline) *
                                   row_bytes;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

// HWC uint8 (rgb or gray) -> CHW float32 with channel replication for gray
// inputs (iter_thread_imbin_x grayscale->3-channel behavior), optional
// horizontal mirror, and crop at (crop_y, crop_x) of size (out_h, out_w).
// src dims (src_h, src_w, src_c); out has 3*out_h*out_w floats when
// src_c==1&&gray_to_rgb else src_c*out_h*out_w.
int cxn_hwc_to_chw_float(const unsigned char* src, int src_h, int src_w,
                         int src_c, int crop_y, int crop_x,
                         int out_h, int out_w, int mirror, int gray_to_rgb,
                         float* out) {
  if (crop_y < 0 || crop_x < 0 || crop_y + out_h > src_h ||
      crop_x + out_w > src_w)
    return -1;
  const int out_c = (src_c == 1 && gray_to_rgb) ? 3 : src_c;
  for (int cc = 0; cc < out_c; ++cc) {
    const int sc = (src_c == 1) ? 0 : cc;
    float* dst = out + static_cast<long>(cc) * out_h * out_w;
    for (int y = 0; y < out_h; ++y) {
      const unsigned char* row =
          src + (static_cast<long>(crop_y + y) * src_w + crop_x) * src_c + sc;
      float* drow = dst + static_cast<long>(y) * out_w;
      if (mirror) {
        for (int x = 0; x < out_w; ++x)
          drow[x] = static_cast<float>(row[(out_w - 1 - x) * src_c]);
      } else {
        for (int x = 0; x < out_w; ++x)
          drow[x] = static_cast<float>(row[x * src_c]);
      }
    }
  }
  return out_c;
}

// Fused decode -> full-frame CHW float (no crop), the imgbin page-decode hot
// path. out must hold 3*h*w (gray replicated) or c*h*w floats; call
// cxn_jpeg_decode(out=null) first for dims. scratch must hold h*w*c bytes.
int cxn_decode_chw(const unsigned char* src, long len, unsigned char* scratch,
                   long scratch_cap, float* out, int gray_to_rgb,
                   int* w, int* h, int* c) {
  int rc = cxn_jpeg_decode(src, len, scratch, scratch_cap, w, h, c);
  if (rc != 0) return rc;
  return cxn_hwc_to_chw_float(scratch, *h, *w, *c, 0, 0, *h, *w, 0,
                              gray_to_rgb, out);
}

}  // extern "C"
