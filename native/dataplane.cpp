// Native data plane: JPEG decode + fused decode/crop/mirror-to-float.
//
// TPU-native equivalent of the reference's host-side decode path
// (/root/reference/src/utils/decoder.h JpegDecoder + the per-instance copy
// loops in iter_thread_imbin_x-inl.hpp:269-387). The TPU does the math; this
// library keeps the *host* fast: libjpeg decode and the uint8->float CHW
// conversion are the input-pipeline hot path when feeding a chip at line
// rate. Exposed as a C ABI for ctypes (no pybind11 in this image); all entry
// points are GIL-free by construction so a Python thread pool scales.
//
// Build: make -C native   (produces libcxnetdata.so)

#include <cstdio>
#include <cstring>
#include <csetjmp>
#include <cstdlib>

#include <jpeglib.h>
#include <jerror.h>

namespace {

struct ErrorMgr {
  jpeg_error_mgr base;
  jmp_buf jump;
};

void error_exit(j_common_ptr cinfo) {
  ErrorMgr* mgr = reinterpret_cast<ErrorMgr*>(cinfo->err);
  longjmp(mgr->jump, 1);
}

}  // namespace

extern "C" {

// Decode a JPEG byte buffer to interleaved RGB (or grayscale) HWC uint8,
// optionally at a reduced scale (scale_num/8 — libjpeg decodes the DCT at
// the coarser scale, so a 1/2 decode costs roughly a quarter of the IDCT
// and color-convert work; the input-pipeline decode-at-scale lever).
// Returns 0 on success; fills *w,*h,*c with the OUTPUT (scaled) dims. out
// may be null to only query dims (two-call protocol). out_cap is the byte
// capacity of out.
int cxn_jpeg_decode_scaled(const unsigned char* src, long len,
                           unsigned char* out, long out_cap, int scale_num,
                           int* w, int* h, int* c) {
  if (scale_num < 1 || scale_num > 8) return -4;
  jpeg_decompress_struct cinfo;
  ErrorMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.base);
  jerr.base.error_exit = error_exit;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<unsigned char*>(src),
               static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -2;
  }
  cinfo.scale_num = static_cast<unsigned int>(scale_num);
  cinfo.scale_denom = 8;
  jpeg_calc_output_dimensions(&cinfo);
  *w = static_cast<int>(cinfo.output_width);
  *h = static_cast<int>(cinfo.output_height);
  *c = cinfo.num_components >= 3 ? 3 : 1;
  if (out == nullptr) {
    jpeg_destroy_decompress(&cinfo);
    return 0;
  }
  cinfo.out_color_space = (*c == 3) ? JCS_RGB : JCS_GRAYSCALE;
  jpeg_start_decompress(&cinfo);
  const long row_bytes = static_cast<long>(cinfo.output_width) *
                         cinfo.output_components;
  if (row_bytes * static_cast<long>(cinfo.output_height) > out_cap) {
    jpeg_abort_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    return -3;
  }
  while (cinfo.output_scanline < cinfo.output_height) {
    unsigned char* row = out + static_cast<long>(cinfo.output_scanline) *
                                   row_bytes;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

// Decode a JPEG byte buffer to interleaved RGB (or grayscale) HWC uint8.
// Returns 0 on success; fills *w,*h,*c. out may be null to only query dims
// (two-call protocol). out_cap is the byte capacity of out.
int cxn_jpeg_decode(const unsigned char* src, long len,
                    unsigned char* out, long out_cap,
                    int* w, int* h, int* c) {
  return cxn_jpeg_decode_scaled(src, len, out, out_cap, 8, w, h, c);
}

// HWC uint8 (rgb or gray) -> CHW float32 with channel replication for gray
// inputs (iter_thread_imbin_x grayscale->3-channel behavior), optional
// horizontal mirror, and crop at (crop_y, crop_x) of size (out_h, out_w).
// src dims (src_h, src_w, src_c); out has 3*out_h*out_w floats when
// src_c==1&&gray_to_rgb else src_c*out_h*out_w.
int cxn_hwc_to_chw_float(const unsigned char* src, int src_h, int src_w,
                         int src_c, int crop_y, int crop_x,
                         int out_h, int out_w, int mirror, int gray_to_rgb,
                         float* out) {
  if (crop_y < 0 || crop_x < 0 || crop_y + out_h > src_h ||
      crop_x + out_w > src_w)
    return -1;
  const int out_c = (src_c == 1 && gray_to_rgb) ? 3 : src_c;
  for (int cc = 0; cc < out_c; ++cc) {
    const int sc = (src_c == 1) ? 0 : cc;
    float* dst = out + static_cast<long>(cc) * out_h * out_w;
    for (int y = 0; y < out_h; ++y) {
      const unsigned char* row =
          src + (static_cast<long>(crop_y + y) * src_w + crop_x) * src_c + sc;
      float* drow = dst + static_cast<long>(y) * out_w;
      if (mirror) {
        for (int x = 0; x < out_w; ++x)
          drow[x] = static_cast<float>(row[(out_w - 1 - x) * src_c]);
      } else {
        for (int x = 0; x < out_w; ++x)
          drow[x] = static_cast<float>(row[x * src_c]);
      }
    }
  }
  return out_c;
}

// Fused decode -> full-frame CHW float (no crop), the imgbin page-decode hot
// path. out must hold 3*h*w (gray replicated) or c*h*w floats; call
// cxn_jpeg_decode(out=null) first for dims. scratch must hold h*w*c bytes.
int cxn_decode_chw(const unsigned char* src, long len, unsigned char* scratch,
                   long scratch_cap, float* out, int gray_to_rgb,
                   int* w, int* h, int* c) {
  int rc = cxn_jpeg_decode(src, len, scratch, scratch_cap, w, h, c);
  if (rc != 0) return rc;
  return cxn_hwc_to_chw_float(scratch, *h, *w, *c, 0, 0, *h, *w, 0,
                              gray_to_rgb, out);
}

}  // extern "C"

// ---------------------------------------------------------------------------
// PNG decode (libpng simplified API) — the reference's `img` iterator
// decodes any OpenCV-supported format (iter_img-inl.hpp:16-137); JPEG and
// PNG cover the reference example datasets, everything else falls back to
// the Python PIL path.
// ---------------------------------------------------------------------------

#if defined(__has_include)
#  if __has_include(<png.h>)
#    define CXN_HAVE_PNG 1
#  endif
#endif

#ifdef CXN_HAVE_PNG
#include <png.h>
#endif

extern "C" {

#ifdef CXN_HAVE_PNG
// Same two-call protocol as cxn_jpeg_decode: out == null queries dims.
// Decodes to 8-bit RGB (or GRAY when the source is single-channel).
int cxn_png_decode(const unsigned char* src, long len,
                   unsigned char* out, long out_cap,
                   int* w, int* h, int* c) {
  png_image image;
  memset(&image, 0, sizeof(image));
  image.version = PNG_IMAGE_VERSION;
  if (!png_image_begin_read_from_memory(&image, src,
                                        static_cast<size_t>(len))) {
    return -1;
  }
  const int gray = (image.format & PNG_FORMAT_FLAG_COLOR) == 0;
  image.format = gray ? PNG_FORMAT_GRAY : PNG_FORMAT_RGB;
  *w = static_cast<int>(image.width);
  *h = static_cast<int>(image.height);
  *c = gray ? 1 : 3;
  if (out == nullptr) {
    png_image_free(&image);
    return 0;
  }
  const long need = static_cast<long>(PNG_IMAGE_SIZE(image));
  if (out_cap < need) {
    png_image_free(&image);
    return -2;
  }
  if (!png_image_finish_read(&image, nullptr, out, 0, nullptr)) {
    png_image_free(&image);
    return -3;
  }
  return 0;
}
#endif  // CXN_HAVE_PNG (absent: decoder.py's hasattr check falls to PIL)

// ---------------------------------------------------------------------------
// Affine warp (inverse map, bicubic a=-1.0 — PIL's transform kernel), HWC
// uint8. The reference ran this warp through OpenCV on the host hot path
// (image_augmenter-inl.hpp:95-121); this keeps the augmentation chain
// native end to end (decode -> warp -> crop/mirror/float).
//   dst(y, x) <- src(i10*x + i11*y + it1, i00*x + i01*y + it0)
// matching PIL.Image.transform(AFFINE, (i00, i01, it0, i10, i11, it1)).
// ---------------------------------------------------------------------------

static inline double cubic_w(double t) {
  // Keys cubic, a = -1.0 — what PIL's AFFINE transform uses (its
  // *resize* bicubic is a=-0.5; Geometry.c's transform kernel is not)
  const double a = -1.0;
  t = t < 0 ? -t : t;
  if (t <= 1.0) return ((a + 2.0) * t - (a + 3.0)) * t * t + 1.0;
  if (t < 2.0) return (((t - 5.0) * t + 8.0) * t - 4.0) * a;
  return 0.0;
}

int cxn_affine_warp_u8(const unsigned char* src, int src_h, int src_w,
                       int ch, unsigned char* dst, int dst_h, int dst_w,
                       const double* m /* i00 i01 it0 i10 i11 it1 */,
                       int fill) {
  if (ch <= 0 || ch > 4) return -1;
  for (int y = 0; y < dst_h; ++y) {
    for (int x = 0; x < dst_w; ++x) {
      // PIL samples at pixel centers: (x+0.5, y+0.5), then -0.5 back
      const double xs = m[0] * (x + 0.5) + m[1] * (y + 0.5) + m[2] - 0.5;
      const double ys = m[3] * (x + 0.5) + m[4] * (y + 0.5) + m[5] - 0.5;
      unsigned char* d = dst + (static_cast<long>(y) * dst_w + x) * ch;
      if (xs < -1.0 || ys < -1.0 || xs >= src_w || ys >= src_h) {
        for (int k = 0; k < ch; ++k) d[k] = static_cast<unsigned char>(fill);
        continue;
      }
      const int x0 = static_cast<int>(xs >= 0 ? xs : xs - 1.0);  // floor
      const int y0 = static_cast<int>(ys >= 0 ? ys : ys - 1.0);
      double wx[4], wy[4];
      for (int k = 0; k < 4; ++k) {
        wx[k] = cubic_w(xs - (x0 - 1 + k));
        wy[k] = cubic_w(ys - (y0 - 1 + k));
      }
      for (int k = 0; k < ch; ++k) {
        double acc = 0.0, wsum = 0.0;
        for (int j = 0; j < 4; ++j) {
          const int yy = y0 - 1 + j;
          for (int i = 0; i < 4; ++i) {
            const int xx = x0 - 1 + i;
            const double wgt = wx[i] * wy[j];
            double v;
            if (yy < 0 || yy >= src_h || xx < 0 || xx >= src_w) {
              v = fill;                       // outside: fill color
            } else {
              v = src[(static_cast<long>(yy) * src_w + xx) * ch + k];
            }
            acc += wgt * v;
            wsum += wgt;
          }
        }
        acc /= (wsum != 0.0 ? wsum : 1.0);
        acc = acc < 0.0 ? 0.0 : (acc > 255.0 ? 255.0 : acc);
        d[k] = static_cast<unsigned char>(acc + 0.5);
      }
    }
  }
  return 0;
}

}  // extern "C"
