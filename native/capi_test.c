/* Smoke test driving the C ABI end-to-end from pure C: build a tiny MLP,
 * train it on synthetic separable data, check prediction accuracy and
 * weight round-tripping. Run from the repo root (or pass repo path):
 *   ./native/capi_test [repo_path]
 */
#include <stdio.h>
#include <stdlib.h>

#include "capi.h"

static const char *CFG =
    "netconfig=start\n"
    "layer[+1:fc1] = fullc:fc1\n"
    "  nhidden = 32\n"
    "  init_sigma = 0.1\n"
    "layer[+1] = relu\n"
    "layer[+1:fc2] = fullc:fc2\n"
    "  nhidden = 2\n"
    "  init_sigma = 0.1\n"
    "layer[+0] = softmax\n"
    "netconfig=end\n"
    "input_shape = 1,1,8\n"
    "batch_size = 32\n"
    "eta = 0.2\n"
    "momentum = 0.9\n"
    "dev = cpu\n";

static void make_batch(unsigned seed, cxn_real_t *data, cxn_real_t *label) {
  unsigned s = seed * 2654435761u + 1;
  for (int i = 0; i < 32; ++i) {
    s = s * 1664525u + 1013904223u;
    int cls = (s >> 16) & 1;
    label[i] = (cxn_real_t)cls;
    for (int j = 0; j < 8; ++j) {
      s = s * 1664525u + 1013904223u;
      float noise = ((s >> 8) & 0xffff) / 65536.0f - 0.5f;
      data[i * 8 + j] = (cls ? 1.0f : -1.0f) + noise;
    }
  }
}

int main(int argc, char **argv) {
  if (CXNInit(argc > 1 ? argv[1] : ".") != 0) {
    fprintf(stderr, "CXNInit failed: %s\n", CXNGetLastError());
    return 1;
  }
  void *net = CXNNetCreate("cpu", CFG);
  if (net == NULL) {
    fprintf(stderr, "CXNNetCreate failed: %s\n", CXNGetLastError());
    return 1;
  }
  CXNNetInitModel(net);

  cxn_real_t data[32 * 8], label[32];
  cxn_uint64 dshape[4] = {32, 1, 1, 8}, lshape[2] = {32, 1};
  for (int step = 0; step < 30; ++step) {
    make_batch(step, data, label);
    CXNNetUpdateBatch(net, data, dshape, label, lshape);
  }

  make_batch(999, data, label);
  cxn_uint64 n = 0;
  const cxn_real_t *pred = CXNNetPredictBatch(net, data, dshape, &n);
  if (pred == NULL || n != 32) {
    fprintf(stderr, "predict failed (%s)\n", CXNGetLastError());
    return 1;
  }
  int correct = 0;
  for (int i = 0; i < 32; ++i) correct += (pred[i] == label[i]);
  printf("capi_test: accuracy %d/32\n", correct);
  if (correct < 30) return 1;

  cxn_uint64 wshape[4], ndim = 0;
  const cxn_real_t *w = CXNNetGetWeight(net, "fc1", "wmat", wshape, &ndim);
  if (w == NULL || ndim != 2 || wshape[0] != 32 || wshape[1] != 8) {
    fprintf(stderr, "get_weight failed (%s)\n", CXNGetLastError());
    return 1;
  }
  printf("capi_test: fc1 wmat %llu x %llu OK\n",
         (unsigned long long)wshape[0], (unsigned long long)wshape[1]);

  CXNNetFree(net);
  printf("capi_test: PASS\n");
  return 0;
}
