// im2bin — pack images listed in a .lst file into a BinaryPage .bin dataset.
//
// Native counterpart of tools/im2bin.py, capability parity with the
// reference tool (/root/reference/tools/im2bin.cpp:1-67). Output is
// format-compatible with cxxnet_tpu.io.binpage and reference .bin files:
// 64MB pages of little-endian int32 words, word 0 = object count N,
// words 1..N+1 = cumulative byte end-offsets, payloads packed backward
// from the end of the page.
//
// Usage: im2bin image.lst image_root_dir output_file

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <string>
#include <vector>

namespace {

constexpr int64_t kPageWords = 64 << 18;          // 1 << 24 int32 words
constexpr int64_t kPageBytes = kPageWords * 4;    // 64 MB

class PageWriter {
 public:
  explicit PageWriter(FILE* out)
      : out_(out), buf_(kPageBytes, 0), ends_(1, 0) {}

  bool Push(const std::vector<unsigned char>& obj) {
    const int64_t len = static_cast<int64_t>(obj.size());
    if (len + 12 > kPageBytes) return false;      // can never fit
    if (FreeBytes() < len + 4) Flush();
    const int32_t new_end = ends_.back() + static_cast<int32_t>(len);
    if (len > 0)
      std::memcpy(buf_.data() + kPageBytes - new_end, obj.data(), obj.size());
    ends_.push_back(new_end);
    ++n_objects;
    return true;
  }

  void Close() {
    if (ends_.size() > 1) Flush();
  }

  int64_t n_objects = 0;
  int64_t n_pages = 0;

 private:
  int64_t FreeBytes() const {
    // header = count word + (N existing + 1 new + 1 sentinel) offset words
    const int64_t n = static_cast<int64_t>(ends_.size()) - 1;
    return (kPageWords - (n + 2)) * 4 - ends_.back();
  }

  void Flush() {
    int32_t head[1] = {static_cast<int32_t>(ends_.size() - 1)};
    std::memcpy(buf_.data(), head, 4);
    std::memcpy(buf_.data() + 4, ends_.data(), ends_.size() * 4);
    if (std::fwrite(buf_.data(), 1, kPageBytes, out_) !=
        static_cast<size_t>(kPageBytes)) {
      std::fprintf(stderr, "im2bin: short write to output file\n");
      std::exit(1);
    }
    std::fill(buf_.begin(), buf_.end(), 0);
    ends_.assign(1, 0);
    ++n_pages;
  }

  FILE* out_;
  std::vector<char> buf_;
  std::vector<int32_t> ends_;
};

// .lst line: index<TAB>label[<TAB>more labels]<TAB>relative/path; the
// filename is the last field. Same accept/skip rules as parse_list_line
// (cxxnet_tpu/io/imgbin.py): tab-split first, any-whitespace split as
// fallback, skip lines with fewer than two fields.
bool FileNameOfLine(const std::string& line, std::string* fname) {
  size_t end = line.find_last_not_of(" \t\r\n");
  if (end == std::string::npos) return false;
  size_t sep = line.find_last_of('\t', end);
  if (sep == std::string::npos)
    sep = line.find_last_of(" \t", end);
  if (sep == std::string::npos) return false;  // single field: malformed
  *fname = line.substr(sep + 1, end - sep);
  return true;
}

bool ReadWhole(const std::string& path, std::vector<unsigned char>* out) {
  FILE* f = std::fopen(path.c_str(), "rb");
  if (f == nullptr) return false;
  std::fseek(f, 0, SEEK_END);
  long len = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  out->resize(len < 0 ? 0 : static_cast<size_t>(len));
  size_t got = out->empty() ? 0 : std::fread(out->data(), 1, out->size(), f);
  std::fclose(f);
  return got == out->size();
}

}  // namespace

int main(int argc, char** argv) {
  if (argc != 4) {
    std::fprintf(stderr, "Usage: im2bin image.lst image_root_dir output_file\n");
    return 1;
  }
  FILE* lst = std::fopen(argv[1], "r");
  if (lst == nullptr) {
    std::fprintf(stderr, "im2bin: cannot open list file %s\n", argv[1]);
    return 1;
  }
  FILE* out = std::fopen(argv[3], "wb");
  if (out == nullptr) {
    std::fprintf(stderr, "im2bin: cannot open output file %s\n", argv[3]);
    std::fclose(lst);
    return 1;
  }
  const std::time_t start = std::time(nullptr);
  std::printf("creating image binary pack from %s...\n", argv[1]);

  PageWriter writer(out);
  std::string root(argv[2]);
  if (!root.empty() && root.back() != '/') root += '/';

  char linebuf[1 << 16];
  std::vector<unsigned char> obj;
  while (std::fgets(linebuf, sizeof(linebuf), lst) != nullptr) {
    std::string fname;
    if (!FileNameOfLine(linebuf, &fname)) continue;
    const std::string path = root + fname;
    if (!ReadWhole(path, &obj)) {
      std::fprintf(stderr, "im2bin: cannot read image %s\n", path.c_str());
      return 1;
    }
    if (!writer.Push(obj)) {
      std::fprintf(stderr, "im2bin: image %s exceeds the 64MB page size\n",
                   path.c_str());
      return 1;
    }
    if (writer.n_objects % 1000 == 0) {
      std::printf("\r[%8ld] images processed to %ld pages, %ld sec elapsed",
                  static_cast<long>(writer.n_objects),
                  static_cast<long>(writer.n_pages),
                  static_cast<long>(std::time(nullptr) - start));
      std::fflush(stdout);
    }
  }
  writer.Close();
  std::fclose(lst);
  std::fclose(out);
  std::printf("\nfinished [%8ld] images packed to %ld pages, %ld sec elapsed\n",
              static_cast<long>(writer.n_objects),
              static_cast<long>(writer.n_pages),
              static_cast<long>(std::time(nullptr) - start));
  return 0;
}
