// C ABI implementation: embeds CPython and adapts cxxnet_tpu.wrapper.
//
// Counterpart of the reference's wrapper/cxxnet_wrapper.cpp (which adapted
// the C++ INetTrainer); here the trainer is Python/JAX, so the adapter goes
// the other direction. Each handle owns a Python object plus a pinned
// "last result" buffer so returned pointers outlive the call (same lifetime
// contract as the reference wrapper's temp tensors).
//
// Build: make -C native capi   (produces libcxnettpu.so)

#include "capi.h"

#include <Python.h>

#include <unistd.h>

#include <cstdlib>
#include <cstring>
#include <string>

namespace {

std::string g_last_error;

void set_error_from_python() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  if (value != nullptr) {
    PyObject *s = PyObject_Str(value);
    g_last_error = s ? PyUnicode_AsUTF8(s) : "unknown python error";
    Py_XDECREF(s);
  } else {
    g_last_error = "unknown error";
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

// Wrapped python object + buffers backing the most recent returned pointer.
struct Handle {
  PyObject *obj = nullptr;       // wrapper.Net or wrapper.DataIter
  PyObject *last = nullptr;      // numpy array pinning the returned memory
  std::string last_str;
  ~Handle() {
    Py_XDECREF(last);
    Py_XDECREF(obj);
  }
};

PyObject *g_wrapper_module = nullptr;
PyObject *g_np_module = nullptr;

// Call obj.method(*args) with a new reference result (nullptr on error).
PyObject *call(PyObject *obj, const char *method, PyObject *args) {
  PyObject *fn = PyObject_GetAttrString(obj, method);
  if (!fn) { set_error_from_python(); Py_XDECREF(args); return nullptr; }
  PyObject *ret = PyObject_CallObject(fn, args);
  Py_DECREF(fn);
  Py_XDECREF(args);
  if (!ret) set_error_from_python();
  return ret;
}

// float32 C-contiguous numpy array from raw floats.
PyObject *np_from_floats(const cxn_real_t *data, const cxn_uint64 *shape,
                         int ndim) {
  cxn_uint64 size = 1;
  PyObject *shp = PyTuple_New(ndim);
  for (int i = 0; i < ndim; ++i) {
    size *= shape[i];
    PyTuple_SET_ITEM(shp, i, PyLong_FromUnsignedLongLong(shape[i]));
  }
  PyObject *bytes = PyBytes_FromStringAndSize(
      reinterpret_cast<const char *>(data), size * sizeof(cxn_real_t));
  PyObject *ret = PyObject_CallMethod(g_np_module, "frombuffer", "Os", bytes,
                                      "float32");
  Py_DECREF(bytes);
  if (ret) {
    PyObject *reshaped = PyObject_CallMethod(ret, "reshape", "O", shp);
    Py_DECREF(ret);
    ret = reshaped;
  }
  Py_DECREF(shp);
  if (!ret) set_error_from_python();
  return ret;
}

// Expose a numpy array's data: pin it on the handle, return pointer+shape.
const cxn_real_t *expose(Handle *h, PyObject *arr, cxn_uint64 *oshape,
                         cxn_uint64 *ondim, int max_dim) {
  if (!arr) return nullptr;
  PyObject *contig = PyObject_CallMethod(
      g_np_module, "ascontiguousarray", "Os", arr, "float32");
  Py_DECREF(arr);
  if (!contig) { set_error_from_python(); return nullptr; }
  Py_XDECREF(h->last);
  h->last = contig;
  Py_buffer view;
  if (PyObject_GetBuffer(contig, &view, PyBUF_CONTIG_RO) != 0) {
    set_error_from_python();
    return nullptr;
  }
  if (oshape != nullptr) {
    for (int i = 0; i < max_dim; ++i)
      oshape[i] = i < view.ndim ? static_cast<cxn_uint64>(view.shape[i]) : 1;
  }
  if (ondim != nullptr) *ondim = view.ndim;
  const cxn_real_t *ptr = static_cast<const cxn_real_t *>(view.buf);
  PyBuffer_Release(&view);   // memory stays alive via h->last
  return ptr;
}

struct Gil {
  PyGILState_STATE st;
  Gil() : st(PyGILState_Ensure()) {}
  ~Gil() { PyGILState_Release(st); }
};

}  // namespace

extern "C" {

const char *CXNGetLastError(void) { return g_last_error.c_str(); }

int CXNInit(const char *repo_path) {
  bool fresh = false;
  if (!Py_IsInitialized()) {
    // Point the embedded runtime at a specific interpreter so its
    // environment (a venv's site-packages, via pyvenv.cfg discovery) is
    // adopted: CXN_PYTHON=<path/to/python> explicitly, else an active
    // VIRTUAL_ENV. Without either, the bare libpython prefix is used,
    // which may lack numpy/jax.
    PyConfig config;
    PyConfig_InitPythonConfig(&config);
    config.install_signal_handlers = 0;
    std::string exe;
    if (const char *p = getenv("CXN_PYTHON")) {
      exe = p;
    } else if (const char *ve = getenv("VIRTUAL_ENV")) {
      // Some venvs ship only bin/python; try python3 first, then python.
      exe = std::string(ve) + "/bin/python3";
      if (access(exe.c_str(), X_OK) != 0) {
        std::string alt = std::string(ve) + "/bin/python";
        if (access(alt.c_str(), X_OK) == 0) exe = alt;
      }
    }
    PyStatus st;
    if (!exe.empty()) {
      if (access(exe.c_str(), X_OK) != 0) {
        PyConfig_Clear(&config);
        g_last_error = "CXNInit: CXN_PYTHON/VIRTUAL_ENV interpreter not "
                       "executable: " + exe;
        return -1;
      }
      st = PyConfig_SetBytesString(&config, &config.executable, exe.c_str());
      if (PyStatus_Exception(st)) {
        PyConfig_Clear(&config);
        g_last_error = "CXNInit: bad CXN_PYTHON/VIRTUAL_ENV path";
        return -1;
      }
    }
    st = Py_InitializeFromConfig(&config);
    PyConfig_Clear(&config);
    if (PyStatus_Exception(st)) {
      g_last_error = st.err_msg ? st.err_msg : "Py_InitializeFromConfig failed";
      return -1;
    }
    fresh = true;
  }
  {
    Gil gil;
    if (g_wrapper_module == nullptr) {
      if (repo_path != nullptr && repo_path[0] != '\0') {
        PyObject *sys_path = PySys_GetObject("path");   // borrowed
        PyObject *p = PyUnicode_FromString(repo_path);
        PyList_Insert(sys_path, 0, p);
        Py_DECREF(p);
      }
      g_np_module = PyImport_ImportModule("numpy");
      if (!g_np_module) { set_error_from_python(); return -1; }
      g_wrapper_module = PyImport_ImportModule("cxxnet_tpu.wrapper");
      if (!g_wrapper_module) { set_error_from_python(); return -1; }
    }
  }
  // Py_InitializeEx leaves this thread holding the GIL; release it so other
  // threads' PyGILState_Ensure calls can proceed (the embedder never needs
  // the GIL between CXN* calls)
  if (fresh) PyEval_SaveThread();
  return 0;
}

/* ---------------- iterators ---------------- */

void *CXNIOCreateFromConfig(const char *cfg) {
  Gil gil;
  PyObject *obj = call(g_wrapper_module, "DataIter",
                       Py_BuildValue("(s)", cfg));
  if (!obj) return nullptr;
  Handle *h = new Handle();
  h->obj = obj;
  return h;
}

int CXNIONext(void *handle) {
  Gil gil;
  Handle *h = static_cast<Handle *>(handle);
  PyObject *r = call(h->obj, "next", nullptr);
  if (!r) return -1;
  int ret = PyObject_IsTrue(r);
  Py_DECREF(r);
  return ret;
}

void CXNIOBeforeFirst(void *handle) {
  Gil gil;
  Handle *h = static_cast<Handle *>(handle);
  Py_XDECREF(call(h->obj, "before_first", nullptr));
}

const cxn_real_t *CXNIOGetData(void *handle, cxn_uint64 *oshape) {
  Gil gil;
  Handle *h = static_cast<Handle *>(handle);
  return expose(h, call(h->obj, "get_data", nullptr), oshape, nullptr, 4);
}

const cxn_real_t *CXNIOGetLabel(void *handle, cxn_uint64 *oshape) {
  Gil gil;
  Handle *h = static_cast<Handle *>(handle);
  return expose(h, call(h->obj, "get_label", nullptr), oshape, nullptr, 2);
}

void CXNIOFree(void *handle) {
  Gil gil;
  Handle *h = static_cast<Handle *>(handle);
  Py_XDECREF(call(h->obj, "close", nullptr));  // stop prefetch threads
  delete h;
}

/* ---------------- trainer ---------------- */

void *CXNNetCreate(const char *device, const char *cfg) {
  Gil gil;
  PyObject *obj = call(g_wrapper_module, "Net",
                       Py_BuildValue("(ss)", device ? device : "", cfg));
  if (!obj) return nullptr;
  Handle *h = new Handle();
  h->obj = obj;
  return h;
}

void CXNNetFree(void *handle) {
  Gil gil;
  delete static_cast<Handle *>(handle);
}

void CXNNetSetParam(void *handle, const char *name, const char *val) {
  Gil gil;
  Handle *h = static_cast<Handle *>(handle);
  Py_XDECREF(call(h->obj, "set_param", Py_BuildValue("(ss)", name, val)));
}

void CXNNetInitModel(void *handle) {
  Gil gil;
  Handle *h = static_cast<Handle *>(handle);
  Py_XDECREF(call(h->obj, "init_model", nullptr));
}

void CXNNetSaveModel(void *handle, const char *fname) {
  Gil gil;
  Handle *h = static_cast<Handle *>(handle);
  Py_XDECREF(call(h->obj, "save_model", Py_BuildValue("(s)", fname)));
}

void CXNNetLoadModel(void *handle, const char *fname) {
  Gil gil;
  Handle *h = static_cast<Handle *>(handle);
  Py_XDECREF(call(h->obj, "load_model", Py_BuildValue("(s)", fname)));
}

void CXNNetStartRound(void *handle, int round_counter) {
  Gil gil;
  Handle *h = static_cast<Handle *>(handle);
  Py_XDECREF(call(h->obj, "start_round", Py_BuildValue("(i)", round_counter)));
}

void CXNNetUpdateIter(void *handle, void *data_handle) {
  Gil gil;
  Handle *h = static_cast<Handle *>(handle);
  Handle *d = static_cast<Handle *>(data_handle);
  Py_XDECREF(call(h->obj, "update", Py_BuildValue("(O)", d->obj)));
}

void CXNNetUpdateBatch(void *handle, const cxn_real_t *pdata,
                       const cxn_uint64 dshape[4], const cxn_real_t *plabel,
                       const cxn_uint64 lshape[2]) {
  Gil gil;
  Handle *h = static_cast<Handle *>(handle);
  PyObject *data = np_from_floats(pdata, dshape, 4);
  PyObject *label = np_from_floats(plabel, lshape, 2);
  if (!data || !label) { Py_XDECREF(data); Py_XDECREF(label); return; }
  Py_XDECREF(call(h->obj, "update", Py_BuildValue("(NN)", data, label)));
}

const cxn_real_t *CXNNetPredictBatch(void *handle, const cxn_real_t *pdata,
                                     const cxn_uint64 dshape[4],
                                     cxn_uint64 *out_size) {
  Gil gil;
  Handle *h = static_cast<Handle *>(handle);
  PyObject *data = np_from_floats(pdata, dshape, 4);
  if (!data) return nullptr;
  cxn_uint64 shp[4] = {0, 1, 1, 1};
  const cxn_real_t *p = expose(
      h, call(h->obj, "predict", Py_BuildValue("(N)", data)), shp, nullptr, 4);
  if (out_size != nullptr) *out_size = shp[0] * shp[1] * shp[2] * shp[3];
  return p;
}

const cxn_real_t *CXNNetPredictIter(void *handle, void *data_handle,
                                    cxn_uint64 *out_size) {
  Gil gil;
  Handle *h = static_cast<Handle *>(handle);
  Handle *d = static_cast<Handle *>(data_handle);
  cxn_uint64 shp[4] = {0, 1, 1, 1};
  const cxn_real_t *p = expose(
      h, call(h->obj, "predict", Py_BuildValue("(O)", d->obj)), shp, nullptr,
      4);
  if (out_size != nullptr) *out_size = shp[0] * shp[1] * shp[2] * shp[3];
  return p;
}

const cxn_real_t *CXNNetExtractBatch(void *handle, const cxn_real_t *pdata,
                                     const cxn_uint64 dshape[4],
                                     const char *node_name,
                                     cxn_uint64 *out_size) {
  Gil gil;
  Handle *h = static_cast<Handle *>(handle);
  PyObject *data = np_from_floats(pdata, dshape, 4);
  if (!data) return nullptr;
  cxn_uint64 shp[4] = {0, 1, 1, 1};
  const cxn_real_t *p = expose(
      h, call(h->obj, "extract", Py_BuildValue("(Ns)", data, node_name)),
      shp, nullptr, 4);
  if (out_size != nullptr) *out_size = shp[0] * shp[1] * shp[2] * shp[3];
  return p;
}

const cxn_real_t *CXNNetExtractIter(void *handle, void *data_handle,
                                    const char *node_name,
                                    cxn_uint64 *out_size) {
  Gil gil;
  Handle *h = static_cast<Handle *>(handle);
  Handle *d = static_cast<Handle *>(data_handle);
  cxn_uint64 shp[4] = {0, 1, 1, 1};
  const cxn_real_t *p = expose(
      h, call(h->obj, "extract", Py_BuildValue("(Os)", d->obj, node_name)),
      shp, nullptr, 4);
  if (out_size != nullptr) *out_size = shp[0] * shp[1] * shp[2] * shp[3];
  return p;
}

const char *CXNNetEvaluate(void *handle, void *data_handle,
                           const char *name) {
  Gil gil;
  Handle *h = static_cast<Handle *>(handle);
  PyObject *arg = data_handle
      ? Py_BuildValue("(Os)", static_cast<Handle *>(data_handle)->obj, name)
      : Py_BuildValue("(Os)", Py_None, name);
  PyObject *r = call(h->obj, "evaluate", arg);
  if (!r) return nullptr;
  const char *s = PyUnicode_AsUTF8(r);
  h->last_str = s ? s : "";
  Py_DECREF(r);
  return h->last_str.c_str();
}

void CXNNetSetWeight(void *handle, const cxn_real_t *pdata, cxn_uint64 size,
                     const char *layer_name, const char *tag) {
  Gil gil;
  Handle *h = static_cast<Handle *>(handle);
  cxn_uint64 shape[1] = {size};
  PyObject *arr = np_from_floats(pdata, shape, 1);
  if (!arr) return;
  Py_XDECREF(call(h->obj, "set_weight",
                  Py_BuildValue("(Nss)", arr, layer_name, tag)));
}

const cxn_real_t *CXNNetGetWeight(void *handle, const char *layer_name,
                                  const char *tag, cxn_uint64 *oshape,
                                  cxn_uint64 *out_ndim) {
  Gil gil;
  Handle *h = static_cast<Handle *>(handle);
  return expose(h, call(h->obj, "get_weight",
                        Py_BuildValue("(ss)", layer_name, tag)),
                oshape, out_ndim, 4);
}

}  // extern "C"
