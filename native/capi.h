/* C ABI for the cxxnet_tpu framework — language-binding surface.
 *
 * TPU-native equivalent of the reference C wrapper
 * (/root/reference/wrapper/cxxnet_wrapper.h:36-232): the same CXNNet* /
 * CXNIO* entry points, but backed by an embedded CPython interpreter running
 * the JAX trainer instead of the C++ thread trainer. Handles are opaque;
 * returned buffers stay valid until the next call on the same handle.
 */
#ifndef CXXNET_TPU_CAPI_H_
#define CXXNET_TPU_CAPI_H_

#ifdef __cplusplus
extern "C" {
#endif

typedef float cxn_real_t;
typedef unsigned long long cxn_uint64;

/* global interpreter bootstrap; safe to call more than once. repo_path may
 * be NULL if cxxnet_tpu is already importable. Returns 0 on success. */
int CXNInit(const char *repo_path);
/* last error message ("" if none) */
const char *CXNGetLastError(void);

/* ---- data iterators ---- */
void *CXNIOCreateFromConfig(const char *cfg);
int   CXNIONext(void *handle);
void  CXNIOBeforeFirst(void *handle);
const cxn_real_t *CXNIOGetData(void *handle, cxn_uint64 *oshape /*[4]*/);
const cxn_real_t *CXNIOGetLabel(void *handle, cxn_uint64 *oshape /*[2]*/);
void  CXNIOFree(void *handle);

/* ---- trainer ---- */
void *CXNNetCreate(const char *device, const char *cfg);
void  CXNNetFree(void *handle);
void  CXNNetSetParam(void *handle, const char *name, const char *val);
void  CXNNetInitModel(void *handle);
void  CXNNetSaveModel(void *handle, const char *fname);
void  CXNNetLoadModel(void *handle, const char *fname);
void  CXNNetStartRound(void *handle, int round_counter);
void  CXNNetUpdateIter(void *handle, void *data_handle);
/* batch: row-major (nbatch, c, y, x) data + (nbatch, label_width) labels */
void  CXNNetUpdateBatch(void *handle, const cxn_real_t *pdata,
                        const cxn_uint64 dshape[4],
                        const cxn_real_t *plabel,
                        const cxn_uint64 lshape[2]);
const cxn_real_t *CXNNetPredictBatch(void *handle, const cxn_real_t *pdata,
                                     const cxn_uint64 dshape[4],
                                     cxn_uint64 *out_size);
const cxn_real_t *CXNNetPredictIter(void *handle, void *data_handle,
                                    cxn_uint64 *out_size);
const cxn_real_t *CXNNetExtractBatch(void *handle, const cxn_real_t *pdata,
                                     const cxn_uint64 dshape[4],
                                     const char *node_name,
                                     cxn_uint64 *out_size);
const cxn_real_t *CXNNetExtractIter(void *handle, void *data_handle,
                                    const char *node_name,
                                    cxn_uint64 *out_size);
const char *CXNNetEvaluate(void *handle, void *data_handle, const char *name);
void  CXNNetSetWeight(void *handle, const cxn_real_t *pdata,
                      cxn_uint64 size, const char *layer_name,
                      const char *tag);
const cxn_real_t *CXNNetGetWeight(void *handle, const char *layer_name,
                                  const char *tag, cxn_uint64 *oshape /*[4]*/,
                                  cxn_uint64 *out_ndim);

#ifdef __cplusplus
}
#endif
#endif  /* CXXNET_TPU_CAPI_H_ */
