"""Optimizers ("updaters") with the reference's math and schedules.

Reference (/root/reference/src/updater/):
- UpdaterParam schedules  param.h:13-133 — lr schedules constant/expdecay/
  polydecay/factor (integer-division quirks preserved), momentum ramp,
  lr_minimum floor, start_epoch freeze, per-tag hyperparams (``wmat:lr``)
- SGD   sgd_updater-inl.hpp:25-85 — m = mu*m - lr*(clip(g) + wd*w); w += m;
  ``clip`` maps NaN -> 0 (sgd_updater-inl.hpp:14-22)
- NAG   nag_updater-inl.hpp:15-73 — w += (1+mu)*m_new - mu*m_old
- Adam  adam_updater-inl.hpp:16-83 — one-minus convention (decay1=0.1 means
  beta1=0.9); weight decay applied as ``grad -= wd*w`` (sign quirk kept)

TPU-first design: each weight tensor gets an updater whose hyperparameters are
static Python floats and whose (lr, momentum) schedule is computed *inside* the
jitted train step from the traced epoch scalar — one compiled step serves the
whole run, no per-epoch recompilation. ``epoch`` counts update steps, as in the
reference (CXXNetThreadTrainer passes epoch_counter++ per Update).
"""

from __future__ import annotations

from typing import Dict, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..utils.config import ConfigError

Pairs = Sequence[Tuple[str, str]]


class UpdaterParam:
    """Hyper-parameters + schedules for one weight tensor (tag 'wmat'/'bias')."""

    def __init__(self, tag: str) -> None:
        self.tag = tag
        self.base_lr = 0.01
        self.wd = 0.0
        self.momentum = 0.9
        self.lr_schedule = 0
        self.momentum_schedule = 0
        self.lr_step = 1
        self.lr_gamma = 0.5
        self.lr_alpha = 0.5
        self.lr_factor = 0.1
        self.lr_minimum = 1e-5
        self.start_epoch = 0
        self.base_momentum = 0.5
        self.final_momentum = 0.9
        self.saturation_epoch = 0
        self.clip_gradient = 0.0

    def set_param(self, name: str, val: str) -> None:
        # tag-scoped override: "bias:wd" applies only when tag == "bias"
        if name.startswith(self.tag + ":"):
            name = name[len(self.tag) + 1:]
        elif ":" in name and not (name.startswith("lr:") or name.startswith("eta:")):
            other = name.split(":", 1)[0]
            if other in ("wmat", "bias"):
                return          # scoped to a different tag
        if name in ("lr", "eta"):
            self.base_lr = float(val)
        elif name == "wd":
            self.wd = float(val)
        elif name == "momentum":
            self.momentum = float(val)
        elif name == "momentum_schedule":
            self.momentum_schedule = int(val)
        elif name == "clip_gradient":
            self.clip_gradient = float(val)
        elif name == "final_momentum":
            self.final_momentum = float(val)
        elif name == "base_momentum":
            self.base_momentum = float(val)
        elif name == "saturation_epoch":
            self.saturation_epoch = int(val)
        elif name.startswith("lr:") or name.startswith("eta:"):
            sub = name.split(":", 1)[1]
            if sub == "schedule":
                table = {"constant": 0, "expdecay": 1, "polydecay": 2, "factor": 3}
                if val not in table:
                    raise ConfigError("unknown lr schedule %r" % val)
                self.lr_schedule = table[val]
            elif sub == "gamma":
                self.lr_gamma = float(val)
            elif sub == "alpha":
                self.lr_alpha = float(val)
            elif sub == "step":
                self.lr_step = int(val)
            elif sub == "factor":
                self.lr_factor = float(val)
            elif sub == "minimum_lr":
                self.lr_minimum = float(val)
            elif sub == "start_epoch":
                self.start_epoch = int(val)

    def schedule(self, epoch) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """(learning_rate, momentum) at update-step ``epoch`` (traced scalar)."""
        e = jnp.asarray(epoch, jnp.float32)
        e_div = jnp.floor(e / self.lr_step)   # the reference's integer division
        if self.lr_schedule == 0:
            lr = jnp.asarray(self.base_lr, jnp.float32)
        elif self.lr_schedule == 1:
            lr = self.base_lr * jnp.power(self.lr_gamma, e / self.lr_step)
        elif self.lr_schedule == 2:
            lr = self.base_lr * jnp.power(1.0 + e_div * self.lr_gamma,
                                          -self.lr_alpha)
        else:
            lr = self.base_lr * jnp.power(self.lr_factor, e_div)
        lr = jnp.maximum(lr, self.lr_minimum)
        lr = jnp.where(e < self.start_epoch, self.base_lr, lr)
        mom = jnp.asarray(self.momentum, jnp.float32)
        if self.momentum_schedule and self.saturation_epoch:
            # the reference accumulates the ramp in-place each step, so momentum
            # reaches final_momentum almost immediately; the clipped closed form:
            ramp = (self.momentum + self.base_momentum
                    + (self.final_momentum - self.base_momentum)
                    / self.saturation_epoch * e)
            mom = jnp.minimum(ramp, self.final_momentum)
        return lr, mom


def clip_grad(grad: jnp.ndarray, bound: float) -> jnp.ndarray:
    """Reference ``clip`` functor: NaN -> 0, then clamp to [-bound, bound]."""
    grad = jnp.where(jnp.isnan(grad), 0.0, grad)
    return jnp.clip(grad, -bound, bound)


class Updater:
    """Per-tensor optimizer; state is a dict pytree of arrays."""
    type_name = ""

    def __init__(self, tag: str, cfg: Pairs) -> None:
        self.param = UpdaterParam(tag)
        for k, v in cfg:
            self.param.set_param(k, v)
            self.set_param(k, v)

    def set_param(self, name: str, val: str) -> None:
        pass

    def init_state(self, w: jnp.ndarray) -> Dict[str, jnp.ndarray]:
        raise NotImplementedError

    def update(self, w: jnp.ndarray, grad: jnp.ndarray,
               state: Dict[str, jnp.ndarray], epoch):
        raise NotImplementedError

    def _prep_grad(self, grad, w):
        if self.param.clip_gradient != 0.0:
            grad = clip_grad(grad, self.param.clip_gradient)
        return grad


class SGDUpdater(Updater):
    type_name = "sgd"

    def init_state(self, w):
        return {"m": jnp.zeros_like(w)}

    def update(self, w, grad, state, epoch):
        lr, mom = self.param.schedule(epoch)
        grad = self._prep_grad(grad, w)
        m = mom * state["m"] - lr * (grad + self.param.wd * w)
        return w + m, {"m": m}


class NAGUpdater(Updater):
    type_name = "nag"

    def init_state(self, w):
        return {"m": jnp.zeros_like(w)}

    def update(self, w, grad, state, epoch):
        lr, mom = self.param.schedule(epoch)
        grad = self._prep_grad(grad, w)
        m_old = state["m"]
        m = mom * m_old - lr * (grad + self.param.wd * w)
        return w + (1 + mom) * m - mom * m_old, {"m": m}


class AdamUpdater(Updater):
    type_name = "adam"

    def __init__(self, tag, cfg):
        self.decay1 = 0.1
        self.decay2 = 0.001
        self.eps = 1e-8
        super().__init__(tag, cfg)

    def set_param(self, name, val):
        if name == "beta1":
            self.decay1 = float(val)
        elif name == "beta2":
            self.decay2 = float(val)
        elif name == "eps":
            self.eps = float(val)

    def init_state(self, w):
        return {"m1": jnp.zeros_like(w), "m2": jnp.zeros_like(w)}

    def _adam_step(self, grad, state, epoch, lr):
        """Shared bias-corrected moment update; returns (delta, new_state)."""
        e = jnp.asarray(epoch, jnp.float32)
        fix1 = 1.0 - jnp.power(1.0 - self.decay1, e + 1)
        fix2 = 1.0 - jnp.power(1.0 - self.decay2, e + 1)
        lr_t = lr * jnp.sqrt(fix2) / fix1
        m1 = state["m1"] + self.decay1 * (grad - state["m1"])
        m2 = state["m2"] + self.decay2 * (jnp.square(grad) - state["m2"])
        return -lr_t * (m1 / (jnp.sqrt(m2) + self.eps)), {"m1": m1, "m2": m2}

    def update(self, w, grad, state, epoch):
        grad = self._prep_grad(grad, w)
        if self.param.wd > 0.0:
            grad = grad - self.param.wd * w   # reference sign quirk
        # reference adam ignores the lr schedule (adam_updater-inl.hpp
        # recomputes from base lr every step) — reproduced deliberately
        delta, new_state = self._adam_step(grad, state, epoch,
                                           self.param.base_lr)
        return w + delta, new_state


class AdamWUpdater(AdamUpdater):
    """Decoupled weight decay (AdamW): wd scales the weight directly by
    lr*wd per step instead of entering the gradient moments — the modern
    extension beyond the reference's ``grad -= wd*w`` Adam quirk
    (adam_updater-inl.hpp:73-82). Betas use the same one-minus ("decay")
    convention as the reference Adam."""
    type_name = "adamw"

    def update(self, w, grad, state, epoch):
        grad = self._prep_grad(grad, w)
        # the scheduled lr scales both the step and the decay, like
        # torch.optim.AdamW (unlike the reference adam, adamw honors
        # lr:schedule — it is a modern extension, not a parity op)
        lr, _ = self.param.schedule(epoch)
        delta, new_state = self._adam_step(grad, state, epoch, lr)
        return w - lr * self.param.wd * w + delta, new_state


UPDATER_REGISTRY = {c.type_name: c
                    for c in (SGDUpdater, NAGUpdater, AdamUpdater,
                              AdamWUpdater)}


def global_norm_scale(grads, max_norm: float):
    """Scale factor for global-norm gradient clipping over a pytree of
    grads: min(1, max_norm / ||g||_2). NaN entries are excluded from the
    norm; the caller is responsible for zeroing them in the gradients
    themselves (Net._apply_grads does) — scaling alone leaves NaN*scale
    = NaN."""
    leaves = jax.tree.leaves(grads)
    sq = sum(jnp.sum(jnp.square(jnp.nan_to_num(g.astype(jnp.float32))))
             for g in leaves)
    norm = jnp.sqrt(sq)
    return jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-12))


def create_updater(kind: str, tag: str, cfg: Pairs) -> Updater:
    """Factory (updater.h:117-127 analogue)."""
    if kind not in UPDATER_REGISTRY:
        raise ConfigError("unknown updater %r" % kind)
    return UPDATER_REGISTRY[kind](tag, cfg)
