"""Token sampling: temperature + top-k + top-p, one implementation for
BOTH inference surfaces.

``task=generate`` / ``gpt_decode`` (offline batch, one rng key per call)
and the serving tick (``serve/engine.py``, one key + one parameter set
PER SLOT ROW) must produce identical tokens for the same request given
the same logits — that is the continuous-batching correctness contract
(a request served from a recycled slot must match the same request run
alone; pinned bit-level on the shared XLA decode path, see
serve/engine.py for the fused-kernel caveat). So the
filtering math lives here once, written row-wise so it accepts scalar
parameters (generate: one temperature/top_k/top_p per call, traced or
static) or per-row arrays (serve: mixed per-request params in one batch)
with the same per-row arithmetic either way.

Semantics (HuggingFace-conventional order): logits are temperature-scaled
first, then top-k keeps the k highest-probability tokens, then top-p
keeps the smallest prefix of the remaining distribution whose cumulative
probability reaches p; the filtered logits feed one categorical draw.
``top_k <= 0`` and ``top_p >= 1`` disable their filter — with both
disabled the filtered logits are VALUE-IDENTICAL to the input (the mask
is all-true), so adding the filter to an existing sampling path cannot
change previously pinned token streams.

The filters are threshold-based (compare against the k-th / nucleus-edge
logit VALUE) rather than scatter-based, so ``top_k``/``top_p`` may be
traced per-row values — ``lax.top_k`` with its static k cannot express a
batch where every request carries its own k. Exact logit ties at the
threshold are all kept (deterministic, order-free); for sampling this is
the right bias — a tie at the boundary means the distribution itself
does not distinguish the candidates.

Speculative decoding (serve/speculative.py) builds on the same filtered
distribution: :func:`accept_draft_rows` runs the per-row rejection test
for a deterministic draft proposal and :func:`residual_sample_rows`
draws the correction/bonus token from the draft-excluded residual —
together they leave the emitted distribution exactly equal to a direct
:func:`sample_rows` draw (chi-squared-pinned in tests/test_sampling.py),
and greedy rows reduce to argmax-prefix acceptance, which is what keeps
speculative greedy streams bit-identical to the plain decode.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["filter_logits", "sample_rows", "accept_draft_rows",
           "residual_sample_rows"]


def filter_logits(logits: jnp.ndarray, top_k=0, top_p=1.0) -> jnp.ndarray:
    """Mask ``logits`` (..., V) to the top-k / top-p candidate set.

    ``top_k``/``top_p`` are scalars or arrays broadcastable to the batch
    shape ``logits.shape[:-1]`` (per-row values in the serving tick).
    Masked entries become -inf; kept entries pass through UNCHANGED, so
    disabled filters are a value-level no-op. The filters apply
    SEQUENTIALLY: top-p's nucleus is measured on the softmax of the
    top-k-filtered logits (survivor mass renormalized, as in the HF
    convention), not on the original distribution. At least one token
    always survives (the argmax: it is >= the k-th largest for any
    k >= 1, and the first token of the nucleus prefix for any p > 0;
    ``top_p <= 0`` is clamped to keep exactly that first token).
    """
    v = logits.shape[-1]
    batch = logits.shape[:-1]
    k = jnp.broadcast_to(jnp.asarray(top_k, jnp.int32), batch)
    p = jnp.broadcast_to(jnp.asarray(top_p, jnp.float32), batch)
    # top-k: keep logits >= the k-th largest VALUE (ties at the edge kept)
    sl = jnp.flip(jnp.sort(logits, axis=-1), axis=-1)
    kth = jnp.take_along_axis(sl, jnp.clip(k - 1, 0, v - 1)[..., None],
                              axis=-1)
    keep = (k <= 0)[..., None] | (logits >= kth)
    out = jnp.where(keep, logits, -jnp.inf)
    # top-p over the SURVIVORS: -inf entries softmax to 0 and sort last,
    # so the cumulative mass is implicitly renormalized to the top-k set.
    # nucleus = smallest sorted prefix with cumulative prob >= p, i.e.
    # keep sorted position j iff the mass BEFORE j is still < p; the
    # edge logit's value is then the row threshold
    sl2 = jnp.flip(jnp.sort(out, axis=-1), axis=-1)
    probs = jax.nn.softmax(sl2.astype(jnp.float32), axis=-1)
    before = jnp.cumsum(probs, axis=-1) - probs
    in_nucleus = before < jnp.maximum(p, 1e-9)[..., None]
    edge = jnp.min(jnp.where(in_nucleus, sl2, jnp.inf), axis=-1,
                   keepdims=True).astype(logits.dtype)
    keep_p = (p >= 1.0)[..., None] | (out >= edge)
    return jnp.where(keep_p, out, -jnp.inf)


def _scaled_filtered(logits, temperature, top_k, top_p):
    """Shared per-row prologue: (f32 temperature, temperature-scaled
    top-k/top-p-filtered logits). EVERY per-row sampler below must run
    this exact pipeline — the speculative accept/residual pair's
    distribution identity with a direct :func:`sample_rows` draw (and
    with it the serve-vs-generate identity tests) holds only while all
    of them filter byte-identically. Greedy rows scale by 1 so the
    division never sees 0."""
    temperature = jnp.asarray(temperature, jnp.float32)
    safe_t = jnp.where(temperature > 0, temperature, 1.0)
    return temperature, filter_logits(
        logits / safe_t[:, None].astype(logits.dtype), top_k, top_p)


def _draw_rows(logits, filt, keys, temperature):
    """Shared per-row epilogue: one categorical draw per row from the
    filtered logits (vmap — semantically identical to the per-row loop,
    which is what lets a slot row reproduce gpt_decode's batch-1 pick),
    greedy argmax of the RAW logits where temperature <= 0."""
    sampled = jax.vmap(
        lambda l, k: jax.random.categorical(k, l[None, :], -1)[0])(filt, keys)
    greedy = jnp.argmax(logits, -1)
    return jnp.where(temperature > 0, sampled, greedy).astype(jnp.int32)


def sample_rows(logits: jnp.ndarray, keys: jnp.ndarray,
                temperature: jnp.ndarray, top_k: jnp.ndarray,
                top_p: jnp.ndarray) -> jnp.ndarray:
    """Per-row sampling for the serving tick: ``logits`` (b, V), ``keys``
    (b, 2) uint32 (one PRNG key per slot), per-row temperature/top_k/
    top_p (b,). Rows with ``temperature <= 0`` take the greedy argmax.

    Each row's draw is ``jax.random.categorical(key_b, filtered_b[None])``
    — via vmap, which JAX guarantees is semantically identical to the
    per-row loop — so a slot row reproduces exactly what ``gpt_decode``'s
    batch-1 ``pick`` computes for the same key and parameters. That
    equality is what the serve-vs-generate identity tests pin.
    """
    temperature, filt = _scaled_filtered(logits, temperature, top_k, top_p)
    return _draw_rows(logits, filt, keys, temperature)


def accept_draft_rows(logits: jnp.ndarray, draft: jnp.ndarray,
                      keys: jnp.ndarray, temperature: jnp.ndarray,
                      top_k: jnp.ndarray, top_p: jnp.ndarray) -> jnp.ndarray:
    """Per-row speculative accept test for a DEGENERATE (probability-1)
    draft proposal — both serving drafters are deterministic: the n-gram
    lookup proposes one continuation, and the draft model drafts
    greedily. ``logits`` (rows, V) are the TARGET model's logits at each
    draft position, ``draft`` (rows,) the proposed tokens, ``keys``
    (rows, 2) one PRNG key per row (derived from the per-token fold_in
    schedule by the caller).

    Greedy rows (``temperature <= 0``) accept iff the draft equals the
    target argmax — the longest-matching-prefix rule that keeps
    speculative greedy output bit-identical to the solo decode. Sampled
    rows run the standard rejection test ``u < p(draft)`` with ``p`` the
    temperature-scaled, top-k/top-p-filtered softmax (the q ≡ 1 case of
    accept-with-min(1, p/q)); combined with
    :func:`residual_sample_rows` on rejection the emitted token is
    distributed exactly as a direct ``sample_rows`` draw — pinned by the
    chi-squared test in tests/test_sampling.py."""
    temperature, filt = _scaled_filtered(logits, temperature, top_k, top_p)
    probs = jax.nn.softmax(filt.astype(jnp.float32), axis=-1)
    p_d = jnp.take_along_axis(probs, draft[:, None].astype(jnp.int32),
                              axis=-1)[:, 0]
    u = jax.vmap(lambda k: jax.random.uniform(k, ()))(keys)
    greedy_acc = draft == jnp.argmax(logits, -1)
    return jnp.where(temperature > 0, u < p_d, greedy_acc)


def residual_sample_rows(logits: jnp.ndarray, draft: jnp.ndarray,
                         keys: jnp.ndarray, temperature: jnp.ndarray,
                         top_k: jnp.ndarray,
                         top_p: jnp.ndarray) -> jnp.ndarray:
    """Per-row draw of the token EMITTED at a speculative verify row:
    the residual distribution after rejecting a degenerate proposal
    ``draft`` — the filtered softmax with the draft token masked out and
    implicitly renormalized ((p - q)+ with q ≡ 1 on the draft). Pass
    ``draft = -1`` (matches no vocab index) for the no-rejection bonus
    row, where this reduces to a plain filtered draw — the same
    computation :func:`sample_rows` performs. Greedy rows take the plain
    argmax (a greedy rejection already means draft != argmax, so the
    exclusion is vacuous and the emitted token is exactly the solo
    path's pick).

    Together the accept/residual pair leaves the output distribution
    unchanged: P(emit x) = p(x)·1[x = d] + (1 - p(d))·p(x)/(1 - p(d)) =
    p(x). The all-masked corner (the draft is the ONLY filtered
    candidate yet was rejected — measure-zero since p(draft) = 1 makes
    the accept test u < 1 always pass) falls back to the unexcluded
    filtered row rather than sampling an all -inf one; with a single
    finite candidate that deterministically re-emits the draft, the only
    token the filters left."""
    temperature, filt = _scaled_filtered(logits, temperature, top_k, top_p)
    v = logits.shape[-1]
    excl = jnp.where(jnp.arange(v)[None, :] == draft[:, None].astype(
        jnp.int32), -jnp.inf, filt)
    excl = jnp.where(jnp.isfinite(excl).any(-1, keepdims=True), excl, filt)
    return _draw_rows(logits, excl, keys, temperature)
