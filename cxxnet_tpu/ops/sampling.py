"""Token sampling: temperature + top-k + top-p, one implementation for
BOTH inference surfaces.

``task=generate`` / ``gpt_decode`` (offline batch, one rng key per call)
and the serving tick (``serve/engine.py``, one key + one parameter set
PER SLOT ROW) must produce identical tokens for the same request given
the same logits — that is the continuous-batching correctness contract
(a request served from a recycled slot must match the same request run
alone; pinned bit-level on the shared XLA decode path, see
serve/engine.py for the fused-kernel caveat). So the
filtering math lives here once, written row-wise so it accepts scalar
parameters (generate: one temperature/top_k/top_p per call, traced or
static) or per-row arrays (serve: mixed per-request params in one batch)
with the same per-row arithmetic either way.

Semantics (HuggingFace-conventional order): logits are temperature-scaled
first, then top-k keeps the k highest-probability tokens, then top-p
keeps the smallest prefix of the remaining distribution whose cumulative
probability reaches p; the filtered logits feed one categorical draw.
``top_k <= 0`` and ``top_p >= 1`` disable their filter — with both
disabled the filtered logits are VALUE-IDENTICAL to the input (the mask
is all-true), so adding the filter to an existing sampling path cannot
change previously pinned token streams.

The filters are threshold-based (compare against the k-th / nucleus-edge
logit VALUE) rather than scatter-based, so ``top_k``/``top_p`` may be
traced per-row values — ``lax.top_k`` with its static k cannot express a
batch where every request carries its own k. Exact logit ties at the
threshold are all kept (deterministic, order-free); for sampling this is
the right bias — a tie at the boundary means the distribution itself
does not distinguish the candidates.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["filter_logits", "sample_rows"]


def filter_logits(logits: jnp.ndarray, top_k=0, top_p=1.0) -> jnp.ndarray:
    """Mask ``logits`` (..., V) to the top-k / top-p candidate set.

    ``top_k``/``top_p`` are scalars or arrays broadcastable to the batch
    shape ``logits.shape[:-1]`` (per-row values in the serving tick).
    Masked entries become -inf; kept entries pass through UNCHANGED, so
    disabled filters are a value-level no-op. The filters apply
    SEQUENTIALLY: top-p's nucleus is measured on the softmax of the
    top-k-filtered logits (survivor mass renormalized, as in the HF
    convention), not on the original distribution. At least one token
    always survives (the argmax: it is >= the k-th largest for any
    k >= 1, and the first token of the nucleus prefix for any p > 0;
    ``top_p <= 0`` is clamped to keep exactly that first token).
    """
    v = logits.shape[-1]
    batch = logits.shape[:-1]
    k = jnp.broadcast_to(jnp.asarray(top_k, jnp.int32), batch)
    p = jnp.broadcast_to(jnp.asarray(top_p, jnp.float32), batch)
    # top-k: keep logits >= the k-th largest VALUE (ties at the edge kept)
    sl = jnp.flip(jnp.sort(logits, axis=-1), axis=-1)
    kth = jnp.take_along_axis(sl, jnp.clip(k - 1, 0, v - 1)[..., None],
                              axis=-1)
    keep = (k <= 0)[..., None] | (logits >= kth)
    out = jnp.where(keep, logits, -jnp.inf)
    # top-p over the SURVIVORS: -inf entries softmax to 0 and sort last,
    # so the cumulative mass is implicitly renormalized to the top-k set.
    # nucleus = smallest sorted prefix with cumulative prob >= p, i.e.
    # keep sorted position j iff the mass BEFORE j is still < p; the
    # edge logit's value is then the row threshold
    sl2 = jnp.flip(jnp.sort(out, axis=-1), axis=-1)
    probs = jax.nn.softmax(sl2.astype(jnp.float32), axis=-1)
    before = jnp.cumsum(probs, axis=-1) - probs
    in_nucleus = before < jnp.maximum(p, 1e-9)[..., None]
    edge = jnp.min(jnp.where(in_nucleus, sl2, jnp.inf), axis=-1,
                   keepdims=True).astype(logits.dtype)
    keep_p = (p >= 1.0)[..., None] | (out >= edge)
    return jnp.where(keep_p, out, -jnp.inf)


def sample_rows(logits: jnp.ndarray, keys: jnp.ndarray,
                temperature: jnp.ndarray, top_k: jnp.ndarray,
                top_p: jnp.ndarray) -> jnp.ndarray:
    """Per-row sampling for the serving tick: ``logits`` (b, V), ``keys``
    (b, 2) uint32 (one PRNG key per slot), per-row temperature/top_k/
    top_p (b,). Rows with ``temperature <= 0`` take the greedy argmax.

    Each row's draw is ``jax.random.categorical(key_b, filtered_b[None])``
    — via vmap, which JAX guarantees is semantically identical to the
    per-row loop — so a slot row reproduces exactly what ``gpt_decode``'s
    batch-1 ``pick`` computes for the same key and parameters. That
    equality is what the serve-vs-generate identity tests pin.
    """
    temperature = jnp.asarray(temperature, jnp.float32)
    safe_t = jnp.where(temperature > 0, temperature, 1.0)
    filt = filter_logits(logits / safe_t[:, None].astype(logits.dtype),
                         top_k, top_p)
    sampled = jax.vmap(
        lambda l, k: jax.random.categorical(k, l[None, :], -1)[0])(filt, keys)
    greedy = jnp.argmax(logits, -1)
    return jnp.where(temperature > 0, sampled, greedy).astype(jnp.int32)
