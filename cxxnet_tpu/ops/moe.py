"""Switch-style mixture-of-experts with expert parallelism.

No reference counterpart (SURVEY §2.7 lists expert parallelism as
to-be-designed-fresh). TPU-first shape: the GShard dispatch/combine einsum
formulation — top-1 routing, bounded per-expert capacity, overflow tokens
dropped (pass through the residual), auxiliary load-balancing loss. The
expert dim of every tensor is sharded over a mesh axis (default ``model``)
with ordinary NamedShardings; GSPMD partitions the dispatch/combine einsums
into the all-to-all exchanges that a hand-written expert-parallel backend
would issue, and the per-expert FFN batch rides the MXU.
"""

from __future__ import annotations

import math
from typing import Tuple

import jax
import jax.numpy as jnp


def switch_moe(x: jnp.ndarray, w_gate: jnp.ndarray, w_up: jnp.ndarray,
               w_down: jnp.ndarray, capacity_factor: float = 1.25,
               ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Top-1 MoE FFN.

    x: (S, D) tokens; w_gate: (D, E); w_up: (E, D, H); w_down: (E, H, D).
    Returns (out (S, D), aux_loss scalar). Tokens beyond an expert's
    capacity ``ceil(S/E * capacity_factor)`` contribute zero (caller keeps
    the residual path).
    """
    s, d = x.shape
    e = w_gate.shape[1]
    capacity = max(1, math.ceil(s / e * capacity_factor))

    logits = (x @ w_gate.astype(x.dtype)).astype(jnp.float32)   # (S, E)
    probs = jax.nn.softmax(logits, axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)                     # (S,)
    onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)   # (S, E)
    gate = (probs * onehot).sum(-1)                             # (S,)

    # position of each token within its expert's queue; >= capacity -> drop
    pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot           # (S, E)
    keep = (pos < capacity) * onehot
    pos = jnp.clip(pos.sum(-1).astype(jnp.int32), 0, capacity - 1)  # (S,)
    pos_oh = jax.nn.one_hot(pos, capacity, dtype=jnp.float32)   # (S, C)

    dispatch = keep[:, :, None] * pos_oh[:, None, :]            # (S, E, C)
    combine = dispatch * gate[:, None, None]

    xin = jnp.einsum("sec,sd->ecd", dispatch.astype(x.dtype), x)
    h = jax.nn.relu(jnp.einsum("ecd,edh->ech", xin,
                               w_up.astype(x.dtype)))
    out_e = jnp.einsum("ech,ehd->ecd", h, w_down.astype(x.dtype))
    out = jnp.einsum("sec,ecd->sd", combine.astype(x.dtype), out_e)

    # switch-transformer load-balancing loss: E * sum_e f_e * p_e
    frac_tokens = onehot.mean(axis=0)
    frac_probs = probs.mean(axis=0)
    aux = e * jnp.sum(frac_tokens * frac_probs)
    return out, aux


__all__ = ["switch_moe"]
