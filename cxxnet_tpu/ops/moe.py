"""Switch-style mixture-of-experts with expert parallelism.

No reference counterpart (SURVEY §2.7 lists expert parallelism as
to-be-designed-fresh). TPU-first shapes, three dispatch strategies behind
one routing function:

- ``dispatch="sort"`` (default): sort-based sparse dispatch. Tokens are
  ordered by expert with one stable argsort, their queue positions come
  from segment offsets, and the (E, C, D) expert batch is built with a
  single scatter-add (and read back with a single gather). No (S, E, C)
  one-hot tensor ever exists, so cost scales with S·D + S·log S instead
  of S·E·C — the difference is decisive at real expert counts (measured
  on one v5e chip, doc/performance.md round 3).
- ``dispatch="dense"``: the GShard einsum formulation ((S,E,C) one-hot
  dispatch/combine). Kept because GSPMD partitions einsums into clean
  all-to-alls when the expert dim of the weights is sharded but the
  tokens are not expert-sharded, and as the oracle for the sort path.
- ``dispatch="ragged"``: dropless (Megablocks-style) dispatch — no
  capacity, no dropped tokens. Tokens sort by expert and the expert FFN
  runs as a grouped GEMM over the ragged segments (``lax.ragged_dot``).
  Measured on one v5e (doc/performance.md round 4): 1.03x the sort
  path's time at E=8 rising to 1.49x at E=64 (top-1) — sort+capacity
  stays the default; ragged is the opt-in when drop-free semantics
  matter more than the last 3-50% of step time.
- :func:`switch_moe_alltoall`: explicit expert parallelism for use INSIDE
  a ``shard_map`` over the ``expert`` mesh axis. Tokens are sharded over
  the axis; each shard routes locally, builds its (E, C_local, D) block,
  and two ``lax.all_to_all`` exchanges move token blocks to the expert's
  owner and back — the hand-written form of what a GShard backend issues.
  Capacity is per (source shard, expert) group, exactly GShard's grouped
  dispatch semantics.

All three share the routing in :func:`_route` — top-1 (switch
transformer) or top-k (GShard: renormalized gates, first choices win
capacity before second choices; ``top_k=2`` on the sort and all-to-all
paths) — bounded per-expert capacity with overflow entries dropped (they
pass through the caller's residual), and the auxiliary load-balancing
loss computed from the first choice.
"""

from __future__ import annotations

import math
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax


def _route(x: jnp.ndarray, w_gate: jnp.ndarray, capacity: int,
           top_k: int = 1):
    """Shared top-k routing. Returns (gate (S*k,), expert_idx (S*k,) i32,
    pos (S*k,) i32 queue position, keep (S*k,) bool, aux scalar) — the
    k choices of token t occupy flat entries t*k .. t*k+k-1.

    Queue positions are assigned per expert in (choice, token) order:
    every token's FIRST choice competes for capacity before any second
    choice does (GShard's top-2 policy), and within a choice rank the
    stable sort preserves token order, so at k=1 the keep set is
    identical to the dense cumsum formulation's. Top-k gates are the
    top-k softmax probabilities renormalized to sum 1 (GShard); top-1
    keeps the raw max probability (switch transformer).
    """
    s, _ = x.shape
    e = w_gate.shape[1]
    logits = (x @ w_gate.astype(x.dtype)).astype(jnp.float32)    # (S, E)
    probs = jax.nn.softmax(logits, axis=-1)
    top_p, top_i = lax.top_k(probs, top_k)                       # (S, k)
    if top_k > 1:
        top_p = top_p / jnp.maximum(top_p.sum(-1, keepdims=True), 1e-9)
    gate = top_p.reshape(-1)                                     # (S*k,)
    expert_idx = top_i.astype(jnp.int32).reshape(-1)             # (S*k,)

    # sort key (expert, choice, token): choice-major within each expert so
    # 1st choices win the queue head
    choice = jnp.tile(jnp.arange(top_k, dtype=jnp.int32), (s,))  # (S*k,)
    key = (expert_idx * top_k + choice) * s \
        + jnp.arange(s * top_k, dtype=jnp.int32) // top_k
    order = jnp.argsort(key)                                     # (S*k,)
    sorted_e = expert_idx[order]
    seg_start = jnp.searchsorted(sorted_e, jnp.arange(e))        # (E,)
    pos_sorted = jnp.arange(s * top_k, dtype=jnp.int32) \
        - seg_start[sorted_e].astype(jnp.int32)
    pos = jnp.zeros((s * top_k,), jnp.int32).at[order].set(pos_sorted)
    keep = pos < capacity

    # load-balancing loss from the FIRST choice (switch/GShard): E * f.p
    first = top_i[:, 0]
    frac_tokens = jnp.zeros((e,), jnp.float32).at[first].add(1.0) / s
    frac_probs = probs.mean(axis=0)
    aux = e * jnp.sum(frac_tokens * frac_probs)
    return gate, expert_idx, pos, keep, aux


def _expert_ffn(xin: jnp.ndarray, w_up: jnp.ndarray,
                w_down: jnp.ndarray) -> jnp.ndarray:
    """(E, C, D) expert batch -> (E, C, D); the per-expert FFN rides the
    MXU as E batched (C, D) x (D, H) matmuls."""
    h = jax.nn.relu(jnp.einsum("ecd,edh->ech", xin, w_up.astype(xin.dtype)))
    return jnp.einsum("ech,ehd->ecd", h, w_down.astype(xin.dtype))


def _scatter_tokens(x, expert_idx, pos, keep, e, capacity):
    """Tokens -> (E*C, D) expert batch via one scatter-add; dropped tokens
    land in a dummy trailing row that is sliced off."""
    s, d = x.shape
    slot = jnp.where(keep, expert_idx * capacity + pos, e * capacity)
    xin = jnp.zeros((e * capacity + 1, d), x.dtype).at[slot].add(x)
    return xin[:e * capacity], slot


def switch_moe(x: jnp.ndarray, w_gate: jnp.ndarray, w_up: jnp.ndarray,
               w_down: jnp.ndarray, capacity_factor: float = 1.25,
               dispatch: str = "sort",
               top_k: int = 1) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Top-k MoE FFN on one logical shard (k=1: switch transformer; k=2:
    GShard routing — gates renormalized over the chosen experts, first
    choices win capacity before second choices).

    x: (S, D) tokens; w_gate: (D, E); w_up: (E, D, H); w_down: (E, H, D).
    Returns (out (S, D), aux_loss scalar). Entries beyond an expert's
    capacity ``ceil(k*S/E * capacity_factor)`` contribute zero (caller
    keeps the residual path).
    """
    if dispatch not in ("sort", "dense", "ragged"):
        raise ValueError("dispatch must be 'sort', 'dense' or 'ragged', "
                         "got %r" % (dispatch,))
    if top_k < 1 or top_k > w_gate.shape[1]:
        raise ValueError("top_k must be in [1, n_experts], got %d" % top_k)
    s, d = x.shape
    e = w_gate.shape[1]
    capacity = max(1, math.ceil(top_k * s / e * capacity_factor))

    if dispatch == "dense":
        if top_k != 1:
            raise ValueError("dispatch='dense' supports top_k=1 only "
                             "(the one-hot einsum formulation); use "
                             "dispatch='sort'")
        return _switch_moe_dense(x, w_gate, w_up, w_down, capacity)
    if dispatch == "ragged":
        return _switch_moe_ragged(x, w_gate, w_up, w_down, top_k)

    gate, expert_idx, pos, keep, aux = _route(x, w_gate, capacity, top_k)
    x_flat = x if top_k == 1 else jnp.repeat(x, top_k, axis=0)
    xin, slot = _scatter_tokens(x_flat, expert_idx, pos, keep, e, capacity)
    out_e = _expert_ffn(xin.reshape(e, capacity, d), w_up, w_down)
    out_flat = out_e.reshape(e * capacity, d)
    tok = out_flat[jnp.minimum(slot, e * capacity - 1)]
    out = tok * (gate * keep).astype(tok.dtype)[:, None]
    if top_k > 1:
        out = out.reshape(s, top_k, d).sum(axis=1)
    return out.astype(x.dtype), aux


def grouped_order(ids: jnp.ndarray, n_groups: int):
    """Segment-sort plan for a ragged grouped GEMM: stable argsort of
    the per-row group ids plus the per-group segment sizes
    ``lax.ragged_dot`` consumes. Shared by the dropless MoE dispatch
    below and the serve-time multi-LoRA delta (serve/lora.py) — both
    are the same "sort rows by matrix id, run one grouped GEMM over the
    ragged segments, unsort" move. The stable sort keeps same-group
    rows in submission order, so every row's dot is a full contraction
    regardless of which neighbours share its group (per-row results are
    bit-identical across batch compositions — the property the LoRA
    solo-oracle identity pins lean on)."""
    order = jnp.argsort(ids, stable=True)
    group_sizes = jnp.bincount(ids, length=n_groups).astype(jnp.int32)
    return order, group_sizes


def _switch_moe_ragged(x, w_gate, w_up, w_down, top_k):
    """Dropless (Megablocks-style) dispatch: no capacity, no dropped
    tokens. Tokens are sorted by expert and the per-expert FFN runs as a
    grouped GEMM over the ragged expert segments (``lax.ragged_dot``,
    the TPU grouped-matmul primitive), so every token is processed no
    matter how unbalanced the routing. Gates/aux match the sort path
    (renormalized top-k, first-choice load-balance loss)."""
    s, d = x.shape
    e = w_gate.shape[1]
    logits = (x @ w_gate.astype(x.dtype)).astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    top_p, top_i = lax.top_k(probs, top_k)
    if top_k > 1:
        top_p = top_p / jnp.maximum(top_p.sum(-1, keepdims=True), 1e-9)
    gate = top_p.reshape(-1)
    expert_idx = top_i.astype(jnp.int32).reshape(-1)            # (S*k,)

    order, group_sizes = grouped_order(expert_idx, e)           # (S*k,)
    x_flat = x if top_k == 1 else jnp.repeat(x, top_k, axis=0)
    x_sorted = x_flat[order]
    h = jax.nn.relu(lax.ragged_dot(x_sorted, w_up.astype(x.dtype),
                                   group_sizes))
    y = lax.ragged_dot(h, w_down.astype(x.dtype), group_sizes)
    out_flat = jnp.zeros_like(y).at[order].set(y)               # unsort
    out = out_flat * gate.astype(y.dtype)[:, None]
    if top_k > 1:
        out = out.reshape(s, top_k, d).sum(axis=1)

    first = top_i[:, 0]
    frac_tokens = jnp.zeros((e,), jnp.float32).at[first].add(1.0) / s
    aux = e * jnp.sum(frac_tokens * probs.mean(axis=0))
    return out.astype(x.dtype), aux


def _switch_moe_dense(x, w_gate, w_up, w_down, capacity):
    """GShard one-hot einsum formulation — the GSPMD-friendly and oracle
    path (the original round-1 implementation)."""
    s, d = x.shape
    e = w_gate.shape[1]
    logits = (x @ w_gate.astype(x.dtype)).astype(jnp.float32)   # (S, E)
    probs = jax.nn.softmax(logits, axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)                     # (S,)
    onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)   # (S, E)
    gate = (probs * onehot).sum(-1)                             # (S,)

    # position of each token within its expert's queue; >= capacity -> drop
    pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot           # (S, E)
    keep = (pos < capacity) * onehot
    pos = jnp.clip(pos.sum(-1).astype(jnp.int32), 0, capacity - 1)  # (S,)
    pos_oh = jax.nn.one_hot(pos, capacity, dtype=jnp.float32)   # (S, C)

    dispatch = keep[:, :, None] * pos_oh[:, None, :]            # (S, E, C)
    combine = dispatch * gate[:, None, None]

    xin = jnp.einsum("sec,sd->ecd", dispatch.astype(x.dtype), x)
    out_e = _expert_ffn(xin, w_up, w_down)
    out = jnp.einsum("sec,ecd->sd", combine.astype(x.dtype), out_e)

    frac_tokens = onehot.mean(axis=0)
    frac_probs = probs.mean(axis=0)
    aux = e * jnp.sum(frac_tokens * frac_probs)
    return out, aux


def switch_moe_alltoall(x: jnp.ndarray, w_gate: jnp.ndarray,
                        w_up: jnp.ndarray, w_down: jnp.ndarray,
                        axis_name: str = "expert",
                        capacity_factor: float = 1.25,
                        top_k: int = 1) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Expert-parallel top-k MoE for use INSIDE a shard_map over
    ``axis_name`` (k=1 switch, k=2 GShard — see :func:`_route`).

    Per shard: x (S_local, D) local tokens; w_gate (D, E) replicated;
    w_up (E_local, D, H) / w_down (E_local, H, D) local expert shards
    (E = E_local * axis size). Routing is local; the (E, C_local, D)
    dispatch block is exchanged with one ``all_to_all`` so each shard
    holds its E_local experts' tokens from every source shard, the FFN
    runs, and a mirror ``all_to_all`` returns the outputs. Capacity
    ``ceil(top_k*S_local/E * capacity_factor)`` applies per (source
    shard, expert) — GShard's grouped dispatch.

    The aux loss is computed from the shard-local routing statistics and
    psum-averaged, which equals the global statistic when shards see
    i.i.d. token groups (and is the standard GShard formulation).
    """
    p = lax.psum(1, axis_name)
    s, d = x.shape
    e = w_gate.shape[1]
    e_local = w_up.shape[0]
    if e_local * p != e:
        raise ValueError(
            "switch_moe_alltoall: gate has %d experts but shards hold "
            "%d x %d" % (e, p, e_local))
    if top_k < 1 or top_k > e:
        raise ValueError("top_k must be in [1, n_experts], got %d" % top_k)
    capacity = max(1, math.ceil(top_k * s / e * capacity_factor))

    gate, expert_idx, pos, keep, aux = _route(x, w_gate, capacity, top_k)
    aux = lax.psum(aux, axis_name) / p
    x_flat = x if top_k == 1 else jnp.repeat(x, top_k, axis=0)
    xin, slot = _scatter_tokens(x_flat, expert_idx, pos, keep, e, capacity)
    xin = xin.reshape(e, capacity, d)
    # (E, C, D) -> (E_local, P*C, D): expert dim split across shards,
    # every shard's contribution concatenated on the capacity dim
    xin = lax.all_to_all(xin, axis_name, split_axis=0, concat_axis=1,
                         tiled=True)
    out_e = _expert_ffn(xin, w_up, w_down)
    # mirror exchange: (E_local, P*C, D) -> (E, C, D) back on the source
    out_e = lax.all_to_all(out_e, axis_name, split_axis=1, concat_axis=0,
                           tiled=True)
    out_flat = out_e.reshape(e * capacity, d)
    tok = out_flat[jnp.minimum(slot, e * capacity - 1)]
    out = tok * (gate * keep).astype(tok.dtype)[:, None]
    if top_k > 1:
        out = out.reshape(s, top_k, d).sum(axis=1)
    return out.astype(x.dtype), aux


__all__ = ["switch_moe", "switch_moe_alltoall", "grouped_order"]
