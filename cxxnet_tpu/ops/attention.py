"""Exact and ring (sequence-parallel) multi-head attention.

Design (TPU-first):
- ``full_attention`` is the reference math: one fused softmax(QK^T)V — XLA
  maps the two matmuls onto the MXU; fine whenever the whole sequence fits.
- ``ring_attention`` shards the sequence over a mesh axis. Each device holds
  one Q/K/V shard; K/V shards rotate around the ring with
  ``jax.lax.ppermute`` while a numerically-stable *online softmax*
  (max/sum carries, flash-attention style) accumulates each query block's
  output. Peak memory per device is O((N/P)^2) scores instead of O(N^2),
  and the P permute steps overlap with the block matmuls (ICI and MXU run
  concurrently). Causal masking uses global positions derived from the ring
  step, so block (i, j) with no unmasked entries still costs one fused
  masked-matmul but no extra softmax pass.

All accumulation is float32 regardless of input dtype (bfloat16 inputs stay
bfloat16 on the matmul operands — MXU native — with f32 accumulators).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

_NEG_INF = -1e30


def full_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   causal: bool = False,
                   q_offset: int = 0, k_offset: int = 0) -> jnp.ndarray:
    """Exact attention. q,k,v: (batch, seq, heads, head_dim) -> same shape.

    ``q_offset``/``k_offset`` are the global positions of element 0 (used by
    the ring to mask across shards; traced values are fine).
    """
    d = q.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        qpos = q_offset + jnp.arange(q.shape[1])[:, None]
        kpos = k_offset + jnp.arange(k.shape[1])[None, :]
        s = jnp.where(qpos >= kpos, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.astype(v.dtype)


def local_attention(q, k, v, causal: bool = False) -> jnp.ndarray:
    """Single-device attention dispatch: the Pallas flash kernel (O(N) memory,
    ops/pallas_kernels.py) for long block-aligned sequences on TPU, else the
    exact XLA formulation."""
    from .pallas_kernels import flash_attention
    if _ring_chunk_kernels(q.shape[1]):
        return flash_attention(q, k, v, causal)
    return full_attention(q, k, v, causal=causal)


def full_attention_bhnd(q, k, v, causal: bool = False) -> jnp.ndarray:
    """Exact attention on head-major (batch, heads, seq, head_dim)."""
    d = q.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        qpos = jnp.arange(q.shape[2])[:, None]
        kpos = jnp.arange(k.shape[2])[None, :]
        s = jnp.where(qpos >= kpos, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.astype(v.dtype)


def local_attention_bhnd(q, k, v, causal: bool = False) -> jnp.ndarray:
    """``local_attention`` on head-major (batch, heads, seq, head_dim) —
    the flash kernels' native layout.  A caller that projects straight
    into head-major (einsum ``bnf,fhd->bhnd``) and consumes head-major
    output skips every layout copy at the kernel boundary (measured ~36
    ms/step on the 303M GPT flagship through the (b,n,h,d) entry)."""
    from .pallas_kernels import flash_attention_bhnd
    if _ring_chunk_kernels(q.shape[2]):
        return flash_attention_bhnd(q, k, v, causal)
    return full_attention_bhnd(q, k, v, causal=causal)


def _block(q, k, v, o, m, l, causal, q_off, k_off):
    """One online-softmax accumulation step over a K/V block, head-major.

    q: (b, h, nq, d); k/v: (b, h, nk, d); o: (b, h, nq, d) f32;
    m/l: (b, h, nq) f32 running max / normalizer. (Round 3 moved the
    whole ring core to the flash kernels' native (b, h, n, d) layout —
    the merges need no transposes and the kernel chunks no copies.)
    """
    scale = 1.0 / jnp.sqrt(jnp.asarray(q.shape[-1], jnp.float32))
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        qpos = q_off + jnp.arange(q.shape[2])[:, None]
        kpos = k_off + jnp.arange(k.shape[2])[None, :]
        s = jnp.where(qpos >= kpos, s, _NEG_INF)
    m_new = jnp.maximum(m, s.max(axis=-1))
    corr = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])            # (b,h,q,k) f32
    l_new = l * corr + p.sum(axis=-1)
    pv = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v,
                    preferred_element_type=jnp.float32)
    o_new = o * corr[..., None] + pv
    return o_new, m_new, l_new


# Pallas dispatch threshold, shared by local_attention and the ring's
# chunk path (monkeypatched down by the interpret-mode tests): sequences /
# per-device chunks at least this long and aligned run their blockwise
# math in the flash kernels, making memory O(n) instead of an O(n^2) f32
# score matrix. NOTE the isolated micro-benchmark is misleading here: XLA
# exact wins the standalone fwd+bwd at seq 1024 (8.6 vs 9.6 ms with
# 256-blocks), but in the full rematerialized GPT step the flash path is
# ~50% faster end to end (117k vs 76.7k tok/s at batch 32 x 1024 on one
# v5e chip, adaptive 512-blocks; doc/performance.md) — the O(n^2) f32
# scores XLA materializes per microbatch per layer cost more HBM traffic
# during remat than the kernels' layout copies.
_RING_PALLAS_MIN = 512
_RING_PALLAS_ALIGN = 256


def _ring_chunk_kernels(n_local: int) -> bool:
    from .pallas_kernels import use_pallas
    return (use_pallas() and n_local >= _RING_PALLAS_MIN
            and n_local % _RING_PALLAS_ALIGN == 0)


def _chunk_case(causal, k_shard, my_idx, full_fn, diag_fn, skip_fn):
    """Whole-chunk causal-mask cases of a ring step: chunks strictly
    earlier than this device's queries are fully visible, the home chunk
    is standard causal, later chunks are fully masked."""
    if not causal:
        return full_fn(None)
    idx = jnp.clip(k_shard - my_idx, -1, 1) + 1
    return lax.switch(idx, (full_fn, diag_fn, skip_fn), None)


def _ring_vary(x, q, k, axis_name):
    """Enter a ring loop with device-varying type (under check_vma
    shard_map the carries become varying after the first accumulation)."""
    vary_axes = tuple(jax.typeof(q).vma | jax.typeof(k).vma | {axis_name})
    return lax.pcast(x, vary_axes, to='varying')


def _ring_fwd_pass(q, k, v, axis_name, causal):
    """One forward ring rotation, head-major. q/k/v (b, h, n_local, d).
    Returns (out (b,h,n,d), lse (b,h,n)) — lse = max + log(sum) of the
    scaled logits, the backward's residual."""
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    n_local = q.shape[2]
    b, h, _, dd = q.shape

    o0 = _ring_vary(jnp.zeros((b, h, n_local, dd), jnp.float32), q, k, axis_name)
    m0 = _ring_vary(jnp.full((b, h, n_local), _NEG_INF, jnp.float32), q, k, axis_name)
    l0 = _ring_vary(jnp.zeros((b, h, n_local), jnp.float32), q, k, axis_name)

    use_kernels = _ring_chunk_kernels(n_local)

    def accumulate(k_shard, o, m, l, kk, vv):
        if not use_kernels:
            return _block(q, kk, vv, o, m, l, causal,
                          q_off=my_idx * n_local, k_off=k_shard * n_local)
        # flash-kernel chunk: compute (o_c, lse_c) for this (q, chunk)
        # pair and fold it into the running (o, m, l) accumulators. The
        # causal mask across chunks is one of three whole-chunk cases.
        from .pallas_kernels import flash_fwd_with_lse_bhnd

        def chunk_full(_):
            return flash_fwd_with_lse_bhnd(q, kk, vv, False)

        def chunk_diag(_):
            return flash_fwd_with_lse_bhnd(q, kk, vv, True)

        def chunk_skip(_):
            # f32 to match the kernels' f32 partial outputs across branches
            return (jnp.zeros(q.shape, jnp.float32),
                    jnp.full((b, h, n_local), _NEG_INF, jnp.float32))

        o_c, lse_c = _chunk_case(causal, k_shard, my_idx,
                                 chunk_full, chunk_diag, chunk_skip)
        # exact partial-softmax merge; lse_c = -1e30 (skip) only ever
        # combines after the diagonal chunk (step 0) made m finite, so
        # exp(lse_c - M) underflows to 0 rather than exp(0)
        m_new = jnp.maximum(m, lse_c)
        w_acc = jnp.exp(m - m_new)                    # (b, h, nq)
        w_c = jnp.exp(lse_c - m_new)
        o = (o * w_acc[..., None]
             + o_c.astype(jnp.float32) * w_c[..., None])
        return o, m_new, l * w_acc + w_c

    def step(i, carry):
        o, m, l, kk, vv = carry
        # after i left-rotations we hold the K/V shard of rank (my_idx + i)
        k_shard = (my_idx + i) % axis_size
        o, m, l = accumulate(k_shard, o, m, l, kk, vv)
        perm = [(j, (j - 1) % axis_size) for j in range(axis_size)]
        kk = lax.ppermute(kk, axis_name, perm)
        vv = lax.ppermute(vv, axis_name, perm)
        return o, m, l, kk, vv

    # the last block is peeled out of the loop so its (discarded) rotation
    # is never issued: axis_size-1 permutes move the ring full circle
    o, m, l, kk, vv = lax.fori_loop(0, axis_size - 1, step,
                                    (o0, m0, l0, k, v))
    last_shard = (my_idx + axis_size - 1) % axis_size
    o, m, l = accumulate(last_shard, o, m, l, kk, vv)
    out = (o / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)
    lse = m + jnp.log(jnp.maximum(l, 1e-30))
    return out, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _ring_inner(q, k, v, axis_name, causal):
    out, _ = _ring_fwd_pass(q, k, v, axis_name, causal)
    return out


def _ring_inner_fwd(q, k, v, axis_name, causal):
    out, lse = _ring_fwd_pass(q, k, v, axis_name, causal)
    return out, (q, k, v, out, lse)


def _ring_inner_bwd(axis_name, causal, res, g):
    """Backward ring: a second rotation recomputing each chunk's
    probabilities from the saved lse (flash-style). dK/dV partials rotate
    in lockstep with their K/V chunks, so after a full circle every chunk's
    gradient has collected contributions from every query shard and is back
    on its home device. O(n_local) residual memory — reverse-mode AD
    through the forward loop would instead save every rotated chunk and
    every per-step probability matrix (O(P * n_local^2)). Head-major
    (b, h, n, d) throughout — zero layout copies at the kernel chunks."""
    q, k, v, out, lse = res
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    n_local = q.shape[2]
    scale = 1.0 / jnp.sqrt(jnp.asarray(q.shape[-1], jnp.float32))
    do = g.astype(jnp.float32)                         # (b, h, nq, d)
    # softmax-grad correction: rowsum(dO * O), (b, h, nq)
    delta = jnp.einsum("bhqd,bhqd->bhq", do, out.astype(jnp.float32))

    dq0 = _ring_vary(jnp.zeros(q.shape, jnp.float32), q, k, axis_name)
    dk0 = _ring_vary(jnp.zeros(k.shape, jnp.float32), q, k, axis_name)
    dv0 = _ring_vary(jnp.zeros(v.shape, jnp.float32), q, k, axis_name)

    use_kernels = _ring_chunk_kernels(n_local)
    g_in = g.astype(q.dtype)

    def accumulate(i, dq, kk, vv, dk, dv):
        k_shard = (my_idx + i) % axis_size
        if use_kernels:
            # blockwise kernels with the *global* lse/delta: p = exp(s -
            # lse) is globally normalized, so each chunk's grads are its
            # exact contribution (pallas_kernels.flash_bwd_blocks_bhnd)
            from .pallas_kernels import flash_bwd_blocks_bhnd

            def chunk_full(_):
                return flash_bwd_blocks_bhnd(q, kk, vv, lse, delta, g_in,
                                             False, out_dtype=jnp.float32)

            def chunk_diag(_):
                return flash_bwd_blocks_bhnd(q, kk, vv, lse, delta, g_in,
                                             True, out_dtype=jnp.float32)

            def chunk_skip(_):
                return (jnp.zeros(q.shape, jnp.float32),
                        jnp.zeros(kk.shape, jnp.float32),
                        jnp.zeros(vv.shape, jnp.float32))

            dq_c, dk_c, dv_c = _chunk_case(causal, k_shard, my_idx,
                                           chunk_full, chunk_diag,
                                           chunk_skip)
            return dq + dq_c, dk + dk_c, dv + dv_c
        s = jnp.einsum("bhqd,bhkd->bhqk", q, kk,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            qpos = my_idx * n_local + jnp.arange(n_local)[:, None]
            kpos = k_shard * n_local + jnp.arange(n_local)[None, :]
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        p = jnp.exp(s - lse[..., None])                # exact probabilities
        dp = jnp.einsum("bhqd,bhkd->bhqk", do, vv,
                        preferred_element_type=jnp.float32)
        ds = p * (dp - delta[..., None]) * scale
        dq = dq + jnp.einsum("bhqk,bhkd->bhqd", ds, kk,
                             preferred_element_type=jnp.float32)
        dk = dk + jnp.einsum("bhqk,bhqd->bhkd", ds, q,
                             preferred_element_type=jnp.float32)
        dv = dv + jnp.einsum("bhqk,bhqd->bhkd", p, do,
                             preferred_element_type=jnp.float32)
        return dq, dk, dv

    perm = [(j, (j - 1) % axis_size) for j in range(axis_size)]

    def step(i, carry):
        dq, kk, vv, dk, dv = carry
        dq, dk, dv = accumulate(i, dq, kk, vv, dk, dv)
        # rotate the chunk and its gradient together (full circle = home)
        kk = lax.ppermute(kk, axis_name, perm)
        vv = lax.ppermute(vv, axis_name, perm)
        dk = lax.ppermute(dk, axis_name, perm)
        dv = lax.ppermute(dv, axis_name, perm)
        return dq, kk, vv, dk, dv

    # last step peeled (like the forward): its kk/vv rotation would be
    # discarded — only dk/dv still need one final hop to get home
    dq, kk, vv, dk, dv = lax.fori_loop(0, axis_size - 1, step,
                                       (dq0, k, v, dk0, dv0))
    dq, dk, dv = accumulate(axis_size - 1, dq, kk, vv, dk, dv)
    dk = lax.ppermute(dk, axis_name, perm)
    dv = lax.ppermute(dv, axis_name, perm)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_inner.defvjp(_ring_inner_fwd, _ring_inner_bwd)


def ring_attention_inner_bhnd(q, k, v, axis_name: str = "seq",
                              causal: bool = False):
    """Head-major ring attention for use INSIDE an existing shard_map:
    q,k,v are local (b, h, n_local, d) shards of a sequence sharded over
    ``axis_name`` — the flash kernels' native layout, so a caller that
    projects head-major (e.g. the GPT ``attn_layout="bhnd"`` block) pays
    zero layout copies through the whole ring. Custom VJP: the backward
    is a second ring pass recomputing probabilities from the saved
    log-sum-exp."""
    return _ring_inner(q, k, v, axis_name, causal)


def ring_attention_inner(q, k, v, axis_name: str = "seq",
                         causal: bool = False):
    """Ring attention for use INSIDE an existing shard_map (e.g. a gpipe
    block): q,k,v are the local (b, n_local, h, d) shards of a sequence
    sharded over ``axis_name``. ``ring_attention`` wraps this in its own
    shard_map for standalone use. The core runs head-major (one transpose
    in, one out — round 2 paid three per ring step); token-major callers
    keep this entry, head-major ones use ring_attention_inner_bhnd."""
    tr = lambda t: jnp.transpose(t, (0, 2, 1, 3))
    return tr(_ring_inner(tr(q), tr(k), tr(v), axis_name, causal))


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   mesh: Mesh, axis_name: str = "seq",
                   causal: bool = False,
                   batch_axis: Optional[str] = "data") -> jnp.ndarray:
    """Sequence-parallel attention: seq dim sharded over ``axis_name``.

    q,k,v: (batch, seq, heads, head_dim), seq divisible by the axis size.
    Works under jit (shard_map nests); on a size-1 axis it degenerates to one
    local exact-attention block.
    """
    n_seq = mesh.shape.get(axis_name, 1)
    if q.shape[1] % n_seq:
        raise ValueError(
            "ring_attention: sequence length %d is not divisible by the "
            "%r mesh axis (size %d)" % (q.shape[1], axis_name, n_seq))
    batch_ax = batch_axis if (batch_axis and
                              mesh.shape.get(batch_axis, 1) > 1 and
                              q.shape[0] % mesh.shape[batch_axis] == 0) \
        else None
    spec = P(batch_ax, axis_name, None, None)
    body = functools.partial(ring_attention_inner, axis_name=axis_name,
                             causal=causal)
    # disable the varying-axes checker only when the chunks are long enough
    # that the body will dispatch to the Pallas flash kernels, which the
    # checker rejects inside shard_map (JAX 0.9)
    vma_ok = not _ring_chunk_kernels(q.shape[1] // max(n_seq, 1))
    return jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=vma_ok)(q, k, v)


# ---------------------------------------------------------------------------
# Ulysses (all-to-all) sequence parallelism
# ---------------------------------------------------------------------------
# DeepSpeed-Ulysses formulation: instead of rotating K/V chunks around a
# ring (P steps, online-softmax merging), ONE all-to-all per tensor
# re-shards from sequence-sharded (b, n/P, h, d) to head-sharded
# (b, n, h/P, d); each device then runs plain local attention over the
# FULL sequence for its h/P heads, and a mirror all-to-all restores the
# sequence sharding. Requires heads % P == 0 (the ring does not).
#
# When each wins (doc/multi-device.md "Sequence parallelism"): ulysses
# moves 4 * (b * n/P * h * d) elements per device in two collective
# phases and computes attention in one dense local call — fewer, larger
# kernels, no P-step loop, and the flash kernel sees the whole sequence
# (better q-block pipelining). Ring keeps memory at O((n/P)^2) scores per
# step, needs no head divisibility, and overlaps its ppermutes with the
# block matmuls — it is the only option when h < P (long-context many-
# shard regimes) and degrades more gracefully on slow links because each
# hop is 1/P the ulysses payload. Rule of thumb: ulysses when h >= P and
# the all-to-all rides ICI; ring otherwise.


def ulysses_attention_inner(q, k, v, axis_name: str = "seq",
                            causal: bool = False):
    """Ulysses attention for use INSIDE an existing shard_map: q,k,v are
    local (b, n_local, h, d) shards of a sequence sharded over
    ``axis_name``; h must divide by the axis size."""
    p = lax.psum(1, axis_name)
    h = q.shape[2]
    if h % p:
        raise ValueError(
            "ulysses attention: %d heads must divide over the %r axis "
            "(size %d); use ring attention instead" % (h, axis_name, p))

    def seq_to_heads(t):
        # (b, n/P, h, d) -> (b, n, h/P, d)
        return lax.all_to_all(t, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def heads_to_seq(t):
        return lax.all_to_all(t, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    out = local_attention(seq_to_heads(q), seq_to_heads(k),
                          seq_to_heads(v), causal=causal)
    return heads_to_seq(out)


def ulysses_attention_inner_bhnd(q, k, v, axis_name: str = "seq",
                                 causal: bool = False):
    """Head-major ulysses for use INSIDE a shard_map: q,k,v local
    (b, h, n_local, d) shards. The all-to-alls split the head dim (1) and
    concat the seq dim (2); the local full-sequence attention runs in the
    flash kernels' native layout with zero copies."""
    p = lax.psum(1, axis_name)
    h = q.shape[1]
    if h % p:
        raise ValueError(
            "ulysses attention: %d heads must divide over the %r axis "
            "(size %d); use ring attention instead" % (h, axis_name, p))

    def seq_to_heads(t):
        # (b, h, n/P, d) -> (b, h/P, n, d)
        return lax.all_to_all(t, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def heads_to_seq(t):
        return lax.all_to_all(t, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    out = local_attention_bhnd(seq_to_heads(q), seq_to_heads(k),
                               seq_to_heads(v), causal=causal)
    return heads_to_seq(out)


def ring_attention_bhnd(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                        mesh: Mesh, axis_name: str = "seq",
                        causal: bool = False,
                        batch_axis: Optional[str] = "data") -> jnp.ndarray:
    """Standalone HEAD-MAJOR ring attention: q,k,v (batch, heads, seq,
    head_dim) with seq (dim 2) sharded over ``axis_name``. The layer-path
    twin of :func:`ring_attention` for callers that project straight into
    the flash kernels' native layout (``attn_layout = bhnd``) — zero
    layout copies through the whole ring."""
    n_seq = mesh.shape.get(axis_name, 1)
    if q.shape[2] % max(n_seq, 1):
        raise ValueError(
            "ring_attention_bhnd: sequence length %d is not divisible by "
            "the %r mesh axis (size %d)" % (q.shape[2], axis_name, n_seq))
    batch_ax = batch_axis if (batch_axis and
                              mesh.shape.get(batch_axis, 1) > 1 and
                              q.shape[0] % mesh.shape[batch_axis] == 0) \
        else None
    spec = P(batch_ax, None, axis_name, None)
    body = functools.partial(ring_attention_inner_bhnd, axis_name=axis_name,
                             causal=causal)
    vma_ok = not _ring_chunk_kernels(q.shape[2] // max(n_seq, 1))
    return jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=vma_ok)(q, k, v)


def ulysses_attention_bhnd(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                           mesh: Mesh, axis_name: str = "seq",
                           causal: bool = False,
                           batch_axis: Optional[str] = "data") -> jnp.ndarray:
    """Standalone HEAD-MAJOR Ulysses attention: q,k,v (batch, heads, seq,
    head_dim), seq sharded over ``axis_name``; heads must divide the axis
    (same contract as :func:`ulysses_attention`)."""
    n_seq = mesh.shape.get(axis_name, 1)
    if q.shape[2] % max(n_seq, 1):
        raise ValueError(
            "ulysses_attention_bhnd: sequence length %d is not divisible "
            "by the %r mesh axis (size %d)" % (q.shape[2], axis_name, n_seq))
    if q.shape[1] % max(n_seq, 1):
        raise ValueError(
            "ulysses_attention_bhnd: %d heads must divide over the %r axis "
            "(size %d); use ring_attention_bhnd instead"
            % (q.shape[1], axis_name, n_seq))
    batch_ax = batch_axis if (batch_axis and
                              mesh.shape.get(batch_axis, 1) > 1 and
                              q.shape[0] % mesh.shape[batch_axis] == 0) \
        else None
    spec = P(batch_ax, None, axis_name, None)
    body = functools.partial(ulysses_attention_inner_bhnd,
                             axis_name=axis_name, causal=causal)
    vma_ok = not _ring_chunk_kernels(q.shape[2])
    return jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=vma_ok)(q, k, v)


def ulysses_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                      mesh: Mesh, axis_name: str = "seq",
                      causal: bool = False,
                      batch_axis: Optional[str] = "data") -> jnp.ndarray:
    """Standalone Ulysses sequence-parallel attention (shard_map wrapper,
    same signature/contract as :func:`ring_attention`)."""
    n_seq = mesh.shape.get(axis_name, 1)
    if q.shape[1] % n_seq:
        raise ValueError(
            "ulysses_attention: sequence length %d is not divisible by "
            "the %r mesh axis (size %d)" % (q.shape[1], axis_name, n_seq))
    if q.shape[2] % max(n_seq, 1):
        raise ValueError(
            "ulysses_attention: %d heads must divide over the %r axis "
            "(size %d); use ring_attention instead"
            % (q.shape[2], axis_name, n_seq))
    batch_ax = batch_axis if (batch_axis and
                              mesh.shape.get(batch_axis, 1) > 1 and
                              q.shape[0] % mesh.shape[batch_axis] == 0) \
        else None
    spec = P(batch_ax, axis_name, None, None)
    body = functools.partial(ulysses_attention_inner, axis_name=axis_name,
                             causal=causal)
    vma_ok = not _ring_chunk_kernels(q.shape[1])
    return jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=vma_ok)(q, k, v)


__all__ = ["full_attention", "local_attention", "ring_attention",
           "ring_attention_bhnd", "ring_attention_inner",
           "ring_attention_inner_bhnd", "ulysses_attention",
           "ulysses_attention_bhnd", "ulysses_attention_inner",
           "ulysses_attention_inner_bhnd"]
