"""TPU kernel-level ops: attention (full / ring), fused primitives.

The reference framework predates attention entirely (SURVEY §5.7) — its
long-context analogues were im2col chunking and the fullc_gather activation
shipping trick. This package supplies the modern capability: exact multi-head
attention, and ring attention for sequence/context parallelism where the
KV shards rotate around the mesh's ``seq`` axis via ``ppermute`` while each
device accumulates its queries' output with an online softmax.
"""

from .attention import full_attention, ring_attention  # noqa: F401

__all__ = ["full_attention", "ring_attention"]
