"""Pallas TPU kernels for the hot ops.

Kernel families, all with CPU interpret-mode fallback for differential
testing (the PairTest philosophy, SURVEY §4.1 — Pallas vs XLA-reference
numerics):

- **fused LRN** (reference chpool LRN, lrn_layer-inl.hpp:46-57): forward and
  backward are each ONE VMEM pass; the cross-channel window sum is an
  in-kernel band matmul on the MXU and the backward recomputes it from x
  (residual: x only). Opt-in (CXN_PALLAS_LRN=1): measured on one v5e chip
  the XLA band-matmul formulation in layers/conv.py still wins (fwd+bwd
  bf16: 10.9 vs 18.9 ms @ 1024x55x55x96, 8.0 vs 11.5 @ 1024x27x27x256,
  5.4 vs 5.8 @ 256x14x14x1024, measured before the width cap) — sub-128
  channel widths halve the kernel's effective DMA bandwidth, and XLA's
  fusion of the pow/scale passes is already near the traffic floor.
  Supported domain: n <= channels <= LRN_MAX_CHANNELS (the in-kernel
  (C, C) band must fit VMEM); wider LRN uses the XLA paths.
- **flash attention** (forward + backward): O(N) memory exact attention for
  a single device — the in-chip complement of ring attention (which bounds
  memory *across* chips). Forward: online softmax over K/V tiles held in
  VMEM, queries blocked over the grid, saving the per-row log-sum-exp.
  Backward: FlashAttention-2-style blockwise kernels — one pass over
  q-blocks for dq, one over k-blocks for dk/dv, probabilities recomputed
  from the saved lse (never materializing the N x N matrix).
- **fused relu->LRN->maxpool** (the AlexNet head-of-block chain): one pass
  per direction, saving (u, norm) as training residuals. NOT the default
  path — measured on one v5e chip it loses to the XLA chain ~2.8x
  (fwd+bwd bf16: 53.6 vs 19.5 ms @ 1024x55x55x96, 27.1 vs 11.5 @
  1024x27x27x256): the unaligned spatial shapes make every in-kernel
  pad/reshape/slice a vreg relayout, so the kernel is VPU-bound while
  XLA's fusions run at the HBM floor. Kept as the *reference-semantics
  oracle* for pooling gradients: its backward credits every tied maximum
  with the full window gradient (mshadow unpool, pooling_layer-inl.hpp
  backprop expression), which XLA's select-and-scatter (first-max-only)
  cannot express — the PairTest role, not the hot path.

Use ``use_pallas()`` to gate: True on TPU backends, else the jnp reference
paths in the callers stay active.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_INTERPRET = False      # flipped by tests on CPU

# jax < 0.5 names the Mosaic compiler-params class TPUCompilerParams;
# newer releases renamed it CompilerParams — same fields either way
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or getattr(pltpu, "TPUCompilerParams", None)


def _out_struct(shape, dtype, like):
    """ShapeDtypeStruct for pallas_call that survives a ``check_vma``
    shard_map: when tracing inside one (e.g. the gpipe body), the output
    must carry the same varying-mesh-axes set as the input, or shard_map
    rejects it (JAX >= 0.9)."""
    typeof = getattr(jax, "typeof", None)   # jax < 0.6 has no typeof
    vma = getattr(typeof(like), "vma", None) if typeof is not None \
        else None
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def use_pallas() -> bool:
    if _INTERPRET:
        return True
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


# ---------------------------------------------------------------------------
# fused LRN
# ---------------------------------------------------------------------------

def _lrn_band(c: int, n: int, transpose: bool = False):
    """(C, C) 0/1 band matrix in-kernel: B[j, c] = 1 iff channel j is in the
    size-n window (left-biased center, reference chpool) of channel c.
    Generated from iotas in VMEM — never touches HBM."""
    pad_lo = (n - 1) // 2
    j = jax.lax.broadcasted_iota(jnp.int32, (c, c), 1 if transpose else 0)
    cc = jax.lax.broadcasted_iota(jnp.int32, (c, c), 0 if transpose else 1)
    band = (j >= cc - pad_lo) & (j <= cc + n - 1 - pad_lo)
    return band.astype(jnp.float32)


def _lrn_kernel(x_ref, o_ref, *, n: int, alpha: float, beta: float,
                knorm: float):
    """One-pass fwd: the cross-channel window sum rides the MXU as
    x^2 @ band inside the kernel — one HBM read, one write. Dot operands
    stay in the input dtype (bf16 on the fast MXU path, like the XLA band
    formulation); only the accumulator and the pow are f32."""
    xb = x_ref[:]                               # (TR, C), input dtype
    c = xb.shape[-1]
    s = jax.lax.dot(xb * xb, _lrn_band(c, n).astype(xb.dtype),
                    preferred_element_type=jnp.float32)
    x = xb.astype(jnp.float32)
    norm = knorm + (alpha / n) * s
    o_ref[:] = (x * jnp.exp(-beta * jnp.log(norm))).astype(o_ref.dtype)


def _lrn_bwd_kernel(x_ref, g_ref, dx_ref, *, n: int, alpha: float,
                    beta: float, knorm: float):
    """One-pass bwd: recompute the window sum (MXU, free vs an extra HBM
    round-trip), then
      dx = g * norm^-b - (2ab/n) * x * ((g * x * norm^(-b-1)) @ band^T).
    """
    xb = x_ref[:]
    c = xb.shape[-1]
    s = jax.lax.dot(xb * xb, _lrn_band(c, n).astype(xb.dtype),
                    preferred_element_type=jnp.float32)
    x = xb.astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    norm = knorm + (alpha / n) * s
    p = jnp.exp(-beta * jnp.log(norm))          # norm^-beta
    t = g * x * (p / norm)                      # g*x*norm^(-beta-1)
    u = jax.lax.dot(t.astype(xb.dtype), _lrn_band(c, n, transpose=True)
                    .astype(xb.dtype), preferred_element_type=jnp.float32)
    dx_ref[:] = (g * p - (2.0 * alpha * beta / n) * x * u).astype(
        dx_ref.dtype)


def _lrn_reference(x, n, alpha, beta, knorm):
    """XLA reduce_window formulation (the differentiable reference)."""
    pad_lo = (n - 1) // 2
    sq = jax.lax.reduce_window(
        x * x, 0.0, jax.lax.add, (1,) * (x.ndim - 1) + (n,),
        (1,) * x.ndim, ((0, 0),) * (x.ndim - 1) + ((pad_lo, n - 1 - pad_lo),))
    return x * (knorm + (alpha / n) * sq) ** (-beta)


LRN_MAX_CHANNELS = 512     # in-kernel (C, C) band + iotas must fit VMEM


def _lrn_row_tile(c: int, rows: int, row_tile: int, n_bufs: int) -> int:
    """Bound VMEM: ``n_bufs`` live (tile, C) f32 buffers (~6 for the
    forward kernel, ~10 for the backward's larger temporary set) plus the
    in-kernel (C, C) band and its iota intermediates (~12 bytes/element,
    reserved first). Callers must keep C <= LRN_MAX_CHANNELS."""
    budget_bytes = 6 * 1024 * 1024 - 12 * c * c
    budget = max(budget_bytes, 8 * n_bufs * 4 * c) // (n_bufs * 4 * max(c, 1))
    tile = min(row_tile, max(8, budget // 8 * 8))
    return min(tile, max(8, -(-rows // 8) * 8))


def _lrn_call(kern, args, shape, dtype, like, c, tile, n_in):
    rows = shape[0]
    pad = (-rows) % tile
    if pad:
        args = [jnp.pad(a, ((0, pad), (0, 0))) for a in args]
    out = pl.pallas_call(
        kern,
        grid=((rows + pad) // tile,),
        in_specs=[pl.BlockSpec((tile, c), lambda i: (i, 0))] * n_in,
        out_specs=pl.BlockSpec((tile, c), lambda i: (i, 0)),
        out_shape=_out_struct(((rows + pad), c), dtype, like),
        interpret=_INTERPRET,
    )(*args)
    return out[:rows] if pad else out


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5))
def lrn_fused(x: jnp.ndarray, n: int, alpha: float, beta: float,
              knorm: float, row_tile: int = 512) -> jnp.ndarray:
    """Fused LRN over the channel (last) dim of NHWC ``x``. Forward and
    backward are each ONE Pallas VMEM pass; the windowed channel sum is an
    in-kernel (C, C)-band matmul on the MXU (the band never touches HBM),
    and the backward recomputes it instead of saving norm (an MXU dot is
    cheaper than 2x the activation's HBM traffic). Residual: x only."""
    return _lrn_fused_impl(x, n, alpha, beta, knorm, row_tile)


def _lrn_fwd(x, n, alpha, beta, knorm, row_tile):
    return _lrn_fused_impl(x, n, alpha, beta, knorm, row_tile), x


def _lrn_bwd(n, alpha, beta, knorm, row_tile, x, g):
    shape = x.shape
    c = shape[-1]
    rows = 1
    for d in shape[:-1]:
        rows *= d
    tile = _lrn_row_tile(c, rows, row_tile, n_bufs=10)
    kern = functools.partial(_lrn_bwd_kernel, n=n, alpha=alpha, beta=beta,
                             knorm=knorm)
    dx = _lrn_call(kern, [x.reshape(rows, c), g.reshape(rows, c)],
                   (rows, c), x.dtype, x, c, tile, n_in=2)
    return (dx.reshape(shape),)


def _lrn_fused_impl(x: jnp.ndarray, n: int, alpha: float, beta: float,
                    knorm: float, row_tile: int = 512) -> jnp.ndarray:
    shape = x.shape
    c = shape[-1]
    if not n <= c <= LRN_MAX_CHANNELS:
        raise ValueError(
            "lrn_fused supports n <= channels <= %d (got channels=%d): the "
            "in-kernel (C, C) band must fit VMEM — use the XLA band/"
            "reduce_window formulation in layers/conv.py beyond that"
            % (LRN_MAX_CHANNELS, c))
    rows = 1
    for d in shape[:-1]:
        rows *= d
    tile = _lrn_row_tile(c, rows, row_tile, n_bufs=6)
    kern = functools.partial(_lrn_kernel, n=n, alpha=alpha, beta=beta,
                             knorm=knorm)
    out = _lrn_call(kern, [x.reshape(rows, c)], (rows, c), x.dtype, x, c,
                    tile, n_in=1)
    return out.reshape(shape)


lrn_fused.defvjp(_lrn_fwd, _lrn_bwd)


# ---------------------------------------------------------------------------
# flash attention (forward + blockwise backward kernels)
# ---------------------------------------------------------------------------

_NEG_INF = -1e30


def _causal_mask(sc, q0, k0):
    """Mask score block ``sc`` (rows = queries at global offset q0, cols =
    keys at k0) to the causal lower triangle."""
    qpos = q0 + jax.lax.broadcasted_iota(jnp.int32, sc.shape, 0)
    kpos = k0 + jax.lax.broadcasted_iota(jnp.int32, sc.shape, 1)
    return jnp.where(qpos >= kpos, sc, _NEG_INF)


def _mm(a, b):
    """a @ b in the operands' storage dtype with f32 MXU accumulation —
    bf16 operands run the MXU at full (2x f32) rate; casting to f32 first
    (the obvious formulation) measured the whole flash family at ~30% of
    peak, i.e. ~60% of the f32-matmul ceiling, on one v5e chip."""
    return jax.lax.dot_general(a, b, (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)


def _mm_t(a, b):
    """a @ b.T (contract last dims), f32 accumulation."""
    return jax.lax.dot_general(a, b, (((1,), (1,)), ((), ())),
                               preferred_element_type=jnp.float32)


def _mm_tt(a, b):
    """a.T @ b (contract first dims), f32 accumulation."""
    return jax.lax.dot_general(a, b, (((0,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref,
                  l_ref, *, causal: bool, scale: float):
    """Online-softmax accumulation for one (batch, head, q-block, k-block)
    grid step. K/V stream through VMEM one block at a time (grid innermost
    dim) — VMEM use is O(block), so sequence length is bounded by HBM, not
    VMEM. The (q-block)-persistent accumulators live in scratch and are
    normalized into the output at the last k-block."""
    ki = pl.program_id(3)
    nk = pl.num_programs(3)
    tq = q_ref.shape[2]
    bk = k_ref.shape[2]
    q0 = pl.program_id(2) * tq
    k0 = ki * bk

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    def _compute():
        q = q_ref[0, 0]                                   # (TQ, D) raw dtype
        k = k_ref[0, 0]                                   # (BK, D)
        v = v_ref[0, 0]
        sc = _mm_t(q, k) * scale                          # (TQ, BK) f32
        if causal:
            sc = _causal_mask(sc, q0, k0)
        m_prev = m_ref[:, 0]
        m_new = jnp.maximum(m_prev, sc.max(-1))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(sc - m_new[:, None])
        l_ref[:, 0] = l_ref[:, 0] * corr + p.sum(-1)
        acc_ref[:] = acc_ref[:] * corr[:, None] + _mm(p.astype(v.dtype), v)
        m_ref[:, 0] = m_new

    if causal:
        # skip fully-masked K blocks past the diagonal (no compute; the
        # block DMA still happens — grids are rectangular)
        pl.when(q0 + tq - 1 >= k0)(_compute)
    else:
        _compute()

    @pl.when(ki == nk - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, 0], 1e-30)
        o_ref[0, 0] = (acc_ref[:] / l[:, None]).astype(o_ref.dtype)
        # log-sum-exp of the scaled logits per row — the backward residual
        # (trailing singleton dim keeps the TPU block-tiling rule happy)
        lse_ref[0, 0] = (m_ref[:, 0] + jnp.log(l))[:, None]


# --- VMEM-resident kernel family: K/V (or Q/dO) held fully in VMEM per
# (batch, head); fastest for seq <= _FLASH_RESIDENT_MAX, where they fit.
# Beyond that the streaming family above (K/V blocks as a grid dim with
# scratch accumulators) keeps VMEM O(block) at some per-step overhead
# (measured ~3x on short seqs, hence the split).

def _flash_kernel_res(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k: int,
                  causal: bool, scale: float):
    # q_ref: (1, 1, TQ, D) one (batch*head, q-block); k/v: (1, 1, N, D)
    q = q_ref[0, 0]                                   # (TQ, D) raw dtype
    tq, d = q.shape
    n = k_ref.shape[2]
    qi = pl.program_id(2)
    q0 = qi * tq

    def body(s, carry):
        o, m, l = carry
        k = k_ref[0, 0, pl.dslice(s * block_k, block_k), :]
        v = v_ref[0, 0, pl.dslice(s * block_k, block_k), :]
        sc = _mm_t(q, k) * scale                       # (TQ, BK) f32
        if causal:
            sc = _causal_mask(sc, q0, s * block_k)
        m_new = jnp.maximum(m, sc.max(-1))
        p = jnp.exp(sc - m_new[:, None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(-1)
        o_new = o * corr[:, None] + _mm(p.astype(v.dtype), v)
        return o_new, m_new, l_new

    o0 = jnp.zeros((tq, d), jnp.float32)
    m0 = jnp.full((tq,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((tq,), jnp.float32)
    n_blocks = n // block_k
    if causal:
        # skip fully-masked K blocks past the diagonal
        n_run = jnp.minimum(n_blocks, (q0 + tq + block_k - 1) // block_k)
    else:
        n_run = n_blocks
    o, m, l = jax.lax.fori_loop(0, n_run, body, (o0, m0, l0))
    o_ref[0, 0] = (o / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)
    # log-sum-exp of the scaled logits per row — the backward's residual
    # (trailing singleton dim keeps the TPU block-tiling rule happy)
    lse_ref[0, 0] = (m + jnp.log(jnp.maximum(l, 1e-30)))[:, None]




def _flash_dq_kernel_res(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, dq_ref, *,
                     block_k: int, causal: bool, scale: float):
    """dq for one (batch, head, q-block): dq = sum_s ds_s @ k_s * scale,
    ds = p * (do @ v^T - delta), p = exp(q k^T scale - lse)."""
    q = q_ref[0, 0]                                    # (TQ, D) raw dtype
    do = do_ref[0, 0]
    lse = lse_ref[0, 0, :, 0]                          # (TQ,)
    delta = dl_ref[0, 0, :, 0]                         # (TQ,) rowsum(do*o)
    tq, d = q.shape
    n = k_ref.shape[2]
    q0 = pl.program_id(2) * tq

    def body(s, dq):
        k = k_ref[0, 0, pl.dslice(s * block_k, block_k), :]
        v = v_ref[0, 0, pl.dslice(s * block_k, block_k), :]
        sc = _mm_t(q, k) * scale                       # (TQ, BK) scaled logits
        if causal:
            sc = _causal_mask(sc, q0, s * block_k)
        p = jnp.exp(sc - lse[:, None])
        ds = p * (_mm_t(do, v) - delta[:, None])
        return dq + _mm(ds.astype(k.dtype), k)

    n_blocks = n // block_k
    n_run = jnp.minimum(n_blocks, (q0 + tq + block_k - 1) // block_k) \
        if causal else n_blocks
    dq = jax.lax.fori_loop(0, n_run, body, jnp.zeros((tq, d), jnp.float32))
    dq_ref[0, 0] = (dq * scale).astype(dq_ref.dtype)



def _flash_dkv_kernel_res(k_ref, v_ref, q_ref, do_ref, lse_ref, dl_ref,
                      dk_ref, dv_ref, *, block_q: int, causal: bool,
                      scale: float):
    """dk, dv for one (batch, head, k-block): dv = sum_i p_i^T @ do_i,
    dk = sum_i ds_i^T @ q_i * scale."""
    k = k_ref[0, 0]                                    # (TK, D) raw dtype
    v = v_ref[0, 0]
    tk, d = k.shape
    n = q_ref.shape[2]
    k0 = pl.program_id(2) * tk

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, 0, pl.dslice(i * block_q, block_q), :]
        do = do_ref[0, 0, pl.dslice(i * block_q, block_q), :]
        lse = lse_ref[0, 0, pl.dslice(i * block_q, block_q), 0]
        delta = dl_ref[0, 0, pl.dslice(i * block_q, block_q), 0]
        sc = _mm_t(q, k) * scale                       # (BQ, TK)
        if causal:
            sc = _causal_mask(sc, i * block_q, k0)
        p = jnp.exp(sc - lse[:, None])
        ds = p * (_mm_t(do, v) - delta[:, None])
        return dk + _mm_tt(ds.astype(q.dtype), q), \
            dv + _mm_tt(p.astype(do.dtype), do)

    n_blocks = n // block_q
    # causal: q-blocks strictly before this k-block contribute nothing
    lo = jnp.minimum(n_blocks, k0 // block_q) if causal else 0
    dk, dv = jax.lax.fori_loop(
        lo, n_blocks, body,
        (jnp.zeros((tk, d), jnp.float32), jnp.zeros((tk, d), jnp.float32)))
    dk_ref[0, 0] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)



_FLASH_RESIDENT_MAX = 4096       # at head_dim 64; scaled by 64/d below


def _flash_resident(n: int, d: int) -> bool:
    """True when the VMEM-resident kernel family may hold full-sequence
    K/V (and Q/dO) blocks: its footprint scales with n*d, measured to fit
    up to n=4096 at d=64 (doc/performance.md). Wider heads shrink the
    budget proportionally; beyond it the streaming family keeps VMEM
    O(block)."""
    return n * max(d, 1) <= _FLASH_RESIDENT_MAX * 64


def _flash_block(n: int, req, d: int = 64) -> int:
    """Resolve a block-size request: explicit sizes are clamped to n.

    Default (None), by measurement on one v5e chip (doc/performance.md):
    - RESIDENT family (K/V whole in VMEM): 512 when the sequence divides
      it (~35% over 256 at seq 1024/4096), else 256. 1024-row blocks win
      the isolated micro 6-8% but measured SLOWER inside the full
      rematerialized GPT step (437 vs 422 ms @ 303M d64; 277.5 vs 276.6
      @ 305M d128) — coarser blocks serialize against the surrounding
      fusions.
    - STREAMING family (long sequences, K/V blocks as a grid dim):
      1024x1024 wins decisively — 85M d64 @ 4x8192: 661 vs 891 ms/step
      (+35% tok/s); 305M-class d128 @ 4x4096: 355 vs 391 ms; @ 2x8192:
      419 vs 504 ms (+20%). Larger k-blocks amortize the per-block
      scratch-accumulator round trips that the resident family does not
      have.
    Pass block_q/block_k explicitly to override."""
    if req is not None:
        return min(req, n)
    if not _flash_resident(n, d) and n % 1024 == 0:
        return 1024
    return 512 if n >= 512 and n % 512 == 0 else min(256, n)


def _check_flash_divisible(n: int, bq: int, bk: int) -> None:
    """The kernel grids use floor division, so a sequence that is not a
    multiple of the resolved block size would silently leave tail rows
    uninitialized.  Fail loudly instead."""
    if n % bq or n % bk:
        raise ValueError(
            "flash attention: seq length %d must be divisible by the "
            "resolved block sizes (block_q=%d, block_k=%d); pass "
            "block_q/block_k that divide the sequence" % (n, bq, bk))


def _flash_fwd_impl(q, k, v, causal: bool, block_q, block_k,
                    out_dtype=None):
    """Returns (out (b,n,h,d), lse (b,h,n,1)) — lse kept for the backward;
    the trailing singleton dim satisfies the TPU block-tiling rule."""
    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    out, lse = _flash_fwd_bhnd(qt, kt, vt, causal, block_q, block_k,
                               out_dtype)
    return jnp.transpose(out, (0, 2, 1, 3)), lse


def _flash_fwd_bhnd(qt, kt, vt, causal: bool, block_q, block_k,
                    out_dtype=None):
    """Head-major core: q,k,v (b, h, n, d) — the kernels' native layout
    (the grid walks (batch, head, q-block)).  Returns (out (b,h,n,d),
    lse (b,h,n,1)) with no layout copies."""
    b, h, n, d = qt.shape
    scale = 1.0 / (d ** 0.5)
    bq = _flash_block(n, block_q, d)
    bk = _flash_block(n, block_k, d)
    _check_flash_divisible(n, bq, bk)
    if _flash_resident(n, d):
        kern = functools.partial(_flash_kernel_res, block_k=bk,
                                 causal=causal, scale=scale)
        out, lse = pl.pallas_call(
            kern,
            grid=(b, h, n // bq),
            in_specs=[
                pl.BlockSpec((1, 1, bq, d), lambda i, j, s: (i, j, s, 0)),
                pl.BlockSpec((1, 1, n, d), lambda i, j, s: (i, j, 0, 0)),
                pl.BlockSpec((1, 1, n, d), lambda i, j, s: (i, j, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, 1, bq, d), lambda i, j, s: (i, j, s, 0)),
                pl.BlockSpec((1, 1, bq, 1), lambda i, j, s: (i, j, s, 0)),
            ],
            out_shape=[
                _out_struct((b, h, n, d), out_dtype or qt.dtype, qt),
                _out_struct((b, h, n, 1), jnp.float32, qt),
            ],
            interpret=_INTERPRET,
        )(qt, kt, vt)
        return out, lse
    kern = functools.partial(_flash_kernel, causal=causal, scale=scale)
    out, lse = pl.pallas_call(
        kern,
        grid=(b, h, n // bq, n // bk),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda i, j, s, t: (i, j, s, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda i, j, s, t: (i, j, t, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda i, j, s, t: (i, j, t, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda i, j, s, t: (i, j, s, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda i, j, s, t: (i, j, s, 0)),
        ],
        out_shape=[
            _out_struct((b, h, n, d), out_dtype or qt.dtype, qt),
            _out_struct((b, h, n, 1), jnp.float32, qt),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),      # acc
            pltpu.VMEM((bq, 1), jnp.float32),      # running max
            pltpu.VMEM((bq, 1), jnp.float32),      # running sum
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=_INTERPRET,
    )(qt, kt, vt)
    return out, lse


def _flash_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, dq_ref,
                     acc_ref, *, causal: bool, scale: float):
    """dq accumulation for one (batch, head, q-block, k-block) grid step:
    dq += ds @ k, ds = p * (do @ v^T - delta), p = exp(q k^T scale - lse).
    K/V stream per k-block (grid innermost); dq lives in scratch and is
    written (scaled) at the last k-block."""
    ki = pl.program_id(3)
    nk = pl.num_programs(3)
    tq = q_ref.shape[2]
    bk = k_ref.shape[2]
    q0 = pl.program_id(2) * tq
    k0 = ki * bk

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def _compute():
        q = q_ref[0, 0]                                # (TQ, D) raw dtype
        do = do_ref[0, 0]
        lse = lse_ref[0, 0, :, 0]                      # (TQ,)
        delta = dl_ref[0, 0, :, 0]                     # (TQ,) rowsum(do*o)
        k = k_ref[0, 0]                                # (BK, D)
        v = v_ref[0, 0]
        sc = _mm_t(q, k) * scale                       # (TQ, BK) scaled logits
        if causal:
            sc = _causal_mask(sc, q0, k0)
        p = jnp.exp(sc - lse[:, None])
        ds = p * (_mm_t(do, v) - delta[:, None])
        acc_ref[:] = acc_ref[:] + _mm(ds.astype(k.dtype), k)

    if causal:
        pl.when(q0 + tq - 1 >= k0)(_compute)
    else:
        _compute()

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0, 0] = (acc_ref[:] * scale).astype(dq_ref.dtype)


def _flash_dkv_kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, dl_ref,
                      dk_ref, dv_ref, dk_acc, dv_acc, *, causal: bool,
                      scale: float):
    """dk/dv accumulation for one (batch, head, k-block, q-block) grid
    step: dv += p^T @ do, dk += ds^T @ q (raw-dtype operands; the 1/sqrt(d)
    scale is applied once at the final dk write). Q/dO stream per q-block
    (grid innermost); dk/dv live in scratch and are written at the last
    q-block."""
    qi = pl.program_id(3)
    nq = pl.num_programs(3)
    tk = k_ref.shape[2]
    bq = q_ref.shape[2]
    k0 = pl.program_id(2) * tk
    q0 = qi * bq

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def _compute():
        k = k_ref[0, 0]                                # (TK, D) raw dtype
        v = v_ref[0, 0]
        q = q_ref[0, 0]                                # (BQ, D)
        do = do_ref[0, 0]
        lse = lse_ref[0, 0, :, 0]
        delta = dl_ref[0, 0, :, 0]
        sc = _mm_t(q, k) * scale                       # (BQ, TK)
        if causal:
            sc = _causal_mask(sc, q0, k0)
        p = jnp.exp(sc - lse[:, None])
        ds = p * (_mm_t(do, v) - delta[:, None])
        dk_acc[:] = dk_acc[:] + _mm_tt(ds.astype(q.dtype), q)
        dv_acc[:] = dv_acc[:] + _mm_tt(p.astype(do.dtype), do)

    if causal:
        # q-blocks strictly before this k-block contribute nothing
        pl.when(q0 + bq - 1 >= k0)(_compute)
    else:
        _compute()

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0, 0] = (dk_acc[:] * scale).astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd_impl(q, k, v, o, lse, g, causal, block_q, block_k):
    # delta[b,h,i,1] = rowsum(dO * O) — the softmax-grad correction term.
    # lse stays in the forward kernel's (b, h, n, 1) shape all the way to
    # the backward kernels (no squeeze/unsqueeze round-trip). NB the lse
    # layout copies visible in step profiles come from layout assignment
    # at the pallas custom-call boundary, not from this reshape — removing
    # the round-trip measured within noise on the 32x1024 flagship.
    delta = jnp.einsum("bqhd,bqhd->bhq", g.astype(jnp.float32),
                       o.astype(jnp.float32))[..., None]
    return _flash_bwd_blocks4(q, k, v, lse, delta, g, causal,
                              block_q, block_k, None)


def flash_fwd_with_lse(q, k, v, causal: bool, block_q=None,
                       block_k=None):
    """Forward kernel returning (out (b,n,h,d) f32, lse (b,h,n)) for
    callers that combine partial softmaxes themselves (ring attention
    chunks). The partial output stays f32 so the caller's merge does not
    accumulate per-chunk bf16 rounding."""
    out, lse = _flash_fwd_impl(q, k, v, causal, block_q, block_k,
                               out_dtype=jnp.float32)
    return out, lse[..., 0]


def flash_fwd_with_lse_bhnd(q, k, v, causal: bool, block_q=None,
                            block_k=None):
    """Head-major chunk forward for ring attention: q,k,v (b, h, n, d) ->
    (out (b, h, n, d) f32, lse (b, h, n)) with NO layout copies — the
    kernels' native layout end to end."""
    out, lse = _flash_fwd_bhnd(q, k, v, causal, block_q, block_k,
                               out_dtype=jnp.float32)
    return out, lse[..., 0]


def flash_bwd_blocks_bhnd(q, k, v, lse, delta, g, causal: bool,
                          block_q=None, block_k=None, out_dtype=None):
    """Head-major blockwise dq/dk/dv for ring chunks: all tensors
    (b, h, n, d), lse/delta (b, h, n) f32 (possibly from a GLOBAL softmax
    spanning more chunks than k). No layout copies."""
    return _flash_bwd_bhnd(q, k, v, lse[..., None], delta[..., None], g,
                           causal, block_q, block_k, out_dtype)


def flash_bwd_blocks(q, k, v, lse, delta, g, causal: bool,
                     block_q=None, block_k=None,
                     out_dtype=None):
    """Blockwise dq/dk/dv given the softmax row statistics.

    q,k,v,g: (b, n, h, d); lse/delta: (b, h, n) f32 — lse may come from a
    *global* softmax spanning more chunks than k (ring attention): then
    p = exp(s - lse) are the globally-normalized probabilities and the
    returned grads are this chunk's exact contribution."""
    return _flash_bwd_blocks4(q, k, v, lse[..., None], delta[..., None], g,
                              causal, block_q, block_k, out_dtype)


def _flash_bwd_blocks4(q, k, v, lse, delta, g, causal, block_q, block_k,
                       out_dtype):
    """flash_bwd_blocks with lse/delta already in the kernels' native
    (b, h, n, 1) shape (no squeeze/unsqueeze round-trip)."""
    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    dot = jnp.transpose(g, (0, 2, 1, 3))
    dq, dk, dv = _flash_bwd_bhnd(qt, kt, vt, lse, delta, dot, causal,
                                 block_q, block_k, out_dtype)
    tr = lambda x: jnp.transpose(x, (0, 2, 1, 3))
    return tr(dq), tr(dk), tr(dv)


def _flash_bwd_bhnd(qt, kt, vt, lse, delta, dot, causal, block_q, block_k,
                    out_dtype=None):
    """Head-major blockwise backward: all tensors (b, h, n, d) (lse/delta
    (b, h, n, 1)); returns (dq, dk, dv) in the same layout — no copies."""
    b, h, n, d = qt.shape
    scale = 1.0 / (d ** 0.5)
    bq = _flash_block(n, block_q, d)
    bk = _flash_block(n, block_k, d)
    _check_flash_divisible(n, bq, bk)
    if _flash_resident(n, d):
        blk_qd = pl.BlockSpec((1, 1, bq, d), lambda i, j, s: (i, j, s, 0))
        blk_kd = pl.BlockSpec((1, 1, bk, d), lambda i, j, s: (i, j, s, 0))
        full_nd = pl.BlockSpec((1, 1, n, d), lambda i, j, s: (i, j, 0, 0))
        blk_q1 = pl.BlockSpec((1, 1, bq, 1), lambda i, j, s: (i, j, s, 0))
        full_n1 = pl.BlockSpec((1, 1, n, 1), lambda i, j, s: (i, j, 0, 0))

        dq = pl.pallas_call(
            functools.partial(_flash_dq_kernel_res, block_k=bk,
                              causal=causal, scale=scale),
            grid=(b, h, n // bq),
            in_specs=[blk_qd, full_nd, full_nd, blk_qd, blk_q1, blk_q1],
            out_specs=blk_qd,
            out_shape=_out_struct((b, h, n, d), out_dtype or qt.dtype, qt),
            interpret=_INTERPRET,
        )(qt, kt, vt, dot, lse, delta)

        dk, dv = pl.pallas_call(
            functools.partial(_flash_dkv_kernel_res, block_q=bq,
                              causal=causal, scale=scale),
            grid=(b, h, n // bk),
            in_specs=[blk_kd, blk_kd, full_nd, full_nd, full_n1, full_n1],
            out_specs=[blk_kd, blk_kd],
            out_shape=[_out_struct((b, h, n, d), out_dtype or kt.dtype, kt),
                       _out_struct((b, h, n, d), out_dtype or vt.dtype, vt)],
            interpret=_INTERPRET,
        )(kt, vt, qt, dot, lse, delta)
        return dq, dk, dv

    # dq: grid (b, h, q-block, k-block) — K/V stream per innermost step
    q_by_q = pl.BlockSpec((1, 1, bq, d), lambda i, j, s, t: (i, j, s, 0))
    k_by_k = pl.BlockSpec((1, 1, bk, d), lambda i, j, s, t: (i, j, t, 0))
    q1_by_q = pl.BlockSpec((1, 1, bq, 1), lambda i, j, s, t: (i, j, s, 0))

    dq = pl.pallas_call(
        functools.partial(_flash_dq_kernel, causal=causal, scale=scale),
        grid=(b, h, n // bq, n // bk),
        in_specs=[q_by_q, k_by_k, k_by_k, q_by_q, q1_by_q, q1_by_q],
        out_specs=q_by_q,
        out_shape=_out_struct((b, h, n, d), out_dtype or qt.dtype, qt),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=_INTERPRET,
    )(qt, kt, vt, dot, lse, delta)

    # dk/dv: grid (b, h, k-block, q-block) — Q/dO stream per innermost step
    k_by_k2 = pl.BlockSpec((1, 1, bk, d), lambda i, j, s, t: (i, j, s, 0))
    q_by_q2 = pl.BlockSpec((1, 1, bq, d), lambda i, j, s, t: (i, j, t, 0))
    q1_by_q2 = pl.BlockSpec((1, 1, bq, 1), lambda i, j, s, t: (i, j, t, 0))

    dk, dv = pl.pallas_call(
        functools.partial(_flash_dkv_kernel, causal=causal, scale=scale),
        grid=(b, h, n // bk, n // bq),
        in_specs=[k_by_k2, k_by_k2, q_by_q2, q_by_q2, q1_by_q2, q1_by_q2],
        out_specs=[k_by_k2, k_by_k2],
        out_shape=[_out_struct((b, h, n, d), out_dtype or kt.dtype, kt),
                   _out_struct((b, h, n, d), out_dtype or vt.dtype, vt)],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=_INTERPRET,
    )(kt, vt, qt, dot, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q, k, v, causal: bool = False, block_q=None,
                    block_k=None):
    """Exact attention, O(N) memory. q,k,v: (batch, seq, heads, head_dim);
    seq must divide by the block sizes (default: 512 when seq is a
    multiple of 512, else 256 — the local_attention alignment; explicit
    sizes clamp to seq)."""
    out, _ = _flash_fwd_impl(q, k, v, causal, block_q, block_k)
    return out


def _flash_fwd(q, k, v, causal, block_q, block_k):
    out, lse = _flash_fwd_impl(q, k, v, causal, block_q, block_k)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, block_q, block_k, res, g):
    # blockwise flash backward (FlashAttention-2 style): recompute p from
    # the saved log-sum-exp, two pallas passes (dq; dk+dv), O(N) memory
    q, k, v, o, lse = res
    return _flash_bwd_impl(q, k, v, o, lse, g, causal, block_q, block_k)


flash_attention.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention_bhnd(q, k, v, causal: bool = False, block_q=None,
                         block_k=None):
    """Exact attention, O(N) memory, in the kernels' native head-major
    layout: q,k,v (batch, heads, seq, head_dim) -> out (b, h, n, d).

    The (b,n,h,d) entry point :func:`flash_attention` pays ~0.1 ms of
    layout copy per 32 MB tensor per call at the custom-call boundary
    (q/k/v in, out back — and again for every backward operand). A caller
    that projects straight into head-major (einsum ``bnf,fhd->bhnd``, the
    transpose fused into the projection matmul) and consumes head-major
    output (``bhnd,hdf->bnf``) skips ALL of those copies; residuals are
    saved head-major too, so the backward is copy-free as well. Measured
    on the 303M GPT flagship: ~36 ms/step of pure layout copies removed."""
    out, _ = _flash_fwd_bhnd(q, k, v, causal, block_q, block_k)
    return out





# ---------------------------------------------------------------------------
# fused relu -> LRN -> max-pool (the AlexNet head-of-block chain)
# ---------------------------------------------------------------------------
#
# The reference runs these as three layers (activation_layer-inl.hpp,
# lrn_layer-inl.hpp:46-77, pooling_layer-inl.hpp:33-86); as separate XLA
# ops the chain costs ~5 full HBM round-trips of the conv activation per
# step (band-matmul + pow/mul forward passes, a backward mega-fusion, and
# a select-and-scatter for the pool gradient).  This kernel family fuses
# the chain into one pass per direction:
#
#   forward (inference):  read x            -> write pooled
#   forward (training):   read x            -> write pooled, u, norm
#   backward:             read u, norm, g   -> write dx
#
# where u = lrn(relu(x)) and norm is the LRN denominator.  Saving (u,
# norm) instead of x lets the backward run without any re-derivation
# chain: r·p == u recovers every term (t = du·u/norm, r = u/p, and the
# relu mask is u > 0), so each pass stays a single whole-image VMEM
# block with a small live set — no halo banding, no manual DMA.
#
# Pool-gradient semantics: every element equal to its window's max gets
# the full window gradient, summed over covering windows — exactly the
# reference's unpool expression ((src == pooled) * grad, mshadow), unlike
# XLA's select-and-scatter which credits only the first maximum.

def _rlp_win_sum(v, n, transpose=False):
    """Windowed sum over the channel (lane) dim via static lane rotates +
    iota edge masks (f32 accumulation; bf16 terms like the XLA band
    path). Window: reference left-biased center (chpool); ``transpose``
    flips the offset range (the band-matrix transpose of the backward)."""
    pad_lo = (n - 1) // 2
    c = v.shape[-1]
    offs = range(-(n - 1 - pad_lo), pad_lo + 1) if transpose \
        else range(-pad_lo, n - pad_lo)
    lane = jax.lax.broadcasted_iota(
        jnp.int32, (1,) * (v.ndim - 1) + (c,), v.ndim - 1)
    acc = None
    for d in offs:
        rolled = v if d == 0 else jnp.roll(v, -d, axis=-1)
        ok = (lane + d >= 0) & (lane + d < c)
        term = jnp.where(ok, rolled, jnp.zeros((), v.dtype))
        acc = term.astype(jnp.float32) if acc is None \
            else acc + term.astype(jnp.float32)
    return acc


def _rlp_u_norm_p(x, relu, n, alpha, beta, knorm):
    """u = lrn(relu(x)), norm (input dtype — the XLA band path's bf16
    cast), p = norm^-beta (f32)."""
    r = jnp.maximum(x, 0) if relu else x
    sq = _rlp_win_sum(r * r, n)
    norm = (knorm + (alpha / n) * sq).astype(x.dtype)
    p = jnp.exp(-beta * jnp.log(norm.astype(jnp.float32)))
    u = (r.astype(jnp.float32) * p).astype(x.dtype)
    return u, norm, p


def _pool_slice3(u, oy, ox, a, b, stride):
    """(IB, H, W, C) -> the (a, b) window-offset plane u[:, a+s*wy, b+s*wx].

    Mosaic only lowers unit-stride vector slices, so the stride is taken
    by pad -> reshape (rows, s, ...) -> index 0; the zero padding is never
    selected (index 0 of each s-block stays in-bounds)."""
    ib, h, w, c = u.shape
    s = stride
    if s == 1:
        return jax.lax.slice(u, (0, a, b, 0), (ib, a + oy, b + ox, c))
    v = u[:, a:]
    pad_y = oy * s - v.shape[1]
    if pad_y > 0:
        v = jnp.pad(v, ((0, 0), (0, pad_y), (0, 0), (0, 0)))
    v = v[:, :oy * s].reshape(ib, oy, s, v.shape[2], c)[:, :, 0]
    v = v[:, :, b:]
    pad_x = ox * s - v.shape[2]
    if pad_x > 0:
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad_x), (0, 0)))
    return v[:, :, :ox * s].reshape(ib, oy, ox, s, c)[:, :, :, 0]


def _rlp_pool(u, oy, ox, kernel, stride):
    pooled = _pool_slice3(u, oy, ox, 0, 0, stride)
    for a in range(kernel):
        for b in range(kernel):
            if a == 0 and b == 0:
                continue
            pooled = jnp.maximum(pooled,
                                 _pool_slice3(u, oy, ox, a, b, stride))
    return pooled


def _shift_win(v, da, db, fill):
    """result[:, i, j] = v[:, i - da, j - db] (``fill`` outside)."""
    h, w = v.shape[1], v.shape[2]
    if da or db:
        v = jnp.pad(v[:, :h - da, :w - db],
                    ((0, 0), (da, 0), (db, 0), (0, 0)),
                    constant_values=fill)
    return v


def _rlp_infer_kernel(x_ref, o_ref, *, relu, n, alpha, beta, knorm,
                      kernel, stride, oy, ox):
    u, _, _ = _rlp_u_norm_p(x_ref[:], relu, n, alpha, beta, knorm)
    o_ref[:] = _rlp_pool(u, oy, ox, kernel, stride)


def _rlp_train_kernel(x_ref, o_ref, u_ref, norm_ref, *, relu, n, alpha,
                      beta, knorm, kernel, stride, oy, ox):
    u, norm, _ = _rlp_u_norm_p(x_ref[:], relu, n, alpha, beta, knorm)
    u_ref[:] = u
    norm_ref[:] = norm
    o_ref[:] = _rlp_pool(u, oy, ox, kernel, stride)


def _rlp_bwd_kernel(u_ref, norm_ref, g_ref, *dx_refs, relu, n, alpha,
                    beta, kernel, stride, oy, ox, ny, nx):
    """Backward over the s x s stride-residue sub-grids.

    Interleaving sub-grids back onto the input grid is a sublane-minor
    relayout Mosaic cannot lower, so each residue (ry, rx) — input rows
    y = s*i + ry, cols x = s*j + rx — is computed independently (the LRN
    and relu parts are per-pixel, and the pool windows covering a
    position map to plain shifts in window space) and written to its own
    (1, ny, nx, C) output; the caller re-interleaves in XLA.

    Tie test: window maxima are matched by f32 value equality (bf16
    compares don't lower on this target; the f32 cast of a bf16 value is
    exact, so every element equal to its window's max matches — the
    mshadow ``(src == pooled)`` reference semantics)."""
    u = u_ref[:]
    g = g_ref[:]
    s = stride
    pooled = _rlp_pool(u, oy, ox, kernel, s)
    # pad the window grid to the sub-grid size: indices past the last
    # window contribute nothing (-inf never matches finite data); the
    # tie test runs in f32 (bf16/i16 compares don't lower on this target)
    pooled_pad = jnp.pad(
        pooled.astype(jnp.float32),
        ((0, 0), (0, ny - oy), (0, nx - ox), (0, 0)),
        constant_values=-jnp.inf)
    g_pad = jnp.pad(g, ((0, 0), (0, ny - oy), (0, nx - ox), (0, 0)))
    for ry in range(s):
        for rx in range(s):
            u_sub = _pool_slice3(u, ny, nx, ry, rx, s)
            u_f32 = u_sub.astype(jnp.float32)
            du = jnp.zeros(u_sub.shape, u.dtype)
            # windows covering y = s*i + ry have offset a ≡ ry (mod s):
            # window row i - da with da = (a - ry) // s
            for a in range(ry, kernel, s):
                for b in range(rx, kernel, s):
                    da, db = (a - ry) // s, (b - rx) // s
                    eq = _shift_win(pooled_pad, da, db, -jnp.inf) == u_f32
                    du = du + jnp.where(eq, _shift_win(g_pad, da, db, 0),
                                        jnp.zeros((), u.dtype))
            # LRN backward from the saved (u, norm): with r·p == u,
            #   t  = du·r·p/norm = du·u/norm
            #   dx = du·p − (2αβ/n)·(u/p)·Σ_T(t)
            # (pad rows carry norm == 0 -> NaNs, discarded by the caller's
            # final slice)
            nf = _pool_slice3(norm_ref[:], ny, nx, ry, rx, s) \
                .astype(jnp.float32)
            p = jnp.exp(-beta * jnp.log(nf))
            duf = du.astype(jnp.float32)
            uf = u_sub.astype(jnp.float32)
            t = (duf * uf / nf).astype(u.dtype)
            s2 = _rlp_win_sum(t, n, transpose=True)
            dr = duf * p - (2.0 * (alpha / n) * beta) * (uf / p) * s2
            if relu:
                # u > 0 <=> r > 0 <=> x > 0 (p is strictly positive)
                dr = jnp.where(uf > 0, dr, 0.0)
            dx_refs[ry * s + rx][:] = dr.astype(u.dtype)


def _rlp_pool_shape(h: int, w: int, kernel: int, stride: int):
    oy = (h - kernel) // stride + 1
    ox = (w - kernel) // stride + 1
    return oy, ox


def fused_relu_lrn_maxpool_supported(shape, n: int, kernel: int,
                                     stride: int, pad: int,
                                     pool_out) -> bool:
    """True iff the fused kernel reproduces the unfused chain exactly:
    in-bounds pool windows (ceil-mode never pads) and a whole image +
    intermediates within the VMEM budget."""
    b, h, w, c = shape
    if not use_pallas():
        return False
    if pad != 0 or n > c or kernel > h or kernel > w:
        return False
    oy, ox = _rlp_pool_shape(h, w, kernel, stride)
    if pool_out is not None and (oy, ox) != tuple(pool_out):
        return False
    return h * w * c * 30 < 12 * 1024 * 1024


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5, 6, 7))
def fused_relu_lrn_maxpool(x: jnp.ndarray, relu: bool, n: int, alpha: float,
                           beta: float, knorm: float, kernel: int,
                           stride: int) -> jnp.ndarray:
    """maxpool(lrn(relu(x))) in one VMEM pass over NHWC ``x``.

    Under differentiation the forward additionally saves (u, norm) so the
    backward is also a single pass.  Call
    :func:`fused_relu_lrn_maxpool_supported` first."""
    b, h, w, c = x.shape
    oy, ox = _rlp_pool_shape(h, w, kernel, stride)
    kern = functools.partial(_rlp_infer_kernel, relu=relu, n=n, alpha=alpha,
                             beta=beta, knorm=knorm, kernel=kernel,
                             stride=stride, oy=oy, ox=ox)
    return pl.pallas_call(
        kern,
        grid=(b,),
        in_specs=[pl.BlockSpec((1, h, w, c), lambda i: (i, 0, 0, 0))],
        out_specs=pl.BlockSpec((1, oy, ox, c), lambda i: (i, 0, 0, 0)),
        out_shape=_out_struct((b, oy, ox, c), x.dtype, x),
        interpret=_INTERPRET,
    )(x)


def _rlp_fwd(x, relu, n, alpha, beta, knorm, kernel, stride):
    b, h, w, c = x.shape
    oy, ox = _rlp_pool_shape(h, w, kernel, stride)
    kern = functools.partial(_rlp_train_kernel, relu=relu, n=n, alpha=alpha,
                             beta=beta, knorm=knorm, kernel=kernel,
                             stride=stride, oy=oy, ox=ox)
    img = pl.BlockSpec((1, h, w, c), lambda i: (i, 0, 0, 0))
    pooled, u, norm = pl.pallas_call(
        kern,
        grid=(b,),
        in_specs=[img],
        out_specs=[pl.BlockSpec((1, oy, ox, c), lambda i: (i, 0, 0, 0)),
                   img, img],
        out_shape=[_out_struct((b, oy, ox, c), x.dtype, x),
                   _out_struct((b, h, w, c), x.dtype, x),
                   _out_struct((b, h, w, c), x.dtype, x)],
        interpret=_INTERPRET,
    )(x)
    return pooled, (u, norm)


def _rlp_bwd(relu, n, alpha, beta, knorm, kernel, stride, res, g):
    u, norm = res
    b, h, w, c = u.shape
    s = stride
    oy, ox = _rlp_pool_shape(h, w, kernel, s)
    ny, nx = -(-h // s), -(-w // s)
    kern = functools.partial(_rlp_bwd_kernel, relu=relu, n=n, alpha=alpha,
                             beta=beta, kernel=kernel, stride=s,
                             oy=oy, ox=ox, ny=ny, nx=nx)
    img = pl.BlockSpec((1, h, w, c), lambda i: (i, 0, 0, 0))
    sub = pl.BlockSpec((1, ny, nx, c), lambda i: (i, 0, 0, 0))
    parts = pl.pallas_call(
        kern,
        grid=(b,),
        in_specs=[img, img,
                  pl.BlockSpec((1, oy, ox, c), lambda i: (i, 0, 0, 0))],
        out_specs=[sub] * (s * s),
        out_shape=[_out_struct((b, ny, nx, c), u.dtype, u)] * (s * s),
        interpret=_INTERPRET,
    )(u, norm, g)
    if s == 1:
        return (parts[0][:, :h, :w],)
    # re-interleave the stride-residue sub-grids: (b, ny, nx, c) x s^2
    # -> (b, ny, s, nx, s, c) -> (b, ny*s, nx*s, c) -> crop.  Pure
    # stack/transpose/reshape: one XLA copy fusion.
    stacked = jnp.stack(parts, axis=1).reshape(b, s, s, ny, nx, c)
    dx = jnp.transpose(stacked, (0, 3, 1, 4, 2, 5)) \
        .reshape(b, ny * s, nx * s, c)[:, :h, :w]
    return (dx,)


fused_relu_lrn_maxpool.defvjp(_rlp_fwd, _rlp_bwd)


# --- packed-residual backward (head-major, d == 64) -----------------------
#
# A (…, 64) minor dim pads 2x to the 128-lane tile, so saving flash
# residuals separately doubles their HBM footprint (the difference between
# remat_mode="attn_saved" fitting a 303M model on one v5e chip or OOMing
# by 3 GB).  When 2*d fills the lane tile exactly, the custom-vjp instead
# saves two lane-full arrays — qo = concat(q, out) and kv = concat(k, v) —
# and these kernels slice the halves in VMEM and derive the delta term
# (rowsum(do*o)) on the fly, so no unpack copies ever reach HBM.

def _flash_dq_kernel_res_packed(qo_ref, kv_ref, do_ref, lse_ref, dq_ref, *,
                                block_k: int, causal: bool, scale: float):
    d = do_ref.shape[3]
    q = qo_ref[0, 0, :, :d]                            # (TQ, D) raw dtype
    o = qo_ref[0, 0, :, d:]
    do = do_ref[0, 0]
    lse = lse_ref[0, 0, :, 0]                          # (TQ,)
    delta = (do.astype(jnp.float32) * o.astype(jnp.float32)).sum(-1)
    tq = q.shape[0]
    n = kv_ref.shape[2]
    q0 = pl.program_id(2) * tq

    def body(s, dq):
        kv = kv_ref[0, 0, pl.dslice(s * block_k, block_k), :]
        k = kv[:, :d]
        v = kv[:, d:]
        sc = _mm_t(q, k) * scale
        if causal:
            sc = _causal_mask(sc, q0, s * block_k)
        p = jnp.exp(sc - lse[:, None])
        ds = p * (_mm_t(do, v) - delta[:, None])
        return dq + _mm(ds.astype(k.dtype), k)

    n_blocks = n // block_k
    n_run = jnp.minimum(n_blocks, (q0 + tq + block_k - 1) // block_k) \
        if causal else n_blocks
    dq = jax.lax.fori_loop(0, n_run, body,
                           jnp.zeros((tq, d), jnp.float32))
    dq_ref[0, 0] = (dq * scale).astype(dq_ref.dtype)


def _flash_dkv_kernel_res_packed(kv_ref, qo_ref, do_ref, lse_ref,
                                 dk_ref, dv_ref, *, block_q: int,
                                 causal: bool, scale: float):
    d = do_ref.shape[3]
    kv = kv_ref[0, 0]
    k = kv[:, :d]                                      # (TK, D) raw dtype
    v = kv[:, d:]
    tk = k.shape[0]
    n = qo_ref.shape[2]
    k0 = pl.program_id(2) * tk

    def body(i, carry):
        dk, dv = carry
        qo = qo_ref[0, 0, pl.dslice(i * block_q, block_q), :]
        q = qo[:, :d]
        o = qo[:, d:]
        do = do_ref[0, 0, pl.dslice(i * block_q, block_q), :]
        lse = lse_ref[0, 0, pl.dslice(i * block_q, block_q), 0]
        delta = (do.astype(jnp.float32) * o.astype(jnp.float32)).sum(-1)
        sc = _mm_t(q, k) * scale
        if causal:
            sc = _causal_mask(sc, i * block_q, k0)
        p = jnp.exp(sc - lse[:, None])
        ds = p * (_mm_t(do, v) - delta[:, None])
        return dk + _mm_tt(ds.astype(q.dtype), q), \
            dv + _mm_tt(p.astype(do.dtype), do)

    n_blocks = n // block_q
    lo = jnp.minimum(n_blocks, k0 // block_q) if causal else 0
    dk, dv = jax.lax.fori_loop(
        lo, n_blocks, body,
        (jnp.zeros((tk, d), jnp.float32), jnp.zeros((tk, d), jnp.float32)))
    dk_ref[0, 0] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def _flash_pack_res(d: int, n: int) -> bool:
    """Packed residuals: lane-tile-exact pair width and the resident
    family (the streaming family keeps the plain path)."""
    return d == 64 and _flash_resident(n, d)


def _flash_bwd_bhnd_packed(qo, kv, lse, g, causal, block_q, block_k):
    """Blockwise backward from packed residuals (b, h, n, 2d)."""
    b, h, n, d2 = qo.shape
    d = d2 // 2
    scale = 1.0 / (d ** 0.5)
    bq = _flash_block(n, block_q, d)
    bk = _flash_block(n, block_k, d)
    _check_flash_divisible(n, bq, bk)
    blk_qo = pl.BlockSpec((1, 1, bq, d2), lambda i, j, s: (i, j, s, 0))
    blk_kv = pl.BlockSpec((1, 1, bk, d2), lambda i, j, s: (i, j, s, 0))
    blk_do = pl.BlockSpec((1, 1, bq, d), lambda i, j, s: (i, j, s, 0))
    blk_dk = pl.BlockSpec((1, 1, bk, d), lambda i, j, s: (i, j, s, 0))
    full_kv = pl.BlockSpec((1, 1, n, d2), lambda i, j, s: (i, j, 0, 0))
    full_qo = pl.BlockSpec((1, 1, n, d2), lambda i, j, s: (i, j, 0, 0))
    full_do = pl.BlockSpec((1, 1, n, d), lambda i, j, s: (i, j, 0, 0))
    blk_l = pl.BlockSpec((1, 1, bq, 1), lambda i, j, s: (i, j, s, 0))
    full_l = pl.BlockSpec((1, 1, n, 1), lambda i, j, s: (i, j, 0, 0))

    dq = pl.pallas_call(
        functools.partial(_flash_dq_kernel_res_packed, block_k=bk,
                          causal=causal, scale=scale),
        grid=(b, h, n // bq),
        in_specs=[blk_qo, full_kv, blk_do, blk_l],
        out_specs=blk_do,
        out_shape=_out_struct((b, h, n, d), g.dtype, qo),
        interpret=_INTERPRET,
    )(qo, kv, g, lse)

    dk, dv = pl.pallas_call(
        functools.partial(_flash_dkv_kernel_res_packed, block_q=bq,
                          causal=causal, scale=scale),
        grid=(b, h, n // bk),
        in_specs=[blk_kv, full_qo, full_do, full_l],
        out_specs=[blk_dk, blk_dk],
        out_shape=[_out_struct((b, h, n, d), g.dtype, kv),
                   _out_struct((b, h, n, d), g.dtype, kv)],
        interpret=_INTERPRET,
    )(kv, qo, g, lse)
    return dq, dk, dv


def _flash_fwd_t(q, k, v, causal, block_q, block_k):
    out, lse = _flash_fwd_bhnd(q, k, v, causal, block_q, block_k)
    if _flash_pack_res(q.shape[-1], q.shape[2]):
        res = (jnp.concatenate([q, out], -1),
               jnp.concatenate([k, v], -1), lse)
    else:
        res = (q, k, v, out, lse)
    return out, res


def _flash_bwd_t(causal, block_q, block_k, res, g):
    if len(res) == 3:
        qo, kv, lse = res
        return _flash_bwd_bhnd_packed(qo, kv, lse, g, causal,
                                      block_q, block_k)
    q, k, v, o, lse = res
    delta = jnp.einsum("bhnd,bhnd->bhn", g.astype(jnp.float32),
                       o.astype(jnp.float32))[..., None]
    return _flash_bwd_bhnd(q, k, v, lse, delta, g, causal,
                           block_q, block_k)


flash_attention_bhnd.defvjp(_flash_fwd_t, _flash_bwd_t)

__all__ = ["use_pallas", "lrn_fused", "flash_attention",
           "fused_decode_step", "fused_decode_supported",
           "flash_attention_bhnd", "flash_fwd_with_lse",
           "flash_bwd_blocks",
           "fused_relu_lrn_maxpool", "fused_relu_lrn_maxpool_supported",
           "layernorm_fused", "layernorm_fused_supported",
           "int4_matmul", "int4_matmul_supported",
           "int4_matmul_geometry_ok", "int4_matmul_fallback_reason",
           "lora_bgmv", "lora_bgmv_supported",
           "lora_bgmv_geometry_ok", "lora_bgmv_fallback_reason"]


# ---------------------------------------------------------------------------
# fused LayerNorm (transformer block norm; rows x features, f32 stats)
# ---------------------------------------------------------------------------
#
# XLA runs the (16k x 1024) LN pair of a transformer block at ~2.7
# ms/layer fwd+bwd on one v5e chip (multi-pass f32 stat/reduction
# fusions; ~11% of the whole 303M GPT step). These kernels do one pass
# per direction over lane-aligned feature dims: the forward saves
# (mean, rstd) f32 per row; the backward computes dx and accumulates
# dgamma/dbeta partials across the row grid in a revisited output block
# (the TPU grid is sequential, so read-modify-write accumulation is
# race-free).

def _ln_fwd_kernel(x_ref, g_ref, b_ref, y_ref, mean_ref, rstd_ref, *,
                   eps: float):
    x = x_ref[:].astype(jnp.float32)               # (TR, F)
    mean = x.mean(-1, keepdims=True)
    xc = x - mean
    var = (xc * xc).mean(-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    y = xc * rstd * g_ref[:].astype(jnp.float32) + b_ref[:].astype(
        jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)
    mean_ref[:] = mean
    rstd_ref[:] = rstd


def _ln_bwd_kernel(x_ref, mean_ref, rstd_ref, g_ref, dy_ref, dx_ref,
                   dg_ref, db_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        dg_ref[:] = jnp.zeros_like(dg_ref)
        db_ref[:] = jnp.zeros_like(db_ref)

    x = x_ref[:].astype(jnp.float32)
    dy = dy_ref[:].astype(jnp.float32)
    rstd = rstd_ref[:]
    xh = (x - mean_ref[:]) * rstd                  # x-hat
    dxh = dy * g_ref[:].astype(jnp.float32)
    dx = rstd * (dxh - dxh.mean(-1, keepdims=True)
                 - xh * (dxh * xh).mean(-1, keepdims=True))
    dx_ref[:] = dx.astype(dx_ref.dtype)
    dg_ref[:] = dg_ref[:] + (dy * xh).sum(0, keepdims=True)
    db_ref[:] = db_ref[:] + dy.sum(0, keepdims=True)


def _ln_rows(shape):
    rows = 1
    for d in shape[:-1]:
        rows *= d
    return rows


def _ln_tile(rows: int, f: int) -> int:
    """Row tile: ~8 live (tile, F) f32 buffers within ~4 MB."""
    if rows % 8:
        # fail loudly (mirrors _check_flash_divisible): without this the
        # search below would underflow tile to 0 and die with a confusing
        # ZeroDivisionError
        raise ValueError(
            "layernorm_fused: flattened row count %d must be a multiple "
            "of 8; gate callers with layernorm_fused_supported" % rows)
    tile = max(8, (4 * 1024 * 1024 // (8 * 4 * f)) // 8 * 8)
    while rows % tile:
        tile -= 8
    return max(tile, 8)


def layernorm_fused_supported(shape, dtype) -> bool:
    f = shape[-1]
    rows = _ln_rows(shape)
    return (use_pallas() and f % 128 == 0 and f * 4 * 10 < 8 * 1024 * 1024
            and rows % 8 == 0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def layernorm_fused(x: jnp.ndarray, g: jnp.ndarray, b: jnp.ndarray,
                    eps: float = 1e-5) -> jnp.ndarray:
    """LayerNorm over the last dim: one Pallas pass per direction.
    ``layernorm_fused_supported`` gates callers (lane-aligned features,
    row count a multiple of 8)."""
    return _ln_fwd_impl(x, g, b, eps)[0]


def _ln_fwd_impl(x, g, b, eps):
    shape = x.shape
    f = shape[-1]
    rows = _ln_rows(shape)
    x2 = x.reshape(rows, f)
    tile = _ln_tile(rows, f)
    kern = functools.partial(_ln_fwd_kernel, eps=eps)
    row_blk = pl.BlockSpec((tile, f), lambda i: (i, 0))
    stat_blk = pl.BlockSpec((tile, 1), lambda i: (i, 0))
    par_blk = pl.BlockSpec((f,), lambda i: (0,))
    y, mean, rstd = pl.pallas_call(
        kern,
        grid=(rows // tile,),
        in_specs=[row_blk, par_blk, par_blk],
        out_specs=[row_blk, stat_blk, stat_blk],
        out_shape=[_out_struct((rows, f), x.dtype, x),
                   _out_struct((rows, 1), jnp.float32, x),
                   _out_struct((rows, 1), jnp.float32, x)],
        interpret=_INTERPRET,
    )(x2, g, b)
    return y.reshape(shape), (x2, mean, rstd, g)


def _ln_fwd(x, g, b, eps):
    y, res = _ln_fwd_impl(x, g, b, eps)
    return y, res


def _ln_bwd(eps, res, dy):
    x2, mean, rstd, g = res
    rows, f = x2.shape
    shape = dy.shape
    tile = _ln_tile(rows, f)
    row_blk = pl.BlockSpec((tile, f), lambda i: (i, 0))
    stat_blk = pl.BlockSpec((tile, 1), lambda i: (i, 0))
    par_blk = pl.BlockSpec((f,), lambda i: (0,))
    acc_blk = pl.BlockSpec((1, f), lambda i: (0, 0))
    dx, dg, db = pl.pallas_call(
        _ln_bwd_kernel,
        grid=(rows // tile,),
        in_specs=[row_blk, stat_blk, stat_blk, par_blk, row_blk],
        out_specs=[row_blk, acc_blk, acc_blk],
        out_shape=[_out_struct((rows, f), dy.dtype, dy),
                   _out_struct((1, f), jnp.float32, dy),
                   _out_struct((1, f), jnp.float32, dy)],
        interpret=_INTERPRET,
    )(x2, mean, rstd, g, dy.reshape(rows, f))
    return (dx.reshape(shape), dg[0].astype(g.dtype),
            db[0].astype(g.dtype))


layernorm_fused.defvjp(_ln_fwd, _ln_bwd)


# ---------------------------------------------------------------------------
# cached attention (autoregressive decode)
# ---------------------------------------------------------------------------
# One query position against a K/V cache, the per-layer hot op of the KV
# decode scan (models/gpt.py). Batch-1 decode is op-count-bound
# (doc/performance.md round 3): the XLA formulation issues ~6 kernels per
# layer (2 einsums + masked-softmax chain); this is ONE kernel per
# (batch, head) doing scores -> causal mask -> softmax -> PV in VMEM.
# Inference-only (no VJP; the train paths use the flash kernels).


def _cached_attn_kernel(pos_ref, q_ref, k_ref, v_ref, o_ref, *,
                        scale: float):
    # q: (1, 1, 1, D); k/v: (1, 1, S, D) — HEAD-MAJOR cache; pos: scalar
    # int32 (current position; cache entries > pos are masked out)
    q = q_ref[0, 0]                                    # (1, D)
    k = k_ref[0, 0]                                    # (S, D)
    v = v_ref[0, 0]
    # scores stay (1, S): Mosaic's vector ops are 2-D (sublane, lane) —
    # this file's kernels never drop to 1-D iota/reduce shapes
    s = _mm_t(q, k) * scale                            # (1, S) f32
    idx = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(idx <= pos_ref[0], s, _NEG_INF)
    m = jnp.max(s, axis=1, keepdims=True)              # (1, 1)
    p = jnp.exp(s - m)
    o = _mm(p.astype(v.dtype), v)                      # (1, D) f32
    o_ref[0, 0] = (o / jnp.sum(p, axis=1, keepdims=True)).astype(o_ref.dtype)


def cached_attention_supported(cache_shape) -> bool:
    """(b, h, S, d) head-major cache with lane-aligned d. OPT-IN
    (CXN_PALLAS_DECODE=1): measured NEUTRAL on the 85M batch-1 decode
    (0.73-0.83 ms/token both ways across repeated A/Bs on one v5e chip) —
    XLA already fuses the masked-softmax chain between the two tiny
    einsums, so the op-count reduction buys no wall-clock. Kept as the
    measured alternative and the single-kernel form of the op."""
    import os
    _, _, s, d = cache_shape
    return (os.environ.get("CXN_PALLAS_DECODE", "0") == "1"
            and use_pallas() and d % 128 in (0, 64) and s % 8 == 0)


def cached_attention(q: jnp.ndarray, ck: jnp.ndarray, cv: jnp.ndarray,
                     pos) -> jnp.ndarray:
    """q (b, h, 1, d) against HEAD-MAJOR caches (b, h, S, d); positions >
    ``pos`` (traced int32 scalar) are masked. Returns (b, h, 1, d) in q's
    dtype — the Pallas form of models/gpt.py:_attn_cached."""
    b, h, s, d = ck.shape
    scale = 1.0 / (d ** 0.5)
    kern = functools.partial(_cached_attn_kernel, scale=scale)
    out = pl.pallas_call(
        kern,
        grid=(b, h),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, 1, d), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec((1, 1, s, d), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec((1, 1, s, d), lambda i, j: (i, j, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, 1, d), lambda i, j: (i, j, 0, 0)),
        out_shape=_out_struct((b, h, 1, d), q.dtype, q),
        interpret=_INTERPRET,
    )(jnp.asarray(pos, jnp.int32).reshape(1), q, ck, cv)
    return out


# ---------------------------------------------------------------------------
# fused paged-attention decode (serve_tick / serve_verify_chunk)
# ---------------------------------------------------------------------------
# The paged serve programs' gather formulation (serve/engine.py
# _gather_rows + _attn_cached_rows/_attn_verify) makes XLA materialize
# every row's logical (H, row_len, d) K/V cache in HBM before attention
# — a copy the hardware never needed. This kernel walks each row's block
# table DIRECTLY: grid (rows, blocks_per_row) with the table and the
# per-row positions as scalar-prefetch operands, so each grid step DMAs
# exactly ONE physical (H, bs, d) block of each pool out of HBM into a
# VMEM-resident row image, and the q·K / masked softmax / ·V chain runs
# in the same pass — gathered caches exist only in VMEM, never in HBM.
#
# Numerics contract (serve/engine.py fused_attn_tolerance — the ONE
# place it is defined): the compute step reproduces the gather
# reference's arithmetic EXACTLY — q and the row image cast to f32, one
# head-batched dot_general (batch dim = heads, the einsum's own dims),
# the same / sqrt(d), the same -1e30 position mask, jax.nn.softmax, and
# a head-batched f32 ·V — so in interpret mode on CPU the fused and
# gather programs are bit-identical (pinned by tests/test_serve_fused.py;
# a per-head 2-D dot formulation measurably diverges in f32 low-order
# bits because XLA lowers differently-shaped contractions with different
# reduction orders). On a real TPU the Mosaic lowering may still differ
# from XLA's in low-order bits, which is what the tolerance helper's
# accelerator branch bounds.
#
# Masking carries the whole correctness argument, same as the gather
# path: garbage blocks (a table's unallocated tail points at block 0)
# and parked rows only ever contribute score columns strictly above the
# row's position, which the -1e30 mask softmaxes to an exact 0.0.

# VMEM budget of the RESIDENT formulation's two (H, row_len, d) row
# images. Module-level (not inlined in the gate) so differential tests
# can shrink it and drive a small geometry across the resident ->
# streaming crossover the way they flip _INTERPRET.
_PAGED_RESIDENT_VMEM = 12 * 1024 * 1024


def _paged_row_vmem(n_head: int, bpr: int, block_size: int,
                    head_dim: int, itemsize: int) -> int:
    """Bytes of one row's TWO (H, row_len, d) VMEM images — what the
    resident formulation must hold at once."""
    s = bpr * block_size
    vmem = 2 * n_head * s * head_dim * itemsize
    if itemsize == 1:
        # per-block-scaled int8 pool (serve_kv_dtype=int8): the row
        # image also holds the two scale planes — budget them at f32,
        # the widest compute dtype they can carry
        vmem += 2 * n_head * s * 4
    return vmem


def _paged_alignment_ok(block_size: int, head_dim: int) -> bool:
    """Lane-friendly head_dim / sublane-aligned block size — the Mosaic
    tiling constraints BOTH fused formulations share."""
    return head_dim % 128 in (0, 64) and block_size % 8 == 0


def paged_attention_geometry_ok(n_head: int, bpr: int, block_size: int,
                                head_dim: int,
                                itemsize: int = 2) -> bool:
    """The TPU-geometry half of the RESIDENT fused-attention gate:
    lane-friendly head_dim / sublane-aligned block size, and the two
    (H, row_len, d) VMEM row images within budget. Split out so
    surfaces that audit off-TPU (tools/cxn_lint.py arming interpret
    mode) can still decide whether a REAL TPU would resolve fused or
    gather for this geometry — auditing a fused program production
    would never run pins the wrong executable. Row images past the
    budget are no longer a fused fallback: they stream
    (:func:`paged_attention_streaming_ok`)."""
    if _paged_row_vmem(n_head, bpr, block_size, head_dim,
                       itemsize) > _PAGED_RESIDENT_VMEM:
        return False
    return _paged_alignment_ok(block_size, head_dim)


def paged_attention_streaming_ok(n_head: int, bpr: int, block_size: int,
                                 head_dim: int,
                                 itemsize: int = 2) -> bool:
    """The STREAMING formulation's gate: same alignment constraints as
    the resident form, but VMEM holds only one (H, bs, d) block pair
    plus the f32 running accumulators — O(block), independent of
    row_len — so any row length the pool can hold qualifies. The one
    remaining footprint check keeps a pathological single BLOCK inside
    the resident budget (a block that large would already have failed
    upstream sizing)."""
    if not _paged_alignment_ok(block_size, head_dim):
        return False
    return _paged_row_vmem(n_head, 1, block_size, head_dim,
                           itemsize) <= _PAGED_RESIDENT_VMEM


def paged_attention_formulation(n_head: int, bpr: int, block_size: int,
                                head_dim: int,
                                itemsize: int = 2) -> str:
    """Which fused formulation serves this geometry: ``"resident"``
    (whole row image in VMEM, bit-exact against the gather reference in
    interpret mode), ``"streaming"`` (online-softmax accumulation
    across the blocks-per-row grid dimension — rows past the resident
    VMEM budget stay fused; numerics under the ``streaming`` branch of
    serve/engine.py:fused_attn_tolerance), or ``""`` (unsupported —
    the engine keeps the XLA gather formulation).

    Interpret mode waives the ALIGNMENT limits (tiny differential-test
    models run), but the VMEM crossover still decides resident vs
    streaming, so tests — and a shrunken ``_PAGED_RESIDENT_VMEM`` —
    exercise the same formulation a real TPU would pick."""
    if os.environ.get("CXN_FUSED_ATTN", "1") == "0":
        return ""
    if not use_pallas():
        return ""
    resident_fits = _paged_row_vmem(
        n_head, bpr, block_size, head_dim,
        itemsize) <= _PAGED_RESIDENT_VMEM
    if _INTERPRET:
        return "resident" if resident_fits else "streaming"
    if resident_fits and _paged_alignment_ok(block_size, head_dim):
        return "resident"
    if paged_attention_streaming_ok(n_head, bpr, block_size, head_dim,
                                    itemsize):
        return "streaming"
    return ""


def paged_attention_supported(n_head: int, bpr: int, block_size: int,
                              head_dim: int, itemsize: int = 2) -> bool:
    """True when :func:`paged_attention` may serve this geometry under
    EITHER formulation: TPU backend (or interpret mode under test —
    there the alignment limits are waived, so tiny differential-test
    models run), the off-switch ``CXN_FUSED_ATTN=0`` not thrown, and
    a formulation whose gate holds. Beyond any of these the engine
    keeps the XLA gather formulation (doc/serving.md \"Fused paged
    attention\" records when and why)."""
    return paged_attention_formulation(n_head, bpr, block_size,
                                       head_dim, itemsize) != ""


def paged_attention_fallback_reason(n_head: int, bpr: int,
                                    block_size: int, head_dim: int,
                                    itemsize: int = 2) -> str:
    """Why the support gate rejected this geometry — ``"env_off"``
    (``CXN_FUSED_ATTN=0``), ``"backend"`` (no TPU and no interpret
    mode), or ``"geometry"`` (alignment fails both formulations) —
    or ``""`` when fused is supported. The engine logs this once and
    counts it in ``cxn_fused_fallback_total{reason=}`` so a fleet
    silently serving the slow gather path shows up on a dashboard."""
    if os.environ.get("CXN_FUSED_ATTN", "1") == "0":
        return "env_off"
    if not use_pallas():
        return "backend"
    if paged_attention_formulation(n_head, bpr, block_size, head_dim,
                                   itemsize) == "":
        return "geometry"
    return ""


def _paged_attn_kernel(table_ref, pos_ref, q_ref, k_ref, v_ref, *rest,
                       bs: int, bpr: int, n_head: int, rows: int,
                       quant: bool = False):
    """One grid step = one (slot row, logical block): copy the DMA'd
    physical block into the row image scratch; the LAST block of each
    row runs the attention over the completed image. Scalar-prefetched
    ``table`` drives the block DMAs (the index_map reads it), so the
    gather IS the block pipeline — no HBM intermediate ever exists.

    ``quant`` (serve_kv_dtype=int8): two extra operands/scratches carry
    the per-(head, token) scale planes; the block copy moves the stored
    int8 payload (half the DMA bytes — the point), and the finalize
    step dequantizes the completed row image IN VMEM exactly as the
    gather formulation's ``engine._kv_dequant`` does (int8 -> the scale
    dtype, times the scale, THEN the attention's f32 cast), so
    interpret mode stays bit-exact against the gather reference."""
    if quant:
        sk_ref, sv_ref, o_ref, k_scr, v_scr, sk_scr, sv_scr = rest
    else:
        o_ref, k_scr, v_scr = rest
    i = pl.program_id(0)
    j = pl.program_id(1)
    k_scr[:, pl.dslice(j * bs, bs), :] = k_ref[0, 0]
    v_scr[:, pl.dslice(j * bs, bs), :] = v_ref[0, 0]
    if quant:
        sk_scr[:, pl.dslice(j * bs, bs)] = sk_ref[0, 0]
        sv_scr[:, pl.dslice(j * bs, bs)] = sv_ref[0, 0]

    @pl.when(j == bpr - 1)
    def _finalize():
        s_len = bpr * bs
        d = q_ref.shape[-1]
        if quant:
            kk = k_scr[:].astype(sk_scr.dtype) * sk_scr[:][..., None]
            vv = v_scr[:].astype(sv_scr.dtype) * sv_scr[:][..., None]
        else:
            kk, vv = k_scr[:], v_scr[:]
        # EXACT mirror of _attn_cached_rows/_attn_verify (serve/engine
        # .py): head-major f32 q, ONE head-batched dot (batch dim 0 =
        # heads — the einsum's own contraction), then / sqrt(d)
        qh = jnp.swapaxes(q_ref[0], 0, 1).astype(jnp.float32)  # (H, R, d)
        sc = jax.lax.dot_general(
            qh, kk.astype(jnp.float32),
            (((2,), (2,)), ((0,), (0,)))) / (d ** 0.5)         # (H, R, S)
        kpos = jax.lax.broadcasted_iota(jnp.int32,
                                        (n_head, rows, s_len), 2)
        qpos = pos_ref[i] + jax.lax.broadcasted_iota(
            jnp.int32, (n_head, rows, s_len), 1)
        w = jax.nn.softmax(jnp.where(kpos <= qpos, sc, _NEG_INF),
                           axis=-1)
        o = jax.lax.dot_general(
            w, vv.astype(jnp.float32),
            (((2,), (1,)), ((0,), (0,))))                      # (H, R, d)
        o_ref[0] = jnp.swapaxes(o, 0, 1).astype(o_ref.dtype)


def _paged_attn_stream_kernel(table_ref, pos_ref, q_ref, k_ref, v_ref,
                              *rest, bs: int, bpr: int, n_head: int,
                              rows: int, quant: bool = False):
    """STREAMING formulation: one grid step = one (slot row, logical
    block), but instead of building a whole-row VMEM image it folds the
    block straight into flash-style running accumulators (the
    ``_flash_kernel`` machinery re-cut over the block-table grid):
    per-(head, query) running max ``m``, softmax denominator ``l`` and
    un-normalized output ``acc`` persist in scratch across the
    blocks-per-row grid dimension, and the LAST block normalizes into
    the output. VMEM is O(block) — one (H, bs, d) K/V pair plus the
    f32 accumulators — so row images past the resident budget stay
    fused (the long-context gate, ``paged_attention_streaming_ok``).

    Numerics: the per-block masked scores are the same f32 arithmetic
    as the resident kernel's, but the softmax sum and the ·V product
    accumulate block-by-block with rescaling — a reassociation of the
    reference's single-softmax reduction that is NOT bit-identical in
    floating point even in interpret mode. The band lives in the ONE
    contract (serve/engine.py:fused_attn_tolerance, ``streaming``
    formulation); the masking argument is unchanged — a fully-masked
    garbage block contributes an exact 0.0 to ``l`` and ``acc``
    (``exp(-1e30 - m)`` underflows to 0, and the correction factor is
    exp(0) = 1 because ``m`` never decreases)."""
    if quant:
        sk_ref, sv_ref, o_ref, acc_scr, m_scr, l_scr = rest
    else:
        o_ref, acc_scr, m_scr, l_scr = rest
    i = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    d = q_ref.shape[-1]
    if quant:
        # in-VMEM dequant of ONE block, mirroring engine._kv_dequant
        # (int8 -> scale dtype, times the scale, THEN the f32 cast)
        kk = k_ref[0, 0].astype(sk_ref.dtype) * sk_ref[0, 0][..., None]
        vv = v_ref[0, 0].astype(sv_ref.dtype) * sv_ref[0, 0][..., None]
    else:
        kk, vv = k_ref[0, 0], v_ref[0, 0]                  # (H, bs, d)
    qh = jnp.swapaxes(q_ref[0], 0, 1).astype(jnp.float32)  # (H, R, d)
    sc = jax.lax.dot_general(
        qh, kk.astype(jnp.float32),
        (((2,), (2,)), ((0,), (0,)))) / (d ** 0.5)         # (H, R, bs)
    kpos = j * bs + jax.lax.broadcasted_iota(
        jnp.int32, (n_head, rows, bs), 2)
    qpos = pos_ref[i] + jax.lax.broadcasted_iota(
        jnp.int32, (n_head, rows, bs), 1)
    sc = jnp.where(kpos <= qpos, sc, _NEG_INF)
    m_prev = m_scr[:, :, 0]                                # (H, R)
    m_new = jnp.maximum(m_prev, sc.max(-1))
    corr = jnp.exp(m_prev - m_new)
    p = jnp.exp(sc - m_new[:, :, None])
    l_scr[:, :, 0] = l_scr[:, :, 0] * corr + p.sum(-1)
    acc_scr[:] = acc_scr[:] * corr[:, :, None] + jax.lax.dot_general(
        p, vv.astype(jnp.float32), (((2,), (1,)), ((0,), (0,))))
    m_scr[:, :, 0] = m_new

    @pl.when(j == bpr - 1)
    def _finalize():
        # pos >= 0 guarantees block 0's first column is unmasked, so l
        # is never 0 in practice; the clamp matches _flash_kernel's
        l = jnp.maximum(l_scr[:, :, 0], 1e-30)
        o_ref[0] = jnp.swapaxes(acc_scr[:] / l[:, :, None],
                                0, 1).astype(o_ref.dtype)


def paged_attention(q, pool_k, pool_v, table, pos, layer: int,
                    block_size: int, scale_k=None, scale_v=None,
                    streaming: bool = False):
    """Fused block-table gather + cached attention for the paged decode
    programs. ``q`` (b, R, H, d) — R = 1 for the batched tick, K+1 for
    the draft-and-verify step; ``pool_k``/``pool_v`` the WHOLE
    (L, num_blocks, H, bs, d) pools (only the table's blocks of
    ``layer`` are ever DMA'd); ``table`` (b, bpr) int32 physical block
    ids; ``pos`` (b,) int32 — query r of row i is masked at absolute
    position ``pos[i] + r``, the union of the tick's (R=1) and the
    verify's masking semantics. Returns (b, R, H, d) in q's dtype.

    ``scale_k``/``scale_v`` (both or neither): the (L, num_blocks, H,
    bs) scale planes of a per-block-scaled int8 pool
    (serve_kv_dtype=int8) — the kernel then DMAs int8 payload blocks
    plus their scales and dequantizes the row image in VMEM
    (_paged_attn_kernel ``quant`` path).

    ``streaming`` selects the online-softmax formulation
    (_paged_attn_stream_kernel): same grid, same operands, same
    output, but VMEM O(block) instead of O(row) — the long-context
    form, selected by the engine when
    :func:`paged_attention_formulation` says so. Both formulations
    share one abstract signature per geometry; the flag is a builder
    constant, never a traced value."""
    b, rows, n_head, d = q.shape
    bpr = table.shape[1]
    bs = int(block_size)
    quant = scale_k is not None
    kern = functools.partial(
        _paged_attn_stream_kernel if streaming else _paged_attn_kernel,
        bs=bs, bpr=bpr, n_head=n_head, rows=rows, quant=quant)
    in_specs = [
        pl.BlockSpec((1, rows, n_head, d),
                     lambda i, j, tab, pp: (i, 0, 0, 0)),
        pl.BlockSpec((1, 1, n_head, bs, d),
                     lambda i, j, tab, pp: (layer, tab[i, j],
                                            0, 0, 0)),
        pl.BlockSpec((1, 1, n_head, bs, d),
                     lambda i, j, tab, pp: (layer, tab[i, j],
                                            0, 0, 0)),
    ]
    if streaming:
        # O(block) VMEM: the flash-style running accumulators persist
        # across the blocks-per-row grid dim; no row image exists
        scratch = [
            pltpu.VMEM((n_head, rows, d), jnp.float32),     # acc
            pltpu.VMEM((n_head, rows, 1), jnp.float32),     # m
            pltpu.VMEM((n_head, rows, 1), jnp.float32),     # l
        ]
    else:
        scratch = [
            pltpu.VMEM((n_head, bpr * bs, d), pool_k.dtype),
            pltpu.VMEM((n_head, bpr * bs, d), pool_v.dtype),
        ]
    operands = (table, pos, q, pool_k, pool_v)
    if quant:
        in_specs += [
            pl.BlockSpec((1, 1, n_head, bs),
                         lambda i, j, tab, pp: (layer, tab[i, j], 0, 0)),
            pl.BlockSpec((1, 1, n_head, bs),
                         lambda i, j, tab, pp: (layer, tab[i, j], 0, 0)),
        ]
        if not streaming:
            # the streaming kernel dequantizes each block inline; only
            # the resident row image carries whole-row scale planes
            scratch += [
                pltpu.VMEM((n_head, bpr * bs), scale_k.dtype),
                pltpu.VMEM((n_head, bpr * bs), scale_v.dtype),
            ]
        operands += (scale_k, scale_v)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, bpr),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, rows, n_head, d),
                               lambda i, j, tab, pp: (i, 0, 0, 0)),
        scratch_shapes=scratch,
    )
    return pl.pallas_call(
        kern, grid_spec=grid_spec,
        out_shape=_out_struct((b, rows, n_head, d), q.dtype, q),
        interpret=_INTERPRET,
    )(*operands)


def paged_attention_sharded(q, pool_k, pool_v, table, pos, layer: int,
                            block_size: int, mesh, scale_k=None,
                            scale_v=None, streaming: bool = False):
    """:func:`paged_attention` shard_mapped over ``mesh``'s model axis:
    each shard runs the SAME kernel on its LOCAL head slice — q and
    the pools arrive head-sharded from the engine's gather-form TP
    placement (serve/engine.py: w_qkv output-sharded, the KV pool on
    axis 2), the block table and positions replicated — so a Mosaic
    custom call GSPMD cannot partition becomes N independent per-shard
    calls with ZERO collectives inside the wrap. Heads are independent
    in attention, so each shard's output rows are exactly the
    single-device kernel's rows for those heads: TP-fused decode stays
    under the same single-device tolerance contract. The engine
    re-replicates the output at the block boundary exactly as the
    gather formulation does (the one all-gather either path pays)."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P
    from ..parallel.mesh import MODEL_AXIS
    hsp = P(None, None, MODEL_AXIS, None)          # q / scales / out
    psp = P(None, None, MODEL_AXIS, None, None)    # pools (head axis 2)
    rep = P()
    quant = scale_k is not None

    def local(qs, pk, pv, tab, pp, sk, sv):
        return paged_attention(qs, pk, pv, tab, pp, layer, block_size,
                               scale_k=sk, scale_v=sv,
                               streaming=streaming)

    if quant:
        fn = shard_map(local, mesh=mesh,
                       in_specs=(hsp, psp, psp, rep, rep, hsp, hsp),
                       out_specs=hsp, check_rep=False)
        return fn(q, pool_k, pool_v, table, pos, scale_k, scale_v)
    fn = shard_map(lambda qs, pk, pv, tab, pp: local(qs, pk, pv, tab,
                                                     pp, None, None),
                   mesh=mesh, in_specs=(hsp, psp, psp, rep, rep),
                   out_specs=hsp, check_rep=False)
    return fn(q, pool_k, pool_v, table, pos)


# ---------------------------------------------------------------------------
# fused whole-step decode kernel (round 4)
# ---------------------------------------------------------------------------
# The round-3 decode analysis (doc/performance.md) isolated batch-1 decode's
# binding constraint as per-layer op DISPATCH plus O(cache) scan work — not
# weight streaming — and named this kernel as the fix: ONE Pallas dispatch
# per decode step runs the entire layer stack (layer-major grid; each grid
# step = LN1 -> fused-QKV matmul -> cache-window update -> cached attention
# over every head -> proj + residual -> LN2 -> MLP + residual). Each layer's
# updated aligned 8-row cache window is emitted stacked; the caller splices
# it back with one dynamic_update_slice per cache (in place, because the
# caches are token-loop carries). Inference-only, single-device (a Mosaic
# custom call cannot be GSPMD-partitioned; sharded decode keeps the XLA
# scan).


def _scoped_vmem_kib() -> int:
    """The configured --xla_tpu_scoped_vmem_limit_kib (default 16 MB)."""
    import re
    m = re.search(r"--xla_tpu_scoped_vmem_limit_kib=(\d+)",
                  os.environ.get("LIBTPU_INIT_ARGS", ""))
    return int(m.group(1)) if m else 16384


def fused_decode_supported(cache_shape, n_head: int, feat: int,
                           itemsize: int = 2,
                           weight_itemsize: int = None,
                           head_bytes: int = 0) -> bool:
    """Whole-step fused decode: head-major (b, h, S, d) caches,
    lane-friendly dims, and a scoped-VMEM budget that covers one layer's
    resident weights + one row's caches with the pipeline's double
    buffering (~2.2x; compile fails with a scoped-vmem OOM otherwise —
    bench.py and the GPT example set --xla_tpu_scoped_vmem_limit_kib=
    65536). Batch rows run on consecutive layer-major grid steps, so the
    weight stream is amortized over the batch (measured: batch 8 decodes
    6,300 tok/s aggregate vs 1,235 unfused, batch 32 8,240 vs 930). ``itemsize``: compute-dtype
    bytes (2 bf16 / 4 f32). Auto-engaged by the decode path when neither
    the mesh nor the param placements shard model/pipe/seq/expert dims
    (models/gpt.py)."""
    b, h, s, d = cache_shape
    if weight_itemsize is None:
        weight_itemsize = itemsize      # int8 decode passes 1
    # head_bytes: the resident (feat, vocab) head matrix of the folded
    # greedy path — its gate is evaluated SEPARATELY by gpt_decode so a
    # too-large head only drops the fold, never the fused kernel itself
    layer_bytes = (12 * feat * feat * weight_itemsize
                   + (2 * n_head * s * d + b * feat) * itemsize)
    need_kib = int(2.2 * layer_bytes + head_bytes) // 1024
    return (use_pallas() and h == n_head and d * n_head == feat
            and d % 64 == 0 and s % 8 == 0 and feat % 128 == 0
            and b <= 64 and _scoped_vmem_kib() >= need_kib
            and os.environ.get("CXN_FUSED_DECODE", "1") == "1")


def _decode_token_kernel(pos_ref, h_ref, ln1g_ref, ln1b_ref, wqkv_ref,
                         bqkv_ref, wproj_ref, bproj_ref, ln2g_ref, ln2b_ref,
                         wm1_ref, bm1_ref, wm2_ref, bm2_ref, ck_ref, cv_ref,
                         *rest, n_head: int, eps: float = 1e-5,
                         quantized: bool = False, with_head: bool = False):
    """One grid step = one transformer layer of one batch row; grid =
    (layer, batch) — LAYER-MAJOR, so the batch rows of a layer run on
    consecutive grid steps and pallas's block pipeline fetches each
    layer's weights from HBM exactly ONCE per token (revisited blocks are
    not re-DMA'd), amortizing the weight stream over the whole batch.
    The per-row hidden states ride VMEM scratch (B, 1, F) across the
    layer steps (TPU grid steps are sequential), so a WHOLE decode step
    is ONE kernel dispatch.

    ``quantized``: the four matmul weight refs hold INT8 (per-out-column
    symmetric) and four f32 scale refs follow ck/cv in ``rest`` —
    weights stream HBM->VMEM at HALF the bf16 bytes (decode is weight-
    bandwidth-bound: the round-5 XPlane decomposition put this kernel at
    98.5% of the bf16 streaming floor, so halving the bytes is the one
    remaining lever). Dequant = in-kernel astype + one row-scale
    multiply after each matmul (per-column scales commute with the
    contraction).

    ``with_head``: three more refs (lnf gain/bias + the LM head matrix)
    follow, and the first OUTPUT ref is the (b, 1) int32 GREEDY token
    instead of the hidden state — the whole next-token computation
    (final LN -> head matmul -> argmax) stays in the kernel, removing
    the per-token glue ops whose dispatch gaps the round-5 decomposition
    measured at ~0.09 ms/token."""
    rest = list(rest)
    if quantized:
        sqkv_ref, sproj_ref, sm1_ref, sm2_ref = rest[:4]
        rest = rest[4:]
    if with_head:
        lnfg_ref, lnfb_ref, whead_ref = rest[:3]
        rest = rest[3:]
    out_ref, kwin_ref, vwin_ref, h_scr = rest
    li = pl.program_id(0)
    bi = pl.program_id(1)
    pos = pos_ref[0]

    def scaled(acc, s_ref):
        """Apply the per-out-column dequant scale to a matmul result."""
        return acc * s_ref[0] if quantized else acc

    @pl.when(li == 0)
    def _():
        h_scr[bi] = h_ref[0]

    x = h_scr[bi]                                      # (1, F)
    f = x.shape[-1]
    d = f // n_head
    scale = 1.0 / (d ** 0.5)

    def ln(xf, g_ref, b_ref):
        mu = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(xf - mu), axis=-1, keepdims=True)
        return ((xf - mu) * jax.lax.rsqrt(var + eps)
                * g_ref[0].astype(jnp.float32)
                + b_ref[0].astype(jnp.float32))

    def wload(ref):
        # int8 weights convert to the compute dtype AFTER the (halved)
        # HBM->VMEM stream; the converts ride the VPU under the next
        # layer's weight DMA
        return ref[0].astype(x.dtype) if quantized else ref[0]

    xf = x.astype(jnp.float32)
    xn = ln(xf, ln1g_ref, ln1b_ref).astype(x.dtype)
    qkv = scaled(_mm(xn, wload(wqkv_ref)), sqkv_ref if quantized
                 else None) \
        + bqkv_ref[0].astype(jnp.float32)            # (1, 3F) f32
    q = qkv[:, :f]
    kfr = [qkv[:, f + hd * d:f + (hd + 1) * d].astype(ck_ref.dtype)
           for hd in range(n_head)]
    vfr = [qkv[:, 2 * f + hd * d:2 * f + (hd + 1) * d].astype(cv_ref.dtype)
           for hd in range(n_head)]
    base = (pos // 8) * 8
    rowi = jax.lax.broadcasted_iota(jnp.int32, (8, 1), 0) + base
    for hd in range(n_head):
        win_k = ck_ref[0, 0, hd, pl.dslice(base, 8), :]     # (8, D)
        win_v = cv_ref[0, 0, hd, pl.dslice(base, 8), :]
        kwin_ref[0, 0, hd] = jnp.where(rowi == pos, kfr[hd], win_k)
        vwin_ref[0, 0, hd] = jnp.where(rowi == pos, vfr[hd], win_v)

    rows = [_mm_t(q[:, hd * d:(hd + 1) * d].astype(x.dtype),
                  ck_ref[0, 0, hd]) for hd in range(n_head)]
    s = jnp.concatenate(rows, axis=0) * scale           # (H, S) f32
    s_fresh = jnp.concatenate(
        [jnp.sum(q[:, hd * d:(hd + 1) * d]
                 * kfr[hd].astype(jnp.float32), axis=1, keepdims=True)
         for hd in range(n_head)], axis=0) * scale      # (H, 1)
    idx = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(idx == pos, s_fresh, s)
    s = jnp.where(idx <= pos, s, _NEG_INF)
    m = jnp.max(s, axis=1, keepdims=True)
    p = jnp.exp(s - m)
    p = p / jnp.sum(p, axis=1, keepdims=True)           # (H, S) f32
    p_pos = jnp.sum(jnp.where(idx == pos, p, 0.0), axis=1, keepdims=True)
    p0 = jnp.where(idx == pos, 0.0, p).astype(cv_ref.dtype)
    att = [_mm(p0[hd:hd + 1], cv_ref[0, 0, hd])
           + p_pos[hd:hd + 1] * vfr[hd].astype(jnp.float32)
           for hd in range(n_head)]
    o = jnp.concatenate(att, axis=-1).astype(x.dtype)   # (1, F)
    h2f = xf + scaled(_mm(o, wload(wproj_ref)),
                      sproj_ref if quantized else None) \
        + bproj_ref[0].astype(jnp.float32)

    x2n = ln(h2f, ln2g_ref, ln2b_ref).astype(x.dtype)
    m1 = jnp.maximum(scaled(_mm(x2n, wload(wm1_ref)),
                            sm1_ref if quantized else None)
                     + bm1_ref[0].astype(jnp.float32), 0.0)
    y = scaled(_mm(m1.astype(x.dtype), wload(wm2_ref)),
               sm2_ref if quantized else None)
    new_h = (h2f + y + bm2_ref[0].astype(jnp.float32)).astype(x.dtype)
    h_scr[bi] = new_h

    # the out block (this row) is revisited every layer; guarding on the
    # last layer makes the "last write wins" contract EXPLICIT instead of
    # an implicit Mosaic flush-order assumption (ADVICE r4). The block is
    # still DMA'd back each grid step (bi is the fast dim, so the block
    # index changes every step) — the guard buys correctness-by-
    # construction, not traffic; pre-final flushes just carry don't-care
    # data that the final layer's write overwrites
    @pl.when(li == pl.num_programs(0) - 1)
    def _():
        if with_head:
            hl = ln(new_h.astype(jnp.float32), lnfg_ref, lnfb_ref)
            logits = _mm(hl.astype(x.dtype), whead_ref[...])  # (1, V) f32
            # first-occurrence argmax via 2-D iota (Mosaic rejects 1-D
            # iota; min-index-at-max matches jnp.argmax tie-breaking)
            cols = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
            mx = jnp.max(logits, axis=-1, keepdims=True)
            idx = jnp.min(jnp.where(logits == mx, cols, jnp.int32(1 << 30)),
                          axis=-1, keepdims=True)        # (1, 1)
            out_ref[...] = idx       # 2-D store (Mosaic rejects scalars)
        else:
            out_ref[0] = new_h.astype(out_ref.dtype)


def fused_decode_step(blocks, h, ck, cv, pos, n_head: int, head=None):
    """Run the WHOLE decode step's layer stack as one kernel per batch row.

    blocks: the stacked (L, ...) fused-QKV weight dict, already in the
    compute dtype; h: (b, 1, F); ck/cv: (L, b, H, S, D) stacked head-major
    caches (the prefill layout); pos: traced i32. Returns (h_out, ck', cv')
    with each layer's cache updated at pos via one dynamic_update_slice
    per cache (in-place when ck/cv are loop carries).

    ``head`` (optional): (lnf_g (F,), lnf_b (F,), w_head (F, V)) — fold
    the final LN + LM-head matmul + GREEDY argmax into the kernel; the
    first return becomes the (b, 1) int32 next-token ids. (Folding the
    EMBEDDING lookup in as well was measured a wash — the positional
    table's per-token DMA costs what the removed glue saved — and is
    not offered; doc/performance.md round 5.)
    """
    b, _, f = h.shape
    dt = h.dtype
    nl, _, nh, s, d = ck.shape
    quantized = blocks["w_qkv"].dtype == jnp.int8
    row = lambda a: a.reshape(nl, 1, -1)
    w = {k: blocks[k] for k in ("w_qkv", "w_proj", "w_mlp1", "w_mlp2")}
    v = {k: row(blocks[k]) for k in ("ln1_g", "ln1_b", "b_qkv", "b_proj",
                                     "ln2_g", "ln2_b", "b_mlp1", "b_mlp2")}
    wspec = lambda a: pl.BlockSpec((1,) + a.shape[1:],
                                   lambda li, bi: (li,) + (0,) * (a.ndim - 1))
    vspec = lambda a: pl.BlockSpec((1, 1, a.shape[-1]),
                                   lambda li, bi: (li, 0, 0))
    kern = functools.partial(_decode_token_kernel, n_head=n_head,
                             quantized=quantized,
                             with_head=head is not None)
    extra_args, extra_specs = [], []
    if quantized:
        extra_args += [row(blocks[k]) for k in ("s_qkv", "s_proj",
                                                "s_mlp1", "s_mlp2")]
        extra_specs += [vspec(a) for a in extra_args]
    if head is not None:
        lnf_g, lnf_b, w_head = head
        vocab = w_head.shape[-1]
        extra_args += [lnf_g.reshape(1, -1), lnf_b.reshape(1, -1), w_head]
        extra_specs += [
            pl.BlockSpec((1, f), lambda li, bi: (0, 0)),
            pl.BlockSpec((1, f), lambda li, bi: (0, 0)),
            pl.BlockSpec((f, vocab), lambda li, bi: (0, 0)),
        ]
        out0_spec = pl.BlockSpec((1, 1), lambda li, bi: (bi, 0))
        out0_shape = _out_struct((b, 1), jnp.int32, h)
    else:
        out0_spec = pl.BlockSpec((1, 1, f), lambda li, bi: (bi, 0, 0))
        out0_shape = _out_struct((b, 1, f), dt, h)
    out, kwin, vwin = pl.pallas_call(
        kern,
        grid=(nl, b),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec((1, 1, f), lambda li, bi: (bi, 0, 0)),
                  vspec(v["ln1_g"]), vspec(v["ln1_b"]), wspec(w["w_qkv"]),
                  vspec(v["b_qkv"]), wspec(w["w_proj"]), vspec(v["b_proj"]),
                  vspec(v["ln2_g"]), vspec(v["ln2_b"]), wspec(w["w_mlp1"]),
                  vspec(v["b_mlp1"]), wspec(w["w_mlp2"]), vspec(v["b_mlp2"]),
                  pl.BlockSpec((1, 1, nh, s, d),
                               lambda li, bi: (li, bi, 0, 0, 0)),
                  pl.BlockSpec((1, 1, nh, s, d),
                               lambda li, bi: (li, bi, 0, 0, 0))]
        + extra_specs,
        out_specs=[out0_spec,
                   pl.BlockSpec((1, 1, nh, 8, d),
                                lambda li, bi: (li, bi, 0, 0, 0)),
                   pl.BlockSpec((1, 1, nh, 8, d),
                                lambda li, bi: (li, bi, 0, 0, 0))],
        out_shape=[out0_shape,
                   _out_struct((nl, b, nh, 8, d), ck.dtype, ck),
                   _out_struct((nl, b, nh, 8, d), cv.dtype, cv)],
        scratch_shapes=[pltpu.VMEM((b, 1, f), dt)],
        interpret=_INTERPRET,
    )(jnp.asarray(pos, jnp.int32).reshape(1), h.reshape(b, 1, f),
      v["ln1_g"], v["ln1_b"], w["w_qkv"], v["b_qkv"], w["w_proj"],
      v["b_proj"], v["ln2_g"], v["ln2_b"], w["w_mlp1"], v["b_mlp1"],
      w["w_mlp2"], v["b_mlp2"], ck, cv, *extra_args)
    base = (pos // 8) * 8
    ck2 = jax.lax.dynamic_update_slice(ck, kwin, (0, 0, 0, base, 0))
    cv2 = jax.lax.dynamic_update_slice(cv, vwin, (0, 0, 0, base, 0))
    if head is not None:
        return out, ck2, cv2                   # (b, 1) int32 next tokens
    return out.reshape(b, 1, f), ck2, cv2


# ---------------------------------------------------------------------------
# int4 weight streaming: fused dequant-matmul (packed nibbles, group scales)
# ---------------------------------------------------------------------------
#
# y = x @ dequant(packed) for the serve programs' block matmuls under
# serve_int4_weights=1 (models/gpt.py:_qmat4 routes here; its XLA
# reference _qmat4_ref mirrors this kernel op for op, so interpret-mode
# output is bit-identical). The weight arrives PACKED: a (k, n/2) uint8
# plane whose byte j carries out-columns j (low nibble) and j + n/2
# (high nibble), each stored as code + 8 with code in [-7, 7], plus an
# f32 (G, n) scale plane — one symmetric scale per (group of k rows,
# out column). The grid streams the G row groups through VMEM in the
# PR 16 K-tile idiom: nibble unpack + scale dequant happen INSIDE the
# tile, partial products accumulate in an f32 scratch across the
# sequential grid dim, and the unpacked bf16/f32 weight never exists
# in HBM — the whole point of packing (the decode stream is weight-
# bandwidth-bound; nibbles halve the int8 byte count again).

# per-tile VMEM budget of the dequant-matmul (x tile + packed tile +
# unpack temporaries + f32 accumulator + out tile); module-level so
# tests can shrink it and drive geometries across the fused -> XLA
# reference crossover the way they flip _INTERPRET
_INT4_TILE_VMEM = 12 * 1024 * 1024


def _int4_tile_vmem(m: int, k: int, n: int, groups: int,
                    itemsize: int = 2) -> int:
    """Bytes one (m, k-group, n) grid step holds at once."""
    g0 = k // max(1, groups)
    return (m * g0 * itemsize               # x tile
            + g0 * (n // 2)                 # packed nibble tile
            + g0 * n * (1 + itemsize)       # unpacked i8 + compute cast
            + n * 4                         # scale row (f32)
            + m * n * (4 + itemsize))       # f32 accumulator + out tile


def int4_matmul_geometry_ok(m: int, k: int, n: int, groups: int,
                            itemsize: int = 2) -> bool:
    """The geometry half of the int4 dequant-matmul gate: the scale
    groups must tile the contraction dim exactly (ragged groups keep
    the XLA reference — BlockSpec grids are rectangular), the packed
    column count must be whole bytes, the tile must fit the VMEM
    budget, and on a real TPU the operand dims must be lane/sublane
    friendly (n spanning full 128-lane registers for BOTH the packed
    and unpacked views, the k-group a sublane multiple, m at least one
    sublane). Interpret mode waives the alignment limits (tiny
    differential-test models run) but keeps the structural and VMEM
    checks, so tests exercise the same crossover a real TPU would."""
    if groups < 1 or k % groups or n % 2:
        return False
    if _int4_tile_vmem(m, k, n, groups, itemsize) > _INT4_TILE_VMEM:
        return False
    if _INTERPRET:
        return True
    g0 = k // groups
    return m >= 8 and n % 256 == 0 and g0 % 8 == 0


def int4_matmul_supported(m: int, k: int, n: int, groups: int,
                          itemsize: int = 2) -> bool:
    """True when :func:`int4_matmul` may serve this matmul shape: TPU
    backend (or interpret mode under test), the ``CXN_INT4_MATMUL=0``
    off-switch not thrown, and the geometry gate holds. Anything else
    keeps models/gpt.py's XLA reference ``_qmat4_ref`` — the
    bit-reference the kernel is pinned against."""
    if os.environ.get("CXN_INT4_MATMUL", "1") == "0":
        return False
    return use_pallas() and int4_matmul_geometry_ok(m, k, n, groups,
                                                    itemsize)


def int4_matmul_fallback_reason(m: int, k: int, n: int, groups: int,
                                itemsize: int = 2) -> str:
    """Why the support gate rejected this shape — ``"env_off"``
    (``CXN_INT4_MATMUL=0``), ``"backend"`` (no TPU and no interpret
    mode), ``"geometry"`` — or ``""`` when the kernel serves it. The
    engine logs this once and counts it in
    ``cxn_int4_fallback_total{reason=}`` (serve/engine.py)."""
    if os.environ.get("CXN_INT4_MATMUL", "1") == "0":
        return "env_off"
    if not use_pallas():
        return "backend"
    if not int4_matmul_geometry_ok(m, k, n, groups, itemsize):
        return "geometry"
    return ""


def _int4_matmul_kernel(x_ref, w_ref, s_ref, o_ref, acc_ref):
    """One grid step = one scale group of k rows: unpack the nibble
    tile to i8, cast to the compute dtype (int4 codes are exact in
    bf16's 8 mantissa bits — never a silent f32 widen, the CXN209
    contract), run the MXU partial product with f32 accumulation, and
    scale-dequant the PARTIAL — group scales live on the contraction
    dim, so unlike int8's per-out-column scheme the multiply must land
    before the cross-group sum. The f32 scratch persists across the
    sequential grid dim; the last group casts it into the output."""
    gi = pl.program_id(0)
    ng = pl.num_programs(0)

    @pl.when(gi == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    packed = w_ref[...]                             # (g0, n // 2) uint8
    lo = (packed & jnp.uint8(0xF)).astype(jnp.int8) - 8
    hi = (packed >> jnp.uint8(4)).astype(jnp.int8) - 8
    # byte j holds columns (j, j + n/2): the unpack is a lane concat,
    # never an interleaving relayout
    wq = jnp.concatenate([lo, hi], axis=-1).astype(x_ref.dtype)
    acc_ref[...] += _mm(x_ref[...], wq) * s_ref[...]

    @pl.when(gi == ng - 1)
    def _done():
        o_ref[...] = acc_ref[...].astype(o_ref.dtype)


def int4_matmul(x, packed, scales):
    """``x (m, k) @ dequant(packed (k, n/2) uint8, scales (G, n) f32)``
    -> (m, n) in x's dtype. Callers gate on
    :func:`int4_matmul_supported` — k must split into G equal row
    groups and n into whole bytes (models/gpt.py pads the out dim to
    even at quantize time and the gate rejects ragged groups)."""
    m, k = x.shape
    g = int(scales.shape[0])
    n = int(scales.shape[1])
    assert n == 2 * int(packed.shape[1]), \
        "scale plane n=%d vs packed n/2=%d" % (n, int(packed.shape[1]))
    g0 = k // g
    return pl.pallas_call(
        _int4_matmul_kernel,
        grid=(g,),
        in_specs=[pl.BlockSpec((m, g0), lambda i: (0, i)),
                  pl.BlockSpec((g0, n // 2), lambda i: (i, 0)),
                  pl.BlockSpec((1, n), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((m, n), lambda i: (0, 0)),
        out_shape=_out_struct((m, n), x.dtype, x),
        scratch_shapes=[pltpu.VMEM((m, n), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=_INTERPRET,
    )(x, packed, scales)


# ---------------------------------------------------------------------------
# batched grouped low-rank matmul (multi-LoRA serving, round 20): every
# slot row of one decode tick may carry a DIFFERENT rank-r adapter, so
# the delta matmul is a batch of tiny (n, in) x (in, r) x (r, out)
# products indexed by a per-row adapter id. The kernel rides the paged-
# attention scalar-prefetch idiom: the adapter-id vector is prefetched,
# the index_map gathers row i's A/B factor tiles straight from the
# device adapter pool into VMEM (rows arrive segment-sorted by id, so
# consecutive rows hit the SAME block index and Mosaic skips the
# re-fetch — the sort IS the batching), and the two dots accumulate in
# f32 before folding into the base projection. The XLA reference is the
# ragged grouped dispatch in serve/lora.py (ops/moe.py grouped_order +
# lax.ragged_dot) — op-for-op the same per-row contraction, pinned
# bit-exact in interpret mode.

# per-row VMEM budget of the bgmv tile (x/base tiles + A/B factor pair
# + f32 accumulators); module-level so tests can shrink it and drive
# geometries across the fused -> XLA reference crossover
_LORA_TILE_VMEM = 8 * 1024 * 1024


def _lora_tile_vmem(n: int, d_in: int, r: int, d_out: int,
                    itemsize: int = 2) -> int:
    """Bytes one (row) grid step holds at once."""
    return (n * d_in * itemsize             # x tile
            + d_in * r * itemsize           # A factor tile
            + r * d_out * itemsize          # B factor tile
            + n * r * 4                     # f32 intermediate
            + n * d_out * (4 + 2 * itemsize))   # f32 acc + base + out


def lora_bgmv_geometry_ok(n: int, d_in: int, r: int, d_out: int,
                          itemsize: int = 2) -> bool:
    """The geometry half of the bgmv gate: the factor pair and the f32
    intermediates must fit the per-row VMEM budget, and on a real TPU
    the operand dims must be lane/sublane friendly (in/out spanning
    full 128-lane registers, the rank a sublane multiple — rank 8 is
    the floor). Interpret mode waives the alignment limits (tiny
    differential-test models run) but keeps the VMEM check."""
    if r < 1 or n < 1:
        return False
    if _lora_tile_vmem(n, d_in, r, d_out, itemsize) > _LORA_TILE_VMEM:
        return False
    if _INTERPRET:
        return True
    return r % 8 == 0 and d_in % 128 == 0 and d_out % 128 == 0


def lora_bgmv_supported(n: int, d_in: int, r: int, d_out: int,
                        itemsize: int = 2) -> bool:
    """True when :func:`lora_bgmv` may serve this delta shape: TPU
    backend (or interpret mode under test), the ``CXN_LORA_BGMV=0``
    off-switch not thrown, and the geometry gate holds. Anything else
    keeps serve/lora.py's ragged XLA reference — the bit-reference the
    kernel is pinned against."""
    if os.environ.get("CXN_LORA_BGMV", "1") == "0":
        return False
    return use_pallas() and lora_bgmv_geometry_ok(n, d_in, r, d_out,
                                                  itemsize)


def lora_bgmv_fallback_reason(n: int, d_in: int, r: int, d_out: int,
                              itemsize: int = 2) -> str:
    """Why the support gate rejected this shape — ``"env_off"``
    (``CXN_LORA_BGMV=0``), ``"backend"`` (no TPU and no interpret
    mode), ``"geometry"`` — or ``""`` when the kernel serves it. The
    engine logs this once and counts it in
    ``cxn_lora_fallback_total{reason=}`` (serve/engine.py)."""
    if os.environ.get("CXN_LORA_BGMV", "1") == "0":
        return "env_off"
    if not use_pallas():
        return "backend"
    if not lora_bgmv_geometry_ok(n, d_in, r, d_out, itemsize):
        return "geometry"
    return ""


def _lora_bgmv_kernel(ids_ref, x_ref, y_ref, a_ref, b_ref, o_ref):
    """One grid step = one slot row: two MXU dots through the rank-r
    bottleneck with f32 accumulation (``preferred_element_type``), the
    per-adapter scale already folded into the stored B factor, and the
    delta added to the base projection in f32 before the one cast back
    to the compute dtype — op-for-op the ragged reference's per-row
    contraction (serve/lora.py _delta_ref), so interpret-mode
    bit-identity is a structural property, not a tolerance."""
    del ids_ref                 # consumed by the index_maps
    t = jax.lax.dot_general(
        x_ref[0], a_ref[0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)            # (n, r) f32
    d = jax.lax.dot_general(
        t, b_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)            # (n, out) f32
    o_ref[0] = (y_ref[0].astype(jnp.float32) + d).astype(o_ref.dtype)


def lora_bgmv(x, y, a, b, ids):
    """``y + (x @ a[ids]) @ b[ids]`` per row, f32-accumulated:
    ``x`` (rows, n, d_in) activations, ``y`` (rows, n, d_out) base
    projection, ``a`` (P, d_in, r) / ``b`` (P, r, d_out) the device
    adapter pool's factor planes for ONE site of ONE layer (the
    per-adapter scale is folded into ``b`` at pool build), ``ids``
    (rows,) int32 pool slot per row — scalar-prefetched so the
    index_map gathers each row's factor pair by id (callers pass rows
    segment-sorted by id; consecutive equal ids reuse the resident
    tile). Returns (rows, n, d_out) in y's dtype. Callers gate on
    :func:`lora_bgmv_supported`."""
    rows, n, d_in = x.shape
    d_out = int(y.shape[-1])
    r = int(a.shape[-1])
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(rows,),
        in_specs=[
            pl.BlockSpec((1, n, d_in), lambda i, ids: (i, 0, 0)),
            pl.BlockSpec((1, n, d_out), lambda i, ids: (i, 0, 0)),
            pl.BlockSpec((1, d_in, r), lambda i, ids: (ids[i], 0, 0)),
            pl.BlockSpec((1, r, d_out), lambda i, ids: (ids[i], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, n, d_out), lambda i, ids: (i, 0, 0)),
        scratch_shapes=[],
    )
    return pl.pallas_call(
        _lora_bgmv_kernel, grid_spec=grid_spec,
        out_shape=_out_struct((rows, n, d_out), y.dtype, y),
        interpret=_INTERPRET,
    )(ids, x, y, a, b)
