"""The network trainer — TPU-native equivalent of the reference nnet runtime.

Reference surface (/root/reference/src/nnet/nnet.h:18-92 INetTrainer):
SetParam / InitModel / SaveModel / LoadModel / CopyModelFrom / StartRound /
Update(batch) / Evaluate / Predict / ExtractFeature / SetWeight / GetWeight.

Architecture (vs. reference CXXNetThreadTrainer + NeuralNetThread,
nnet_impl-inl.hpp:15-455, neural_net-inl.hpp:22-628): there are no per-device
worker threads, no replica broadcast, and no parameter server. One jitted SPMD
train step runs over a ``jax.sharding.Mesh``; the batch is sharded along the
``data`` axis, parameters are replicated, and XLA inserts/overlaps the gradient
all-reduce that mshadow-ps Push/PullReq performed (SURVEY §5.8). Gradient
accumulation (``update_period``) and per-tag optimizers keep capability parity.

Key jit facts: the step is traced once per (shapes, do-update-phase); learning
-rate schedules are computed inside the step from the traced epoch scalar, so
no recompilation across epochs. Host batches arrive NCHW (reference layout)
and are transposed to NHWC on device entry — the single-transpose cost is
fused by XLA into the first conv.
"""

from __future__ import annotations

import json
import os
import struct
from functools import partial
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..graph import NetGraph
from ..io.device_prefetch import DeviceBatch
from ..layers import ApplyContext, create_layer
from ..layers.base import Layer
from ..metrics import MetricSet
from ..parallel.distributed import (global_batch, init_distributed,
                                    local_rows)
from ..parallel.mesh import batch_sharding, make_mesh, replicated_sharding
from ..parallel.sharding import resolve_shardings
from ..updaters import create_updater, global_norm_scale
from ..utils.config import ConfigError

_CKPT_MAGIC = b"CXTPU001"


class Net:
    """Config-driven trainer (INetTrainer equivalent)."""

    def __init__(self, cfg: Optional[List[Tuple[str, str]]] = None) -> None:
        self.cfg: List[Tuple[str, str]] = list(cfg) if cfg else []
        self.graph: Optional[NetGraph] = None
        self.layers: List[Layer] = []
        self.params: Dict[str, Dict[str, jnp.ndarray]] = {}
        self.states: Dict[str, dict] = {}
        self.opt_state: Dict[str, Dict[str, dict]] = {}
        self.gsum: Optional[dict] = None
        self.epoch_counter = 0
        self.round = 0
        self.sample_counter = 0
        self._initialized = False
        self._pp_segment = None
        self._remat_segment = None
        self._remat_split = None

    # ------------------------------------------------------------ config
    def set_param(self, name: str, val: str) -> None:
        self.cfg.append((str(name), str(val)))

    def _parse_trainer_cfg(self) -> None:
        g = self.graph
        self.batch_size = 0
        self.update_period = 1
        self.eval_train = 1
        self.device_metrics = 1
        self.seed = 0
        self.dev = ""
        self.model_parallel = 1
        self.seq_parallel = 1
        self.expert_parallel = 1
        self.pipeline_parallel = 1
        self.pipeline_microbatch = 0    # 0 = default to the pipe size
        self.shard_optimizer = 0
        self.dist_feed = "replicated"
        self.clip_norm = 0.0
        self.precision = "float32"
        self.remat = 0
        self.remat_mode = "block"
        # cxn-lint (analysis/): recompilation guard on the hot jitted
        # steps (0 = off; N = max distinct abstract signatures per step),
        # whether a trip raises (strict) or only logs (the CXN_LINT=1
        # log-only hook sets 0), and the per-step collective budget the
        # compiled-step audit pins (-1 = unbudgeted)
        self.lint_recompile_limit = 0
        self.lint_recompile_strict = 1
        self.lint_collective_budget = -1
        # per-step AOT compile-time budget for the compiled-step audit
        # (CXN207; 0 = unbudgeted) — the compile-time regression gate
        # tools/cxn_lint.py --compile enforces in CI
        self.lint_compile_budget_s = 0.0
        # AOT executable cache dir (analysis/aot_cache.py; the
        # CXN_AOT_CACHE env var is the fallback): the four hot jitted
        # steps resolve through it on first call — deserialize-and-load
        # on a key hit instead of compiling, persist-after-compile on a
        # miss — so trainer startup over an unchanged config skips XLA
        # entirely. "" (default) is a pinned no-op.
        self.aot_cache = ""
        # device/compiler observatory (obs/devprof.py): one BLOCKING
        # device-time sample per prof_every train steps publishing
        # cxn_program_seconds / cxn_mfu gauges; 0 (default) keeps the
        # async-dispatch hot loop completely sync-free
        self.prof_every = 0
        self.train_metrics = MetricSet()
        self.eval_metrics = MetricSet()
        for k, v in g.defcfg:
            if k == "batch_size":
                self.batch_size = int(v)
            elif k == "update_period":
                self.update_period = int(v)
            elif k == "eval_train":
                self.eval_train = int(v)
            elif k == "device_metrics":
                # 0 forces the per-step host metric path even for metrics
                # with a device twin (debug / exact-f64-accumulation knob)
                self.device_metrics = int(v)
            elif k == "seed":
                self.seed = int(v)
            elif k == "dev":
                self.dev = v
            elif k == "model_parallel":
                self.model_parallel = int(v)
            elif k == "seq_parallel":
                self.seq_parallel = int(v)
            elif k == "expert_parallel":
                self.expert_parallel = int(v)
            elif k == "pipeline_parallel":
                self.pipeline_parallel = int(v)
            elif k == "pipeline_microbatch":
                self.pipeline_microbatch = int(v)
            elif k in ("shard_optimizer", "zero"):
                # 'zero' is the models/gpt.py name for the same levels
                # (1 = opt state, 2 = + grad reduce-scatter, 3 = FSDP);
                # accepted as an alias so the two surfaces match
                self.shard_optimizer = int(v)
            elif k == "remat":
                self.remat = int(v)
            elif k == "remat_mode":
                if v not in ("block", "attn_saved"):
                    raise ConfigError(
                        "remat_mode must be 'block' or 'attn_saved', "
                        "got %r" % v)
                self.remat_mode = v
            elif k == "pipeline_schedule":
                # the config-DSL pipeline runs the gpipe schedule; 1f1b
                # (manual per-stage VJPs with the loss in the last
                # stage) needs the functional models/gpt.py trainer —
                # reject rather than silently ignore the request
                if v != "gpipe":
                    raise ConfigError(
                        "pipeline_schedule %r is not available on the "
                        "config path (gpipe only); the 1f1b schedule "
                        "lives on the models/gpt.py trainer "
                        "(GPTConfig.pipeline_schedule, "
                        "doc/multi-device.md)" % v)
            elif k == "clip_norm":
                self.clip_norm = float(v)
            elif k == "dist_feed":
                if v not in ("replicated", "sharded"):
                    raise ConfigError(
                        "dist_feed must be 'replicated' or 'sharded'")
                self.dist_feed = v
            elif k == "precision":
                self.precision = v
            elif k == "lint_recompile_limit":
                self.lint_recompile_limit = int(v)
            elif k == "lint_recompile_strict":
                self.lint_recompile_strict = int(v)
            elif k == "lint_collective_budget":
                self.lint_collective_budget = int(v)
            elif k == "lint_compile_budget_s":
                self.lint_compile_budget_s = float(v)
            elif k == "prof_every":
                self.prof_every = int(v)
            elif k == "aot_cache":
                self.aot_cache = v
            elif k.startswith("metric"):
                self.train_metrics.configure(k, v)
                self.eval_metrics.configure(k, v)
        if self.batch_size <= 0:
            raise ConfigError("batch_size must be set")
        if not self.train_metrics.metrics:
            self.train_metrics.add_metric("error")
            self.eval_metrics.add_metric("error")

    # -------------------------------------------------------------- build
    def _build(self, from_loaded_graph: bool = False) -> None:
        """Parse config into graph + layers + shapes (InitNet analogue)."""
        if not from_loaded_graph:
            self.graph = NetGraph().configure(self.cfg)
        else:
            self.graph.configure(self.cfg)
        g = self.graph
        if g.input_shape is None:
            raise ConfigError("input_shape must be set")
        self._parse_trainer_cfg()

        # instantiate layers; shared layers reuse the primary's object+params
        self.layers = []
        for spec in g.layers:
            if spec.type == "share":
                self.layers.append(self.layers[spec.primary])
            else:
                self.layers.append(create_layer(spec, g.defcfg))

        # shape inference over logical (c, y, x) node shapes
        self.node_shapes: List[Optional[Tuple[int, int, int]]] = \
            [None] * g.num_nodes
        self.node_shapes[0] = g.input_shape
        for i in range(g.extra_data_num):
            self.node_shapes[1 + i] = g.extra_shapes[i]
        for spec, layer in zip(g.layers, self.layers):
            in_shapes = []
            for ni in spec.inputs:
                if self.node_shapes[ni] is None:
                    raise ConfigError("node %r used before it is produced"
                                      % g.node_names[ni])
                in_shapes.append(self.node_shapes[ni])
            out_shapes = layer.infer_shapes(in_shapes)
            for ni, s in zip(spec.outputs, out_shapes):
                self.node_shapes[ni] = s

        # join the multi-host runtime first (no-op single-host), then build
        # the mesh over the now-global device set
        init_distributed()
        if jax.process_count() > 1 and \
                self.batch_size % jax.process_count():
            raise ConfigError(
                "batch_size %d must divide the %d-process run"
                % (self.batch_size, jax.process_count()))
        self.mesh = make_mesh(self.dev, self.model_parallel,
                              self.seq_parallel,
                              pipeline_parallel=self.pipeline_parallel,
                              expert_parallel=self.expert_parallel)
        self.n_data_shards = self.mesh.shape["data"]
        if self.batch_size % self.n_data_shards:
            raise ConfigError(
                "batch_size %d must divide the %d-way data mesh"
                % (self.batch_size, self.n_data_shards))

        # config-DSL pipeline parallelism: detect the repeated block
        # segment now so misconfiguration fails at build, not in jit
        self._pp_segment = None
        if self.pipeline_parallel > 1:
            if self.seq_parallel > 1 or self.expert_parallel > 1:
                raise ConfigError(
                    "pipeline_parallel composes with data and model "
                    "parallelism on the config path (round 5); seq/expert "
                    "parallelism inside a pipelined segment needs the "
                    "models/gpt.py path (doc/multi-device.md)")
            from .pipeline_dsl import find_pp_segment
            self._pp_segment = find_pp_segment(g, self.layers,
                                               self.pipeline_parallel)
            if self.pipeline_microbatch <= 0:
                self.pipeline_microbatch = self.pipeline_parallel
            local_b = self.batch_size // self.n_data_shards
            if local_b % self.pipeline_microbatch:
                raise ConfigError(
                    "pipeline_microbatch %d must divide the per-data-shard "
                    "batch %d (batch_size %d / %d data shards)"
                    % (self.pipeline_microbatch, local_b, self.batch_size,
                       self.n_data_shards))

        # block rematerialization (remat = 1): checkpoint each repetition
        # of the repeated block stack — the config-path twin of the
        # models/gpt.py remat/remat_mode levers. With pipeline_parallel
        # the remat happens inside the gpipe block body; standalone it
        # wraps each repetition in _run_graph.
        self._remat_segment = None
        self._remat_split = None
        if self.remat:
            from .pipeline_dsl import attn_saved_split, find_block_segment
            seg = self._pp_segment
            if seg is None:
                # remat recomputes each rep over the SAME full batch, so
                # quirk-mode (stateless) batch_norm is admissible here —
                # unlike pipelining, whose microbatching would change the
                # BN statistics (pipeline_dsl._layer_ok)
                seg = find_block_segment(g, self.layers,
                                         allow_batch_stats=True)
                if seg is None:
                    raise ConfigError(
                        "remat = 1 needs a repeated block segment (>= 2 "
                        "consecutive structurally-identical single-entry/"
                        "single-exit blocks of stateless rng-free layers), "
                        "e.g. a transformer block stack")
                self._remat_segment = seg
            if self.remat_mode == "attn_saved":
                self._remat_split = attn_saved_split(g, seg)

        # id entry nodes (consumed by an embedding) must stay exact f32 on
        # device entry — a bf16 cast would corrupt ids > 256; the compute
        # dtype applies from the embedding lookup onward (ApplyContext
        # .compute_dtype)
        self._id_entry_nodes = set()
        for spec in g.layers:
            if spec.type == "embedding":
                self._id_entry_nodes.update(
                    n for n in spec.inputs if n <= g.extra_data_num)

        # metric -> node binding (default: the final node's output)
        self._metric_nodes: List[int] = []
        for node_name in self.train_metrics.node_names:
            if node_name:
                self._metric_nodes.append(self.graph.node_map[node_name])
            else:
                self._metric_nodes.append(g.num_nodes - 1)
        self._out_node = g.num_nodes - 1
        for n in self._metric_nodes:
            self._check_pp_visible(n, "metric node")

        # train-metric accumulation mode: "device" keeps (sum, count)
        # accumulators on device between log boundaries (zero per-step
        # device->host syncs); "host" is the classic fetch-predictions-
        # every-step path, used when eval_train metrics lack a device twin
        # (rec@n's host-RNG tie-break) or device_metrics = 0
        if not self.eval_train:
            self._metric_mode = "off"
        elif self.device_metrics and all(
                m.device_capable for m in self.train_metrics.metrics):
            self._metric_mode = "device"
        else:
            self._metric_mode = "host"

        self._compile_steps()
        self._initialized = True

    @property
    def _compute_dtype(self):
        return jnp.bfloat16 if self.precision == "bfloat16" else jnp.float32

    def _compile_steps(self) -> None:
        # arg 3 of update/accum is the on-device train-metric accumulator,
        # donated like the states it rides along with
        self._jit_update = jax.jit(self._step_update,
                                   donate_argnums=(0, 1, 2, 3))
        self._jit_accum = jax.jit(self._step_accum, donate_argnums=(0, 3))
        self._jit_apply = jax.jit(self._step_apply, donate_argnums=(0, 1, 2))
        # node_ids is static: each distinct request set compiles a forward
        # that materializes only those nodes (XLA fuses the rest away)
        self._jit_forward = jax.jit(self._forward_eval, static_argnums=(4,))
        # AOT executable cache (analysis/aot_cache.py): wrap each hot
        # step so its ONE training signature resolves from disk on
        # first call — load instead of compile on a warm startup,
        # compile-then-persist otherwise. Off-signature calls (a second
        # eval batch shape, a new forward node set) keep the lazy jit
        # path untouched. The config hash covers every (key, value)
        # pair: python constants baked into the trace (eta, wiring)
        # can never alias across configs.
        aot_path = self.aot_cache or os.environ.get("CXN_AOT_CACHE", "")
        if aot_path:
            from ..analysis.aot_cache import (CachedProgram, config_hash,
                                              get_cache)
            from ..obs.metrics import default_registry as _dreg
            aot = get_cache(aot_path)
            aot.add_sink(_dreg())
            chash = config_hash(sorted(
                p for p in self.cfg if p[0] != "aot_cache"))

            def wrap(fn, name, donate, static=()):
                return CachedProgram(fn, name, config=chash,
                                     donate_argnums=donate,
                                     static_argnums=static, cache=aot,
                                     mesh=self.mesh)

            self._jit_update = wrap(self._jit_update, "net_update",
                                    (0, 1, 2, 3))
            self._jit_accum = wrap(self._jit_accum, "net_accum", (0, 3))
            self._jit_apply = wrap(self._jit_apply, "net_apply",
                                   (0, 1, 2))
            self._jit_forward = wrap(self._jit_forward, "net_forward",
                                     (), (4,))
        # process-level train-step counter in the obs registry (shared
        # across Nets, like any Prometheus process counter)
        from ..obs.metrics import default_registry
        self._obs_steps = default_registry().counter(
            "cxn_train_steps_total", "jitted train steps dispatched")
        # device/compiler observatory (obs/devprof.py): the process
        # registry is a compile-accounting sink — every compile this
        # net triggers lands in cxn_compile_seconds{fn=net_update|...}
        # — and `prof_every` arms the cadence-gated step sampler. Its
        # MFU gauges stay silent until a cost table exists
        # (devprof.profile_net / task=prof fills it; extracting one
        # here would double every startup compile unasked).
        from ..obs import devprof
        devprof.compile_watch().add_sink(default_registry())
        self._prof_sampler = None
        self._cost_table = getattr(self, "_cost_table", None)
        if self.prof_every > 0:
            self._prof_sampler = devprof.LiveSampler(
                default_registry(), cadence=self.prof_every,
                table=self._cost_table)
        if self.lint_recompile_limit > 0:
            # cxn-lint recompilation guard: each hot step errors when its
            # abstract input signature changes more than N times — the
            # silent re-specialization the audit exists to catch. The
            # guard is attribute-transparent, so .lower()/AOT inspection
            # still reach the underlying jit.
            from ..analysis.recompile import RecompileGuard, trip_counter
            from ..utils import profiler
            n = self.lint_recompile_limit
            # trips land in the process-global obs registry so a
            # training job's telemetry shows signature churn alongside
            # its round counters (doc/observability.md)
            trips = trip_counter(default_registry())
            guard = partial(RecompileGuard,
                            strict=bool(self.lint_recompile_strict),
                            log=profiler.warn,
                            on_trip=lambda name: trips.labels(name).inc())
            self._jit_update = guard(self._jit_update, "net_update", n)
            self._jit_accum = guard(self._jit_accum, "net_accum", n)
            self._jit_apply = guard(self._jit_apply, "net_apply", n)
            # the eval forward legitimately traces once per requested
            # node set on top of shape changes; give it headroom
            self._jit_forward = guard(self._jit_forward, "net_forward",
                                      2 * n)

    # ------------------------------------------------------ initialization
    def init_model(self) -> None:
        """Random-init weights + optimizer state (InitModel, nnet_impl:70)."""
        self._build()
        key = jax.random.PRNGKey(self.seed)
        self.params = {}
        self.states = {}
        for i, (spec, layer) in enumerate(zip(self.graph.layers, self.layers)):
            if spec.type == "share":
                continue
            lkey = spec.key()
            in_shapes = [self.node_shapes[n] for n in spec.inputs]
            p = layer.init_params(jax.random.fold_in(key, i), in_shapes)
            if p:
                self.params[lkey] = p
            if hasattr(layer, "init_state"):
                st = layer.init_state()
                if st:
                    self.states[lkey] = st
        self._init_updaters()
        self.epoch_counter = 0
        self.sample_counter = 0
        self._rng = jax.random.PRNGKey(self.seed + 777)
        self._place_state()

    def _init_updaters(self) -> None:
        """One updater per weight tensor, per-tag config (updater_impl:49-108)."""
        self.updaters = {}
        self.opt_state = {}
        g = self.graph
        for spec, layer in zip(g.layers, self.layers):
            if spec.type == "share":
                continue
            lkey = spec.key()
            if lkey not in self.params or lkey in self.opt_state:
                continue
            self.updaters[lkey] = {}
            self.opt_state[lkey] = {}
            for tag, w in self.params[lkey].items():
                upd = create_updater(g.updater_type, tag,
                                     list(g.defcfg) + list(spec.cfg))
                self.updaters[lkey][tag] = upd
                self.opt_state[lkey][tag] = upd.init_state(w)
        self.gsum = jax.tree.map(jnp.zeros_like, self.params) \
            if self.update_period > 1 else None

    def _place_state(self) -> None:
        """Place params / optimizer state on the mesh. Weights follow each
        layer's declared tensor-parallel axes (replicated on a pure-DP mesh);
        optimizer state additionally shards over the data axis under
        ``shard_optimizer`` levels 1/2/3 (ZeRO-1/2/3 — see
        parallel/sharding.py). XLA GSPMD derives the collectives
        that mshadow-ps Push/PullReq performed by hand (SURVEY §5.8)."""
        param_sh, opt_sh = resolve_shardings(
            self.mesh, self.graph, self.layers, self.params,
            zero=int(self.shard_optimizer))
        self._param_shardings = param_sh
        self._opt_shardings = opt_sh
        self.params = jax.device_put(self.params, param_sh)
        # opt_sh is a pytree *prefix*: one sharding per weight covers every
        # tensor of that weight's optimizer state (all weight-shaped)
        self.opt_state = jax.device_put(self.opt_state, opt_sh)
        if self.states:
            self.states = jax.device_put(self.states,
                                         replicated_sharding(self.mesh))
        if self.gsum is not None:
            # ZeRO-2+: the accumulation buffer lives sharded like the
            # optimizer state (each rank accumulates only its slice)
            self.gsum = jax.device_put(
                self.gsum, opt_sh if self.shard_optimizer >= 2 else param_sh)
        self._reset_train_accum()
        self.metric_sync_count = 0      # train-metric device->host folds
        # device-memory ledger pools (obs/devprof.py): params/opt_state
        # predicted bytes as collection-time callbacks in the process
        # registry — a rebuilt or second Net rebinds them (latest wins)
        from ..obs import devprof
        devprof.register_net_pools(self)

    def _reset_train_accum(self) -> None:
        """Fresh on-device (sum, count) train-metric accumulators — one
        row per metric; a (0, 2) placeholder keeps the jitted step's
        signature uniform when the host/off path is active."""
        n = len(self.train_metrics.metrics) \
            if getattr(self, "_metric_mode", "off") == "device" else 0
        self._train_accum = jax.device_put(
            np.zeros((n, 2), np.float32), replicated_sharding(self.mesh))

    # ------------------------------------------------------------ executor
    def _check_pp_visible(self, nid: int, what: str,
                          eval_only: bool = False) -> None:
        """Build-time guard: a node consumed by metrics/extract must not be
        internal to the pipelined (or rematted) segment — those nodes are
        never materialized; only the segment's exit is. ``eval_only``:
        the request comes from an inference forward (extract/pred), where
        the remat segment does NOT apply (remat is gated on ctx.train —
        eval forwards run the plain path and materialize every node), so
        only the pipeline segment restricts visibility."""
        for seg, why in ((self._pp_segment, "pipeline_parallel"),
                         (None if eval_only
                          else getattr(self, "_remat_segment", None),
                          "remat")):
            if seg is None:
                continue
            if nid in seg.internal:
                raise ConfigError(
                    "%s %r is internal to the block segment (layers "
                    "%d..%d) and is not materialized under %s; bind to "
                    "the segment exit %r or a later node, or disable %s"
                    % (what, self.graph.node_names[nid], seg.start,
                       seg.stop - 1, why, self.graph.node_names[seg.exit],
                       why))

    def _layer_params(self, params, idx: int):
        spec = self.graph.layers[idx]
        if spec.type == "share":
            spec = self.graph.layers[spec.primary]
        return params.get(spec.key(), {})

    def _run_graph(self, params, nodes: Dict[int, jnp.ndarray],
                   ctx: ApplyContext) -> Dict[int, jnp.ndarray]:
        seg = self._pp_segment
        rseg = self._remat_segment
        i = 0
        while i < len(self.graph.layers):
            if seg is not None and i == seg.start:
                from .pipeline_dsl import run_pp_segment
                nodes[seg.exit] = run_pp_segment(self, params,
                                                 nodes[seg.entry], ctx)
                i = seg.stop
                continue
            if rseg is not None and i == rseg.start and ctx.train:
                # remat only matters where there is a backward pass; eval
                # forwards run the plain path (no checkpoint overhead)
                from .pipeline_dsl import run_remat_segment
                nodes[rseg.exit] = run_remat_segment(self, params,
                                                     nodes[rseg.entry], ctx)
                i = rseg.stop
                continue
            spec, layer = self.graph.layers[i], self.layers[i]
            inputs = [nodes[n] for n in spec.inputs]
            outs = layer.apply(self._layer_params(params, i), inputs, ctx)
            for n, o in zip(spec.outputs, outs):
                nodes[n] = o
            i += 1
        return nodes

    def _entry_nodes(self, data: jnp.ndarray,
                     extras: List[jnp.ndarray]) -> Dict[int, jnp.ndarray]:
        """NCHW host batch -> NHWC device nodes. The data node is cast to
        the compute dtype (fused no-op when _device_batch already delivered
        bf16); extra-data nodes keep their f32 entry dtype, as always."""
        data = jnp.transpose(data, (0, 2, 3, 1))
        # force the net's compute dtype both ways: a bf16 pipeline feed
        # into a float32 net must not silently downgrade the forward pass
        # (layers derive their compute dtype from the data node's dtype) —
        # EXCEPT id entries feeding an embedding, which stay exact f32
        # (the embedding applies the compute dtype after lookup)
        data = data.astype(jnp.float32 if 0 in self._id_entry_nodes
                           else (jnp.bfloat16
                                 if self.precision == "bfloat16"
                                 else jnp.float32))
        nodes = {0: data}
        for i, e in enumerate(extras):
            nodes[1 + i] = jnp.transpose(e, (0, 2, 3, 1))
        return nodes

    def _split_labels(self, label: jnp.ndarray) -> Dict[str, jnp.ndarray]:
        return {name: label[:, a:b]
                for name, (a, b) in
                ((n, self.graph.label_range[i])
                 for n, i in self.graph.label_name_map.items())}

    def _loss_and_outputs(self, params, states, data, extras, label, mask,
                          rng, epoch):
        ctx = ApplyContext(
            train=True, rng=rng, labels=self._split_labels(label),
            sample_mask=mask, batch_size=self.batch_size,
            update_period=self.update_period, epoch=epoch, states=states,
            mesh=self.mesh, compute_dtype=self._compute_dtype)
        nodes = self._run_graph(params, self._entry_nodes(data, extras), ctx)
        if not ctx.losses:
            raise ConfigError("network has no loss layer")
        total = sum(ctx.losses[1:], ctx.losses[0])
        # pin the metric outputs' batch dim to the data axis: under pure
        # sp/pp meshes XLA may otherwise scatter rows across non-data axes,
        # leaving a process owning rows that don't line up with its local
        # label slice (multi-host metric accounting). Only the host metric
        # path reads them — in device/off mode return none so XLA
        # dead-code-eliminates their materialization (e.g. lm_softmax probs)
        metric_outs = [] if self._metric_mode != "host" else [
            jax.lax.with_sharding_constraint(
                nodes[n].reshape(nodes[n].shape[0], -1),
                batch_sharding(self.mesh))
            for n in sorted(set(self._metric_nodes))]
        # device metric path: per-metric (sum over the GLOBAL batch, count)
        # — a full cross-device reduction that replicates, accumulated into
        # the donated on-device accumulator by the step; the host sees it
        # only at round/log boundaries (_fold_train_accum)
        if self._metric_mode == "device":
            mlabels = self._split_labels(label)
            rows = []
            for metric, field, nid in zip(self.train_metrics.metrics,
                                          self.train_metrics.label_fields,
                                          self._metric_nodes):
                pred = nodes[nid].reshape(nodes[nid].shape[0], -1) \
                    .astype(jnp.float32)
                vals = metric.device_calc(pred, mlabels[field])
                rows.append(jnp.stack([
                    jnp.sum(vals.astype(jnp.float32)),
                    jnp.asarray(float(pred.shape[0]), jnp.float32)]))
            metric_sums = jnp.stack(rows)
        else:
            metric_sums = jnp.zeros((0, 2), jnp.float32)
        return total, (metric_outs, metric_sums, ctx.new_states)

    # ------------------------------------------------------------- steps
    def _constrain_grads(self, grads):
        """ZeRO-2+: pin gradients to the optimizer-state sharding — GSPMD
        then lowers the gradient all-reduce to a reduce-scatter and each
        rank updates only its slice (the reference's update_on_server
        bandwidth shape, async_updater-inl.hpp:200-205, without a
        server)."""
        if self.shard_optimizer < 2:
            return grads
        return jax.tree.map(jax.lax.with_sharding_constraint, grads,
                            self._opt_shardings)

    def _step_update(self, params, opt_state, states, maccum, data, extras,
                     label, mask, rng, epoch):
        """Fused grad + optimizer apply (update_period == 1 fast path).
        ``maccum`` is the on-device (n_metrics, 2) train-metric
        accumulator; the step folds this batch's (sum, count) in so
        eval_train needs no per-step host fetch."""
        (loss, (mouts, msums, new_states)), grads = jax.value_and_grad(
            self._loss_and_outputs, has_aux=True)(
                params, states, data, extras, label, mask, rng, epoch)
        grads = self._constrain_grads(grads)
        params, opt_state = self._apply_grads(params, opt_state, grads, epoch)
        return params, opt_state, new_states, maccum + msums, loss, mouts

    def _step_accum(self, gsum, params, states, maccum, data, extras, label,
                    mask, rng, epoch):
        (loss, (mouts, msums, new_states)), grads = jax.value_and_grad(
            self._loss_and_outputs, has_aux=True)(
                params, states, data, extras, label, mask, rng, epoch)
        gsum = jax.tree.map(jnp.add, gsum, self._constrain_grads(grads))
        return gsum, new_states, maccum + msums, loss, mouts

    def _step_apply(self, params, opt_state, gsum, epoch):
        params, opt_state = self._apply_grads(params, opt_state, gsum, epoch)
        gsum = jax.tree.map(jnp.zeros_like, gsum)
        return params, opt_state, gsum

    def _apply_grads(self, params, opt_state, grads, epoch):
        if self.clip_norm > 0.0:
            # global-norm clipping across every weight tensor (config
            # ``clip_norm``) — the whole-model complement of the
            # reference's per-element clip_gradient; NaNs are zeroed
            # first (the reference clip functor's NaN -> 0 behavior)
            scale = global_norm_scale(grads, self.clip_norm)
            grads = jax.tree.map(
                lambda g: jnp.nan_to_num(g) * scale, grads)
        new_params = {}
        new_opt = {}
        constrain = jax.lax.with_sharding_constraint
        for lkey, tensors in params.items():
            new_params[lkey] = {}
            new_opt[lkey] = {}
            for tag, w in tensors.items():
                upd = self.updaters[lkey][tag]
                g = grads[lkey][tag]
                w2, s2 = upd.update(w, g, opt_state[lkey][tag], epoch)
                # pin the resolved shardings so the update step's outputs keep
                # the layout they were placed with (no GSPMD drift between
                # steps; under ZeRO this is where the weight re-gather and the
                # opt-state reduce-scatter materialize)
                new_params[lkey][tag] = constrain(
                    w2, self._param_shardings[lkey][tag])
                new_opt[lkey][tag] = jax.tree.map(
                    lambda t, s=self._opt_shardings[lkey][tag]: constrain(t, s),
                    s2)
        return new_params, new_opt

    def _forward_eval(self, params, states, data, extras, node_ids):
        """Inference forward; returns only the requested nodes' outputs."""
        ctx = ApplyContext(train=False, rng=None, states=states,
                           mesh=self.mesh,
                           compute_dtype=self._compute_dtype)
        nodes = self._run_graph(params, self._entry_nodes(data, extras), ctx)
        return tuple(nodes[n] for n in node_ids)

    # ------------------------------------------------------------- train
    def start_round(self, r: int) -> None:
        self.round = r

    def _device_batch(self, batch):
        """Move a host DataBatch to the mesh (data-axis sharded). Multi-host:
        each process contributes its local slice of the global batch
        (parallel/distributed.py). Iterators that shard their dataset per
        rank (imgbin dist_worker_rank) yield batch_size/P rows which pass
        through as-is; non-sharded iterators (mnist/img with identical
        seeds on every process) yield the full global batch, from which
        each process contributes only its own row range — the replicated-
        reader mode for datasets without rank sharding."""
        sh = batch_sharding(self.mesh)
        # batch.data arrives float32, or already bfloat16 when the pipeline
        # converts in its producer thread (`data_dtype = bfloat16` on the
        # batcher): bf16 passes through, halving host->device bytes, and
        # the jitted step's input cast (_entry_nodes) no-ops; f32 feeds are
        # cast inside the step, fused into the first transpose/conv (no
        # separate device pass, and no host-side cast on this thread).
        data = global_batch(self.mesh, sh, self._local_slice(batch.data))
        label = global_batch(self.mesh, sh, self._local_slice(batch.label))
        extras = [global_batch(self.mesh, sh, self._local_slice(e))
                  for e in batch.extra_data]
        return data, extras, label

    def _local_slice(self, x) -> np.ndarray:
        """This process's row range of a host batch array.

        ``dist_feed = replicated`` (default): every process's iterator
        yields the full global batch (deterministic shuffle, same seed);
        each rank keeps only its row range. ``dist_feed = sharded``: the
        iterator chain is configured to yield batch_size/P rows per
        process (dataset rank-sharded, e.g. imgbin dist_worker_rank with a
        per-section ``batch_size = global/P``); rows pass through as-is.
        Single-process: unchanged."""
        nproc = jax.process_count()
        if nproc <= 1:
            return self._host_array(x)
        if self.mesh.shape["data"] == 1:
            # the batch is replicated over every device (pure sp/ep/pp
            # meshes): make_array_from_process_local_data then requires
            # the FULL batch from each process — a blind per-process split
            # here would silently build a wrong half-size "global" batch
            if self.dist_feed == "sharded":
                raise ConfigError(
                    "dist_feed=sharded needs a data axis spanning the %d "
                    "processes; this mesh replicates the batch (data=1) — "
                    "use dist_feed=replicated" % nproc)
            if x.shape[0] != self.batch_size:
                raise ValueError(
                    "replicated-batch mesh expects the full global batch "
                    "%d per process, got %d rows"
                    % (self.batch_size, x.shape[0]))
            return self._host_array(x)
        step = self.batch_size // nproc
        if self.dist_feed == "sharded":
            if x.shape[0] != step:
                raise ValueError(
                    "dist_feed=sharded expects %d rows/process (global "
                    "batch %d over %d processes), got %d — configure the "
                    "data section's batch_size accordingly"
                    % (step, self.batch_size, nproc, x.shape[0]))
            return self._host_array(x)
        if x.shape[0] != self.batch_size:
            raise ValueError(
                "dist_feed=replicated expects the full global batch %d "
                "per process, got %d rows" % (self.batch_size, x.shape[0]))
        rank = jax.process_index()
        return self._host_array(x[rank * step:(rank + 1) * step])

    @staticmethod
    def _host_array(x) -> np.ndarray:
        """Normalize a host batch array: bfloat16 pipeline output passes
        through unchanged (ml_dtypes view), anything else goes to f32."""
        x = np.asarray(x)
        if x.dtype.name == "bfloat16":
            return x
        return np.asarray(x, np.float32)

    def _rank_valid(self, batch) -> int:
        """Number of this rank's local rows that are real instances (the
        short-pad tail occupies the end of the *global* batch)."""
        n_valid = batch.data.shape[0] - batch.num_batch_padd
        nproc = jax.process_count()
        if nproc <= 1 or self.dist_feed == "sharded":
            return n_valid
        if self.mesh.shape["data"] == 1:
            # replicated-batch meshes (pure sp/ep/pp): every rank holds —
            # and accounts — the full batch; metrics stay correct because
            # the cross-process reduction doubles sum and count alike
            return n_valid
        step = self.batch_size // nproc
        return int(np.clip(n_valid - jax.process_index() * step, 0, step))

    def _train_mask(self, batch) -> Optional[jnp.ndarray]:
        """Mask out short-pad duplicates; round_batch wrap instances are real
        and trained on, as in the reference."""
        if batch.num_batch_padd and getattr(batch, "pad_mode", "wrap") == "short":
            b = batch.data.shape[0]
            mask = np.ones((b,), np.float32)
            mask[b - batch.num_batch_padd:] = 0.0
            return global_batch(self.mesh, batch_sharding(self.mesh),
                                self._local_slice(mask))
        return None

    def place_batch(self, batch) -> DeviceBatch:
        """Move a host DataBatch to the mesh as a :class:`DeviceBatch` —
        the unit the async feed (io/device_prefetch.py) produces on its
        background thread and :meth:`update` consumes. Multi-host
        contract: every process must place the same batches in the same
        order (each contributes its local slice of the same global
        array); the prefetcher enforces/documents this."""
        if not self._initialized:
            raise RuntimeError("call init_model() or load_model() first")
        data, extras, label = self._device_batch(batch)
        mask = self._train_mask(batch)
        host_label = None
        if self._metric_mode == "host":
            # detach from iterator-owned buffers: the label slice outlives
            # the producer thread's next base.next()
            host_label = np.array(self._local_slice(batch.label))
        return DeviceBatch(data, extras, label, mask, host_label=host_label)

    def update(self, batch) -> None:
        """One training step (Update, nnet_impl:141-184) on a host
        DataBatch, or on a pre-placed :class:`DeviceBatch` from the async
        feed — in which case no host->device work happens on this
        thread. No device->host sync either way: the loss is fetched
        lazily by :meth:`last_loss`, and train metrics accumulate on
        device until a log boundary (``_metric_mode == 'device'``)."""
        if not self._initialized:
            raise RuntimeError("call init_model() or load_model() first")
        db = batch if isinstance(batch, DeviceBatch) \
            else self.place_batch(batch)
        rng = jax.random.fold_in(self._rng, self.epoch_counter)
        epoch = jnp.asarray(self.epoch_counter, jnp.int32)
        self.sample_counter += 1
        from ..obs import devprof
        prof = self._prof_sampler
        if self.update_period == 1:
            t0 = prof.begin("net_update") if prof is not None else None
            with devprof.compile_attribution("net_update"):
                (self.params, self.opt_state, self.states,
                 self._train_accum, loss, mouts) = self._jit_update(
                     self.params, self.opt_state, self.states,
                     self._train_accum, db.data, db.extras, db.label,
                     db.mask, rng, epoch)
            if t0 is not None:
                # the one sampled step pays the device sync the async
                # hot loop otherwise never does — that IS the sample
                jax.block_until_ready(loss)
                prof.end("net_update", t0)
        else:
            t0 = prof.begin("net_accum") if prof is not None else None
            with devprof.compile_attribution("net_accum"):
                (self.gsum, self.states, self._train_accum, loss,
                 mouts) = self._jit_accum(
                     self.gsum, self.params, self.states,
                     self._train_accum, db.data, db.extras, db.label,
                     db.mask, rng, epoch)
            if t0 is not None:
                jax.block_until_ready(loss)
                prof.end("net_accum", t0)
            if self.sample_counter % self.update_period == 0:
                with devprof.compile_attribution("net_apply"):
                    (self.params, self.opt_state,
                     self.gsum) = self._jit_apply(
                        self.params, self.opt_state, self.gsum, epoch)
        self.epoch_counter += 1
        self._obs_steps.inc()
        if self._metric_mode == "host":
            self._accumulate_train_metrics(db.host_label, mouts)
        self._last_loss = loss

    def _accumulate_train_metrics(self, host_label, mouts) -> None:
        """Host metric path: fetch this step's predictions (device sync)
        and feed the numpy MetricSet — O(steps) syncs; the device path
        replaces this wholesale."""
        uniq = sorted(set(self._metric_nodes))
        node_to_out = {n: local_rows(o) for n, o in zip(uniq, mouts)}
        labels = self._host_labels(host_label)
        preds = [node_to_out[n] for n in self._metric_nodes]
        nloc = next(iter(labels.values())).shape[0] if labels else 0
        for i, p in enumerate(preds):
            if p.shape[0] != nloc:
                # batch replicated over processes (data axis does not span
                # them, e.g. pure sp/pp meshes): every rank holds all rows;
                # keep this rank's range to match its local labels
                r = jax.process_index()
                assert p.shape[0] >= (r + 1) * nloc, (p.shape, nloc)
                preds[i] = p[r * nloc:(r + 1) * nloc]
        self.train_metrics.add_eval(preds, labels)

    def _host_labels(self, label: np.ndarray) -> Dict[str, np.ndarray]:
        return {name: label[:, a:b]
                for name, (a, b) in
                ((n, self.graph.label_range[i])
                 for n, i in self.graph.label_name_map.items())}

    def _fold_train_accum(self) -> None:
        """Fetch the on-device train-metric accumulators into the numpy
        MetricSet and reset them — the single device->host metric sync
        of a training round (counted in ``metric_sync_count`` so tests
        can pin the O(log boundaries) property)."""
        if self._metric_mode != "device":
            return
        sums = np.asarray(jax.device_get(self._train_accum))
        self.metric_sync_count += 1
        for m, (s, c) in zip(self.train_metrics.metrics, sums):
            m.sum_metric += float(s)
            m.cnt_inst += int(c)
        self._reset_train_accum()

    # ---------------------------------------------------- failure detection
    def last_loss(self) -> float:
        """Fetch the most recent step loss (forces a device sync). SURVEY §5.3
        upgrade: the reference has no runtime failure detection (every error
        is exit(-1), utils.h:60-80); we expose the loss so the driver can
        detect divergence (NaN/Inf) and recover from a checkpoint."""
        if not hasattr(self, "_last_loss"):
            return float("nan")
        return float(self._last_loss)

    def check_replica_consistency(self) -> Tuple[float, Optional[Tuple[str, str]]]:
        """Verify every device's copy of each weight shard is identical —
        the test_on_server analogue (async_updater-inl.hpp:144-154 had each
        worker CheckWeight_ against the server's copy each round). Shards are
        grouped by their index into the global array: shards covering the
        same slice (replicas) must match bit-for-bit; ZeRO/tensor-parallel
        shards with distinct indices are legitimately different and are not
        compared.

        Multi-process runs additionally compare replicas held on OTHER
        hosts (exactly the divergence test_on_server existed for): each
        process contributes per-(weight, shard-slice) f64 checksums
        (sum, sum of squares), all-gathered host-side; groups with the
        same slice must agree across every process. The returned diff for
        a cross-host mismatch is the |mean difference| proxy derived from
        the checksums (raw remote shards are not addressable).

        Returns (max_abs_diff, (layer, tag) of the worst weight)."""
        from ..parallel.distributed import (host_allgather_rows,
                                            is_multi_host, process_count)
        import zlib
        multi = is_multi_host()
        max_diff, worst = 0.0, None
        keys = []          # (lname, tag) in deterministic order
        sums: list = []    # rows [key_id, slice_id, sum, sumsq, count]
        for lname, tags in sorted(self.params.items()):
            for tag, w in sorted(tags.items()):
                groups: Dict[str, list] = {}
                for s in w.addressable_shards:
                    groups.setdefault(str(s.index), []).append(
                        np.asarray(s.data))
                keys.append((lname, tag))
                kid = len(keys) - 1
                for idx, arrs in sorted(groups.items()):
                    for a in arrs[1:]:
                        if arrs[0].size == 0:
                            continue
                        d = float(np.max(np.abs(a.astype(np.float32)
                                                - arrs[0].astype(np.float32))))
                        if d > max_diff:
                            max_diff, worst = d, (lname, tag)
                    if multi:
                        ref = arrs[0].astype(np.float64)
                        sums.append([kid, float(zlib.crc32(idx.encode())),
                                     float(ref.sum()),
                                     float((ref * ref).sum()),
                                     float(ref.size),
                                     # order-sensitive channel: sum/sumsq
                                     # are permutation-invariant, so a
                                     # cross-host element swap would pass
                                     # them; the byte CRC is exact
                                     float(zlib.crc32(ref.tobytes()))])
        if multi and sums:
            rows = host_allgather_rows(np.asarray(sums, np.float64))
            assert rows.shape[0] == len(sums) * process_count()
            local = np.asarray(sums, np.float64)
            for r in range(rows.shape[0]):
                kid, sid = rows[r, 0], rows[r, 1]
                match = (local[:, 0] == kid) & (local[:, 1] == sid)
                if not match.any():
                    continue       # slice not held locally (ZeRO layouts)
                mine = local[match][0]
                cnt = max(mine[4], 1.0)
                # |mean diff| from the sums, plus the sum-of-squares
                # channel (catches +eps/-eps drift); both are
                # permutation-invariant, so the byte-CRC channel flags
                # order divergence (swaps) that preserves them — with no
                # magnitude to report, it contributes a tiny positive d
                d = max(abs(rows[r, 2] - mine[2]) / cnt,
                        abs(rows[r, 3] - mine[3]) / cnt)
                if rows[r, 5] != mine[5]:
                    d = max(d, np.finfo(np.float64).eps)
                if d > max_diff:
                    max_diff, worst = d, keys[int(kid)]
        return max_diff, worst

    # ----------------------------------------------------------- evaluate
    def evaluate(self, data_iter, name: str) -> str:
        """Run metrics over an iterator; excludes padded tails. Prints (and
        clears) accumulated train metrics first when eval_train is on, exactly
        like the reference (Evaluate, nnet_impl:224-245)."""
        from ..parallel.distributed import host_psum
        ret = ""
        if self.eval_train:
            if self._metric_mode == "device":
                # ONE device->host sync per log boundary folds the whole
                # round's (sum, count) accumulators; the sums were reduced
                # over the GLOBAL batch inside the jitted step, so no
                # cross-process reduction applies here
                self._fold_train_accum()
                ret += self.train_metrics.print("train")
            else:
                # cross-process (sum, count) reduction: every rank prints
                # the GLOBAL metric (the reference printed per-worker
                # numbers)
                ret += self.train_metrics.print("train", reduce=host_psum)
            self.train_metrics.clear()
        if data_iter is None:
            return ret
        self.eval_metrics.clear()
        uniq = tuple(sorted(set(self._metric_nodes)))
        # double-buffered: batch k+1's host prep (device_put, label
        # slicing) and device forward are dispatched BEFORE batch k's
        # outputs are fetched to the host, so the device computes while
        # the host prepares — the threaded-inference overlap the
        # reference got from running eval through the same ThreadBuffer
        # machinery as training (cxxnet_main.cpp Evaluate path)
        data_iter.before_first()
        pending = None            # (device outs, host labels, n_valid)
        has = data_iter.next()
        while has or pending is not None:
            nxt = None
            if has:
                batch = data_iter.value()
                data, extras, _ = self._device_batch(batch)
                outs = self._jit_forward(self.params, self.states, data,
                                         extras, uniq)   # async dispatch
                local_label = self._local_slice(batch.label)
                n_valid = self._rank_valid(batch)
                labels = {k: v[:n_valid]
                          for k, v in self._host_labels(local_label).items()}
                nxt = (outs, labels, n_valid)
            if pending is not None:
                outs, labels, n_valid = pending
                node_to_out = dict(zip(uniq, outs))
                preds = []
                for n in self._metric_nodes:
                    out = local_rows(node_to_out[n])     # host fetch
                    preds.append(out.reshape(out.shape[0], -1)[:n_valid])
                self.eval_metrics.add_eval(preds, labels)
            pending = nxt
            has = data_iter.next() if has else False
        return ret + self.eval_metrics.print(name, reduce=host_psum)

    def forward_iter(self, data_iter, node: Optional[str] = None):
        """Double-buffered inference generator: yields one host ndarray of
        node outputs per batch (padded tail rows excluded), overlapping
        each batch's device forward with the previous fetch — the
        pipelined pred/extract path (used by the CLI tasks)."""
        if node is None:
            nid = self._out_node
        elif node.startswith("top[-"):
            nid = self.graph.num_nodes - int(node[len("top[-"):-1])
        else:
            nid = self.graph.node_map[node]
        self._check_pp_visible(nid, "extract node %r" % (node,),
                               eval_only=True)
        data_iter.before_first()
        pending = None            # (device out, n_valid)
        has = data_iter.next()
        while has or pending is not None:
            nxt = None
            if has:
                batch = data_iter.value()
                data, extras, _ = self._device_batch(batch)
                outs = self._jit_forward(self.params, self.states, data,
                                         extras, (nid,))
                nxt = (outs[0], self._rank_valid(batch))
            if pending is not None:
                out, n_valid = pending
                yield local_rows(out)[:n_valid]
            pending = nxt
            has = data_iter.next() if has else False

    # ------------------------------------------------------------ predict
    def predict(self, batch) -> np.ndarray:
        """argmax of the final node if it is a vector, else the raw scalar
        (nnet_impl:286-299)."""
        out = self._forward_node(batch, self._out_node)
        out = out.reshape(out.shape[0], -1)[:self._rank_valid(batch)]
        if out.shape[1] == 1:
            return out[:, 0]
        return np.argmax(out, axis=1).astype(np.float32)

    def extract_feature(self, batch, node: str) -> np.ndarray:
        """Node output by name, or ``top[-k]`` counting back from the output
        (nnet_impl:200-223)."""
        if node.startswith("top[-"):
            k = int(node[len("top[-"):-1])
            nid = self.graph.num_nodes - k
        else:
            nid = self.graph.node_map[node]
        self._check_pp_visible(nid, "extract node %r" % (node,),
                               eval_only=True)
        out = self._forward_node(batch, nid)
        return out[:self._rank_valid(batch)]

    def _forward_node(self, batch, node_id: int) -> np.ndarray:
        data, extras, _ = self._device_batch(batch)
        outs = self._jit_forward(self.params, self.states, data, extras,
                                 (node_id,))
        return local_rows(outs[0])

    # ------------------------------------------------------- weight access
    @staticmethod
    def _fetch(arr) -> np.ndarray:
        """Host copy of a (possibly multi-host-sharded) array. ZeRO-3
        params span non-addressable devices in multi-process runs;
        process_allgather is collective, which is safe here because
        every rank runs save/get at the same points (the CLI's round
        loop is SPMD)."""
        if getattr(arr, "is_fully_addressable", True):
            return np.asarray(arr)
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.process_allgather(arr, tiled=True))

    def get_weight(self, layer_name: str, tag: str) -> np.ndarray:
        idx = self.graph.layer_index(layer_name)
        lkey = self.graph.layers[idx].key()
        if lkey not in self.params or tag not in self.params[lkey]:
            return np.zeros((0,), np.float32)
        return self._fetch(self.params[lkey][tag])

    def set_weight(self, layer_name: str, tag: str, value: np.ndarray) -> None:
        idx = self.graph.layer_index(layer_name)
        lkey = self.graph.layers[idx].key()
        cur = self.params[lkey][tag]
        value = np.asarray(value, np.float32).reshape(cur.shape)
        self.params[lkey][tag] = jax.device_put(
            jnp.asarray(value), self._param_shardings[lkey][tag])

    # --------------------------------------------------------- checkpoint
    def save_model(self, path: str) -> None:
        """Binary checkpoint: structure + epoch + weights (+ layer states).
        Optimizer state is NOT saved, as in the reference (nnet_impl:82-99)."""
        params_np = jax.tree.map(self._fetch, self.params)
        states_np = jax.tree.map(self._fetch, self.states)
        tensors: List[Tuple[str, np.ndarray]] = []
        for lkey in sorted(params_np):
            for tag in sorted(params_np[lkey]):
                tensors.append(("p/%s/%s" % (lkey, tag), params_np[lkey][tag]))
        for lkey in sorted(states_np):
            for tag in sorted(states_np[lkey]):
                tensors.append(("s/%s/%s" % (lkey, tag), states_np[lkey][tag]))
        header = {
            "graph": self.graph.structure_state(),
            "epoch": self.epoch_counter,
            "round": self.round,
            "tensors": [{"name": n, "shape": list(t.shape),
                         "dtype": str(t.dtype)} for n, t in tensors],
        }
        hbytes = json.dumps(header).encode()
        with open(path, "wb") as f:
            f.write(_CKPT_MAGIC)
            f.write(struct.pack("<q", len(hbytes)))
            f.write(hbytes)
            for _, t in tensors:
                f.write(np.ascontiguousarray(t).tobytes())

    def load_model(self, path: str) -> None:
        with open(path, "rb") as f:
            if f.read(8) != _CKPT_MAGIC:
                raise IOError("invalid model file %r" % path)
            hlen = struct.unpack("<q", f.read(8))[0]
            header = json.loads(f.read(hlen))
            self.graph = NetGraph.from_structure_state(header["graph"])
            self._build(from_loaded_graph=True)
            self.params = {}
            self.states = {}
            for meta in header["tensors"]:
                t = np.frombuffer(
                    f.read(int(np.prod(meta["shape"]) *
                               np.dtype(meta["dtype"]).itemsize)),
                    dtype=meta["dtype"]).reshape(meta["shape"])
                kind, lkey, tag = meta["name"].split("/", 2)
                dst = self.params if kind == "p" else self.states
                dst.setdefault(lkey, {})[tag] = jnp.asarray(t)
        self.epoch_counter = header["epoch"]
        self.round = header["round"]
        self._init_updaters()
        self._rng = jax.random.PRNGKey(self.seed + 777)
        self._place_state()

    def copy_model_from(self, other: "Net") -> None:
        """Finetune warm-start: copy layers whose names match, reset epoch
        (CopyModelFrom, nnet_impl:101-134)."""
        if not self._initialized:
            self.init_model()
        copied = []
        for name, idx in self.graph.layer_name_map.items():
            if name in other.graph.layer_name_map:
                lkey = self.graph.layers[idx].key()
                okey = other.graph.layers[
                    other.graph.layer_name_map[name]].key()
                if okey in other.params:
                    src = jax.tree.map(np.asarray, other.params[okey])
                    dst = self.params.get(lkey, {})
                    for tag in dst:
                        if tag in src and src[tag].shape == \
                                tuple(dst[tag].shape):
                            dst[tag] = jnp.asarray(src[tag])
                            copied.append("%s.%s" % (name, tag))
        self.epoch_counter = 0
        self.sample_counter = 0
        self._place_state()
        print("CopyModelFrom: copied %d tensors" % len(copied))
