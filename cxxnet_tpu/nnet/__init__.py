"""Network runtime/trainer package."""

from .net import Net

__all__ = ["Net"]
