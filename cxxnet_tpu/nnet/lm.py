"""LM surface adapter: netconfig GPT <-> the models/gpt.py functional path.

The reference's whole task surface is config-reachable
(/root/reference/src/cxxnet_main.cpp:57-81); this module gives the
framework the same property for GENERATION: a Net built from a GPT-shaped
netconfig (models/transformer.py:gpt_lm_config) exports its weights into
the models/gpt.py parameter layout, so ``task = generate`` (cli.py) and
``Net.generate`` drive the SAME fused whole-step decode kernel
(ops/pallas_kernels.fused_decode_step) as the functional path — one
decode implementation, two surfaces.

Structure contract (validated with precise errors): embedding -> N x
pre-LN dense transformer blocks (layer_norm/attention/add + layer_norm/
1x1-conv MLP/add, the gpt_lm_config shape) -> layer_norm -> 1x1-conv LM
head -> lm_softmax. MoE blocks are rejected (the KV-cache decode path is
dense; MoE generation would need expert dispatch per token).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.config import ConfigError


def _segment(net):
    from .pipeline_dsl import find_block_segment
    seg = net._pp_segment or net._remat_segment
    if seg is None:
        seg = find_block_segment(net.graph, net.layers)
    if seg is None:
        raise ConfigError(
            "generate: no repeated transformer block segment found in the "
            "net (need >= 2 identical pre-LN blocks, e.g. gpt_lm_config)")
    return seg


def _rep_layers(net, seg) -> Dict[str, int]:
    """Identify the block-segment layers of repetition r=0 by type;
    returns rep-relative layer offsets (reps are isomorphic, so offset j
    of rep r is graph layer ``seg.start + r*seg.period + j``)."""
    specs = net.graph.layers[seg.start:seg.start + seg.period]
    by_type: Dict[str, list] = {}
    for j, s in enumerate(specs):
        by_type.setdefault(s.type, []).append(j)
    if "moe" in by_type:
        raise ConfigError("generate: MoE blocks are not supported by the "
                          "KV-cache decode path (dense MLP blocks only)")
    for t, want in (("layer_norm", 2), ("attention", 1), ("conv", 2)):
        if len(by_type.get(t, ())) != want:
            raise ConfigError(
                "generate: block segment is not a pre-LN transformer "
                "block (expected %d %r layers per block, found %d)"
                % (want, t, len(by_type.get(t, ()))))
    ln1, ln2 = by_type["layer_norm"]
    (attn,) = by_type["attention"]
    up, down = by_type["conv"]
    return {"ln1": ln1, "ln2": ln2, "attn": attn, "up": up, "down": down}


def _outer_layers(net, seg):
    """(embedding, final layer_norm, head conv) outside the segment."""
    g = net.graph
    emb = lnf = head = None
    for i, (spec, layer) in enumerate(zip(g.layers, net.layers)):
        if seg.start <= i < seg.stop:
            continue
        if spec.type == "embedding":
            emb = (spec, layer)
        elif spec.type == "layer_norm" and i >= seg.stop:
            lnf = (spec, layer)
        elif spec.type == "conv" and i >= seg.stop:
            head = (spec, layer)
    if emb is None or lnf is None or head is None:
        raise ConfigError(
            "generate: net must be embedding -> blocks -> layer_norm -> "
            "1x1-conv head -> lm_softmax (gpt_lm_config shape)")
    if head[1].param.kernel_width != 1 or head[1].param.kernel_height != 1:
        raise ConfigError("generate: LM head must be a 1x1 conv")
    return emb, lnf, head


def net_gpt_config(net):
    """Build the models/gpt.py GPTConfig mirroring a GPT-shaped Net."""
    from ..models.gpt import GPTConfig
    seg = _segment(net)
    rep = _rep_layers(net, seg)
    emb, _, _ = _outer_layers(net, seg)
    attn_layer = net.layers[seg.start + rep["attn"]]
    feat = attn_layer.feat
    mf = net.layers[seg.start + rep["up"]].param.num_channel
    return GPTConfig(
        vocab_size=emb[1].vocab_size, seq_len=emb[1].seq_len,
        n_layer=seg.count, n_head=attn_layer.nhead, feat=feat,
        mlp_ratio=max(1, mf // feat),
        dtype="bfloat16" if net.precision == "bfloat16" else "float32")


def net_to_gpt_params(net) -> Dict:
    """Export a GPT-shaped Net's weights into the models/gpt.py layout
    (blocks stacked on a leading n_layer dim). Pure host-side reshapes/
    transposes; cited layouts: DSL attention qkv (3F, F) applied as
    ``x @ qkv.T`` (layers/attention.py) vs gpt.py per-matrix ``x @ w_q``
    (models/gpt.py:_attn_core); DSL 1x1 convs are HWIO (1,1,cin,cout)
    (layers/conv.py) vs gpt.py (cin, cout) matmuls."""
    seg = _segment(net)
    rep = _rep_layers(net, seg)
    emb, lnf, head = _outer_layers(net, seg)

    def w(params_key, tag):
        return np.asarray(net._fetch(net.params[params_key][tag]))

    def rep_key(j, r):
        # layer key of repetition r for rep-relative offset j
        return net.graph.layers[seg.start + r * seg.period + j].key()

    f = net.layers[seg.start + rep["attn"]].feat
    stack: Dict[str, list] = {k: [] for k in (
        "ln1_g", "ln1_b", "ln2_g", "ln2_b", "w_q", "w_k", "w_v", "b_q",
        "b_k", "b_v", "w_proj", "b_proj", "w_mlp1", "b_mlp1", "w_mlp2",
        "b_mlp2")}
    for r in range(seg.count):
        k_ln1 = rep_key(rep["ln1"], r)
        k_ln2 = rep_key(rep["ln2"], r)
        k_att = rep_key(rep["attn"], r)
        k_up = rep_key(rep["up"], r)
        k_dn = rep_key(rep["down"], r)
        stack["ln1_g"].append(w(k_ln1, "wmat"))
        stack["ln1_b"].append(w(k_ln1, "bias"))
        stack["ln2_g"].append(w(k_ln2, "wmat"))
        stack["ln2_b"].append(w(k_ln2, "bias"))
        qkv = w(k_att, "qkv")                      # (3F, F), x @ qkv.T
        stack["w_q"].append(qkv[:f].T)
        stack["w_k"].append(qkv[f:2 * f].T)
        stack["w_v"].append(qkv[2 * f:].T)
        if "qkv_bias" in net.params[k_att]:
            qb = w(k_att, "qkv_bias")
            pb = w(k_att, "proj_bias")
        else:
            qb = np.zeros((3 * f,), np.float32)
            pb = np.zeros((f,), np.float32)
        stack["b_q"].append(qb[:f])
        stack["b_k"].append(qb[f:2 * f])
        stack["b_v"].append(qb[2 * f:])
        stack["w_proj"].append(w(k_att, "proj").T)
        stack["b_proj"].append(pb)
        stack["w_mlp1"].append(w(k_up, "wmat")[0, 0])       # (f, mf)
        stack["w_mlp2"].append(w(k_dn, "wmat")[0, 0])       # (mf, f)
        stack["b_mlp1"].append(
            w(k_up, "bias") if "bias" in net.params[k_up]
            else np.zeros((stack["w_mlp1"][-1].shape[1],), np.float32))
        stack["b_mlp2"].append(
            w(k_dn, "bias") if "bias" in net.params[k_dn]
            else np.zeros((f,), np.float32))

    k_emb = emb[0].key()
    k_lnf = lnf[0].key()
    k_head = head[0].key()
    return {
        "emb": jnp.asarray(w(k_emb, "wmat")),
        "pos": jnp.asarray(w(k_emb, "pos")),
        "lnf_g": jnp.asarray(w(k_lnf, "wmat")),
        "lnf_b": jnp.asarray(w(k_lnf, "bias")),
        "head": jnp.asarray(w(k_head, "wmat")[0, 0]),
        "blocks": {k: jnp.asarray(np.stack(v)) for k, v in stack.items()},
    }


def net_gpt_export(net) -> Tuple:
    """(GPTConfig, params) export of a GPT-shaped Net — run ONCE and pass
    to repeated ``net_generate`` calls: the export fetches the whole
    weight tree to the host (ZeRO-aware) and re-stacks it, which at
    flagship scale costs far more than one decode."""
    return net_gpt_config(net), net_to_gpt_params(net)


def net_generate(net, prompt: np.ndarray, max_new: int,
                 temperature: float = 0.0,
                 rng: Optional[jax.Array] = None,
                 export: Optional[Tuple] = None,
                 int8: bool = False,
                 top_k: int = 0, top_p: float = 1.0,
                 speculative=None) -> np.ndarray:
    """Generate tokens from a GPT-shaped Net: prompt (b, n_prompt) int ->
    (b, n_prompt + max_new) int32. Drives models/gpt.py:gpt_decode — the
    fused whole-step decode kernel auto-engages on one chip exactly as on
    the functional path. ``export``: a ``net_gpt_export(net)`` result to
    reuse across calls (otherwise each call re-exports the weight tree —
    fine for one-shot generation, wrong for timing loops; cli.py's
    ``generate_bench`` exports once). ``top_k``/``top_p`` restrict the
    sampling candidate set when ``temperature > 0`` (ops/sampling.py;
    0 / 1.0 disable). ``speculative`` passes through to
    ``gpt_decode(speculative=...)`` — draft-and-verify multi-token
    decoding (an int spec_len for the n-gram drafter, or the full dict
    form; greedy output stays bit-identical)."""
    from ..models.gpt import gpt_decode
    cfg, params = export if export is not None else net_gpt_export(net)
    prompt = jnp.asarray(np.asarray(prompt, np.int32))
    if rng is None and temperature > 0:
        rng = jax.random.PRNGKey(net.seed)
    out = gpt_decode(params, prompt, max_new, cfg,
                     temperature=temperature, rng=rng, int8_weights=int8,
                     top_k=top_k, top_p=top_p, speculative=speculative)
    return np.asarray(out)


__all__ = ["net_gpt_config", "net_gpt_export", "net_to_gpt_params",
           "net_generate"]
