"""Pipeline parallelism for netconfig-DSL models (``pipeline_parallel = k``).

The reference has no pipeline parallelism (SURVEY §2.7 lists it among the
designed-fresh axes); through round 3 the framework's gpipe schedule
(parallel/pipeline.py) was reachable only from models/gpt.py. This module
wires it into the config path: the Net detects the longest run of
structurally-identical repeated blocks in the parsed graph (a transformer's
`attention` block stack), stacks the per-repetition parameters along a
leading layer dim inside the jitted step, and runs the segment through
``gpipe`` — microbatches flow around the ``pipe`` mesh axis ring while each
stage applies its local blocks.

Detection contract (checked, with precise errors): each repetition must be
single-entry/single-exit, chained (rep r's entry is rep r-1's exit), and
contain only stateless, rng-free, non-loss, non-shared layers with identical
types and scoped config across repetitions. The repetition count must divide
the pipe axis.

Composition boundary (doc/multi-device.md): the config-DSL pipeline
composes with data parallelism (and ZeRO); ``model_parallel`` /
``seq_parallel`` / ``expert_parallel`` inside a pipelined segment are
rejected at build time — the DSL layers implement those via GSPMD/shard_map
at the whole-graph level, which cannot nest inside gpipe's shard_map. The
fully-composed pp x tp x sp x ep step lives on the models/gpt.py path
(tested by the dryrun equivalence matrix).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

import jax.numpy as jnp

from ..utils.config import ConfigError


@dataclass
class PPSegment:
    start: int          # first layer index of the first repetition
    period: int         # layers per repetition
    count: int          # number of repetitions
    entry: int          # node id feeding the first repetition
    exit: int           # node id produced by the last repetition
    # nodes produced inside the segment other than exit — never
    # materialized under gpipe (metrics/extract must not bind to them)
    internal: frozenset = frozenset()

    @property
    def stop(self) -> int:
        return self.start + self.period * self.count


def _rep_nodes(specs, start, period):
    """(external_inputs, produced) node-id sets of one repetition."""
    produced = set()
    external = []
    for j in range(start, start + period):
        for n in specs[j].inputs:
            if n not in produced and n not in external:
                external.append(n)
        produced.update(specs[j].outputs)
    return external, produced


def _layer_ok(spec, layer) -> bool:
    # emits_aux_loss (MoE load-balance): run_pp_segment's inner context
    # discards ctx.losses, so such layers would silently train without
    # their auxiliary objective — keep them out of pipelined segments
    return not (spec.type == "share" or spec.pairtest is not None
                or layer.has_state or layer.uses_rng or layer.is_loss
                or getattr(layer, "emits_aux_loss", False))


def _has_params(layers, start, period) -> bool:
    """gpipe stacks per-rep params; a param-free candidate (e.g. repeated
    pooling) has nothing to shard over the pipe axis and nothing to gain —
    detection skips it rather than crash downstream."""
    from ..layers.base import Layer
    return any(type(layers[j]).init_params is not Layer.init_params
               for j in range(start, start + period))


def _iso(specs, start, period, r) -> Optional[Dict[int, int]]:
    """Node map rep0 -> rep r if they are structurally identical."""
    m: Dict[int, int] = {}
    for j in range(period):
        s0, sr = specs[start + j], specs[start + r * period + j]
        if (s0.type != sr.type or s0.cfg != sr.cfg
                or len(s0.inputs) != len(sr.inputs)
                or len(s0.outputs) != len(sr.outputs)):
            return None
        for a, b in zip(s0.inputs, sr.inputs):
            if m.setdefault(a, b) != b:
                return None
        for a, b in zip(s0.outputs, sr.outputs):
            if m.setdefault(a, b) != b:
                return None
    return m


def _count_reps(specs, layers, start, period) -> Optional[PPSegment]:
    """Longest chain of isomorphic single-entry/single-exit reps at start."""
    n = len(specs)
    if any(not _layer_ok(specs[j], layers[j])
           for j in range(start, start + period)):
        return None
    if not _has_params(layers, start, period):
        return None
    ext0, prod0 = _rep_nodes(specs, start, period)
    if len(ext0) != 1:
        return None
    entry = ext0[0]
    outs = specs[start + period - 1].outputs
    if len(outs) != 1 or outs[0] not in prod0:
        return None
    exit0 = outs[0]

    count, prev_exit = 1, exit0
    while start + (count + 1) * period <= n:
        r = count
        if any(not _layer_ok(specs[start + r * period + j],
                             layers[start + r * period + j])
               for j in range(period)):
            break
        m = _iso(specs, start, period, r)
        if m is None or m.get(entry) != prev_exit:
            break
        prev_exit = m[exit0]
        count += 1
    if count < 2:
        return None
    internal = set()
    for j in range(start, start + period * count):
        internal.update(specs[j].outputs)
    internal.discard(prev_exit)
    seg = PPSegment(start, period, count, entry, prev_exit,
                    frozenset(internal))
    # no internal node may leak: outside the segment, only seg.exit and
    # nodes that existed before the segment may be consumed
    for j in range(len(specs)):
        if seg.start <= j < seg.stop:
            continue
        if any(x in internal for x in specs[j].inputs):
            return None
    return seg


def find_pp_segment(graph, layers, n_stage: int) -> PPSegment:
    """The maximal pipelineable segment, or a precise ConfigError."""
    specs = graph.layers
    n = len(specs)
    best: Optional[PPSegment] = None
    for period in range(1, n // 2 + 1):
        for start in range(0, n - 2 * period + 1):
            seg = _count_reps(specs, layers, start, period)
            if seg and (best is None
                        or seg.period * seg.count > best.period * best.count):
                best = seg
    if best is None:
        raise ConfigError(
            "pipeline_parallel > 1 but no repeated block segment found: the "
            "net needs >= 2 consecutive structurally-identical single-entry/"
            "single-exit blocks of stateless rng-free layers without "
            "auxiliary losses (e.g. a dense transformer block stack; moe "
            "blocks pipeline only via the models/gpt.py path)")
    if best.count % n_stage:
        raise ConfigError(
            "pipeline_parallel = %d must divide the repeated block count %d "
            "(layers %d..%d)" % (n_stage, best.count, best.start,
                                 best.stop - 1))
    return best


def run_pp_segment(net, params, h, ctx):
    """Execute the detected segment through gpipe; returns the exit node."""
    from ..layers.base import ApplyContext
    from ..parallel.pipeline import gpipe

    seg: PPSegment = net._pp_segment
    g = net.graph
    stacked = {}
    for j in range(seg.period):
        per_rep = [net._layer_params(params, seg.start + r * seg.period + j)
                   for r in range(seg.count)]
        if per_rep[0]:
            stacked[str(j)] = {
                tag: jnp.stack([p[tag] for p in per_rep])
                for tag in per_rep[0]}
    # fresh context: no mesh (collectives cannot nest inside gpipe's
    # shard_map), no labels/losses/states (rejected at detection time)
    inner_ctx = ApplyContext(train=ctx.train, rng=None,
                             batch_size=ctx.batch_size,
                             update_period=ctx.update_period,
                             epoch=ctx.epoch)
    base = list(zip(g.layers[seg.start:seg.start + seg.period],
                    net.layers[seg.start:seg.start + seg.period]))

    exit0 = base[-1][0].outputs[0]     # rep-0 coordinates of the exit node

    def block_fn(pblock, x):
        local = {seg.entry: x}
        for j, (spec, layer) in enumerate(base):
            outs = layer.apply(pblock.get(str(j), {}),
                               [local[n] for n in spec.inputs], inner_ctx)
            for n, o in zip(spec.outputs, outs):
                local[n] = o
        return local[exit0]

    return gpipe(block_fn, stacked, h, net.mesh, net.pipeline_microbatch)
