"""Pipeline parallelism for netconfig-DSL models (``pipeline_parallel = k``).

The reference has no pipeline parallelism (SURVEY §2.7 lists it among the
designed-fresh axes); through round 3 the framework's gpipe schedule
(parallel/pipeline.py) was reachable only from models/gpt.py. This module
wires it into the config path: the Net detects the longest run of
structurally-identical repeated blocks in the parsed graph (a transformer's
`attention` block stack), stacks the per-repetition parameters along a
leading layer dim inside the jitted step, and runs the segment through
``gpipe`` — microbatches flow around the ``pipe`` mesh axis ring while each
stage applies its local blocks.

Detection contract (checked, with precise errors): each repetition must be
single-entry/single-exit, chained (rep r's entry is rep r-1's exit), and
contain only stateless, rng-free, non-loss, non-shared layers with identical
types and scoped config across repetitions. The repetition count must divide
the pipe axis.

Composition boundary (doc/multi-device.md): the config-DSL pipeline
composes with data parallelism (and ZeRO); ``model_parallel`` /
``seq_parallel`` / ``expert_parallel`` inside a pipelined segment are
rejected at build time — the DSL layers implement those via GSPMD/shard_map
at the whole-graph level, which cannot nest inside gpipe's shard_map. The
fully-composed pp x tp x sp x ep step lives on the models/gpt.py path
(tested by the dryrun equivalence matrix).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

import jax.numpy as jnp

from ..utils.config import ConfigError


@dataclass
class PPSegment:
    start: int          # first layer index of the first repetition
    period: int         # layers per repetition
    count: int          # number of repetitions
    entry: int          # node id feeding the first repetition
    exit: int           # node id produced by the last repetition
    # nodes produced inside the segment other than exit — never
    # materialized under gpipe (metrics/extract must not bind to them)
    internal: frozenset = frozenset()

    @property
    def stop(self) -> int:
        return self.start + self.period * self.count


def _rep_nodes(specs, start, period):
    """(external_inputs, produced) node-id sets of one repetition."""
    produced = set()
    external = []
    for j in range(start, start + period):
        for n in specs[j].inputs:
            if n not in produced and n not in external:
                external.append(n)
        produced.update(specs[j].outputs)
    return external, produced


def _layer_ok(spec, layer, allow_batch_stats: bool = False) -> bool:
    # emits_aux_loss (MoE load-balance): run_pp_segment's inner context
    # discards ctx.losses, so such layers would silently train without
    # their auxiliary objective — keep them out of pipelined segments.
    #
    # batch_norm: under the reference quirk default (moving_average = 0,
    # batch stats at eval) it is STATELESS, but its statistics are
    # per-batch — admissible for REMAT (the rep recomputes over the same
    # full batch, exact) and NOT for pipelining (gpipe applies the block
    # per MICROBATCH, which would silently change the statistics);
    # ``allow_batch_stats`` encodes which caller is asking (round 5).
    if spec.type == "batch_norm":
        # static flag, not init_state(): this predicate runs inside the
        # O(periods x starts) segment search and init_state() allocates
        # device arrays (review r5)
        return allow_batch_stats and not getattr(layer, "moving_average", 1)
    stateful = layer.has_state and bool(layer.init_state()) \
        if hasattr(layer, "init_state") else layer.has_state
    return not (spec.type == "share" or spec.pairtest is not None
                or stateful or layer.uses_rng or layer.is_loss
                or getattr(layer, "emits_aux_loss", False))


def _has_params(layers, start, period) -> bool:
    """gpipe stacks per-rep params; a param-free candidate (e.g. repeated
    pooling) has nothing to shard over the pipe axis and nothing to gain —
    detection skips it rather than crash downstream."""
    from ..layers.base import Layer
    return any(type(layers[j]).init_params is not Layer.init_params
               for j in range(start, start + period))


def _iso(specs, start, period, r) -> Optional[Dict[int, int]]:
    """Node map rep0 -> rep r if they are structurally identical."""
    m: Dict[int, int] = {}
    for j in range(period):
        s0, sr = specs[start + j], specs[start + r * period + j]
        if (s0.type != sr.type or s0.cfg != sr.cfg
                or len(s0.inputs) != len(sr.inputs)
                or len(s0.outputs) != len(sr.outputs)):
            return None
        for a, b in zip(s0.inputs, sr.inputs):
            if m.setdefault(a, b) != b:
                return None
        for a, b in zip(s0.outputs, sr.outputs):
            if m.setdefault(a, b) != b:
                return None
    return m


def _count_reps(specs, layers, start, period,
                allow_batch_stats: bool = False) -> Optional[PPSegment]:
    """Longest chain of isomorphic single-entry/single-exit reps at start."""
    n = len(specs)
    if any(not _layer_ok(specs[j], layers[j], allow_batch_stats)
           for j in range(start, start + period)):
        return None
    if not _has_params(layers, start, period):
        return None
    ext0, prod0 = _rep_nodes(specs, start, period)
    if len(ext0) != 1:
        return None
    entry = ext0[0]
    outs = specs[start + period - 1].outputs
    if len(outs) != 1 or outs[0] not in prod0:
        return None
    exit0 = outs[0]

    count, prev_exit = 1, exit0
    while start + (count + 1) * period <= n:
        r = count
        if any(not _layer_ok(specs[start + r * period + j],
                             layers[start + r * period + j],
                             allow_batch_stats)
               for j in range(period)):
            break
        m = _iso(specs, start, period, r)
        if m is None or m.get(entry) != prev_exit:
            break
        prev_exit = m[exit0]
        count += 1
    if count < 2:
        return None
    internal = set()
    for j in range(start, start + period * count):
        internal.update(specs[j].outputs)
    internal.discard(prev_exit)
    seg = PPSegment(start, period, count, entry, prev_exit,
                    frozenset(internal))
    # no internal node may leak: outside the segment, only seg.exit and
    # nodes that existed before the segment may be consumed
    for j in range(len(specs)):
        if seg.start <= j < seg.stop:
            continue
        if any(x in internal for x in specs[j].inputs):
            return None
    return seg


def find_block_segment(graph, layers,
                       allow_batch_stats: bool = False) -> Optional[PPSegment]:
    """The maximal repeated-block segment of the net, or None. Shared by
    pipeline parallelism (find_pp_segment, ``allow_batch_stats=False``:
    gpipe's per-microbatch application would change BN statistics) and
    block rematerialization (``remat = 1``, True: recompute over the same
    full batch is exact), so the two features agree on what "the block
    stack" is up to that one admission rule."""
    specs = graph.layers
    n = len(specs)
    best: Optional[PPSegment] = None
    for period in range(1, n // 2 + 1):
        for start in range(0, n - 2 * period + 1):
            seg = _count_reps(specs, layers, start, period,
                              allow_batch_stats)
            if seg and (best is None
                        or seg.period * seg.count > best.period * best.count):
                best = seg
    return best


def find_pp_segment(graph, layers, n_stage: int) -> PPSegment:
    """The maximal pipelineable segment, or a precise ConfigError."""
    best = find_block_segment(graph, layers)
    if best is None:
        raise ConfigError(
            "pipeline_parallel > 1 but no repeated block segment found: the "
            "net needs >= 2 consecutive structurally-identical single-entry/"
            "single-exit blocks of stateless rng-free layers without "
            "auxiliary losses (e.g. a dense transformer block stack; moe "
            "blocks pipeline only via the models/gpt.py path)")
    if best.count % n_stage:
        raise ConfigError(
            "pipeline_parallel = %d must divide the repeated block count %d "
            "(layers %d..%d)" % (n_stage, best.count, best.start,
                                 best.stop - 1))
    return best


def attn_saved_split(graph, seg: PPSegment) -> int:
    """The ``remat_mode = attn_saved`` boundary inside one repetition: the
    layer offset of the ``add`` closing the attention half (layers
    [0..split] run un-rematted so the flash custom-vjp's saved residuals
    are reused; [split+1..period) — the MLP half — rematerialize). The
    boundary must be a single-node cut; precise errors otherwise
    (models/gpt.py:_block_mlp_remat is the functional-path twin)."""
    specs = graph.layers[seg.start:seg.start + seg.period]
    attn = [j for j, s in enumerate(specs) if s.type == "attention"]
    if not attn:
        raise ConfigError(
            "remat_mode = attn_saved needs an attention layer in the "
            "repeated block segment (layers %d..%d have none); use "
            "remat_mode = block" % (seg.start, seg.stop - 1))
    adds = [j for j in range(attn[0] + 1, len(specs))
            if specs[j].type == "add"]
    if not adds:
        raise ConfigError(
            "remat_mode = attn_saved: no residual 'add' follows the "
            "attention layer in the repeated block; use remat_mode = block")
    split = adds[0]
    if len(specs[split].outputs) != 1:
        raise ConfigError("remat_mode = attn_saved: the attention-half "
                          "residual add must have one output")
    mid = specs[split].outputs[0]
    produced_late = set()
    for j in range(split + 1, len(specs)):
        for n in specs[j].inputs:
            if n != mid and n not in produced_late:
                raise ConfigError(
                    "remat_mode = attn_saved: the MLP half consumes node "
                    "%r across the remat boundary (only the attention-"
                    "residual output may cross); use remat_mode = block"
                    % (graph.node_names[n],))
        produced_late.update(specs[j].outputs)
    return split


def _segment_base(net, seg: PPSegment):
    """(spec, layer) pairs of repetition 0 + its exit node id."""
    base = list(zip(net.graph.layers[seg.start:seg.start + seg.period],
                    net.layers[seg.start:seg.start + seg.period]))
    return base, base[-1][0].outputs[0]


def _run_range(base, params_of, h, entry_node, j0, j1, ctx):
    """Apply base layers [j0, j1) with ``params_of(j)`` starting from
    ``h`` at ``entry_node``; returns the local node dict."""
    local = {entry_node: h}
    for j in range(j0, j1):
        spec, layer = base[j]
        outs = layer.apply(params_of(j), [local[n] for n in spec.inputs],
                           ctx)
        for n, o in zip(spec.outputs, outs):
            local[n] = o
    return local


# ---------------------------------------------------------------------------
# tensor parallelism inside the pipelined segment (round 5)
# ---------------------------------------------------------------------------
# Inside gpipe's shard_map GSPMD does not reach, so weight sharding over
# the ``model`` axis needs layer-aware execution plans. Three plans cover
# the transformer block zoo:
#   "attn"     — megatron attention: the stacked qkv weight is PERMUTED at
#                stack time from [q;k;v] row blocks to per-head groups
#                [q_h0;k_h0;v_h0;q_h1;...] so "heads" becomes a contiguous
#                dim-0 sharding; each shard runs its local heads and the
#                row-sharded output projection closes with ONE psum
#                (autodiff of shard_map transposes it correctly — the
#                gpt.py gpipe path has pinned this since round 2).
#   "conv_col" — 1x1 ungrouped conv (the position-wise MLP halves):
#                column-parallel out-channel sharding + an all_gather.
#   "plain"    — anything else: weights replicated over ``model``, applied
#                as-is (identical per-shard compute — always correct, no
#                tp speedup for that layer; LN/add/split/relu land here).


def _pp_tp_plan(net, seg, n_tp: int):
    """Per-rep-offset execution plans + the PartitionSpec pytree for the
    stacked params (leading dim = pipe)."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import MODEL_AXIS, PIPE_AXIS
    plans = {}
    specs = {}
    for j in range(seg.period):
        spec_j, layer = net.graph.layers[seg.start + j], \
            net.layers[seg.start + j]
        tags = net._layer_params(net.params, seg.start + j)
        if not tags:
            continue
        plan = "plain"
        if n_tp > 1 and spec_j.type == "attention" \
                and layer.nhead % n_tp == 0:
            plan = "attn"
            table = {
                "qkv": P(PIPE_AXIS, MODEL_AXIS, None),
                "proj": P(PIPE_AXIS, None, MODEL_AXIS),
                "qkv_bias": P(PIPE_AXIS, MODEL_AXIS),
                "proj_bias": P(PIPE_AXIS),
            }
        elif n_tp > 1 and spec_j.type == "conv" \
                and layer.param.kernel_width == 1 \
                and layer.param.kernel_height == 1 \
                and layer.param.num_group == 1 \
                and layer.param.num_channel % n_tp == 0:
            plan = "conv_col"
            table = {
                "wmat": P(PIPE_AXIS, None, None, None, MODEL_AXIS),
                "bias": P(PIPE_AXIS, MODEL_AXIS),
            }
        else:
            table = {}
        # specs must mirror the tags ACTUALLY present (no_bias layers
        # lack the bias tags; a fixed table would break the shard_map
        # in_specs pytree match)
        specs[str(j)] = {tag: table.get(tag, P(PIPE_AXIS))
                         for tag in tags}
        plans[j] = plan
    return plans, specs


def _permute_qkv_rows(qkv, nhead: int):
    """(3F, F) [q;k;v] row blocks -> per-head groups (h, 3, d, F) ->
    (3F, F), so a contiguous dim-0 shard is whole heads of q, k AND v.
    Applied inside the jitted step, so autodiff transposes it — the
    gradients come back in the original layout. The (3F,) bias permutes
    the same way (zero-init biases make a layout mismatch invisible in
    the forward; only gradients would reveal it)."""
    if qkv.ndim == 1:
        return jnp.transpose(qkv.reshape(3, nhead, -1),
                             (1, 0, 2)).reshape(qkv.shape[0])
    f3, f = qkv.shape
    d = f3 // 3 // nhead
    return jnp.transpose(qkv.reshape(3, nhead, d, f),
                         (1, 0, 2, 3)).reshape(f3, f)


def _apply_attn_tp(layer, pblock, x, axis_name: str, n_tp: int):
    """Megatron attention on a per-head qkv shard (permuted layout):
    local heads, row-sharded projection, one psum."""
    from jax import lax

    from ..ops.attention import local_attention
    b, n, _, f = x.shape
    h_loc = layer.nhead // n_tp
    d = f // layer.nhead
    xs = x.reshape(b, n, f)
    w = pblock["qkv"].astype(xs.dtype).reshape(h_loc, 3, d, f)
    q = jnp.einsum("bnf,hdf->bnhd", xs, w[:, 0])
    k = jnp.einsum("bnf,hdf->bnhd", xs, w[:, 1])
    v = jnp.einsum("bnf,hdf->bnhd", xs, w[:, 2])
    if "qkv_bias" in pblock:
        bias = pblock["qkv_bias"].astype(q.dtype).reshape(h_loc, 3, d)
        q = q + bias[None, None, :, 0]
        k = k + bias[None, None, :, 1]
        v = v + bias[None, None, :, 2]
    att = local_attention(q, k, v, causal=bool(layer.causal))
    # proj (F, F) applied as x @ proj.T: input features (dim 1) are
    # head-ordered, so the model shard is this rank's head block
    wp = pblock["proj"].astype(xs.dtype)          # (F, f_loc)
    out = lax.psum(att.reshape(b, n, h_loc * d) @ wp.T, axis_name)
    if "proj_bias" in pblock:
        out = out + pblock["proj_bias"].astype(out.dtype)
    return out.reshape(b, n, 1, f)


def _apply_conv_col_tp(layer, pblock, x, axis_name: str):
    """1x1 conv, out-channels column-sharded: local matmul + all_gather."""
    from jax import lax
    w = pblock["wmat"][0, 0].astype(x.dtype)      # (Cin, Cout/tp)
    out = x @ w
    if "bias" in pblock:
        out = out + pblock["bias"].astype(out.dtype)
    return lax.all_gather(out, axis_name, axis=-1, tiled=True)


def run_pp_segment(net, params, h, ctx):
    """Execute the detected segment through gpipe; returns the exit node.
    With ``remat = 1`` each block body is rematerialized inside the
    pipeline (remat_mode block / attn_saved); with ``model_parallel > 1``
    the attention/MLP weights shard over the ``model`` axis via the
    per-layer plans above — the same levers as the models/gpt.py
    flagship, from the config file."""
    import jax

    from ..layers.base import ApplyContext
    from ..parallel.mesh import MODEL_AXIS
    from ..parallel.pipeline import gpipe

    seg: PPSegment = net._pp_segment
    n_tp = net.mesh.shape.get(MODEL_AXIS, 1)
    plans, specs = _pp_tp_plan(net, seg, n_tp)
    stacked = {}
    for j in range(seg.period):
        per_rep = [net._layer_params(params, seg.start + r * seg.period + j)
                   for r in range(seg.count)]
        if per_rep[0]:
            stacked[str(j)] = {
                tag: jnp.stack([_permute_qkv_rows(
                    p[tag], net.layers[seg.start + j].nhead)
                    if plans.get(j) == "attn"
                    and tag in ("qkv", "qkv_bias")
                    else p[tag] for p in per_rep])
                for tag in per_rep[0]}
    # fresh context: no mesh (collectives cannot nest inside gpipe's
    # shard_map), no labels/losses/states (rejected at detection time)
    inner_ctx = ApplyContext(train=ctx.train, rng=None,
                             batch_size=ctx.batch_size,
                             update_period=ctx.update_period,
                             epoch=ctx.epoch,
                             compute_dtype=ctx.compute_dtype)
    base, exit0 = _segment_base(net, seg)

    def params_of(pblock, j):
        return pblock.get(str(j), {})

    def apply_layer(pblock, j, spec_l, layer, inputs):
        plan = plans.get(j, "plain")
        if plan == "attn":
            return [_apply_attn_tp(layer, params_of(pblock, j), inputs[0],
                                   MODEL_AXIS, n_tp)]
        if plan == "conv_col":
            return [_apply_conv_col_tp(layer, params_of(pblock, j),
                                       inputs[0], MODEL_AXIS)]
        return layer.apply(params_of(pblock, j), inputs, inner_ctx)

    def run_range_tp(pblock, x, entry_node, j0, j1):
        local = {entry_node: x}
        for j in range(j0, j1):
            spec_l, layer = base[j]
            outs = apply_layer(pblock, j, spec_l, layer,
                               [local[n] for n in spec_l.inputs])
            for n, o in zip(spec_l.outputs, outs):
                local[n] = o
        return local

    def whole(pblock, x):
        return run_range_tp(pblock, x, seg.entry, 0, seg.period)[exit0]

    if net.remat and net._remat_split is not None:
        split = net._remat_split
        mid = base[split][0].outputs[0]

        def block_fn(pblock, x):
            hm = run_range_tp(pblock, x, seg.entry, 0, split + 1)[mid]
            return jax.checkpoint(
                lambda pb, hh: run_range_tp(pb, hh, mid, split + 1,
                                            seg.period)[exit0])(pblock, hm)
    elif net.remat:
        block_fn = jax.checkpoint(whole)
    else:
        block_fn = whole

    return gpipe(block_fn, stacked, h, net.mesh, net.pipeline_microbatch,
                 param_specs=specs)


def run_remat_segment(net, params, h, ctx):
    """Execute the repeated block segment with per-repetition
    ``jax.checkpoint`` (``remat = 1`` without a pipeline axis): activation
    memory drops from O(layers) to O(count) block boundaries + one live
    block, at ~1/3 extra FLOPs in the backward — the models/gpt.py remat
    levers on the config path. remat_mode "attn_saved" leaves the
    attention half un-rematted (the flash custom-vjp's residuals stay
    saved; only the MLP half recomputes)."""
    import jax

    seg: PPSegment = net._remat_segment
    base, exit0 = _segment_base(net, seg)
    split = net._remat_split
    for r in range(seg.count):
        plist = [net._layer_params(params, seg.start + r * seg.period + j)
                 for j in range(seg.period)]
        if split is None:
            h = jax.checkpoint(
                lambda pl, hh: _run_range(base, lambda j: pl[j], hh,
                                          seg.entry, 0, seg.period,
                                          ctx)[exit0])(plist, h)
        else:
            mid = base[split][0].outputs[0]
            h_mid = _run_range(base, lambda j: plist[j], h, seg.entry, 0,
                               split + 1, ctx)[mid]
            h = jax.checkpoint(
                lambda pl, hh: _run_range(base, lambda j: pl[j - split - 1],
                                          hh, mid, split + 1, seg.period,
                                          ctx)[exit0])(plist[split + 1:],
                                                       h_mid)
    return h
