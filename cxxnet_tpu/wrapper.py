"""User-facing Python API mirroring the reference wrapper
(/root/reference/wrapper/cxxnet.py:64-307 DataIter/Net/train).

The reference routes every call through a C ABI into the C++ trainer; here
the trainer IS Python/JAX, so this module is a thin semantic adapter giving
reference users the same call surface: config-string-constructed iterators,
``Net(dev, cfg)``, numpy-in/numpy-out update/predict/extract/evaluate, and
the ``train()`` convenience loop. The C ABI itself (CXNNet*/CXNIO*,
cxxnet_wrapper.h:36-232) is provided for other languages by
``native/capi.cpp`` which embeds CPython and calls into this module.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple, Union

import numpy as np

from .io import create_iterator
from .io.data import DataBatch
from .io.device_prefetch import DeviceBatch, DevicePrefetcher
from .nnet.net import Net as _CoreNet
from .utils.config import tokenize

Array = np.ndarray


def _cfg_pairs(cfg: str) -> List[Tuple[str, str]]:
    return tokenize(cfg)


class DataIter:
    """Config-string data iterator (cxxnet.py:64-103 semantics).

    The config uses the same ``iter = <type> ... iter = end`` block grammar
    as the CLI; global pairs outside the block are also applied.
    """

    def __init__(self, cfg: str):
        self._iter = create_iterator(_cfg_pairs(cfg))   # factory inits it
        self._valid = False

    def next(self) -> bool:
        self._valid = self._iter.next()
        return self._valid

    def before_first(self) -> None:
        self._iter.before_first()
        self._valid = False

    def check_valid(self) -> None:
        if not self._valid:
            raise RuntimeError("DataIter: no valid batch "
                               "(call next() and check its result)")

    def close(self) -> None:
        """Stop prefetch threads and release buffers (safe to call twice)."""
        if self._iter is not None and hasattr(self._iter, "close"):
            self._iter.close()
        self._iter = None
        self._valid = False

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    @property
    def batch(self) -> DataBatch:
        self.check_valid()
        return self._iter.value()

    def get_data(self) -> Array:
        return np.asarray(self.batch.data)

    def get_label(self) -> Array:
        return np.asarray(self.batch.label)


def _as_batch(data: Union[DataIter, DataBatch, "DeviceBatch", Array],
              label: Optional[Array] = None):
    if isinstance(data, DataIter):
        return data.batch
    if isinstance(data, (DataBatch, DeviceBatch)):
        return data
    data = np.asarray(data, np.float32)
    if data.ndim == 2:            # (batch, feat) -> (batch, 1, 1, feat)
        data = data.reshape(data.shape[0], 1, 1, data.shape[1])
    if label is None:
        label = np.zeros((data.shape[0], 1), np.float32)
    label = np.asarray(label, np.float32)
    if label.ndim == 1:
        label = label.reshape(-1, 1)
    return DataBatch(data, label)


class Net:
    """Reference-compatible trainer facade (cxxnet.py:105-279).

    ``dev`` follows the reference device-string syntax mapped to TPU
    (``dev='tpu'``/``'cpu'``/``'tpu:0-3'``); ``cfg`` is the full config text
    including the ``netconfig`` block.
    """

    def __init__(self, dev: str = "", cfg: str = ""):
        self._cfg_text = cfg            # kept for lint() line numbers
        self._net = _CoreNet(_cfg_pairs(cfg))
        self._n_ctor_pairs = len(self._net.cfg)
        if dev:
            self._net.set_param("dev", dev)

    # -- lifecycle ----------------------------------------------------
    def set_param(self, name: str, value) -> None:
        self._net.set_param(str(name), str(value))

    def init_model(self) -> None:
        self._net.init_model()

    def load_model(self, fname: str) -> None:
        self._net.load_model(fname)

    def save_model(self, fname: str) -> None:
        self._net.save_model(fname)

    def start_round(self, round_counter: int) -> None:
        self._net.start_round(round_counter)

    # -- training -----------------------------------------------------
    def update(self, data, label: Optional[Array] = None) -> None:
        """One step on a DataIter batch, a DataBatch, or a numpy pair
        (cxxnet.py:152-180)."""
        self._net.update(_as_batch(data, label))

    def evaluate(self, data: Optional[DataIter], name: str) -> str:
        """Metric line '[round] name-metric:value...' (cxxnet.py:182-194)."""
        it = data._iter if isinstance(data, DataIter) else data
        return self._net.evaluate(it, name)

    # -- inference ----------------------------------------------------
    def predict(self, data, label: Optional[Array] = None) -> Array:
        """Label prediction; argmax for vector outputs (cxxnet.py:196-217).
        Accepts a DataIter (whole-epoch prediction) or one batch."""
        if isinstance(data, DataIter):
            outs = []
            data.before_first()
            while data.next():
                outs.append(self._net.predict(data.batch))
            return np.concatenate(outs) if outs else np.zeros((0,), np.float32)
        return self._net.predict(_as_batch(data, label))

    def extract(self, data, name: str, label: Optional[Array] = None) -> Array:
        """Feature extraction by node name or 'top[-k]' (cxxnet.py:219-242)."""
        if isinstance(data, DataIter):
            outs = []
            data.before_first()
            while data.next():
                outs.append(self._net.extract_feature(data.batch, name))
            return np.concatenate(outs) if outs else np.zeros((0,), np.float32)
        return self._net.extract_feature(_as_batch(data, label), name)

    def generate(self, prompt: Array, max_new: int,
                 temperature: float = 0.0, seed: int = 0,
                 top_k: int = 0, top_p: float = 1.0,
                 speculative=None) -> Array:
        """Autoregressive generation from a GPT-shaped net (gpt_lm_config
        structure): prompt (b, n_prompt) int token ids -> (b, n_prompt +
        max_new) int32. Greedy at temperature 0, else categorical
        sampling, optionally top-k / top-p (nucleus) restricted — the
        filters compose with temperature (ops/sampling.py; 0 / 1.0
        disable). Drives the models/gpt.py fused whole-step decode kernel
        — no reference counterpart (the reference has no sequence models,
        SURVEY §5.7); the CLI twin is ``task = generate``.

        ``speculative``: draft-and-verify multi-token decoding — an int
        ``spec_len`` for the n-gram/prompt-lookup drafter, or a dict
        ``{"mode", "spec_len", "model", "stats"}``
        (``gpt_decode(speculative=...)``; greedy output is
        bit-identical, sampled output identical in distribution)."""
        import jax
        from .nnet.lm import net_generate
        rng = jax.random.PRNGKey(seed) if temperature > 0 else None
        return net_generate(self._net, np.asarray(prompt, np.int64),
                            max_new, temperature=temperature, rng=rng,
                            top_k=top_k, top_p=top_p,
                            speculative=speculative)

    # -- online serving (doc/serving.md) ------------------------------
    def serve_start(self, slots: int = 8, queue: int = 32,
                    timeout_ms: float = 0.0, prefill_chunk: int = 64,
                    prefill_budget: int = 1, prefix_mb: float = 32.0,
                    recompile_limit: int = 0, recompile_strict: bool = True,
                    spec_mode: str = "off", spec_len: int = 4,
                    spec_model=None, slow_ms: float = 0.0, tracer=None,
                    registry=None, prof_every: int = 0,
                    paged: bool = True, block_size: int = 0,
                    num_blocks: int = 0, kv_mb: float = 0.0,
                    fused_attn: bool = True, chaos: str = "",
                    max_restarts: int = 3, watchdog_ms: float = 0.0,
                    degrade: bool = True, tp: int = 0,
                    replicas: int = 1, router_policy: str = "prefix",
                    tenants: str = "", int8_weights: bool = False,
                    int4_weights: bool = False, int4_group: int = 64,
                    kv_dtype: str = "", aot_cache: str = "",
                    lora: str = "", lora_rank: int = 8,
                    lora_pool_mb: float = 0.0, lora_adapters=None,
                    fleet: str = "", aot_relabel=None, worker_env=None,
                    **defaults) -> None:
        """Start the continuous-batching inference server over this net's
        decode path (serve/InferenceServer; the CLI twin is ``task =
        serve``). ``prefill_chunk``/``prefill_budget`` shape the chunked
        prefill (0 = legacy whole-prompt prefill), ``prefix_mb`` budgets
        the shared-prefix KV cache (0 disables reuse), and
        ``paged``/``block_size``/``num_blocks``/``kv_mb`` shape the
        paged KV cache — on by default: a global block pool with
        per-row block tables, zero-copy copy-on-write prefix sharing,
        and preemption/swap to host under pool pressure, so admitted
        concurrency scales with tokens in flight (``num_blocks=0``
        auto-sizes to dense-equivalent capacity plus trie headroom, or
        to a ``kv_mb`` MiB budget; ``paged=False`` keeps the dense slot
        pool — doc/serving.md "Paged KV cache"). ``fused_attn`` routes
        the paged tick/verify attention through the fused Pallas
        block-table-walk kernel where the backend supports it
        (``False`` or ``CXN_FUSED_ATTN=0`` pins the XLA gather
        bit-reference — doc/serving.md "Fused paged attention").
        ``recompile_limit`` extends the recompilation guard to the
        engine's prefill/chunk/verify/tick programs
        (``recompile_strict=False`` logs CXN205 instead of raising, the
        CLI's ``lint_recompile_strict=0`` mode).

        Speculative decoding: ``spec_mode`` ∈ off | ngram | model with
        ``spec_len`` draft tokens verified per forward; ``spec_model``
        (mode=model) is a ``(draft_cfg, draft_params)`` pair or another
        GPT-shaped ``wrapper.Net`` (exported automatically). Per-request
        overrides ride in ``serve_submit(spec_mode=..., spec_len=...)``.
        ``defaults`` seed the per-request SamplingParams (max_tokens /
        temperature / top_k / top_p / seed / eos / spec_mode /
        spec_len).

        Observability (doc/observability.md): ``slow_ms`` arms the
        slow-request span-tree exemplar dump; ``tracer`` / ``registry``
        override the span tracer (default: the process-global one —
        what :meth:`trace_export` reads) and the metrics registry
        (default: a server-private one — what :meth:`metrics_text`
        renders); ``prof_every`` arms the device/compiler observatory
        (obs/devprof.py — per-program cost table + one blocking
        device-time sample per N executions publishing live
        ``cxn_mfu{fn=}`` gauges; 0 = off, the CLI serves with 64).

        Resilience (serve/resilience.py, doc/serving.md "Resilience"):
        an engine-fatal fault or — with ``watchdog_ms`` > 0 — a stalled
        loop tears the pool down, rebuilds the engine cold, and replays
        every admitted request bit-identically from its journal record;
        ``max_restarts`` bounds the rebuilds (typed EngineFailedError
        beyond it). ``chaos`` arms the fault-injection harness
        (``CXN_CHAOS`` env overrides; empty = true no-op) and
        ``degrade`` the graceful-degradation ladder (spec off ->
        prefix admission off -> deadline-aware shedding with
        ``retry_after_ms`` hints); :meth:`serve_health` reports
        SERVING / DEGRADED / DRAINING / FAILED.

        Sharded & replicated serving (doc/serving.md): ``tp`` > 1
        shards the decode engine over a model-axis mesh of the first
        ``tp`` local devices (gather-form TP — KV pool head-sharded,
        weights on their output dims, served tokens bit-identical to
        the single-device engine; needs ``n_head % tp == 0`` and
        chunked prefill). ``replicas`` > 1 runs that many engine
        replicas behind the prefix- and health-aware router
        (serve/router.py; ``router_policy`` ∈ prefix | rr) — submit /
        result / metrics / health keep working, failover replays live
        requests on survivors, and :meth:`metrics_text` becomes the
        merged per-replica scrape payload.

        Multi-tenant SLOs (serve/tenancy.py, doc/serving.md
        "Multi-tenant SLOs"): ``tenants`` is the ``serve_tenants``
        policy spec — per-tenant priority classes (guaranteed /
        standard / best_effort), queue/slot/KV-block quotas,
        token-bucket rate limits with ``retry_after_ms`` refill hints,
        and default deadlines; requests opt in via
        ``serve_submit(tenant=...)``. Empty (the default) is a pinned
        no-op — the untenanted server is bit-identical.

        Quantized serving (doc/serving.md "Quantized serving"):
        ``int8_weights`` streams the engine's block matmul weights
        int8-quantized (per-out-column, quantized once at build;
        speculative verify included); ``kv_dtype="int8"`` stores the
        paged KV pool per-block-scaled int8 — ~2x tokens per ``kv_mb``
        and halved swap bandwidth, accuracy pinned by
        ``serve.engine.kv_int8_tolerance``. ``int4_weights`` streams
        the block weights as packed nibbles with group-wise symmetric
        scales (``int4_group`` in-rows per group, 0 = per-out-column)
        through the fused Pallas dequant-matmul where supported —
        doc/serving.md "Int4 weights", accuracy pinned by
        ``serve.engine.w_int4_tolerance``; exclusive with
        ``int8_weights``. All default off (pinned no-ops).

        AOT executable cache (doc/performance.md "AOT executable
        cache"): ``aot_cache`` is a directory of serialized compiled
        serve programs (``CXN_AOT_CACHE`` env is the fallback) — a
        warm start LOADS the engine's chunk-prefill/verify/tick
        executables instead of compiling them, and every recovery
        rebuild / replica spin-up over the same key does the same.
        Empty (the default) is a pinned no-op.

        Batched multi-LoRA (serve/lora.py, doc/serving.md "Batched
        multi-LoRA"): ``lora`` is the ``serve_lora`` adapter registry
        spec (``name:path.npz;...``) — armed, requests opt in via
        ``serve_submit(adapter=...)`` and ONE batched tick serves the
        mixed adapter population through a paged device pool of rank-
        ``lora_rank`` factor pages (``lora_pool_mb`` MiB budget, 0 =
        whole registry resident; ``lora_adapters`` injects in-memory
        adapter dicts for tests). Paged engine only. Empty (the
        default) is a pinned STRUCTURAL no-op.

        Cross-process fleet (serve/fleet.py, doc/serving.md
        "Disaggregated fleet"): ``fleet`` is a tier spec —
        ``"prefill=1,decode=2"`` (or a bare worker count for a
        decode-only pool) — that serves from that many separate OS
        processes behind the out-of-process RPC router instead of
        in-process engines: prefill workers chunk-prefill and the
        checksummed KV record migrates over a socket to a decode
        worker; a SIGKILL'd worker's requests replay bit-identically
        on survivors from the router's journal. ``aot_relabel``
        (default: on when ``aot_cache`` is set) lets replacement
        workers reuse AOT artifacts across device relabeling for
        zero-compile spin-up. Empty (the default) is a pinned no-op —
        no sockets, no processes, the in-process paths above are
        untouched."""
        from .nnet.lm import net_gpt_export
        from .serve import InferenceServer, SamplingParams, ServeRouter
        if getattr(self, "_server", None) is not None:
            raise RuntimeError("serve_start: server already running "
                               "(call serve_stop first)")
        if isinstance(spec_model, Net):
            spec_model = net_gpt_export(spec_model._net)
        cfg, params = net_gpt_export(self._net)
        kw = dict(
            slots=slots, queue=queue, timeout_ms=timeout_ms,
            prefill_chunk=prefill_chunk, prefill_budget=prefill_budget,
            prefix_mb=prefix_mb, recompile_limit=recompile_limit,
            recompile_strict=recompile_strict, spec_mode=spec_mode,
            spec_len=spec_len, spec_model=spec_model, slow_ms=slow_ms,
            tracer=tracer, prof_every=prof_every,
            paged=paged, block_size=block_size, num_blocks=num_blocks,
            kv_mb=kv_mb, fused_attn=fused_attn, chaos=chaos,
            max_restarts=max_restarts, watchdog_ms=watchdog_ms,
            degrade=degrade, tp=tp, tenants=tenants,
            int8_weights=int8_weights, int4_weights=int4_weights,
            int4_group=int4_group, kv_dtype=kv_dtype,
            aot_cache=aot_cache, lora=lora, lora_rank=lora_rank,
            lora_pool_mb=lora_pool_mb, lora_adapters=lora_adapters,
            defaults=SamplingParams(**defaults))
        if fleet.strip():
            # worker processes own their registries and tracers (the
            # spec crosses a process boundary); the merged scrape is
            # metrics_text() — reject what cannot ride along instead
            # of silently dropping it
            if registry is not None or tracer is not None:
                raise ValueError(
                    "serve_start(fleet=%r, registry=.../tracer=...): "
                    "fleet workers own their registries and tracers; "
                    "scrape the merged payload via metrics_text()"
                    % fleet)
            if replicas > 1:
                raise ValueError(
                    "serve_start(fleet=%r, replicas=%d): the fleet "
                    "spec already sizes the worker pool" % (fleet,
                                                            replicas))
            from .serve import FleetRouter, parse_tiers
            tiers = parse_tiers(fleet)
            kw.pop("tracer")
            self._server = FleetRouter(cfg, params,
                                       prefill=tiers["prefill"],
                                       decode=tiers["decode"],
                                       aot_relabel=aot_relabel,
                                       worker_env=worker_env, **kw)
        elif replicas > 1:
            # each replica owns its registry; the merged payload is
            # metrics_text() (a caller-supplied registry would make the
            # replicas' gauges fight over one name set) — surface the
            # conflict instead of silently leaving the registry empty
            if registry is not None:
                raise ValueError(
                    "serve_start(replicas=%d, registry=...): replicas "
                    "own their registries; scrape the merged payload "
                    "via metrics_text()" % replicas)
            self._server = ServeRouter(cfg, params, replicas=replicas,
                                       policy=router_policy, **kw)
        else:
            self._server = InferenceServer(cfg, params,
                                           registry=registry, **kw)

    def _serving(self):
        srv = getattr(self, "_server", None)
        if srv is None:
            raise RuntimeError("no server running (call serve_start)")
        return srv

    def serve_submit(self, prompt: Array, block: bool = False,
                     tenant: str = "", **params):
        """Enqueue one request -> handle (per-request ``params`` override
        the serve_start defaults; ``tenant`` labels the request when
        ``serve_start(tenants=...)`` armed the policy registry;
        ``adapter=`` names the request's LoRA adapter when
        ``serve_start(lora=...)`` armed the pool).
        Raises serve.QueueFullError when the bounded admission queue is
        full (unless ``block=True``) and serve.QuotaExceededError when
        the tenant is over its rate or queue quota."""
        return self._serving().submit(np.asarray(prompt, np.int64),
                                      block=block, tenant=tenant,
                                      **params)

    def serve_result(self, handle, timeout=None):
        """Block for a handle's ServeResult (status / full token
        sequence / TTFT + per-token latency)."""
        return self._serving().result(handle, timeout=timeout)

    def serve_metrics(self) -> Dict:
        """Serving health snapshot (p50/p95/p99 TTFT and tick latencies,
        queue depth, slot occupancy, batch efficiency)."""
        return self._serving().metrics()

    def serve_health(self) -> Dict:
        """Liveness + degradation snapshot (doc/serving.md
        "Resilience"): state SERVING / DEGRADED / DRAINING / FAILED,
        the ladder rung, restart/replay/shed accounting, and the
        current ``retry_after_ms`` hint while shedding."""
        return self._serving().health()

    def serve_stop(self, drain: bool = True) -> None:
        """Stop the server (``drain=True`` finishes in-flight + queued
        requests first); idempotent."""
        srv = getattr(self, "_server", None)
        if srv is not None:
            srv.shutdown(drain=drain)
            self._server = None

    # -- observability (doc/observability.md) -------------------------
    def metrics_text(self) -> str:
        """Prometheus text exposition. While serving, the running
        server's registry (serving + prefix-cache + speculative +
        recompile-guard metrics — the scrape payload); otherwise the
        process-global registry (training counters, trainer recompile
        trips)."""
        srv = getattr(self, "_server", None)
        if srv is not None:
            return srv.metrics_text()
        from .obs.metrics import default_registry
        return default_registry().to_prometheus()

    def profile(self, time_reps: int = 3):
        """Device & compiler observatory over this net's four jitted
        train steps (obs/devprof.py; the CLI twin is ``task = prof``):
        AOT-extracts each program's XLA cost/memory model, times the
        executables ``time_reps`` times on zero-filled inputs
        (``time_reps=0`` skips timing), publishes the
        ``cxn_program_*`` gauges into the process registry — which
        also gives a ``prof_every``-armed net's live ``cxn_mfu{fn=}``
        gauges their FLOPs — and returns the
        :class:`~cxxnet_tpu.obs.devprof.CostTable` (print
        ``.format_roofline()`` for the human table)."""
        from .obs import devprof
        from .obs.metrics import default_registry
        if not self._net._initialized:
            raise RuntimeError("profile() needs an initialized net "
                               "(call init_model or load_model first)")
        return devprof.profile_net(self._net,
                                   registry=default_registry(),
                                   time_reps=time_reps)

    def trace_export(self, path: Optional[str] = None):
        """The process-global span tracer's ring as a Chrome-trace JSON
        object (obs/trace.py; loadable in Perfetto /
        chrome://tracing). ``path`` also writes it to a file; returns
        the dict either way."""
        import json
        from .obs.trace import get_tracer
        doc = get_tracer().chrome_trace()
        if path:
            with open(path, "w") as f:
                json.dump(doc, f)
        return doc

    # -- static analysis (doc/lint.md) --------------------------------
    def lint(self, compile: bool = False):
        """Run cxn-lint over this net's config: pass 1 (graph/config,
        no devices; line numbers refer to the constructor's ``cfg``
        string, later ``set_param`` pairs lint as line-less) and — with
        ``compile=True`` on an initialized net — pass 2, the
        compiled-step audit (donation aliasing, dtype promotion, host
        transfers, collectives). Returns the
        :class:`~cxxnet_tpu.analysis.LintReport`."""
        from .analysis import audit_net, lint_config_text
        extra = [(k, v) for k, v in self._net.cfg[self._n_ctor_pairs:]]
        report = lint_config_text(self._cfg_text, path="<cfg>",
                                  extra_pairs=extra).report
        if compile:
            if not self._net._initialized:
                raise RuntimeError("lint(compile=True) needs an "
                                   "initialized net (call init_model or "
                                   "load_model first)")
            step_report, _ = audit_net(self._net)
            report.extend(step_report.findings)
        return report

    # -- weight surgery -----------------------------------------------
    def set_weight(self, weight: Array, layer_name: str, tag: str) -> None:
        self._net.set_weight(layer_name, tag, np.asarray(weight, np.float32))

    def get_weight(self, layer_name: str, tag: str) -> Array:
        return self._net.get_weight(layer_name, tag)

    # escape hatch to the full trainer (superset of the reference ABI)
    @property
    def core(self) -> _CoreNet:
        return self._net


def train(cfg: str, data: DataIter, num_round: int,
          param: Dict[str, object],
          eval_data: Optional[DataIter] = None) -> Net:
    """Convenience training loop (cxxnet.py:281-307): build Net from config,
    apply ``param`` overrides, run ``num_round`` epochs over ``data``,
    printing eval lines per round.

    Feeds through the async device prefetcher by default (batch k+1's
    host->device placement overlaps step k's compute — io/device_prefetch
    .py); ``param['prefetch_to_device'] = 0`` restores the synchronous
    path, any other N sets the bounded-queue depth."""
    net = Net(cfg=cfg)
    depth = 2
    for k, v in param.items():
        if k == "prefetch_to_device":
            depth = int(v)
        net.set_param(k, v)
    net.init_model()
    feed = DevicePrefetcher(net.core.place_batch, data._iter, depth=depth) \
        if depth > 0 else data._iter
    try:
        for r in range(num_round):
            net.start_round(r)
            feed.before_first()
            while feed.next():
                net.core.update(feed.value())
            line = net.evaluate(eval_data, "eval")
            if line:
                print("[%d]%s" % (r, line))
    finally:
        if isinstance(feed, DevicePrefetcher):
            feed.close()
    return net


__all__ = ["DataIter", "Net", "train"]
