"""Network graph IR — the model structure parsed from the ``netconfig`` DSL.

Capability parity with the reference model IR (/root/reference/src/nnet/nnet_config.h:26-411):
an ordered list of layers over a set of named nodes. Grammar accepted for layer
declarations (nnet_config.h:303-360):

- ``layer[+1:tag] = type:name``  — input is the previous top node, output is a
  new node named ``tag``
- ``layer[+1] = type``           — output is a fresh anonymous node
- ``layer[+0] = type``           — self-loop layer (in == out), e.g. dropout, losses
- ``layer[a,b->c] = type``       — explicit node names/indices, comma-separated fan-in/out
- ``layer[...] = share[tag]``    — weight sharing with the primary layer named ``tag``
- node 0 is named ``in``; ``extra_data_num = k`` adds nodes ``in_1..in_k``

Config scoping (nnet_config.h:207-289): lines before/after the net block are
global (``defcfg``); non-layer lines after a ``layer[...]`` declaration attach
to that layer (``layercfg``). ``label_vec[a,b) = name`` registers named label
fields (nnet_config.h:192-203); field ``label`` -> column 0 by default.

The IR is framework-neutral: execution happens in :mod:`cxxnet_tpu.nnet` by
walking ``layers`` in order (forward) — functional JAX, no mutation.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .utils.config import ConfigError

Pairs = List[Tuple[str, str]]

# layer types with no factory case in the reference (dead enums, layer.h:304/:290):
# 'maxout' and 'softplus' parse but error at creation — we implement softplus
# (trivial in JAX) and reject maxout with the same "unknown/unsupported" contract.
KNOWN_LAYER_TYPES = frozenset([
    "fullc", "fixconn", "bias", "softmax", "relu", "sigmoid", "tanh", "softplus",
    "flatten", "dropout", "conv", "relu_max_pooling", "max_pooling", "sum_pooling",
    "avg_pooling", "lrn", "concat", "xelu", "split", "insanity",
    "insanity_max_pooling", "l2_loss", "multi_logistic", "ch_concat", "prelu",
    "batch_norm", "share",
    # sequence/long-context extensions (no reference counterpart, SURVEY §5.7)
    "attention", "layer_norm", "add", "embedding", "moe", "lm_softmax",
    # external-framework adapter plugin (caffe_adapter-inl.hpp analogue)
    "torch",
])


@dataclass
class LayerSpec:
    """One layer declaration: type + node wiring + scoped config."""
    type: str                      # canonical type string ("conv", "fullc", ...)
    name: str                      # user-given name ("" if anonymous)
    inputs: List[int]
    outputs: List[int]
    primary: int = -1              # index of primary layer when type == "share"
    cfg: Pairs = field(default_factory=list)
    # for pairtest-master-slave differential testing (layer.h:354-358)
    pairtest: Optional[Tuple[str, str]] = None

    def key(self) -> str:
        """Parameter-tree key for this layer (stable across runs)."""
        return self.name if self.name else "!layer-%s" % "_".join(
            map(str, self.outputs))

    def struct_eq(self, other: "LayerSpec") -> bool:
        return (self.type == other.type and self.name == other.name
                and self.inputs == other.inputs and self.outputs == other.outputs
                and self.primary == other.primary
                and self.pairtest == other.pairtest)


_LAYER_PLUS = re.compile(r"^layer\[\+(\d+)(?::([^\]]+))?\]$")
_LAYER_ARROW = re.compile(r"^layer\[([^\]>]+)->([^\]]+)\]$")
_LABEL_VEC = re.compile(r"^label_vec\[(\d+),(\d+)\)$")
_SHARE = re.compile(r"^share\[([^\]]+)\]$")


class NetGraph:
    """Parsed network structure + scoped configuration."""

    def __init__(self) -> None:
        self.node_names: List[str] = ["in"]
        self.node_map: Dict[str, int] = {"in": 0, "0": 0}
        self.layers: List[LayerSpec] = []
        self.layer_name_map: Dict[str, int] = {}
        self.defcfg: Pairs = []
        self.input_shape: Optional[Tuple[int, int, int]] = None  # (c, y, x)
        self.extra_data_num: int = 0
        self.extra_shapes: List[Tuple[int, int, int]] = []
        # label fields: name -> index into label_range; default field "label" is col [0,1)
        self.label_name_map: Dict[str, int] = {"label": 0}
        self.label_range: List[Tuple[int, int]] = [(0, 1)]
        self.updater_type: str = "sgd"

    # ---------------------------------------------------------------- parsing
    def _node_index(self, name: str, alloc_unknown: bool) -> int:
        name = name.strip()
        if name in self.node_map:
            return self.node_map[name]
        if not alloc_unknown:
            raise ConfigError(
                "undefined node name %r: input of a layer must be the output of "
                "an earlier layer" % name)
        idx = len(self.node_names)
        self.node_names.append(name)
        self.node_map[name] = idx
        return idx

    def _parse_layer_decl(self, key: str, val: str, top_node: int,
                          layer_index: int) -> LayerSpec:
        m = _LAYER_PLUS.match(key)
        if m:
            inc, tag = int(m.group(1)), m.group(2)
            if top_node < 0:
                raise ConfigError(
                    "layer[+%d] used but previous layer has multiple outputs; "
                    "use layer[in->out] instead" % inc)
            inputs = [top_node]
            if tag is not None and inc == 1:
                outputs = [self._node_index(tag, True)]
            elif inc == 0:
                outputs = [top_node]
            else:
                outputs = [self._node_index("!node-after-%d" % top_node, True)]
        else:
            m = _LAYER_ARROW.match(key)
            if not m:
                raise ConfigError("invalid layer declaration %r" % key)
            inputs = [self._node_index(s, False) for s in m.group(1).split(",")]
            outputs = [self._node_index(s, True) for s in m.group(2).split(",")]

        # value: "type" or "type:name"; share[tag] / pairtest-a-b special forms
        if ":" in val:
            ltype, lname = val.split(":", 1)
        else:
            ltype, lname = val, ""
        pairtest = None
        sm = _SHARE.match(ltype)
        if ltype.startswith("share"):
            if sm is None:
                raise ConfigError("shared layer must specify share[tag]: %r" % val)
            tag = sm.group(1)
            # a share must name an EARLIER layer: on a fresh parse a later
            # tag is simply absent from layer_name_map, but the name map of
            # a loaded graph (from_structure_state) is fully populated, and
            # the config prescan (_decl_order) knows where every tag will
            # be declared — both cases get the explicit forward-reference
            # error instead of a downstream KeyError/IndexError
            if tag in self.layer_name_map \
                    and self.layer_name_map[tag] >= layer_index:
                raise ConfigError(
                    "share[%s] is a forward reference: the primary layer "
                    "%r is declared at position %d, after this share "
                    "(position %d); share[...] must name an earlier layer"
                    % (tag, tag, self.layer_name_map[tag], layer_index))
            if tag not in self.layer_name_map:
                decl_at = getattr(self, "_decl_order", {}).get(tag)
                if decl_at is not None:
                    raise ConfigError(
                        "share[%s] is a forward reference: the primary "
                        "layer %r is declared at position %d, after this "
                        "share (position %d); share[...] must name an "
                        "earlier layer" % (tag, tag, decl_at, layer_index))
                raise ConfigError("shared layer tag %r not defined before" % tag)
            return LayerSpec("share", "", inputs, outputs,
                             primary=self.layer_name_map[tag])
        if ltype.startswith("pairtest-"):
            parts = ltype[len("pairtest-"):].split("-")
            if len(parts) != 2:
                raise ConfigError("pairtest layer must be pairtest-master-slave")
            for p in parts:
                if p not in KNOWN_LAYER_TYPES:
                    raise ConfigError("unknown layer type %r" % p)
            pairtest = (parts[0], parts[1])
            ltype = "pairtest"
        elif ltype not in KNOWN_LAYER_TYPES:
            raise ConfigError("unknown layer type %r" % ltype)
        if lname:
            if lname in self.layer_name_map:
                if self.layer_name_map[lname] != layer_index:
                    raise ConfigError(
                        "layer name %r does not match the stored network" % lname)
            else:
                self.layer_name_map[lname] = layer_index
        return LayerSpec(ltype, lname, inputs, outputs, pairtest=pairtest)

    def configure(self, cfg: Pairs,
                  lines: Optional[List[int]] = None) -> "NetGraph":
        """Parse an ordered (name, value) list. Re-configuring an already-built
        graph validates structural equality instead of rebuilding
        (nnet_config.h:267-271). ``lines`` (optional, parallel to ``cfg``)
        attributes any ConfigError to its source line — the lint path
        tokenizes ``with_lines`` and passes them through."""
        first_time = not self.layers
        netcfg_mode = 0      # 0 global, 1 inside netconfig, 2 after a layer decl
        top_node = 0
        layer_index = 0
        if not first_time:
            for lyr in self.layers:
                lyr.cfg = []
            self.defcfg = []
        # prescan: where each named layer WILL be declared, so a
        # share[tag] naming a later layer fails as an explicit forward
        # reference at its own line (not a downstream lookup error)
        self._decl_order: Dict[str, int] = {}
        decl_i = 0
        for name, val in cfg:
            if name.startswith("layer["):
                if ":" in val and not val.split(":", 1)[0].startswith("share"):
                    self._decl_order.setdefault(val.split(":", 1)[1], decl_i)
                decl_i += 1
        for pair_i, (name, val) in enumerate(cfg):
          try:
            if name == "extra_data_num":
                self.extra_data_num = int(val)
                for i in range(self.extra_data_num):
                    nm = "in_%d" % (i + 1)
                    if nm not in self.node_map:
                        # extra-data nodes get indices 1..k (nnet_config.h:224-235)
                        self.node_names.insert(i + 1, nm)
                        self.node_map = {n: j for j, n in enumerate(self.node_names)}
                        self.node_map["0"] = 0
            m = re.match(r"^extra_data_shape\[(\d+)\]$", name)
            if m:
                dims = tuple(int(x) for x in val.split(","))
                if len(dims) != 3:
                    raise ConfigError("extra_data_shape must be c,y,x")
                self.extra_shapes.append(dims)
            if name == "input_shape" and first_time:
                dims = tuple(int(x) for x in val.split(","))
                if len(dims) != 3:
                    raise ConfigError(
                        "input_shape must be three comma-separated ints, e.g. 1,1,784")
                self.input_shape = dims    # (c, y, x)
            if netcfg_mode != 2:
                self._set_global(name, val)
            if name == "netconfig" and val == "start":
                netcfg_mode = 1
            if name == "netconfig" and val == "end":
                netcfg_mode = 0
            if name.startswith("layer["):
                info = self._parse_layer_decl(name, val, top_node, layer_index)
                netcfg_mode = 2
                if first_time:
                    self.layers.append(info)
                else:
                    if layer_index >= len(self.layers):
                        raise ConfigError("config layer index exceeds stored network")
                    if not info.struct_eq(self.layers[layer_index]):
                        raise ConfigError(
                            "config does not match existing network structure at "
                            "layer %d" % layer_index)
                top_node = info.outputs[0] if len(info.outputs) == 1 else -1
                layer_index += 1
                continue
            if netcfg_mode == 2:
                if self.layers[layer_index - 1].type == "share":
                    raise ConfigError(
                        "do not set parameters on a shared layer; set them on "
                        "the primary layer")
                self.layers[layer_index - 1].cfg.append((name, val))
            else:
                self.defcfg.append((name, val))
          except ConfigError as e:
            if lines is not None and getattr(e, "line", None) is None:
                raise ConfigError(str(e), line=lines[pair_i]) from None
            raise
        return self

    def _set_global(self, name: str, val: str) -> None:
        if name == "updater":
            self.updater_type = val
        m = _LABEL_VEC.match(name)
        if m:
            self.label_range.append((int(m.group(1)), int(m.group(2))))
            self.label_name_map[val] = len(self.label_range) - 1

    # --------------------------------------------------------------- queries
    @property
    def num_nodes(self) -> int:
        return len(self.node_names)

    def layer_index(self, name: str) -> int:
        if name not in self.layer_name_map:
            raise KeyError("unknown layer name %r" % name)
        return self.layer_name_map[name]

    def label_field(self, name: str) -> Tuple[int, int]:
        """Column range [a, b) of a named label field in the label matrix."""
        return self.label_range[self.label_name_map[name]]

    # --------------------------------------------------------- serialization
    def structure_state(self) -> dict:
        """JSON-serializable network structure (the SaveNet/LoadNet analogue,
        nnet_config.h:126-191). Training params (defcfg/layercfg) are NOT
        saved — they are re-read from the config each run."""
        return {
            "node_names": self.node_names,
            "input_shape": list(self.input_shape) if self.input_shape else None,
            "extra_data_num": self.extra_data_num,
            "extra_shapes": [list(s) for s in self.extra_shapes],
            "layers": [
                {"type": l.type, "name": l.name, "inputs": l.inputs,
                 "outputs": l.outputs, "primary": l.primary,
                 "pairtest": list(l.pairtest) if l.pairtest else None}
                for l in self.layers
            ],
        }

    @classmethod
    def from_structure_state(cls, state: dict) -> "NetGraph":
        g = cls()
        g.node_names = list(state["node_names"])
        g.node_map = {n: i for i, n in enumerate(g.node_names)}
        g.node_map["0"] = 0
        if state.get("input_shape"):
            g.input_shape = tuple(state["input_shape"])
        g.extra_data_num = state.get("extra_data_num", 0)
        g.extra_shapes = [tuple(s) for s in state.get("extra_shapes", [])]
        for i, l in enumerate(state["layers"]):
            pt = l.get("pairtest")
            spec = LayerSpec(l["type"], l["name"], list(l["inputs"]),
                             list(l["outputs"]), primary=l.get("primary", -1),
                             pairtest=tuple(pt) if pt else None)
            g.layers.append(spec)
            if spec.name:
                if spec.name in g.layer_name_map:
                    raise ConfigError("duplicated layer name %r" % spec.name)
                g.layer_name_map[spec.name] = i
        return g
