"""attachtxt — joins per-instance extra feature vectors into batches.

Reference (/root/reference/src/io/iter_attach_txt-inl.hpp:15-101): a text file
whose first token is the feature dim, followed by ``instance_id v1 .. vdim``
records; at Next() the vector for each instance in the batch (matched by
inst_index) lands in ``batch.extra_data[0]`` shaped (batch, 1, 1, dim) —
feeding multi-input networks' ``in_1..in_k`` nodes.
"""

from __future__ import annotations

import numpy as np

from .data import DataBatch, IIterator, register_proc_iterator


@register_proc_iterator("attachtxt")
class AttachTxtIterator(IIterator):
    def __init__(self, base: IIterator) -> None:
        self.base = base
        self.filename = ""
        self.batch_size = 0

    def set_param(self, name: str, val: str) -> None:
        self.base.set_param(name, val)
        if name == "filename":
            self.filename = val
        elif name == "batch_size":
            self.batch_size = int(val)

    def init(self) -> None:
        self.base.init()
        assert self.filename, "attachtxt: must set filename"
        from .binpage import open_maybe_gz
        with open_maybe_gz(self.filename, "r") as f:
            tokens = f.read().split()
        self.dim = int(tokens[0])
        rec = 1 + self.dim
        body = tokens[1:]
        assert len(body) % rec == 0, \
            "attachtxt: data do not match the dimension specified"
        self.id_map = {}
        vecs = []
        for i in range(len(body) // rec):
            self.id_map[int(float(body[i * rec]))] = i
            vecs.append([float(v) for v in body[i * rec + 1:(i + 1) * rec]])
        self.vectors = np.asarray(vecs, np.float32)

    def before_first(self) -> None:
        self.base.before_first()

    def next(self) -> bool:
        if not self.base.next():
            return False
        v = self.base.value()
        b = v.data.shape[0]
        extra = np.zeros((b, 1, 1, self.dim), np.float32)
        if v.inst_index is not None:
            for top in range(b):
                row = self.id_map.get(int(v.inst_index[top]))
                if row is not None:
                    extra[top, 0, 0, :] = self.vectors[row]
        self._value = DataBatch(v.data, v.label, v.inst_index,
                                v.num_batch_padd, [extra], v.pad_mode)
        return True

    def value(self) -> DataBatch:
        return self._value

    def close(self) -> None:
        self.base.close()
