"""Image decoding: native libjpeg fast path with a PIL fallback.

Reference equivalent: /root/reference/src/utils/decoder.h (JpegDecoder on raw
libjpeg / OpenCVDecoder). The native path calls ``native/libcxnetdata.so``
via ctypes — the C functions never touch the GIL, so a Python thread pool of
decoders scales across cores (the role the reference's decode thread played).

Output convention: float32 CHW, RGB channel order, values 0..255 (scaling/
mean-subtraction happen in the augment stage, as in the reference). Grayscale
sources are replicated to 3 channels (iter_thread_imbin_x-inl.hpp behavior)
unless the net's input_shape asks for 1 channel.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

_LIB = None
_LIB_TRIED = False


def _find_native() -> Optional[ctypes.CDLL]:
    global _LIB, _LIB_TRIED
    if _LIB_TRIED:
        return _LIB
    _LIB_TRIED = True
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    candidates = [
        os.environ.get("CXXNET_TPU_NATIVE_LIB", ""),
        os.path.join(here, "native", "libcxnetdata.so"),
    ]
    for cand in candidates:
        if cand and os.path.exists(cand):
            try:
                lib = ctypes.CDLL(cand)
                lib.cxn_jpeg_decode.restype = ctypes.c_int
                lib.cxn_jpeg_decode.argtypes = [
                    ctypes.c_char_p, ctypes.c_long, ctypes.c_void_p,
                    ctypes.c_long, ctypes.POINTER(ctypes.c_int),
                    ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int)]
                lib.cxn_hwc_to_chw_float.restype = ctypes.c_int
                lib.cxn_hwc_to_chw_float.argtypes = [
                    ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                    ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                    ctypes.c_int, ctypes.c_int, ctypes.c_void_p]
                # present from round 3 on (decode-at-scale)
                if hasattr(lib, "cxn_jpeg_decode_scaled"):
                    lib.cxn_jpeg_decode_scaled.restype = ctypes.c_int
                    lib.cxn_jpeg_decode_scaled.argtypes = [
                        ctypes.c_char_p, ctypes.c_long, ctypes.c_void_p,
                        ctypes.c_long, ctypes.c_int,
                        ctypes.POINTER(ctypes.c_int),
                        ctypes.POINTER(ctypes.c_int),
                        ctypes.POINTER(ctypes.c_int)]
                # present from round 2 on; older .so builds simply lack them
                if hasattr(lib, "cxn_png_decode"):
                    lib.cxn_png_decode.restype = ctypes.c_int
                    lib.cxn_png_decode.argtypes = lib.cxn_jpeg_decode.argtypes
                if hasattr(lib, "cxn_affine_warp_u8"):
                    lib.cxn_affine_warp_u8.restype = ctypes.c_int
                    lib.cxn_affine_warp_u8.argtypes = [
                        ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
                        ctypes.c_int, ctypes.c_void_p, ctypes.c_int,
                        ctypes.c_int,
                        ctypes.POINTER(ctypes.c_double), ctypes.c_int]
                _LIB = lib
                break
            except OSError:
                continue
    return _LIB


def have_native() -> bool:
    return _find_native() is not None


def _pil_decode_hwc(buf: bytes, min_hw=None) -> np.ndarray:
    """Shared PIL fallback: bytes -> HWC uint8 (RGB, or 1-channel gray).
    ``min_hw`` engages JPEG decode-at-scale via Image.draft (same
    power-of-two libjpeg reduction the native path picks)."""
    from PIL import Image
    import io as _io
    img = Image.open(_io.BytesIO(buf))
    if min_hw is not None and img.format == "JPEG":
        n = _pick_jpeg_scale(img.height, img.width, min_hw)
        if n < 8:
            # request FLOOR dims: draft picks scale = dim // requested, so
            # ceil dims would under-reduce any source that is not an
            # exact multiple of the step (255x255 at n=4: ceil -> 128,
            # 255 // 128 = 1 = no reduction; floor -> 127, 255 // 127 = 2,
            # the same 1/2 reduction the native path applies)
            img.draft(None, ((img.width * n) // 8, (img.height * n) // 8))
    if img.mode not in ("RGB", "L"):
        img = img.convert("RGB")
    arr = np.asarray(img, np.uint8)
    return arr[:, :, None] if arr.ndim == 2 else arr


# decode-at-scale gating shared by the img/imgbin iterators: any of these
# params defines warp geometry on the FULL source frame, so decode-at-scale
# must stay off when one is configured
WARP_PARAM_NAMES = ("max_rotate_angle", "rotate", "rotate_list",
                    "max_shear_ratio", "min_crop_size", "max_crop_size",
                    "min_img_size", "max_img_size")


def is_warp_param(name: str, val: str) -> bool:
    """True when (name, val) configures a warp-family augmentation."""
    if name in WARP_PARAM_NAMES:
        return True
    return name in ("max_random_scale", "min_random_scale") \
        and float(val) != 1.0


def resolve_min_hw(decode_at_scale: int, target_hw, warp_params: bool):
    """The min (h, w) passed to decode, or None for full-size decode."""
    return target_hw if decode_at_scale and not warp_params else None


def _pick_jpeg_scale(h: int, w: int, min_hw) -> int:
    """Smallest libjpeg scale_num (power of two out of 8, so the PIL
    draft fallback picks the identical reduction) whose output dims still
    cover ``min_hw`` = (min_h, min_w)."""
    mh, mw = min_hw
    for n in (1, 2, 4):                       # 1/8, 1/4, 1/2
        if (h * n + 7) // 8 >= mh and (w * n + 7) // 8 >= mw:
            return n
    return 8


def decode_jpeg_hwc(buf: bytes, min_hw=None) -> np.ndarray:
    """JPEG bytes -> HWC uint8 (RGB or single-channel grayscale).

    ``min_hw`` (min_h, min_w) opts into decode-at-scale: the DCT is
    decoded at the coarsest 1/2^k scale whose output still covers the
    requested minimum (libjpeg scale_num/8 natively, PIL ``draft`` on the
    fallback — both are libjpeg underneath, so the two paths stay
    pixel-identical at the same reduction)."""
    lib = _find_native()
    scaled = (min_hw is not None and lib is not None
              and hasattr(lib, "cxn_jpeg_decode_scaled"))
    if lib is not None:
        w = ctypes.c_int()
        h = ctypes.c_int()
        c = ctypes.c_int()
        rc = lib.cxn_jpeg_decode(buf, len(buf), None, 0,
                                 ctypes.byref(w), ctypes.byref(h),
                                 ctypes.byref(c))
        if rc == 0:
            n = _pick_jpeg_scale(h.value, w.value, min_hw) if scaled else 8
            # output dims are exactly ceil(dim * n / 8) (libjpeg
            # jdiv_round_up) — no second header probe needed
            oh = (h.value * n + 7) // 8
            ow = (w.value * n + 7) // 8
            out = np.empty((oh, ow, c.value), np.uint8)
            if n < 8:
                rc = lib.cxn_jpeg_decode_scaled(
                    buf, len(buf), out.ctypes.data_as(ctypes.c_void_p),
                    out.nbytes, n, ctypes.byref(w), ctypes.byref(h),
                    ctypes.byref(c))
            else:
                rc = lib.cxn_jpeg_decode(
                    buf, len(buf), out.ctypes.data_as(ctypes.c_void_p),
                    out.nbytes, ctypes.byref(w), ctypes.byref(h),
                    ctypes.byref(c))
            if rc == 0 and (h.value, w.value) == (oh, ow):
                return out
        # fall through to PIL on any native failure
    return _pil_decode_hwc(buf, min_hw=min_hw)


def decode_png_hwc(buf: bytes) -> np.ndarray:
    """PNG bytes -> HWC uint8 (RGB or single-channel grayscale); native
    libpng path with a PIL fallback. For 8-bit RGB/gray sources the two
    agree exactly (PNG is lossless). Exotic formats (16-bit depth,
    gray+alpha) go straight to the PIL path in BOTH builds — the native
    normalization differed from PIL's (alpha dropped vs LA->RGB), so the
    same file could decode differently depending on whether the native
    library was built; routing on the IHDR keeps builds consistent."""
    # IHDR layout: 8-byte signature, 4-byte length, b"IHDR", width(4),
    # height(4), bit depth (byte 24), color type (byte 25)
    if len(buf) > 25 and buf[12:16] == b"IHDR" and (
            buf[24] == 16 or buf[25] == 4):
        return _pil_decode_hwc(buf)
    lib = _find_native()
    if lib is not None and hasattr(lib, "cxn_png_decode"):
        w = ctypes.c_int()
        h = ctypes.c_int()
        c = ctypes.c_int()
        rc = lib.cxn_png_decode(buf, len(buf), None, 0,
                                ctypes.byref(w), ctypes.byref(h),
                                ctypes.byref(c))
        if rc == 0:
            out = np.empty((h.value, w.value, c.value), np.uint8)
            rc = lib.cxn_png_decode(
                buf, len(buf), out.ctypes.data_as(ctypes.c_void_p),
                out.nbytes, ctypes.byref(w), ctypes.byref(h),
                ctypes.byref(c))
            if rc == 0:
                return out
    return _pil_decode_hwc(buf)


def affine_warp_hwc(hwc: np.ndarray, size, inverse6, fill: int) -> np.ndarray:
    """Inverse-map affine warp of an HWC uint8 image to ``size`` (w, h),
    bicubic with a = -1.0 (PIL's *transform* kernel — its resize bicubic
    is a = -0.5). Native path when the library is new enough; PIL
    fallback (the two agree to <1 gray level mean even on noise — the
    boundary fill blending differs slightly)."""
    out_w, out_h = size
    lib = _find_native()
    if lib is not None and hasattr(lib, "cxn_affine_warp_u8") \
            and hwc.flags["C_CONTIGUOUS"]:
        h, w, c = hwc.shape
        out = np.empty((out_h, out_w, c), np.uint8)
        m = (ctypes.c_double * 6)(*inverse6)
        rc = lib.cxn_affine_warp_u8(
            hwc.ctypes.data_as(ctypes.c_void_p), h, w, c,
            out.ctypes.data_as(ctypes.c_void_p), out_h, out_w, m, fill)
        if rc == 0:
            return out
    from PIL import Image
    c = hwc.shape[2]
    img = Image.fromarray(hwc[:, :, 0] if c == 1 else hwc,
                          mode="L" if c == 1 else "RGB")
    warped = img.transform((out_w, out_h), Image.AFFINE, tuple(inverse6),
                           resample=Image.BICUBIC,
                           fillcolor=(fill if c == 1 else (fill,) * 3))
    arr = np.asarray(warped, np.uint8)
    return arr[:, :, None] if arr.ndim == 2 else arr


def decode_image_chw(buf: bytes, gray_to_rgb: bool = True,
                     min_hw=None) -> np.ndarray:
    """Image bytes (any PIL-supported format; native paths for JPEG and
    PNG) -> float32 CHW 0..255, grayscale replicated to 3 channels if
    requested. ``min_hw`` opts JPEG sources into decode-at-scale (see
    decode_jpeg_hwc); other formats always decode at full size."""
    is_jpeg = len(buf) > 2 and buf[0] == 0xFF and buf[1] == 0xD8
    is_png = len(buf) > 8 and buf[:8] == b"\x89PNG\r\n\x1a\n"
    if is_jpeg:
        hwc = decode_jpeg_hwc(buf, min_hw=min_hw)
    elif is_png:
        hwc = decode_png_hwc(buf)
    else:
        hwc = _pil_decode_hwc(buf)
    lib = _find_native()
    h, w, c = hwc.shape
    out_c = 3 if (c == 1 and gray_to_rgb) else c
    if lib is not None and hwc.flags["C_CONTIGUOUS"]:
        out = np.empty((out_c, h, w), np.float32)
        rc = lib.cxn_hwc_to_chw_float(
            hwc.ctypes.data_as(ctypes.c_void_p), h, w, c, 0, 0, h, w, 0,
            1 if gray_to_rgb else 0, out.ctypes.data_as(ctypes.c_void_p))
        if rc == out_c:
            return out
    chw = hwc.astype(np.float32).transpose(2, 0, 1)
    if c == 1 and gray_to_rgb:
        chw = np.repeat(chw, 3, axis=0)
    return np.ascontiguousarray(chw)
