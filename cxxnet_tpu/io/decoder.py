"""Image decoding: native libjpeg fast path with a PIL fallback.

Reference equivalent: /root/reference/src/utils/decoder.h (JpegDecoder on raw
libjpeg / OpenCVDecoder). The native path calls ``native/libcxnetdata.so``
via ctypes — the C functions never touch the GIL, so a Python thread pool of
decoders scales across cores (the role the reference's decode thread played).

Output convention: float32 CHW, RGB channel order, values 0..255 (scaling/
mean-subtraction happen in the augment stage, as in the reference). Grayscale
sources are replicated to 3 channels (iter_thread_imbin_x-inl.hpp behavior)
unless the net's input_shape asks for 1 channel.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

_LIB = None
_LIB_TRIED = False


def _find_native() -> Optional[ctypes.CDLL]:
    global _LIB, _LIB_TRIED
    if _LIB_TRIED:
        return _LIB
    _LIB_TRIED = True
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    candidates = [
        os.environ.get("CXXNET_TPU_NATIVE_LIB", ""),
        os.path.join(here, "native", "libcxnetdata.so"),
    ]
    for cand in candidates:
        if cand and os.path.exists(cand):
            try:
                lib = ctypes.CDLL(cand)
                lib.cxn_jpeg_decode.restype = ctypes.c_int
                lib.cxn_jpeg_decode.argtypes = [
                    ctypes.c_char_p, ctypes.c_long, ctypes.c_void_p,
                    ctypes.c_long, ctypes.POINTER(ctypes.c_int),
                    ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int)]
                lib.cxn_hwc_to_chw_float.restype = ctypes.c_int
                lib.cxn_hwc_to_chw_float.argtypes = [
                    ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                    ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                    ctypes.c_int, ctypes.c_int, ctypes.c_void_p]
                # present from round 2 on; older .so builds simply lack them
                if hasattr(lib, "cxn_png_decode"):
                    lib.cxn_png_decode.restype = ctypes.c_int
                    lib.cxn_png_decode.argtypes = lib.cxn_jpeg_decode.argtypes
                if hasattr(lib, "cxn_affine_warp_u8"):
                    lib.cxn_affine_warp_u8.restype = ctypes.c_int
                    lib.cxn_affine_warp_u8.argtypes = [
                        ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
                        ctypes.c_int, ctypes.c_void_p, ctypes.c_int,
                        ctypes.c_int,
                        ctypes.POINTER(ctypes.c_double), ctypes.c_int]
                _LIB = lib
                break
            except OSError:
                continue
    return _LIB


def have_native() -> bool:
    return _find_native() is not None


def _pil_decode_hwc(buf: bytes) -> np.ndarray:
    """Shared PIL fallback: bytes -> HWC uint8 (RGB, or 1-channel gray)."""
    from PIL import Image
    import io as _io
    img = Image.open(_io.BytesIO(buf))
    if img.mode not in ("RGB", "L"):
        img = img.convert("RGB")
    arr = np.asarray(img, np.uint8)
    return arr[:, :, None] if arr.ndim == 2 else arr


def decode_jpeg_hwc(buf: bytes) -> np.ndarray:
    """JPEG bytes -> HWC uint8 (RGB or single-channel grayscale)."""
    lib = _find_native()
    if lib is not None:
        w = ctypes.c_int()
        h = ctypes.c_int()
        c = ctypes.c_int()
        rc = lib.cxn_jpeg_decode(buf, len(buf), None, 0,
                                 ctypes.byref(w), ctypes.byref(h),
                                 ctypes.byref(c))
        if rc == 0:
            out = np.empty((h.value, w.value, c.value), np.uint8)
            rc = lib.cxn_jpeg_decode(
                buf, len(buf), out.ctypes.data_as(ctypes.c_void_p), out.nbytes,
                ctypes.byref(w), ctypes.byref(h), ctypes.byref(c))
            if rc == 0:
                return out
        # fall through to PIL on any native failure
    return _pil_decode_hwc(buf)


def decode_png_hwc(buf: bytes) -> np.ndarray:
    """PNG bytes -> HWC uint8 (RGB or single-channel grayscale); native
    libpng path with a PIL fallback. For 8-bit RGB/gray sources the two
    agree exactly (PNG is lossless). Exotic formats (16-bit depth,
    gray+alpha) go straight to the PIL path in BOTH builds — the native
    normalization differed from PIL's (alpha dropped vs LA->RGB), so the
    same file could decode differently depending on whether the native
    library was built; routing on the IHDR keeps builds consistent."""
    # IHDR layout: 8-byte signature, 4-byte length, b"IHDR", width(4),
    # height(4), bit depth (byte 24), color type (byte 25)
    if len(buf) > 25 and buf[12:16] == b"IHDR" and (
            buf[24] == 16 or buf[25] == 4):
        return _pil_decode_hwc(buf)
    lib = _find_native()
    if lib is not None and hasattr(lib, "cxn_png_decode"):
        w = ctypes.c_int()
        h = ctypes.c_int()
        c = ctypes.c_int()
        rc = lib.cxn_png_decode(buf, len(buf), None, 0,
                                ctypes.byref(w), ctypes.byref(h),
                                ctypes.byref(c))
        if rc == 0:
            out = np.empty((h.value, w.value, c.value), np.uint8)
            rc = lib.cxn_png_decode(
                buf, len(buf), out.ctypes.data_as(ctypes.c_void_p),
                out.nbytes, ctypes.byref(w), ctypes.byref(h),
                ctypes.byref(c))
            if rc == 0:
                return out
    return _pil_decode_hwc(buf)


def affine_warp_hwc(hwc: np.ndarray, size, inverse6, fill: int) -> np.ndarray:
    """Inverse-map affine warp of an HWC uint8 image to ``size`` (w, h),
    bicubic with a = -1.0 (PIL's *transform* kernel — its resize bicubic
    is a = -0.5). Native path when the library is new enough; PIL
    fallback (the two agree to <1 gray level mean even on noise — the
    boundary fill blending differs slightly)."""
    out_w, out_h = size
    lib = _find_native()
    if lib is not None and hasattr(lib, "cxn_affine_warp_u8") \
            and hwc.flags["C_CONTIGUOUS"]:
        h, w, c = hwc.shape
        out = np.empty((out_h, out_w, c), np.uint8)
        m = (ctypes.c_double * 6)(*inverse6)
        rc = lib.cxn_affine_warp_u8(
            hwc.ctypes.data_as(ctypes.c_void_p), h, w, c,
            out.ctypes.data_as(ctypes.c_void_p), out_h, out_w, m, fill)
        if rc == 0:
            return out
    from PIL import Image
    c = hwc.shape[2]
    img = Image.fromarray(hwc[:, :, 0] if c == 1 else hwc,
                          mode="L" if c == 1 else "RGB")
    warped = img.transform((out_w, out_h), Image.AFFINE, tuple(inverse6),
                           resample=Image.BICUBIC,
                           fillcolor=(fill if c == 1 else (fill,) * 3))
    arr = np.asarray(warped, np.uint8)
    return arr[:, :, None] if arr.ndim == 2 else arr


def decode_image_chw(buf: bytes, gray_to_rgb: bool = True) -> np.ndarray:
    """Image bytes (any PIL-supported format; native paths for JPEG and
    PNG) -> float32 CHW 0..255, grayscale replicated to 3 channels if
    requested."""
    is_jpeg = len(buf) > 2 and buf[0] == 0xFF and buf[1] == 0xD8
    is_png = len(buf) > 8 and buf[:8] == b"\x89PNG\r\n\x1a\n"
    if is_jpeg:
        hwc = decode_jpeg_hwc(buf)
    elif is_png:
        hwc = decode_png_hwc(buf)
    else:
        hwc = _pil_decode_hwc(buf)
    lib = _find_native()
    h, w, c = hwc.shape
    out_c = 3 if (c == 1 and gray_to_rgb) else c
    if lib is not None and hwc.flags["C_CONTIGUOUS"]:
        out = np.empty((out_c, h, w), np.float32)
        rc = lib.cxn_hwc_to_chw_float(
            hwc.ctypes.data_as(ctypes.c_void_p), h, w, c, 0, 0, h, w, 0,
            1 if gray_to_rgb else 0, out.ctypes.data_as(ctypes.c_void_p))
        if rc == out_c:
            return out
    chw = hwc.astype(np.float32).transpose(2, 0, 1)
    if c == 1 and gray_to_rgb:
        chw = np.repeat(chw, 3, axis=0)
    return np.ascontiguousarray(chw)
