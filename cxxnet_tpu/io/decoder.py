"""Image decoding: native libjpeg fast path with a PIL fallback.

Reference equivalent: /root/reference/src/utils/decoder.h (JpegDecoder on raw
libjpeg / OpenCVDecoder). The native path calls ``native/libcxnetdata.so``
via ctypes — the C functions never touch the GIL, so a Python thread pool of
decoders scales across cores (the role the reference's decode thread played).

Output convention: float32 CHW, RGB channel order, values 0..255 (scaling/
mean-subtraction happen in the augment stage, as in the reference). Grayscale
sources are replicated to 3 channels (iter_thread_imbin_x-inl.hpp behavior)
unless the net's input_shape asks for 1 channel.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

_LIB = None
_LIB_TRIED = False


def _find_native() -> Optional[ctypes.CDLL]:
    global _LIB, _LIB_TRIED
    if _LIB_TRIED:
        return _LIB
    _LIB_TRIED = True
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    candidates = [
        os.environ.get("CXXNET_TPU_NATIVE_LIB", ""),
        os.path.join(here, "native", "libcxnetdata.so"),
    ]
    for cand in candidates:
        if cand and os.path.exists(cand):
            try:
                lib = ctypes.CDLL(cand)
                lib.cxn_jpeg_decode.restype = ctypes.c_int
                lib.cxn_jpeg_decode.argtypes = [
                    ctypes.c_char_p, ctypes.c_long, ctypes.c_void_p,
                    ctypes.c_long, ctypes.POINTER(ctypes.c_int),
                    ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int)]
                lib.cxn_hwc_to_chw_float.restype = ctypes.c_int
                lib.cxn_hwc_to_chw_float.argtypes = [
                    ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                    ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                    ctypes.c_int, ctypes.c_int, ctypes.c_void_p]
                _LIB = lib
                break
            except OSError:
                continue
    return _LIB


def have_native() -> bool:
    return _find_native() is not None


def decode_jpeg_hwc(buf: bytes) -> np.ndarray:
    """JPEG bytes -> HWC uint8 (RGB or single-channel grayscale)."""
    lib = _find_native()
    if lib is not None:
        w = ctypes.c_int()
        h = ctypes.c_int()
        c = ctypes.c_int()
        rc = lib.cxn_jpeg_decode(buf, len(buf), None, 0,
                                 ctypes.byref(w), ctypes.byref(h),
                                 ctypes.byref(c))
        if rc == 0:
            out = np.empty((h.value, w.value, c.value), np.uint8)
            rc = lib.cxn_jpeg_decode(
                buf, len(buf), out.ctypes.data_as(ctypes.c_void_p), out.nbytes,
                ctypes.byref(w), ctypes.byref(h), ctypes.byref(c))
            if rc == 0:
                return out
        # fall through to PIL on any native failure
    from PIL import Image
    import io as _io
    img = Image.open(_io.BytesIO(buf))
    if img.mode not in ("RGB", "L"):
        img = img.convert("RGB")
    arr = np.asarray(img, np.uint8)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return arr


def decode_image_chw(buf: bytes, gray_to_rgb: bool = True) -> np.ndarray:
    """Image bytes (any PIL-supported format; native path for JPEG) ->
    float32 CHW 0..255, grayscale replicated to 3 channels if requested."""
    is_jpeg = len(buf) > 2 and buf[0] == 0xFF and buf[1] == 0xD8
    if is_jpeg:
        hwc = decode_jpeg_hwc(buf)
    else:
        from PIL import Image
        import io as _io
        img = Image.open(_io.BytesIO(buf))
        if img.mode not in ("RGB", "L"):
            img = img.convert("RGB")
        hwc = np.asarray(img, np.uint8)
        if hwc.ndim == 2:
            hwc = hwc[:, :, None]
    lib = _find_native()
    h, w, c = hwc.shape
    out_c = 3 if (c == 1 and gray_to_rgb) else c
    if lib is not None and hwc.flags["C_CONTIGUOUS"]:
        out = np.empty((out_c, h, w), np.float32)
        rc = lib.cxn_hwc_to_chw_float(
            hwc.ctypes.data_as(ctypes.c_void_p), h, w, c, 0, 0, h, w, 0,
            1 if gray_to_rgb else 0, out.ctypes.data_as(ctypes.c_void_p))
        if rc == out_c:
            return out
    chw = hwc.astype(np.float32).transpose(2, 0, 1)
    if c == 1 and gray_to_rgb:
        chw = np.repeat(chw, 3, axis=0)
    return np.ascontiguousarray(chw)
