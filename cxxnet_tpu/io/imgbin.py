"""imgbin — packed-binary image dataset iterator (the ImageNet-scale path).

Reference (/root/reference/src/io/iter_thread_imbin_x-inl.hpp:17-396,
``imgbin``/``imgbinx``): streams 64MB BinaryPages from one or many .bin files
with parallel .lst label files, shuffles file order and intra-page instance
order, JPEG-decodes into float CHW tensors with grayscale->3-channel
replication, and supports multi-shard datasets (``image_conf_prefix`` +
``image_conf_ids = 1-100``) with **distributed sharding**: shards are divided
across workers by rank (PS_RANK in the reference; here
``dist_worker_rank``/``dist_num_worker``, defaulting to the JAX process index
when running multi-host).

Redesign: the reference's two nested ThreadBuffer pipelines (page loader
thread + decode thread) become one producer thread that streams pages and
fans decode work out to a GIL-free thread pool (the native libjpeg path in
:mod:`.decoder` releases the GIL), feeding a bounded queue of decoded
instances.
"""

from __future__ import annotations

import os
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional, Tuple

import numpy as np

from .binpage import BinaryPage, open_maybe_gz
from .data import (DataInst, IIterator, PrefetchProducerMixin,
                   register_base_iterator)
from .decoder import decode_image_chw

_RAND_MAGIC = 111


def parse_id_range(spec: str) -> List[int]:
    """``1-100`` or ``1,5,7-9`` -> list of ints."""
    out: List[int] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "-" in part:
            a, b = part.split("-")
            out.extend(range(int(a), int(b) + 1))
        else:
            out.append(int(part))
    return out


def parse_list_line(line: str) -> Optional[List[str]]:
    """Parse one .lst line: ``index<TAB>label...<TAB>filename`` (whitespace
    split as fallback). Returns the fields, or None for blank/malformed
    (<2 fields) lines — the single definition shared by the iterator and the
    im2bin/partition tools so all agree on what lines are skipped."""
    parts = line.rstrip("\n").split("\t")
    if len(parts) < 2:
        parts = line.split()
    if len(parts) < 2:
        return None
    return parts


def read_list_file(path: str, label_width: int):
    """.lst file -> (indices uint32, labels float32 (n, label_width),
    filenames)."""
    idx, labels, names = [], [], []
    with open_maybe_gz(path, "r") as f:
        for line in f:
            parts = parse_list_line(line)
            if parts is None:
                continue
            idx.append(int(float(parts[0])))
            lab = [float(v) for v in parts[1:1 + label_width]]
            while len(lab) < label_width:
                lab.append(0.0)
            labels.append(lab)
            names.append(parts[-1])
    return (np.asarray(idx, np.uint32),
            np.asarray(labels, np.float32), names)


class ImageBinIterator(PrefetchProducerMixin, IIterator):
    """Produces decoded DataInst; wrapped by Augment+BatchAdapt at creation
    (see data.py factory wiring)."""

    def __init__(self) -> None:
        self.image_list = ""
        self.image_bin = ""
        self.conf_prefix = ""
        self.conf_ids = ""
        self.shuffle = 0
        self.label_width = 1
        self.silent = 0
        self.seed = _RAND_MAGIC
        self.dist_num_worker = 0
        self.dist_worker_rank = -1
        self.decode_threads = int(os.environ.get("CXXNET_DECODE_THREADS", "4"))
        # decoded full-frame float32 instances are MBs each at ImageNet source
        # sizes; a small buffer keeps decode ahead of consumption without
        # holding gigabytes of host RAM
        self.queue_size = 64
        self.gray_to_rgb = True
        # decode-at-scale (opt-in): decode JPEGs at the coarsest power-of-
        # two libjpeg scale still covering the crop target. Only engaged
        # on the plain crop/mirror path — any warp-family augment param
        # (rotation/shear/crop-size/scale jitter) needs the full source
        # box and the warp geometry is defined relative to the source
        # size, so those disable it. NOTE the crop offsets are then drawn
        # in the scaled frame: the output is a crop of the DCT-downscaled
        # image, not a downscale of the original's crop (doc/io.md).
        self.decode_at_scale = 0
        self.target_hw = None
        self._warp_params = False

    def set_param(self, name: str, val: str) -> None:
        from .decoder import is_warp_param
        if is_warp_param(name, val):
            self._warp_params = True
        if name == "image_list":
            self.image_list = val
        elif name == "image_bin":
            self.image_bin = val
        elif name == "image_conf_prefix":
            self.conf_prefix = val
        elif name == "image_conf_ids":
            self.conf_ids = val
        elif name == "shuffle":
            self.shuffle = int(val)
        elif name == "label_width":
            self.label_width = int(val)
        elif name == "silent":
            self.silent = int(val)
        elif name == "seed_data":
            self.seed = _RAND_MAGIC + int(val)
        elif name == "dist_num_worker":
            self.dist_num_worker = int(val)
        elif name == "dist_worker_rank":
            self.dist_worker_rank = int(val)
        elif name == "decode_threads":
            self.decode_threads = int(val)
        elif name == "decode_at_scale":
            self.decode_at_scale = int(val)
        elif name == "input_shape":
            parts = [int(v) for v in val.split(",")]
            self.gray_to_rgb = parts[0] == 3
            if len(parts) == 3:
                self.target_hw = (parts[1], parts[2])

    # ---------------------------------------------------------------- setup
    def _shard_files(self) -> List[Tuple[str, str]]:
        if self.conf_prefix:
            if not self.conf_ids:
                raise ValueError("image_conf_prefix requires image_conf_ids")
            ids = parse_id_range(self.conf_ids)
            # printf-style prefix (reference semantics: sprintf(prefix, id),
            # e.g. data/shard_%03d) or plain prefix with the id appended
            if "%" in self.conf_prefix:
                bases = [self.conf_prefix % i for i in ids]
            else:
                bases = ["%s%d" % (self.conf_prefix, i) for i in ids]
            shards = [(b + ".lst", b + ".bin") for b in bases]
            # distributed sharding by worker rank (PS_RANK analogue,
            # iter_thread_imbin_x-inl.hpp:108-139)
            nw, rank = self.dist_num_worker, self.dist_worker_rank
            if nw <= 0:
                nw = int(os.environ.get("CXXNET_NUM_WORKER", "0") or 0)
            if rank < 0:
                rank = int(os.environ.get("CXXNET_RANK",
                                          os.environ.get("PS_RANK", "-1")))
            if nw > 1:
                if rank < 0:
                    try:
                        import jax
                        rank = jax.process_index()
                    except Exception:
                        rank = 0
                # ceil-step split: every shard is owned by exactly one worker
                # (reference iter_thread_imbin_x-inl.hpp:122-130)
                per = (len(shards) + nw - 1) // nw
                shards = shards[rank * per:(rank + 1) * per]
                if not shards:
                    raise ValueError(
                        "imgbin: worker %d/%d received no shards (%d total) — "
                        "use at least one shard per worker" % (rank, nw,
                                                               len(ids)))
            return shards
        if not self.image_list or not self.image_bin:
            raise ValueError(
                "imgbin: must set image_list+image_bin or image_conf_prefix")
        return [(self.image_list, self.image_bin)]

    def init(self) -> None:
        self.shards = self._shard_files()
        self.lists = [read_list_file(lst, self.label_width)
                      for lst, _ in self.shards]
        total = sum(len(l[0]) for l in self.lists)
        if self.silent == 0:
            print("ImageBinIterator: %d shards, %d images, shuffle=%d"
                  % (len(self.shards), total, self.shuffle))
        self.rng = np.random.RandomState(self.seed)
        # resolved once all params are in: decode-at-scale only on the
        # plain crop path (warp-family params need the full source box)
        from .decoder import resolve_min_hw
        self._min_hw = resolve_min_hw(self.decode_at_scale, self.target_hw,
                                      self._warp_params)
        self._pool = ThreadPoolExecutor(max_workers=self.decode_threads)
        self._init_producer(self.queue_size)

    # ------------------------------------------------------------- producer
    def _produce_epoch(self) -> None:
        # decode submissions ride a sliding window so at most ~2x the pool
        # width of decoded full-frame floats is in flight beyond the bounded
        # queue (a whole 64MB page decoded at once is gigabytes at ImageNet
        # source sizes)
        window = max(2 * self.decode_threads, 4)
        order = list(range(len(self.shards)))
        if self.shuffle:
            self.rng.shuffle(order)
        for si in order:
            lst_idx, lst_label, _ = self.lists[si]
            bin_path = self.shards[si][1]
            pos = 0   # instance cursor within the shard (page objs follow .lst order)
            with open_maybe_gz(bin_path, "rb") as f:
                while not self._stop.is_set():
                    page = BinaryPage.load(f)
                    if page is None:
                        break
                    n = page.size
                    inst_order = list(range(n))
                    if self.shuffle:
                        self.rng.shuffle(inst_order)
                    pending: deque = deque()

                    def emit_oldest() -> bool:
                        gi, fut = pending.popleft()
                        return self._put(DataInst(
                            fut.result(), lst_label[gi], int(lst_idx[gi])))

                    for i in inst_order:
                        gi = pos + i
                        if gi >= len(lst_idx):
                            continue   # unmatched trailing object; keep rest
                        pending.append((gi, self._pool.submit(
                            decode_image_chw, bytes(page[i]),
                            self.gray_to_rgb, self._min_hw)))
                        if len(pending) >= window and not emit_oldest():
                            return
                    while pending:
                        if not emit_oldest():
                            return
                    pos += n
        self._put(self._END)

    # ------------------------------------------------------------- consumer
    def before_first(self) -> None:
        self._rewind_producer()

    def next(self) -> bool:
        item = self._next_item()
        if item is None:
            return False
        self._value = item
        return True

    def value(self) -> DataInst:
        return self._value

    def close(self) -> None:
        """Tear down the producer thread and decode pool. Safe to call on a
        partially-consumed iterator."""
        had_thread = getattr(self, "_thread", None) is not None
        self._close_producer()
        if had_thread:
            self._pool.shutdown(wait=False)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def _make_imgbin() -> IIterator:
    """imgbin = BatchAdapt(Augment(ImageBin)) — the composition the reference
    factory builds (data.cpp:41-45)."""
    from .augment import AugmentIterator
    from .batch import BatchAdaptIterator
    return BatchAdaptIterator(AugmentIterator(ImageBinIterator()))


for _name in ("imgbin", "imgbinx", "imgbinold"):
    register_base_iterator(_name)(_make_imgbin)
