"""Per-instance augmentation: affine warp, crop, mirror, scaling, mean
subtraction, contrast/illumination jitter.

Reference semantics (/root/reference/src/io/):
- AugmentIterator (iter_augment_proc-inl.hpp:21-246): crop to input_shape
  (random / center / fixed crop_y_start), rand_mirror / mirror, ``divideby`` /
  ``scale``, mean subtraction by a per-pixel mean-image file (auto-generated
  by a full dataset pass when missing, ``CreateMeanImg`` :171-198) or by
  per-channel ``mean_value``, random contrast (x in [1-c, 1+c]) and
  illumination (+ in [-i, i]) applied before scaling.
- ImageAugmenter (image_augmenter-inl.hpp:13-204): affine warp combining
  rotation (max angle / fixed ``rotate`` / ``rotate_list``), shear, scale
  range, aspect-ratio jitter, min/max image size and fill_value, followed by
  random/center crop; active only when rotation/shear/crop-size params are
  set (``NeedProcess``).

Channel-order note: the reference decodes BGR (OpenCV) and ``mean_value`` is
given as ``b,g,r``; this framework decodes RGB, and ``mean_value`` is applied
positionally to channels 0,1,2 as given. Mean-image files are ``.npy``.
"""

from __future__ import annotations

import math
import os
import time
from typing import Optional

import numpy as np

from .data import DataInst, IIterator

_RAND_MAGIC = 0


class ImageAugmenter:
    """Affine-warp augmenter (rotation/shear/scale/aspect + crop)."""

    def __init__(self) -> None:
        self.rand_crop = 0
        self.crop_y_start = -1
        self.crop_x_start = -1
        self.max_rotate_angle = 0.0
        self.max_aspect_ratio = 0.0
        self.max_shear_ratio = 0.0
        self.min_crop_size = -1
        self.max_crop_size = -1
        self.rotate = -1.0
        self.max_random_scale = 1.0
        self.min_random_scale = 1.0
        self.min_img_size = 0.0
        self.max_img_size = 1e10
        self.fill_value = 255
        self.rotate_list = []
        self.shape = None          # (c, y, x)

    def set_param(self, name: str, val: str) -> None:
        if name == "input_shape":
            self.shape = tuple(int(v) for v in val.split(","))
        elif name == "rand_crop":
            self.rand_crop = int(val)
        elif name == "crop_y_start":
            self.crop_y_start = int(val)
        elif name == "crop_x_start":
            self.crop_x_start = int(val)
        elif name == "max_rotate_angle":
            self.max_rotate_angle = float(val)
        elif name == "max_shear_ratio":
            self.max_shear_ratio = float(val)
        elif name == "max_aspect_ratio":
            self.max_aspect_ratio = float(val)
        elif name == "min_crop_size":
            self.min_crop_size = int(val)
        elif name == "max_crop_size":
            self.max_crop_size = int(val)
        elif name == "min_random_scale":
            self.min_random_scale = float(val)
        elif name == "max_random_scale":
            self.max_random_scale = float(val)
        elif name == "min_img_size":
            self.min_img_size = float(val)
        elif name == "max_img_size":
            self.max_img_size = float(val)
        elif name == "fill_value":
            self.fill_value = int(val)
        elif name == "rotate":
            self.rotate = float(val)
        elif name == "rotate_list":
            self.rotate_list = [int(v) for v in val.split(",") if v]

    def need_process(self) -> bool:
        if (self.max_rotate_angle > 0 or self.max_shear_ratio > 0
                or self.rotate > 0 or self.rotate_list):
            return True
        if self.min_crop_size > 0 and self.max_crop_size > 0:
            return True
        return False

    def process(self, chw: np.ndarray, rng: np.random.RandomState) -> np.ndarray:
        """float32 CHW (0..255) -> warped+cropped CHW at self.shape[1:]."""
        if not self.need_process():
            return chw
        c, h, w = chw.shape
        # random crop-of-random-size mode: crop a square of random side then
        # the affine/crop below resizes to the target
        if self.min_crop_size > 0 and self.max_crop_size > 0:
            side = rng.randint(self.min_crop_size, self.max_crop_size + 1)
            side = min(side, h, w)
            yy = rng.randint(0, h - side + 1)
            xx = rng.randint(0, w - side + 1)
            chw = chw[:, yy:yy + side, xx:xx + side]
            c, h, w = chw.shape
        angle = 0.0
        if self.max_rotate_angle > 0:
            angle = rng.randint(0, int(self.max_rotate_angle * 2) + 1) \
                - self.max_rotate_angle
        if self.rotate > 0:
            angle = self.rotate
        if self.rotate_list:
            angle = self.rotate_list[rng.randint(0, len(self.rotate_list))]
        shear = rng.rand() * self.max_shear_ratio * 2 - self.max_shear_ratio
        scale = rng.rand() * (self.max_random_scale - self.min_random_scale) \
            + self.min_random_scale
        ratio = rng.rand() * self.max_aspect_ratio * 2 \
            - self.max_aspect_ratio + 1
        hs = 2 * scale / (1 + ratio)
        ws = ratio * hs
        a = math.cos(angle / 180.0 * math.pi)
        b = math.sin(angle / 180.0 * math.pi)
        new_w = int(max(self.min_img_size, min(self.max_img_size, scale * w)))
        new_h = int(max(self.min_img_size, min(self.max_img_size, scale * h)))
        # forward affine (output <- input), same matrix construction as the
        # reference warp (image_augmenter-inl.hpp:95-105)
        m00 = hs * a - shear * b * ws
        m01 = hs * b + shear * a * ws
        m10 = -b * ws
        m11 = a * ws
        t0 = (new_w - (m00 * w + m01 * h)) / 2.0
        t1 = (new_h - (m10 * w + m11 * h)) / 2.0
        # PIL wants the inverse map (input <- output)
        det = m00 * m11 - m01 * m10
        if abs(det) < 1e-8:
            det = 1e-8
        i00, i01 = m11 / det, -m01 / det
        i10, i11 = -m10 / det, m00 / det
        it0 = -(i00 * t0 + i01 * t1)
        it1 = -(i10 * t0 + i11 * t1)
        hwc = np.ascontiguousarray(
            np.clip(chw, 0, 255).astype(np.uint8).transpose(1, 2, 0))
        # native bicubic warp (decoder.affine_warp_hwc; PIL fallback) —
        # keeps the whole host augmentation chain GIL-free C when the
        # library is present (the reference ran this in OpenCV,
        # image_augmenter-inl.hpp:95-121)
        from .decoder import affine_warp_hwc
        arr = affine_warp_hwc(hwc, (new_w, new_h),
                              (i00, i01, it0, i10, i11, it1),
                              int(self.fill_value)).astype(np.float32)
        out_y, out_x = self.shape[1], self.shape[2]
        yy = max(0, arr.shape[0] - out_y)
        xx = max(0, arr.shape[1] - out_x)
        if self.rand_crop:
            yy = rng.randint(0, yy + 1)
            xx = rng.randint(0, xx + 1)
        else:
            yy //= 2
            xx //= 2
        if arr.shape[0] < out_y or arr.shape[1] < out_x:
            # pad with fill_value if the warp came out smaller than the target
            pad = np.full((max(out_y, arr.shape[0]), max(out_x, arr.shape[1]),
                           arr.shape[2]), float(self.fill_value), np.float32)
            pad[:arr.shape[0], :arr.shape[1]] = arr
            arr = pad
        arr = arr[yy:yy + out_y, xx:xx + out_x]
        return np.ascontiguousarray(arr.transpose(2, 0, 1))


class AugmentIterator(IIterator):
    """DataInst processor applying the full augmentation suite."""

    def __init__(self, base: IIterator) -> None:
        self.base = base
        self.rand_crop = 0
        self.rand_mirror = 0
        self.mirror = 0
        self.crop_y_start = -1
        self.crop_x_start = -1
        self.scale = 1.0
        self.silent = 0
        self.name_meanimg = ""
        self.mean_value: Optional[np.ndarray] = None
        self.max_random_contrast = 0.0
        self.max_random_illumination = 0.0
        self.shape = None
        self.rng = np.random.RandomState(_RAND_MAGIC)
        self.aug = ImageAugmenter()
        self.meanimg: Optional[np.ndarray] = None

    def set_param(self, name: str, val: str) -> None:
        self.base.set_param(name, val)
        self.aug.set_param(name, val)
        if name == "input_shape":
            self.shape = tuple(int(v) for v in val.split(","))
        elif name == "seed_data":
            self.rng = np.random.RandomState(_RAND_MAGIC + int(val))
        elif name == "rand_crop":
            self.rand_crop = int(val)
        elif name == "silent":
            self.silent = int(val)
        elif name == "divideby":
            self.scale = 1.0 / float(val)
        elif name == "scale":
            self.scale = float(val)
        elif name == "image_mean":
            self.name_meanimg = val
        elif name == "crop_y_start":
            self.crop_y_start = int(val)
        elif name == "crop_x_start":
            self.crop_x_start = int(val)
        elif name == "rand_mirror":
            self.rand_mirror = int(val)
        elif name == "mirror":
            self.mirror = int(val)
        elif name == "max_random_contrast":
            self.max_random_contrast = float(val)
        elif name == "max_random_illumination":
            self.max_random_illumination = float(val)
        elif name == "mean_value":
            self.mean_value = np.array([float(v) for v in val.split(",")],
                                       np.float32)

    def init(self) -> None:
        self.base.init()
        if self.name_meanimg:
            if os.path.exists(self.name_meanimg):
                if self.silent == 0:
                    print("loading mean image from %s" % self.name_meanimg)
                self.meanimg = np.load(self.name_meanimg)
            else:
                self._create_mean_img()

    def before_first(self) -> None:
        self.base.before_first()

    def _process(self, d: DataInst) -> DataInst:
        data = self.aug.process(d.data, self.rng)
        c, y, x = self.shape
        if y == 1:       # flat input: scale only
            return DataInst(np.ascontiguousarray(data) * self.scale,
                            d.label, d.index, d.extra_data)
        dy, dx = data.shape[1] - y, data.shape[2] - x
        assert dy >= 0 and dx >= 0, \
            "data size must be at least the network input size"
        if self.rand_crop and (dy or dx):
            yy = self.rng.randint(0, dy + 1)
            xx = self.rng.randint(0, dx + 1)
        else:
            yy, xx = dy // 2, dx // 2
        if dy and self.crop_y_start != -1:
            assert self.crop_y_start <= dy, \
                "crop_y_start=%d exceeds crop margin %d" % (self.crop_y_start,
                                                            dy)
            yy = self.crop_y_start
        if dx and self.crop_x_start != -1:
            assert self.crop_x_start <= dx, \
                "crop_x_start=%d exceeds crop margin %d" % (self.crop_x_start,
                                                            dx)
            xx = self.crop_x_start
        contrast = 1.0
        illumination = 0.0
        if self.max_random_contrast > 0:
            contrast = self.rng.rand() * self.max_random_contrast * 2 \
                - self.max_random_contrast + 1
        if self.max_random_illumination > 0:
            illumination = self.rng.rand() * self.max_random_illumination * 2 \
                - self.max_random_illumination
        do_mirror = self.mirror == 1 or \
            (self.rand_mirror and self.rng.rand() < 0.5)

        img = data
        if self.mean_value is not None:
            img = img - self.mean_value[:img.shape[0], None, None]
            img = img * contrast + illumination
            img = img[:, yy:yy + y, xx:xx + x]
        elif self.meanimg is not None:
            if img.shape == self.meanimg.shape:
                img = (img - self.meanimg) * contrast + illumination
                img = img[:, yy:yy + y, xx:xx + x]
            else:
                img = img[:, yy:yy + y, xx:xx + x]
                img = (img - self.meanimg) * contrast + illumination
        else:
            img = img[:, yy:yy + y, xx:xx + x]
        if do_mirror:
            img = img[:, :, ::-1]
        img = np.ascontiguousarray(img, np.float32)
        if self.scale != 1.0:       # skip the extra full pass at scale 1
            img = img * self.scale
        return DataInst(img, d.label, d.index, d.extra_data)

    def next(self) -> bool:
        if not self.base.next():
            return False
        self._value = self._process(self.base.value())
        return True

    def value(self) -> DataInst:
        return self._value

    def close(self) -> None:
        self.base.close()

    def _create_mean_img(self) -> None:
        """Full dataset pass averaging the *cropped* images, then save and
        rewind (CreateMeanImg, iter_augment_proc-inl.hpp:171-198)."""
        if self.silent == 0:
            print("cannot find %s: creating mean image, this will take some "
                  "time..." % self.name_meanimg)
        start = time.time()
        total = None
        count = 0
        saved_scale, self.scale = self.scale, 1.0   # mean is in raw 0..255 units
        self.base.before_first()
        while self.base.next():
            img = self._process(self.base.value()).data
            total = img.astype(np.float64) if total is None else total + img
            count += 1
            if count % 1000 == 0 and self.silent == 0:
                print("\r[%8d] images processed, %d sec elapsed"
                      % (count, int(time.time() - start)), end="")
        self.scale = saved_scale
        assert count > 0, "input iterator produced no data"
        self.meanimg = (total / count).astype(np.float32)
        with open(self.name_meanimg, "wb") as f:
            np.save(f, self.meanimg)
        if self.silent == 0:
            print("\nsave mean image to %s" % self.name_meanimg)
        # rewind so non-rewinding consumers (DenseBufferIterator never rewinds
        # its base) see the data; imgbin treats a rewind on an unconsumed
        # epoch as a no-op, so the consumer's own before_first costs nothing
        self.base.before_first()
