"""BinaryPage — 64 MB fixed-size packed-object pages, the imgbin on-disk
dataset format.

Format-compatible with the reference (/root/reference/src/utils/io.h:254-326)
so existing cxxnet .bin datasets work unchanged: a page is kPageSize int32
words; word 0 is the object count N, words 1..N+1 are cumulative byte
end-offsets, and object payloads are packed backward from the end of the page
(object r spans bytes [pagesize - end[r+1], pagesize - end[r]) from the page
start). Pages are always written at full size.
"""

from __future__ import annotations

import io
from typing import BinaryIO, Iterator, List, Optional

import numpy as np

K_PAGE_WORDS = 64 << 18                 # page size in int32 words
K_PAGE_BYTES = K_PAGE_WORDS * 4         # 64 MB


class BinaryPage:
    """One in-memory page; supports reading and building."""

    def __init__(self, buf: Optional[bytes] = None) -> None:
        if buf is None:
            self._buf = bytearray(K_PAGE_BYTES)
            self._count = 0
            self._ends = [0]            # cumulative end offsets
        else:
            if len(buf) != K_PAGE_BYTES:
                raise IOError("BinaryPage: truncated page (%d bytes)" % len(buf))
            self._buf = bytearray(buf)
            head = np.frombuffer(buf, dtype="<i4", count=1)[0]
            self._count = int(head)
            self._ends = np.frombuffer(buf, dtype="<i4", offset=4,
                                       count=self._count + 1).tolist()

    @property
    def size(self) -> int:
        return self._count

    def __len__(self) -> int:
        return self._count

    def __getitem__(self, r: int) -> memoryview:
        if not (0 <= r < self._count):
            raise IndexError("BinaryPage index out of bounds")
        lo = K_PAGE_BYTES - self._ends[r + 1]
        hi = K_PAGE_BYTES - self._ends[r]
        return memoryview(self._buf)[lo:hi]

    def free_bytes(self) -> int:
        return (K_PAGE_WORDS - (self._count + 2)) * 4 - self._ends[-1]

    def push(self, data: bytes) -> bool:
        """Append one object; False if the page is full."""
        if self.free_bytes() < len(data) + 4:
            return False
        new_end = self._ends[-1] + len(data)
        self._buf[K_PAGE_BYTES - new_end:K_PAGE_BYTES - self._ends[-1]] = data
        self._ends.append(new_end)
        self._count += 1
        return True

    def clear(self) -> None:
        self._buf = bytearray(K_PAGE_BYTES)
        self._count = 0
        self._ends = [0]

    def tobytes(self) -> bytes:
        header = np.zeros(self._count + 2, dtype="<i4")
        header[0] = self._count
        header[1:] = self._ends
        hb = header.tobytes()
        self._buf[:len(hb)] = hb
        return bytes(self._buf)

    def save(self, f: BinaryIO) -> None:
        f.write(self.tobytes())

    @classmethod
    def load(cls, f: BinaryIO) -> Optional["BinaryPage"]:
        buf = f.read(K_PAGE_BYTES)
        if len(buf) == 0:
            return None
        return cls(buf)


def open_maybe_gz(path: str, mode: str = "rb"):
    """Open a file, transparently gunzipping ``*.gz`` — the reference's
    GzFile stream (io.h:152-180) generalized to every dataset input
    (.lst, .bin, attachtxt), not just the mnist idx files."""
    if path.endswith(".gz"):
        import gzip
        return gzip.open(path, mode if "b" in mode else mode + "t")
    return open(path, mode)


def iter_pages(path: str) -> Iterator[BinaryPage]:
    with open_maybe_gz(path, "rb") as f:
        while True:
            page = BinaryPage.load(f)
            if page is None:
                return
            yield page


class BinaryPageWriter:
    """Streams objects into consecutive pages of a .bin file (im2bin core)."""

    def __init__(self, path: str) -> None:
        self._f = open(path, "wb")
        self._page = BinaryPage()
        self.n_pages = 0
        self.n_objects = 0

    def push(self, data: bytes) -> None:
        if len(data) + 12 > K_PAGE_BYTES:
            raise ValueError("object of %d bytes exceeds the 64MB page size"
                             % len(data))
        if not self._page.push(data):
            self._flush_page()
            if not self._page.push(data):
                raise ValueError("object does not fit in an empty page")
        self.n_objects += 1

    def _flush_page(self) -> None:
        self._page.save(self._f)
        self._page.clear()
        self.n_pages += 1

    def close(self) -> None:
        if self._page.size:
            self._flush_page()
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
