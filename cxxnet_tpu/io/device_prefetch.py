"""Async training feed: host->device placement on a background thread.

The reference hid host-side batch costs behind compute with the ThreadBuffer
pipeline (utils/thread_buffer.h); the TPU build's `threadbuffer` iterator
reproduces that for *host* work (decode/augment/assembly), but until now the
`device_put`/`global_batch` placement of each batch ran synchronously inside
``Net.update`` on the critical path. :class:`DevicePrefetcher` moves that
placement off the hot loop: a producer thread drains the wrapped host
iterator, places each batch on the mesh (``Net.place_batch``), and parks the
resulting :class:`DeviceBatch` in a bounded queue — so batch k+1's
host->device transfer overlaps step k's compute (the input-transfer overlap
the TensorFlow system paper calls a first-order throughput lever, arxiv
1605.08695 §4.2; Caffe con Troll makes the same case for pipelining host
work, arxiv 1504.04343).

Multi-host contract (IMPORTANT): ``global_batch`` assembles one *global*
array from each process's local slice, so every process MUST place the same
batches in the same order — batch k on process 0 and batch k on process 7
are slices of the same logical array. The prefetcher guarantees per-process
ordering (one producer thread, placements in iterator order, a bounded FIFO
queue), and the usual SPMD deployment (same config, same seeds, same
dataset shards) guarantees the cross-process part. Two guards back the
contract up:

- only ONE DevicePrefetcher may be live per process in a multi-host run —
  a second concurrent producer could interleave placements and there is no
  way to prove the interleaving identical across processes;
- with ``CXN_PREFETCH_CHECK=1``, every ``before_first()`` (a main-thread,
  all-ranks point) all-gathers the previous epoch's consumed-batch count
  and raises if any process disagrees (a count mismatch means the feeds
  diverged and the NEXT epoch's placements would pair wrong slices).

Queue depth (``depth``, default 2) bounds device memory: at most
``depth + 1`` batches are resident beyond the one being consumed —
backpressure comes from the blocking queue put, exactly like the
reference's two-slot ThreadBuffer.
"""

from __future__ import annotations

import os
from typing import Callable, List, Optional

import numpy as np

from ..analysis.concurrency import make_lock
from ..parallel.distributed import is_multi_host, multihost_assert_equal
from .data import PrefetchProducerMixin

__all__ = ["DeviceBatch", "DevicePrefetcher"]


class DeviceBatch:
    """A host DataBatch after mesh placement (``Net.place_batch``).

    ``data`` / ``extras`` / ``label`` are global, data-axis-sharded jax
    arrays; ``mask`` is the short-pad loss mask (or None — padding
    accounting is already baked into it, so no host-side pad metadata
    rides along). ``host_label`` keeps this process's host-side label
    slice ONLY when the trainer needs it (host-path train metrics); with
    on-device metric accumulation it is None and nothing about the batch
    ever returns to the host.
    """

    __slots__ = ("data", "extras", "label", "mask", "host_label")

    def __init__(self, data, extras, label, mask,
                 host_label: Optional[np.ndarray] = None) -> None:
        self.data = data
        self.extras = extras
        self.label = label
        self.mask = mask
        self.host_label = host_label


# multi-host single-producer guard (see module docstring): the set of live
# prefetchers in this process, and the lock serializing placements so two
# prefetchers in a SINGLE-host run (where they are allowed) cannot
# interleave inside one placement either
_live_prefetchers: set = set()      # guarded_by: _live_lock
_live_lock = make_lock("device_prefetch._live_lock")
_place_lock = make_lock("device_prefetch._place_lock")


class DevicePrefetcher(PrefetchProducerMixin):
    """Wrap a host batch iterator; yield pre-placed :class:`DeviceBatch`.

    Drop-in for the iterator contract (``before_first`` / ``next`` /
    ``value`` / ``close``), so the CLI round loop and ``wrapper.train``
    consume it exactly like the host chain. ``place_fn`` is
    ``Net.place_batch`` (or any ``DataBatch -> DeviceBatch``); ``depth``
    is the bounded-queue size (>= 1).
    """

    def __init__(self, place_fn: Callable, base, depth: int = 2) -> None:
        if depth < 1:
            raise ValueError("DevicePrefetcher depth must be >= 1, got %d"
                             % depth)
        self.place_fn = place_fn
        self.base = base
        self.depth = depth
        self._value: Optional[DeviceBatch] = None
        self._consumed = 0          # batches consumed this epoch
        self._last_epoch_count = -1  # consumed count of the last full epoch
        self.placed = 0             # total placements (test/diagnostic hook)
        with _live_lock:
            if is_multi_host() and _live_prefetchers:
                raise RuntimeError(
                    "DevicePrefetcher: a second concurrent prefetcher in a "
                    "multi-host run would interleave device placements, and "
                    "placement order must stay identical across processes "
                    "(io/device_prefetch.py docstring) — close the other "
                    "feed first")
            _live_prefetchers.add(self)
        self._init_producer(depth)

    # ---------------------------------------------------------- producer
    def _produce_epoch(self) -> None:
        self.base.before_first()
        while self.base.next():
            # serialize placements process-wide: with two single-host
            # prefetchers live, each batch's device_put sequence stays
            # contiguous (and the multi-host case is single-feed by the
            # constructor guard)
            with _place_lock:
                db = self.place_fn(self.base.value())
                self.placed += 1
            if not self._put(db):
                return
        self._put(self._END)

    # ---------------------------------------------------------- consumer
    def before_first(self) -> None:
        if self._consumed and self._epoch_done:
            self._last_epoch_count = self._consumed
            # all-ranks point: verify every process consumed the same
            # number of batches last epoch (opt-in — it is a collective)
            if is_multi_host() and os.environ.get("CXN_PREFETCH_CHECK"):
                multihost_assert_equal(
                    [float(self._last_epoch_count)],
                    "DevicePrefetcher epoch batch count")
        self._consumed = 0
        self._rewind_producer()

    def next(self) -> bool:
        item = self._next_item()
        if item is None:
            return False
        self._value = item
        self._consumed += 1
        return True

    def value(self) -> DeviceBatch:
        return self._value

    def close(self) -> None:
        """Mandatory teardown: joins the producer thread and releases the
        multi-host single-feed slot. There is deliberately no ``__del__``
        fallback — the producer thread itself keeps the prefetcher
        strongly referenced, so GC can never reclaim an un-closed feed;
        callers hold it in try/finally (cli/wrapper do) and the test
        harness leak-checks the named threads (tests/conftest.py)."""
        self._close_producer()
        with _live_lock:
            _live_prefetchers.discard(self)

    # the producer thread gets a recognizable name so the test harness can
    # leak-check it (tests/conftest.py) — override the mixin's init to name it
    def _init_producer(self, queue_size: int) -> None:
        PrefetchProducerMixin._init_producer(self, queue_size)
        if self._thread is not None:
            self._thread.name = "cxn-device-prefetch-%x" % id(self)
