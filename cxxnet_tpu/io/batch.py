"""Batching and buffering processor iterators.

Reference (/root/reference/src/io/iter_batch_proc-inl.hpp, iter_mem_buffer-inl.hpp):
- BatchAdaptIterator (16-133): packs DataInst -> DataBatch; tail handling:
  ``round_batch=1`` wraps to the start of the epoch and counts the wrapped
  instances as ``num_batch_padd`` (for eval correctness), else short-pads and
  sets num_batch_padd = missing count; ``test_skipread`` freezes one batch for
  compute-throughput benchmarking.
- ThreadBufferIterator (136-224): background-thread prefetch of whole batches
  (the ThreadBuffer double-buffer pipeline, utils/thread_buffer.h) — here a
  bounded-queue producer thread, which is the idiomatic Python equivalent.
- DenseBufferIterator (17-77): caches the first max_nbatch batches in RAM and
  replays them (dataset-in-memory mode).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from .data import (DataBatch, DataInst, IIterator, PrefetchProducerMixin,
                   register_proc_iterator)


class BatchAdaptIterator(IIterator):
    """DataInst iterator -> DataBatch iterator of fixed batch_size."""

    def __init__(self, base: IIterator) -> None:
        self.base = base
        self.batch_size = 0
        self.label_width = 1
        self.round_batch = 0
        self.num_overflow = 0
        self.test_skipread = 0
        self.silent = 0
        self.head = 1
        self._dtype = np.float32
        self._value: Optional[DataBatch] = None

    def set_param(self, name: str, val: str) -> None:
        self.base.set_param(name, val)
        if name == "batch_size":
            self.batch_size = int(val)
        elif name == "label_width":
            self.label_width = int(val)
        elif name == "round_batch":
            self.round_batch = int(val)
        elif name == "silent":
            self.silent = int(val)
        elif name == "test_skipread":
            self.test_skipread = int(val)
        elif name == "data_dtype":
            # compute-dtype batches straight from the pipeline: with
            # "bfloat16" (under a `threadbuffer` chain) the cast runs in
            # the prefetch producer thread, halving host->device bytes and
            # letting the jitted step's own input cast no-op. Labels stay
            # float32.
            if val not in ("float32", "bfloat16"):
                raise ValueError("data_dtype must be float32 or bfloat16")
            if val == "bfloat16":
                import ml_dtypes
                self._dtype = ml_dtypes.bfloat16
            else:
                self._dtype = np.float32

    def init(self) -> None:
        assert self.batch_size > 0, "batch_size must be set"
        self.base.init()

    def before_first(self) -> None:
        if self.round_batch == 0 or self.num_overflow == 0:
            self.base.before_first()
        else:
            self.num_overflow = 0
        self.head = 1

    def _collect(self, insts: List[DataInst]) -> DataBatch:
        # copy=False: the stack output is already float32, so the default
        # astype would add a second full-batch copy (measured ~0.4 ms/img
        # at AlexNet shapes — as much as the JPEG decode itself)
        data = np.stack([d.data for d in insts]).astype(self._dtype,
                                                        copy=False)
        label = np.zeros((len(insts), self.label_width), np.float32)
        for i, d in enumerate(insts):
            lab = np.asarray(d.label, np.float32).reshape(-1)
            label[i, :min(self.label_width, lab.shape[0])] = \
                lab[:self.label_width]
        index = np.array([d.index for d in insts], np.uint32)
        extra = []
        if insts[0].extra_data:
            for k in range(len(insts[0].extra_data)):
                extra.append(np.stack([d.extra_data[k] for d in insts]))
        return DataBatch(data, label, index, 0, extra)

    def next(self) -> bool:
        if self.test_skipread and self.head == 0 and self._value is not None:
            return True
        self.head = 0
        if self.num_overflow != 0:
            return False
        insts: List[DataInst] = []
        while self.base.next():
            insts.append(self.base.value())
            if len(insts) >= self.batch_size:
                self._value = self._collect(insts)
                return True
        if insts:
            if self.round_batch != 0:
                self.num_overflow = 0
                self.base.before_first()
                while len(insts) < self.batch_size:
                    assert self.base.next(), \
                        "number of inputs must exceed batch size"
                    insts.append(self.base.value())
                    self.num_overflow += 1
                batch = self._collect(insts)
                batch.num_batch_padd = self.num_overflow
                batch.pad_mode = "wrap"    # real wrapped instances: trained on
            else:
                missing = self.batch_size - len(insts)
                # short batch: pad by repeating the last instance to keep
                # shapes static (XLA), and mark the padding count
                insts.extend([insts[-1]] * missing)
                batch = self._collect(insts)
                batch.num_batch_padd = missing
                batch.pad_mode = "short"   # duplicates: masked out of the loss
            self._value = batch
            return True
        return False

    def value(self) -> DataBatch:
        return self._value

    def close(self) -> None:
        self.base.close()


@register_proc_iterator("threadbuffer")
class ThreadBufferIterator(PrefetchProducerMixin, IIterator):
    """Background-thread prefetch with a bounded queue (double-buffer analogue)."""

    def __init__(self, base: IIterator, buffer_size: int = 2) -> None:
        self.base = base
        self.buffer_size = buffer_size
        self.silent = 0
        self._value = None

    def set_param(self, name: str, val: str) -> None:
        self.base.set_param(name, val)
        if name == "buffer_size":
            self.buffer_size = int(val)
        elif name == "silent":
            self.silent = int(val)

    def init(self) -> None:
        self.base.init()
        self._init_producer(self.buffer_size)
        self.before_first()

    def _produce_epoch(self) -> None:
        self.base.before_first()
        while self.base.next():
            v = self.base.value()
            # deep-copy: the base may reuse buffers (CopyFromDense analogue)
            if not self._put(DataBatch(
                    np.array(v.data), np.array(v.label),
                    None if v.inst_index is None else np.array(v.inst_index),
                    v.num_batch_padd,
                    [np.array(e) for e in v.extra_data],
                    v.pad_mode)):
                return
        self._put(self._END)

    def before_first(self) -> None:
        self._rewind_producer()

    def next(self) -> bool:
        item = self._next_item()
        if item is None:
            return False
        self._value = item
        return True

    def value(self):
        return self._value

    def close(self) -> None:
        self._close_producer()
        self.base.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


@register_proc_iterator("membuffer")
class DenseBufferIterator(IIterator):
    """Caches the first max_nbatch batches in RAM and replays them."""

    def __init__(self, base: IIterator) -> None:
        self.base = base
        self.max_nbatch = 1 << 30
        self.silent = 0
        self._cache: List[DataBatch] = []
        self._filled = False
        self._pos = 0

    def set_param(self, name: str, val: str) -> None:
        self.base.set_param(name, val)
        if name == "max_nbatch":
            self.max_nbatch = int(val)
        elif name == "silent":
            self.silent = int(val)

    def init(self) -> None:
        self.base.init()

    def before_first(self) -> None:
        # the base is consumed exactly once, sequentially, into the cache;
        # rewinding it mid-fill would duplicate batches in the replay cache
        self._pos = 0

    def next(self) -> bool:
        if self._pos < len(self._cache):
            self._value = self._cache[self._pos]
            self._pos += 1
            return True
        if not self._filled and len(self._cache) < self.max_nbatch \
                and self.base.next():
            v = self.base.value()
            batch = DataBatch(np.array(v.data), np.array(v.label),
                              None if v.inst_index is None
                              else np.array(v.inst_index),
                              v.num_batch_padd,
                              [np.array(e) for e in v.extra_data],
                              v.pad_mode)
            self._cache.append(batch)
            self._pos += 1
            self._value = batch
            return True
        self._filled = True
        return False

    def value(self):
        return self._value

    def close(self) -> None:
        self.base.close()
