"""Shared base for whole-dataset-in-memory batch iterators (mnist, cifar).

These load the full dataset at init and serve batch-sized *views* of the
preloaded tensors (the reference MNISTIterator pattern,
iter_mnist-inl.hpp:14-158): optional seeded shuffle, tail partial batch
dropped, `data_dtype` conversion applied once at load.
"""

from __future__ import annotations

import numpy as np

from .data import DataBatch, IIterator

RAND_MAGIC = 111


class InMemoryIterator(IIterator):
    """Common config keys + batch serving; subclasses implement ``init``
    and call :meth:`_finalize_load` with the raw f32 data/labels."""

    def __init__(self) -> None:
        self.silent = 0
        self.shuffle = 0
        self.batch_size = 0
        self.inst_offset = 0
        self.seed = RAND_MAGIC
        self.loc = 0
        self._dtype = np.float32

    def set_param(self, name: str, val: str) -> None:
        if name == "silent":
            self.silent = int(val)
        elif name == "batch_size":
            self.batch_size = int(val)
        elif name == "shuffle":
            self.shuffle = int(val)
        elif name == "index_offset":
            self.inst_offset = int(val)
        elif name == "seed_data":
            self.seed = RAND_MAGIC + int(val)
        elif name == "data_dtype":
            # convert once at load, so every batch view is already
            # compute-dtype (batch.py's batcher does the same per batch
            # for instance pipelines)
            if val not in ("float32", "bfloat16"):
                raise ValueError("data_dtype must be float32 or bfloat16")
            if val == "bfloat16":
                import ml_dtypes
                self._dtype = ml_dtypes.bfloat16
            else:
                self._dtype = np.float32

    def _finalize_load(self, img: np.ndarray, labels: np.ndarray,
                       tag: str) -> None:
        """Apply dtype/shuffle/instance-index bookkeeping to the loaded
        dataset and report, then rewind."""
        if self.batch_size <= 0:
            raise ValueError(
                "%s iterator: batch_size must be set > 0 before init "
                "(got %d)" % (tag, self.batch_size))
        self.img = img.astype(self._dtype)
        # labels keep their width (class iterators pass (n,) -> (n, 1);
        # the lm iterator passes (n, seq) token-id label fields)
        self.labels = labels.astype(np.float32).reshape(img.shape[0], -1)
        n = img.shape[0]
        self.inst = np.arange(n, dtype=np.uint32) + self.inst_offset
        if self.shuffle:
            order = np.random.RandomState(self.seed).permutation(n)
            self.img = self.img[order]
            self.labels = self.labels[order]
            self.inst = self.inst[order]
        self.loc = 0
        if self.silent == 0:
            print("%s: load %d images, shuffle=%d, shape=%s"
                  % (tag, n, self.shuffle,
                     (self.batch_size,) + self.img.shape[1:]))

    def before_first(self) -> None:
        self.loc = 0

    def next(self) -> bool:
        if self.loc + self.batch_size <= self.img.shape[0]:
            i, b = self.loc, self.batch_size
            self._value = DataBatch(self.img[i:i + b], self.labels[i:i + b],
                                    self.inst[i:i + b])
            self.loc += b
            return True
        return False

    def value(self) -> DataBatch:
        return self._value
