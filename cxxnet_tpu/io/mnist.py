"""MNIST idx-format iterator (reference: /root/reference/src/io/iter_mnist-inl.hpp:14-158).

Loads gz (or raw) idx images/labels wholly into memory, scales pixels by 1/256,
optional flatten to (1,1,784) (``input_flat``, default on), in-memory shuffle
with a seeded RNG, and drops the tail partial batch (Next at :62-73).
"""

from __future__ import annotations

import gzip
import struct

import numpy as np

from .data import DataBatch, IIterator, register_base_iterator

_RAND_MAGIC = 121


def _open_maybe_gz(path: str):
    if path.endswith(".gz"):
        return gzip.open(path, "rb")
    return open(path, "rb")


def read_idx(path: str) -> np.ndarray:
    """Read an idx-format array (images: magic 2051, labels: 2049)."""
    with _open_maybe_gz(path) as f:
        magic = struct.unpack(">i", f.read(4))[0]
        ndim = magic % 256
        dims = [struct.unpack(">i", f.read(4))[0] for _ in range(ndim)]
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(dims)


@register_base_iterator("mnist")
class MNISTIterator(IIterator):
    def __init__(self) -> None:
        self.mode = 1            # input_flat
        self.silent = 0
        self.shuffle = 0
        self.inst_offset = 0
        self.batch_size = 0
        self.path_img = ""
        self.path_label = ""
        self.seed = _RAND_MAGIC
        self.loc = 0
        self._dtype = np.float32

    def set_param(self, name: str, val: str) -> None:
        if name == "silent":
            self.silent = int(val)
        elif name == "batch_size":
            self.batch_size = int(val)
        elif name == "input_flat":
            self.mode = int(val)
        elif name == "shuffle":
            self.shuffle = int(val)
        elif name == "index_offset":
            self.inst_offset = int(val)
        elif name == "path_img":
            self.path_img = val
        elif name == "path_label":
            self.path_label = val
        elif name == "seed_data":
            self.seed = _RAND_MAGIC + int(val)
        elif name == "data_dtype":
            # whole-dataset batch iterator: convert once at load, so every
            # batch view is already compute-dtype (batch.py's batcher does
            # the same per batch for instance pipelines)
            if val not in ("float32", "bfloat16"):
                raise ValueError("data_dtype must be float32 or bfloat16")
            if val == "bfloat16":
                import ml_dtypes
                self._dtype = ml_dtypes.bfloat16
            else:
                self._dtype = np.float32

    def init(self) -> None:
        img = read_idx(self.path_img).astype(np.float32) * (1.0 / 256.0)
        img = img.astype(self._dtype)
        label = read_idx(self.path_label).astype(np.float32)
        assert img.shape[0] == label.shape[0]
        n, rows, cols = img.shape
        if self.mode == 1:
            self.img = img.reshape(n, 1, 1, rows * cols)
        else:
            self.img = img.reshape(n, 1, rows, cols)
        self.labels = label.reshape(n, 1)
        self.inst = np.arange(n, dtype=np.uint32) + self.inst_offset
        if self.shuffle:
            order = np.random.RandomState(self.seed).permutation(n)
            self.img = self.img[order]
            self.labels = self.labels[order]
            self.inst = self.inst[order]
        self.loc = 0
        if self.silent == 0:
            print("MNISTIterator: load %d images, shuffle=%d, shape=%s"
                  % (n, self.shuffle, (self.batch_size,) + self.img.shape[1:]))

    def before_first(self) -> None:
        self.loc = 0

    def next(self) -> bool:
        if self.loc + self.batch_size <= self.img.shape[0]:
            i, b = self.loc, self.batch_size
            self._value = DataBatch(self.img[i:i + b], self.labels[i:i + b],
                                    self.inst[i:i + b])
            self.loc += b
            return True
        return False

    def value(self) -> DataBatch:
        return self._value
