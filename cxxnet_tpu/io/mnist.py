"""MNIST idx-format iterator (reference: /root/reference/src/io/iter_mnist-inl.hpp:14-158).

Loads gz (or raw) idx images/labels wholly into memory, scales pixels by 1/256,
optional flatten to (1,1,784) (``input_flat``, default on), in-memory shuffle
with a seeded RNG, and drops the tail partial batch (Next at :62-73).
"""

from __future__ import annotations

import gzip
import struct

import numpy as np

from .data import register_base_iterator
from .inmem import InMemoryIterator

_RAND_MAGIC = 121


def _open_maybe_gz(path: str):
    if path.endswith(".gz"):
        return gzip.open(path, "rb")
    return open(path, "rb")


def read_idx(path: str) -> np.ndarray:
    """Read an idx-format array (images: magic 2051, labels: 2049)."""
    with _open_maybe_gz(path) as f:
        magic = struct.unpack(">i", f.read(4))[0]
        ndim = magic % 256
        dims = [struct.unpack(">i", f.read(4))[0] for _ in range(ndim)]
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(dims)


@register_base_iterator("mnist")
class MNISTIterator(InMemoryIterator):
    def __init__(self) -> None:
        super().__init__()
        self.mode = 1            # input_flat
        self.path_img = ""
        self.path_label = ""
        self.seed = _RAND_MAGIC  # mnist's historical default shuffle seed

    def set_param(self, name: str, val: str) -> None:
        if name == "input_flat":
            self.mode = int(val)
        elif name == "path_img":
            self.path_img = val
        elif name == "path_label":
            self.path_label = val
        elif name == "seed_data":
            self.seed = _RAND_MAGIC + int(val)
        else:
            super().set_param(name, val)

    def init(self) -> None:
        img = read_idx(self.path_img).astype(np.float32) * (1.0 / 256.0)
        label = read_idx(self.path_label).astype(np.float32)
        assert img.shape[0] == label.shape[0]
        n, rows, cols = img.shape
        if self.mode == 1:
            img = img.reshape(n, 1, 1, rows * cols)
        else:
            img = img.reshape(n, 1, rows, cols)
        self._finalize_load(img, label, "MNISTIterator")
