"""Token-stream iterator for causal-LM training (``iter = lm``).

No reference counterpart (the reference has no sequence models, SURVEY
§5.7); this extends the reference's whole-dataset-in-memory iterator
pattern (iter_mnist-inl.hpp:14-158 via InMemoryIterator) to token streams
so the GPT flagship trains from a config file through the standard CLI.

Reads a flat token stream and serves contiguous ``seq_len`` windows:
data (b, 1, 1, N) float ids, and the SAME window as the width-N label
field — a causal LM's target is its input shifted by one, and the shift
happens inside the ``lm_softmax`` loss (layers/loss.py), so data and
label are identical windows.

Input formats (``path_data``, gz-transparent like every dataset input):
  *.npy             — any integer dtype, loaded with numpy
  ``format = bytes``  — raw bytes as uint8 tokens (byte-level LM: any
                        text file is a corpus)
  otherwise         — raw binary of ``token_dtype`` (uint8/uint16/uint32,
                        default uint16)

Config: ``seq_len`` (window length, required), ``stride`` (window step,
default seq_len — non-overlapping), plus the shared in-memory keys
(shuffle / seed_data / batch_size / silent). ``data_dtype`` is
intentionally IGNORED (ids must stay exact; bfloat16 has 8 mantissa bits
and would corrupt ids > 256 — the trainer keeps id entry nodes in f32 and
casts to the compute dtype after embedding lookup, nnet/net.py).

Token-id ceiling: the window store is float32, whose 24 mantissa bits
represent integers exactly only up to 2^24 (16,777,216). ``token_dtype =
uint32`` streams with ids >= 2^24 would silently round to the wrong id,
so :meth:`init` REJECTS them at load time — re-tokenize with a smaller
vocabulary (every practical tokenizer fits: 2^24 is ~64x a GPT-4-class
vocab) or split the id space upstream.
"""

from __future__ import annotations

import gzip

import numpy as np

from .data import register_base_iterator
from .inmem import InMemoryIterator


def _read_bytes(path: str) -> bytes:
    if path.endswith(".gz"):
        with gzip.open(path, "rb") as f:
            return f.read()
    with open(path, "rb") as f:
        return f.read()


@register_base_iterator("lm")
class LMIterator(InMemoryIterator):
    def __init__(self) -> None:
        super().__init__()
        self.path_data = ""
        self.seq_len = 0
        self.stride = 0
        self.format = "auto"          # auto | npy | bytes | bin
        self.token_dtype = np.uint16

    def set_param(self, name: str, val: str) -> None:
        if name == "path_data":
            self.path_data = val
        elif name == "seq_len":
            self.seq_len = int(val)
        elif name == "stride":
            self.stride = int(val)
        elif name == "format":
            if val not in ("auto", "npy", "bytes", "bin"):
                raise ValueError("lm iterator: format must be "
                                 "auto|npy|bytes|bin, got %r" % val)
            self.format = val
        elif name == "token_dtype":
            if val not in ("uint8", "uint16", "uint32"):
                raise ValueError("lm iterator: token_dtype must be "
                                 "uint8|uint16|uint32, got %r" % val)
            self.token_dtype = np.dtype(val).type
        elif name == "data_dtype":
            pass    # ids stay exact f32 (module docstring)
        else:
            super().set_param(name, val)

    def _load_tokens(self) -> np.ndarray:
        fmt = self.format
        if fmt == "auto":
            base = self.path_data[:-3] if self.path_data.endswith(".gz") \
                else self.path_data
            fmt = "npy" if base.endswith(".npy") else "bin"
        if fmt == "npy":
            import io as _io
            return np.load(_io.BytesIO(_read_bytes(self.path_data)))
        raw = _read_bytes(self.path_data)
        if fmt == "bytes":
            return np.frombuffer(raw, np.uint8)
        return np.frombuffer(raw, self.token_dtype)

    def init(self) -> None:
        if self.seq_len <= 0:
            raise ValueError("lm iterator: seq_len must be set > 0")
        tok = np.asarray(self._load_tokens()).ravel()
        n = self.seq_len
        if tok.size < n:
            raise ValueError(
                "lm iterator: token stream %r has %d tokens < seq_len %d"
                % (self.path_data, tok.size, n))
        if tok.size and int(tok.max()) >= (1 << 24):
            raise ValueError(
                "lm iterator: token id %d in %r exceeds the float32 "
                "exact-integer ceiling 2^24 = 16777216 — ids ride the "
                "pipeline as exact f32 (module docstring) and larger ids "
                "would silently lose exactness; re-tokenize with a "
                "smaller id space" % (int(tok.max()), self.path_data))
        stride = self.stride if self.stride > 0 else n
        starts = np.arange(0, tok.size - n + 1, stride)
        win = tok[starts[:, None] + np.arange(n)].astype(np.float32)
        self._dtype = np.float32      # ids stay exact (module docstring)
        self._finalize_load(win.reshape(-1, 1, 1, n), win, "LMIterator")
