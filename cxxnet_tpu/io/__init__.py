"""Data pipeline package. Importing registers all iterator types."""

from .data import (DataBatch, DataInst, IIterator, create_iterator,
                   register_base_iterator, register_proc_iterator)
from .device_prefetch import DeviceBatch, DevicePrefetcher
from . import mnist      # noqa: F401
from . import cifar      # noqa: F401
from . import batch      # noqa: F401
from . import imgbin     # noqa: F401  (imgbin/imgbinx/imgbinold)
from . import img        # noqa: F401
from . import attach_txt  # noqa: F401
from . import lm         # noqa: F401

__all__ = ["DataBatch", "DataInst", "IIterator", "create_iterator",
           "register_base_iterator", "register_proc_iterator",
           "DeviceBatch", "DevicePrefetcher"]
