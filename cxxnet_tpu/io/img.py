"""img — plain image-file iterator (reference: src/io/iter_img-inl.hpp:16-137).

Reads ``image_list`` (``index<TAB>label...<TAB>filename``) rooted at
``image_root``, decodes each image at Next() time, optional order shuffle.
Composed as BatchAdapt(Augment(Image)) by the factory (data.cpp:46-50).
"""

from __future__ import annotations

import numpy as np

from .data import DataInst, IIterator, register_base_iterator
from .decoder import decode_image_chw
from .imgbin import read_list_file

_RAND_MAGIC = 121


class ImageIterator(IIterator):
    def __init__(self) -> None:
        self.image_list = ""
        self.image_root = ""
        self.shuffle = 0
        self.label_width = 1
        self.silent = 0
        self.seed = _RAND_MAGIC
        self.gray_to_rgb = True
        self.loc = 0
        # decode-at-scale: same opt-in + warp-param gating as imgbin
        self.decode_at_scale = 0
        self.target_hw = None
        self._warp_params = False

    def set_param(self, name: str, val: str) -> None:
        from .decoder import is_warp_param
        if is_warp_param(name, val):
            self._warp_params = True
        if name == "image_list":
            self.image_list = val
        elif name == "image_root":
            self.image_root = val
        elif name == "shuffle":
            self.shuffle = int(val)
        elif name == "label_width":
            self.label_width = int(val)
        elif name == "silent":
            self.silent = int(val)
        elif name == "seed_data":
            self.seed = _RAND_MAGIC + int(val)
        elif name == "decode_at_scale":
            self.decode_at_scale = int(val)
        elif name == "input_shape":
            parts = [int(v) for v in val.split(",")]
            self.gray_to_rgb = parts[0] == 3
            if len(parts) == 3:
                self.target_hw = (parts[1], parts[2])

    def init(self) -> None:
        if not self.image_list:
            raise ValueError("img iterator: must set image_list")
        from .decoder import resolve_min_hw
        self._min_hw = resolve_min_hw(self.decode_at_scale, self.target_hw,
                                      self._warp_params)
        self.idx, self.labels, self.names = read_list_file(
            self.image_list, self.label_width)
        self.order = np.arange(len(self.idx))
        self.rng = np.random.RandomState(self.seed)
        if self.silent == 0:
            print("ImageIterator: %d images, shuffle=%d"
                  % (len(self.idx), self.shuffle))
        self.before_first()

    def before_first(self) -> None:
        self.loc = 0
        if self.shuffle:
            self.rng.shuffle(self.order)

    def next(self) -> bool:
        if self.loc >= len(self.order):
            return False
        i = self.order[self.loc]
        self.loc += 1
        with open(self.image_root + self.names[i], "rb") as f:
            data = decode_image_chw(f.read(), self.gray_to_rgb,
                                    self._min_hw)
        self._value = DataInst(data, self.labels[i], int(self.idx[i]))
        return True

    def value(self) -> DataInst:
        return self._value


def _make_img() -> IIterator:
    from .augment import AugmentIterator
    from .batch import BatchAdaptIterator
    return BatchAdaptIterator(AugmentIterator(ImageIterator()))


register_base_iterator("img")(_make_img)
