"""Data pipeline core: instance/batch types, iterator interface, chain factory.

Reference (/root/reference/src/io/data.h:18-186, data.cpp:23-75): chainable
iterators configured by ordered ``iter = X`` lines; settings after an ``iter``
line are broadcast to every iterator already in the chain. Base iterators
(mnist/img/imgbin) cannot chain over others; processor iterators
(threadbuffer/membuffer/attachtxt) wrap the chain built so far.

Host-side batches are numpy, NCHW ``(n, c, y, x)`` float32 — the reference's
node layout — and the trainer transposes to the TPU-native NHWC once per step
on device entry.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

Pairs = Sequence[Tuple[str, str]]


class DataBatch:
    """One mini-batch (data.h:96-181, dense path)."""

    def __init__(self, data: np.ndarray, label: np.ndarray,
                 inst_index: Optional[np.ndarray] = None,
                 num_batch_padd: int = 0,
                 extra_data: Optional[List[np.ndarray]] = None,
                 pad_mode: str = "wrap") -> None:
        self.data = data                    # (n, c, y, x) float32
        self.label = label                  # (n, label_width) float32
        self.inst_index = inst_index
        self.num_batch_padd = num_batch_padd
        self.extra_data = extra_data or []
        # how the padded tail was produced: "wrap" = real wrapped instances
        # (trained on, excluded from eval); "short" = duplicated filler
        # (masked out of the loss too)
        self.pad_mode = pad_mode

    @property
    def batch_size(self) -> int:
        return self.data.shape[0]


class DataInst:
    """One instance (data.h:41-56)."""

    def __init__(self, data: np.ndarray, label: np.ndarray, index: int,
                 extra_data: Optional[List[np.ndarray]] = None) -> None:
        self.data = data                    # (c, y, x) float32
        self.label = label                  # (label_width,) float32
        self.index = index
        self.extra_data = extra_data or []


class IIterator:
    """Iterator contract (data.h:18-38): set_param / init / before_first /
    next / value. ``next`` returns bool; ``value`` the current element."""

    def set_param(self, name: str, val: str) -> None:
        pass

    def init(self) -> None:
        pass

    def before_first(self) -> None:
        raise NotImplementedError

    def next(self) -> bool:
        raise NotImplementedError

    def value(self):
        raise NotImplementedError

    def __iter__(self):
        self.before_first()
        while self.next():
            yield self.value()


# base iterators produce DataBatch directly (mnist) or DataInst (img family);
# the factory composes processors exactly as data.cpp:23-75 does.
_BASE_FACTORIES: Dict[str, Callable[[], "IIterator"]] = {}
_PROC_FACTORIES: Dict[str, Callable[["IIterator"], "IIterator"]] = {}


def register_base_iterator(name: str):
    def deco(factory):
        _BASE_FACTORIES[name] = factory
        return factory
    return deco


def register_proc_iterator(name: str):
    def deco(factory):
        _PROC_FACTORIES[name] = factory
        return factory
    return deco


def create_iterator(cfg: Pairs) -> IIterator:
    """Build an iterator chain from ordered config pairs (data.cpp:23-75)."""
    it: Optional[IIterator] = None
    for name, val in cfg:
        if name == "iter":
            if val in _BASE_FACTORIES:
                if it is not None:
                    raise ValueError("%s cannot chain over another iterator" % val)
                it = _BASE_FACTORIES[val]()
            elif val in _PROC_FACTORIES:
                if it is None:
                    raise ValueError("must specify input of %s" % val)
                it = _PROC_FACTORIES[val](it)
            else:
                raise ValueError("unknown iterator type %r" % val)
            continue
        if it is not None:
            it.set_param(name, val)
    if it is None:
        raise ValueError("must specify iterator by iter=itername")
    it.init()
    return it
