"""Data pipeline core: instance/batch types, iterator interface, chain factory.

Reference (/root/reference/src/io/data.h:18-186, data.cpp:23-75): chainable
iterators configured by ordered ``iter = X`` lines; settings after an ``iter``
line are broadcast to every iterator already in the chain. Base iterators
(mnist/img/imgbin) cannot chain over others; processor iterators
(threadbuffer/membuffer/attachtxt) wrap the chain built so far.

Host-side batches are numpy, NCHW ``(n, c, y, x)`` float32 — the reference's
node layout — and the trainer transposes to the TPU-native NHWC once per step
on device entry.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

Pairs = Sequence[Tuple[str, str]]


class DataBatch:
    """One mini-batch (data.h:96-181, dense path)."""

    def __init__(self, data: np.ndarray, label: np.ndarray,
                 inst_index: Optional[np.ndarray] = None,
                 num_batch_padd: int = 0,
                 extra_data: Optional[List[np.ndarray]] = None,
                 pad_mode: str = "wrap") -> None:
        self.data = data                    # (n, c, y, x) float32
        self.label = label                  # (n, label_width) float32
        self.inst_index = inst_index
        self.num_batch_padd = num_batch_padd
        self.extra_data = extra_data or []
        # how the padded tail was produced: "wrap" = real wrapped instances
        # (trained on, excluded from eval); "short" = duplicated filler
        # (masked out of the loss too)
        self.pad_mode = pad_mode
        # sparse CSR view (data.h:96-180): the reference carries these fields
        # but no dense NN path consumes them; kept for surface parity —
        # set_sparse fills them, sparse_row(i) reads one instance back
        self.sparse_values: Optional[np.ndarray] = None
        self.sparse_indices: Optional[np.ndarray] = None
        self.sparse_indptr: Optional[np.ndarray] = None

    def set_sparse(self, values: np.ndarray, indices: np.ndarray,
                   indptr: np.ndarray) -> None:
        assert indptr.shape[0] == self.batch_size + 1
        assert values.shape[0] == indices.shape[0] == indptr[-1]
        self.sparse_values = values
        self.sparse_indices = indices
        self.sparse_indptr = indptr

    def sparse_row(self, i: int) -> Tuple[np.ndarray, np.ndarray]:
        """(indices, values) of instance i, as SparseInst (data.h:62-76)."""
        a, b = self.sparse_indptr[i], self.sparse_indptr[i + 1]
        return self.sparse_indices[a:b], self.sparse_values[a:b]

    @property
    def batch_size(self) -> int:
        return self.data.shape[0]


class DataInst:
    """One instance (data.h:41-56)."""

    def __init__(self, data: np.ndarray, label: np.ndarray, index: int,
                 extra_data: Optional[List[np.ndarray]] = None) -> None:
        self.data = data                    # (c, y, x) float32
        self.label = label                  # (label_width,) float32
        self.index = index
        self.extra_data = extra_data or []


class IIterator:
    """Iterator contract (data.h:18-38): set_param / init / before_first /
    next / value. ``next`` returns bool; ``value`` the current element."""

    def set_param(self, name: str, val: str) -> None:
        pass

    def init(self) -> None:
        pass

    def close(self) -> None:
        """Release background threads/files; wrappers delegate to their base.
        Idempotent; calling any other method after close is undefined."""
        pass

    def before_first(self) -> None:
        raise NotImplementedError

    def next(self) -> bool:
        raise NotImplementedError

    def value(self):
        raise NotImplementedError

    def __iter__(self):
        self.before_first()
        while self.next():
            yield self.value()


class PrefetchProducerMixin:
    """Shared plumbing for iterators that produce epochs on a background
    thread into a bounded queue (the ThreadBuffer analogue, reference
    utils/thread_buffer.h). Subclasses implement ``_produce_epoch`` — pushing
    items via ``self._put`` (aborting when it returns False) and finishing
    with ``self._put(self._END)`` — and call:

    - ``_init_producer(queue_size)`` from init()
    - ``_rewind_producer()`` from before_first()
    - ``_next_item()`` from next(): returns the item, or None at epoch end;
      re-raises exceptions forwarded from the producer
    - ``_close_producer()`` from close(): responsive even when the producer
      is blocked on a full queue (timed puts observe the stop event)
    """

    _END = object()

    def _init_producer(self, queue_size: int) -> None:
        self._queue: "queue.Queue" = queue.Queue(maxsize=queue_size)
        self._cmd: "queue.Queue" = queue.Queue()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = threading.Thread(
            target=self._produce_loop, daemon=True)
        self._thread.start()
        # no epoch queued yet: the first before_first() starts production
        # (queuing at init would produce a throwaway epoch)
        self._started = False
        self._epoch_done = True
        self._fresh = False

    def _produce_epoch(self) -> None:
        raise NotImplementedError

    def _put(self, item) -> bool:
        """Blocking queue put that stays responsive to close(); returns False
        when the iterator is being torn down."""
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _produce_loop(self) -> None:
        while not self._stop.is_set():
            cmd = self._cmd.get()
            if cmd == "stop":
                return
            try:
                self._produce_epoch()
            except Exception as e:      # surface errors to the consumer
                self._put(e)

    def _rewind_producer(self) -> None:
        pending_error = None
        if self._started and not self._epoch_done:
            if self._fresh:
                # an epoch is queued but nothing consumed yet: rewinding is a
                # no-op (lets callers rewind defensively — e.g. augment after
                # mean-image creation — without a wasted production pass)
                return
            while True:
                item = self._queue.get()
                if item is self._END:
                    break
                if isinstance(item, Exception):
                    pending_error = item
                    break
        if pending_error is not None:
            self._epoch_done = True
            raise pending_error
        self._cmd.put("epoch")
        self._started = True
        self._epoch_done = False
        self._fresh = True

    def _next_item(self):
        if self._epoch_done:
            return None
        self._fresh = False
        item = self._queue.get()
        if item is self._END:
            self._epoch_done = True
            return None
        if isinstance(item, Exception):
            self._epoch_done = True
            raise item
        return item

    def _close_producer(self) -> None:
        if getattr(self, "_thread", None) is None:
            return
        self._stop.set()
        self._cmd.put("stop")
        self._thread.join(timeout=5)
        self._thread = None


# base iterators produce DataBatch directly (mnist) or DataInst (img family);
# the factory composes processors exactly as data.cpp:23-75 does.
_BASE_FACTORIES: Dict[str, Callable[[], "IIterator"]] = {}
_PROC_FACTORIES: Dict[str, Callable[["IIterator"], "IIterator"]] = {}


def register_base_iterator(name: str):
    def deco(factory):
        _BASE_FACTORIES[name] = factory
        return factory
    return deco


def register_proc_iterator(name: str):
    def deco(factory):
        _PROC_FACTORIES[name] = factory
        return factory
    return deco


def create_iterator(cfg: Pairs) -> IIterator:
    """Build an iterator chain from ordered config pairs (data.cpp:23-75)."""
    it: Optional[IIterator] = None
    for name, val in cfg:
        if name == "iter":
            if val == "end":
                # block terminator (CLI section grammar); later pairs are
                # globals that still apply to the chain (e.g. batch_size)
                continue
            if val in _BASE_FACTORIES:
                if it is not None:
                    raise ValueError("%s cannot chain over another iterator" % val)
                it = _BASE_FACTORIES[val]()
            elif val in _PROC_FACTORIES:
                if it is None:
                    raise ValueError("must specify input of %s" % val)
                it = _PROC_FACTORIES[val](it)
            else:
                raise ValueError("unknown iterator type %r" % val)
            continue
        if it is not None:
            it.set_param(name, val)
    if it is None:
        raise ValueError("must specify iterator by iter=itername")
    it.init()
    return it
