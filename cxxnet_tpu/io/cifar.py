"""CIFAR-10 binary-format iterator.

The reference documents ``iter = cifar`` among its basic iterators
(doc/io.md:4, example/MNIST/README.md:12) although the shipped src tree
dropped the implementation; this provides the documented capability. Reads
the standard CIFAR-10/100 binary batches: each record is ``label_bytes``
label byte(s) followed by a 3x32x32 uint8 image (3072 bytes, CHW, RGB).

Whole-dataset-in-memory with optional shuffle (io/inmem.py base, the
mnist-iterator pattern); batches are views into the preloaded tensor and
the tail partial batch is dropped. Wrap with ``threadbuffer``/``batchadapt``
chains for padding semantics instead.

Config keys (besides the inmem base's shuffle/seed_data/batch_size/
index_offset/data_dtype):
  path_data    comma-separated .bin files (e.g. the five train batches)
  label_bytes  1 (CIFAR-10; default). CIFAR-100's coarse+fine = 2, the
               LAST label byte is used (the fine label)
"""

from __future__ import annotations

import numpy as np

from .data import register_base_iterator
from .inmem import InMemoryIterator
from .mnist import _open_maybe_gz


@register_base_iterator("cifar")
class CIFARIterator(InMemoryIterator):
    REC_IMG = 3 * 32 * 32

    def __init__(self) -> None:
        super().__init__()
        self.label_bytes = 1
        self.path_data = ""

    def set_param(self, name: str, val: str) -> None:
        if name == "label_bytes":
            self.label_bytes = int(val)
            if self.label_bytes < 1:
                raise ValueError("cifar: label_bytes must be >= 1")
        elif name == "path_data":
            self.path_data = val
        else:
            super().set_param(name, val)

    def _read_file(self, path: str) -> np.ndarray:
        with _open_maybe_gz(path) as f:
            raw = np.frombuffer(f.read(), np.uint8)
        rec = self.label_bytes + self.REC_IMG
        if raw.size == 0 or raw.size % rec:
            raise ValueError(
                "%s: size %d is not a multiple of the %d-byte CIFAR record "
                "(label_bytes=%d + 3072)" % (path, raw.size, rec,
                                             self.label_bytes))
        return raw.reshape(-1, rec)

    def init(self) -> None:
        assert self.path_data, "cifar: must set path_data"
        recs = np.concatenate([self._read_file(p.strip())
                               for p in self.path_data.split(",") if p.strip()])
        labels = recs[:, self.label_bytes - 1]         # fine label last
        img = recs[:, self.label_bytes:].reshape(-1, 3, 32, 32)
        self._finalize_load(img.astype(np.float32) * (1.0 / 256.0), labels,
                            "CIFARIterator")
