"""Device & compiler observatory: per-program cost/memory model, live
MFU sampling, the device-memory ledger, and compile-time accounting.

The request tracer (obs/trace.py) answers "where did this request's
wall-clock go"; nothing before this module answered "what is the DEVICE
doing". Every jitted program the repo runs — the trainer's four steps
(``net_update`` / ``net_accum`` / ``net_apply`` / ``net_forward``) and
the serve engine's programs (``serve_prefill`` / ``serve_prefill_chunk``
/ ``serve_verify_chunk`` / ``serve_tick``) — is a fixed executable with
knowable FLOPs, bytes moved, and peak memory, all sitting in XLA's own
``cost_analysis()`` / ``memory_analysis()``. This module extracts them
through the same AOT path the compiled-step audit uses
(``fn.lower(...).compile()`` on the abstract specs of
``analysis/step_audit.py:net_step_specs`` and
``DecodeEngine.lint_specs``, which previously threw the compiled object
away) and turns them into four observables:

* **static cost table** (:class:`CostTable`) — per program: FLOPs, HBM
  bytes accessed, peak / argument / output / temp memory, compile
  seconds, keyed by program name + abstract signature. Published as
  ``cxn_program_flops{fn=}`` / ``cxn_program_bytes_accessed{fn=}`` /
  ``cxn_program_peak_bytes{fn=}`` gauges and rendered as a roofline
  table (``task=prof`` / ``tools/cxn_prof.py``).
* **live per-program timing** (:class:`LiveSampler`) — ONE blocking
  device-time sample every ``prof_every`` executions (the hot path is
  otherwise untouched: a non-sampled call costs one dict increment).
  Each sample lands in the ``cxn_program_seconds{fn=}`` histogram and
  refreshes ``cxn_mfu{fn=}`` and ``cxn_achieved_bw_frac{fn=}`` against
  the hardware peaks of :func:`hw_peaks` — the ONE source of truth
  bench.py's MFU lines now read instead of a hand-pinned constant.
* **device-memory ledger** (:class:`DeviceLedger`) —
  ``cxn_device_bytes{pool=params|opt_state|kv_slots|prefix_cache|
  spec_draft}`` callback gauges reconciling the pools' PREDICTED sizes
  against the measured ``jax.live_arrays()`` total (``pool=live_total``
  / ``pool=unaccounted``): the memory-headroom signal the paged-KV and
  sharded-serving roadmap items need per row / per replica.
* **compile-time accounting** (:class:`CompileWatch`) — a
  ``jax.monitoring`` duration listener summing every
  ``/jax/core/compile/*`` event into ``cxn_compile_seconds{fn=}``
  (attributed to the program being dispatched via a thread-local
  label) plus one ``compile`` span per backend compile on the engine
  trace track — so AOT-executable-cache wins (ROADMAP item 4) are
  measurable before that cache exists.

Availability: ``cost_analysis``/``memory_analysis`` support varies by
backend and jax version. Extraction NEVER raises for that — a program
whose analyses are missing gets ``available=False`` with an
"unavailable on this backend" note, the roofline table prints the note,
and the gauges for that program are simply absent (the guarded path is
pinned on CPU by tests/test_devprof.py).
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..analysis.concurrency import make_lock

__all__ = ["HWPeaks", "hw_peaks", "ProgramCost", "CostTable",
           "profile_net", "profile_engine", "LiveSampler", "DeviceLedger",
           "CompileWatch", "compile_watch", "compile_attribution",
           "tree_nbytes", "register_net_pools", "DEFAULT_PROF_EVERY"]

# default live-sampling cadence (task=serve's `prof_every`): one blocked
# sample per program per 64 executions — under 2% of executions even if
# every sample cost a full extra step, and in practice far less (the
# tick already syncs per call, so its sample adds only bookkeeping)
DEFAULT_PROF_EVERY = 64

HWPeaks = collections.namedtuple("HWPeaks", ["flops", "bytes_per_s",
                                             "source"])

# device_kind substring -> (peak bf16 matmul FLOP/s, HBM bytes/s) for
# one chip. v5e is the bench rig's chip and the historical denominator
# of every recorded MFU (bench.py rounds 4-10), so it is also the
# fallback for unknown kinds — an unknown backend keeps the trajectory
# comparable instead of dividing by a made-up number.
_PEAKS_BY_KIND = (
    ("v5 lite", (197e12, 819e9)),
    ("v5e", (197e12, 819e9)),
    ("v5p", (459e12, 2765e9)),
    ("v6", (918e12, 1640e9)),
    ("v4", (275e12, 1228e9)),
    ("v3", (123e12, 900e9)),
    ("v2", (45e12, 700e9)),
)
_FALLBACK_PEAKS = (197e12, 819e9)


def hw_peaks(flops: float = 0.0, bytes_per_s: float = 0.0) -> HWPeaks:
    """Peak FLOP/s + HBM bytes/s of ONE local device — the denominator
    of every MFU / achieved-bandwidth fraction this module publishes
    (bench.py imports this instead of pinning its own constant).
    Explicit arguments win, then the ``CXN_PEAK_FLOPS`` /
    ``CXN_PEAK_BW`` environment overrides, then the device-kind table;
    an unrecognized kind (CPU included) falls back to the v5e numbers
    with ``source`` saying so — the absolute MFU is then meaningless
    but still monotone in achieved throughput, which is what the
    regression gate compares."""
    env_f = float(os.environ.get("CXN_PEAK_FLOPS", "0") or 0)
    env_b = float(os.environ.get("CXN_PEAK_BW", "0") or 0)
    f = flops or env_f
    b = bytes_per_s or env_b
    if f and b:
        return HWPeaks(f, b, "explicit")
    kind = ""
    try:
        import jax
        kind = jax.devices()[0].device_kind
    except Exception:               # no backend at all: stay importable
        pass
    for sub, (kf, kb) in _PEAKS_BY_KIND:
        if sub in kind.lower():
            return HWPeaks(f or kf, b or kb, "device_kind:%s" % kind)
    df, db = _FALLBACK_PEAKS
    return HWPeaks(f or df, b or db,
                   "assumed:v5e (device_kind %r unrecognized)"
                   % (kind or "none"))


def tree_nbytes(tree) -> int:
    """Total bytes of every array leaf in a pytree; ShapeDtypeStruct
    leaves count their would-be size (so abstract engines predict the
    same ledger numbers their real twins measure)."""
    import jax
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        n = getattr(leaf, "nbytes", None)
        if n is None:
            shape = getattr(leaf, "shape", None)
            dtype = getattr(leaf, "dtype", None)
            if shape is None or dtype is None:
                continue
            n = int(np.prod(shape)) * np.dtype(dtype).itemsize
        total += int(n)
    return total


# --------------------------------------------------------------- cost table
@dataclasses.dataclass
class ProgramCost:
    """One compiled program's static cost/memory row. ``-1.0`` means
    "the backend did not report this field"; ``available`` is False only
    when NEITHER analysis yielded anything (the guarded path)."""
    name: str
    signature: str = ""
    flops: float = -1.0
    bytes_accessed: float = -1.0
    argument_bytes: float = -1.0
    output_bytes: float = -1.0
    temp_bytes: float = -1.0
    alias_bytes: float = -1.0
    generated_code_bytes: float = -1.0
    peak_bytes: float = -1.0
    compile_s: float = 0.0
    measured_s: float = 0.0         # best timed execution (0 = untimed)
    available: bool = True
    # the label covers MANY compiled programs (the legacy whole-prompt
    # prefill: one per prompt length) — this row describes one
    # representative shape, so live samples must not divide its FLOPs
    # by another shape's time (LiveSampler skips the MFU/bw gauges)
    variable_shape: bool = False
    note: str = ""

    def arithmetic_intensity(self) -> float:
        """FLOPs per HBM byte — the roofline x-axis."""
        if self.flops > 0 and self.bytes_accessed > 0:
            return self.flops / self.bytes_accessed
        return 0.0

    def mfu(self, dt: float, peaks: HWPeaks) -> float:
        return self.flops / dt / peaks.flops \
            if self.flops > 0 and dt > 0 else 0.0

    def bw_frac(self, dt: float, peaks: HWPeaks) -> float:
        return self.bytes_accessed / dt / peaks.bytes_per_s \
            if self.bytes_accessed > 0 and dt > 0 else 0.0


def _cost_from_compiled(name: str, compiled,
                        signature: str = "") -> ProgramCost:
    """Guarded extraction of cost_analysis()/memory_analysis() from an
    XLA compiled executable. Never raises: a backend without either
    analysis yields an ``available=False`` row whose note names what
    was missing (the "unavailable on this backend" contract)."""
    pc = ProgramCost(name=name, signature=signature)
    notes = []
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):       # one dict per device
            ca = ca[0] if ca else {}
        ca = ca or {}
        pc.flops = float(ca.get("flops", -1.0))
        pc.bytes_accessed = float(ca.get("bytes accessed", -1.0))
        if not ca:
            notes.append("cost_analysis empty")
    except Exception as e:                      # noqa: BLE001
        notes.append("cost_analysis unavailable on this backend (%s)"
                     % (type(e).__name__,))
    try:
        ma = compiled.memory_analysis()
        pc.argument_bytes = float(ma.argument_size_in_bytes)
        pc.output_bytes = float(ma.output_size_in_bytes)
        pc.temp_bytes = float(ma.temp_size_in_bytes)
        pc.alias_bytes = float(ma.alias_size_in_bytes)
        pc.generated_code_bytes = float(ma.generated_code_size_in_bytes)
        # peak device footprint while the program runs: everything it
        # must hold at once, minus the donated (aliased) overlap. This
        # is the number the KV-slot / replica headroom math subtracts
        # from HBM capacity.
        pc.peak_bytes = max(0.0, pc.argument_bytes + pc.output_bytes
                            + pc.temp_bytes - pc.alias_bytes)
    except Exception as e:                      # noqa: BLE001
        notes.append("memory_analysis unavailable on this backend (%s)"
                     % (type(e).__name__,))
    pc.available = pc.flops >= 0 or pc.peak_bytes >= 0
    pc.note = "; ".join(notes)
    return pc


def _fmt_qty(v: float, unit: str = "") -> str:
    """Engineering-notation cell for the roofline table (1.23G, 45.6M)."""
    if v < 0:
        return "-"
    for scale, suffix in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "k")):
        if v >= scale:
            return "%.2f%s%s" % (v / scale, suffix, unit)
    return "%.0f%s" % (v, unit)


class CostTable:
    """Ordered {program name -> :class:`ProgramCost`} plus the hardware
    peaks it is read against. The single renderer for the roofline
    table — ``task=prof``, ``tools/cxn_prof.py`` and tests all print
    through :meth:`format_roofline`, so the surfaces cannot drift."""

    def __init__(self, peaks: Optional[HWPeaks] = None):
        self.peaks = peaks or hw_peaks()
        self.programs: Dict[str, ProgramCost] = {}

    def add(self, pc: ProgramCost) -> ProgramCost:
        self.programs[pc.name] = pc
        return pc

    def get(self, name: str) -> Optional[ProgramCost]:
        return self.programs.get(name)

    def names(self) -> List[str]:
        return list(self.programs)

    def __len__(self) -> int:
        return len(self.programs)

    def merge(self, other: "CostTable") -> "CostTable":
        for pc in other.programs.values():
            self.add(pc)
        return self

    def publish(self, registry) -> None:
        """Static per-program gauges into an obs registry (the catalog
        rows of doc/observability.md). Unavailable fields publish
        nothing — an absent series is honest, a 0 or -1 is not."""
        flops = registry.gauge("cxn_program_flops",
                               "XLA cost-model FLOPs per execution",
                               labelnames=("fn",))
        byts = registry.gauge("cxn_program_bytes_accessed",
                              "XLA cost-model HBM bytes per execution",
                              labelnames=("fn",))
        peak = registry.gauge("cxn_program_peak_bytes",
                              "peak device bytes while the program runs "
                              "(arg + output + temp - aliased)",
                              labelnames=("fn",))
        comp = registry.gauge("cxn_program_compile_seconds",
                              "AOT lower+compile seconds of the cost-"
                              "table extraction", labelnames=("fn",))
        for pc in self.programs.values():
            if pc.flops >= 0:
                flops.labels(pc.name).set(pc.flops)
            if pc.bytes_accessed >= 0:
                byts.labels(pc.name).set(pc.bytes_accessed)
            if pc.peak_bytes >= 0:
                peak.labels(pc.name).set(pc.peak_bytes)
            comp.labels(pc.name).set(pc.compile_s)

    def rows(self) -> List[Dict]:
        out = []
        for pc in self.programs.values():
            out.append({
                "fn": pc.name, "flops": pc.flops,
                "bytes": pc.bytes_accessed,
                "intensity": pc.arithmetic_intensity(),
                "peak_bytes": pc.peak_bytes,
                "compile_s": pc.compile_s,
                "measured_ms": pc.measured_s * 1e3,
                "mfu": pc.mfu(pc.measured_s, self.peaks),
                "bw_frac": pc.bw_frac(pc.measured_s, self.peaks),
                "available": pc.available, "note": pc.note,
            })
        return out

    def format_roofline(self) -> str:
        """The per-program roofline table: FLOPs, bytes, arithmetic
        intensity, peak memory, compile time, measured time, MFU and
        achieved-bandwidth fraction (the last three only for timed
        rows)."""
        lines = ["peaks: %s FLOP/s, %s/s HBM (%s)"
                 % (_fmt_qty(self.peaks.flops),
                    _fmt_qty(self.peaks.bytes_per_s, "B"),
                    self.peaks.source),
                 "%-20s %10s %10s %8s %10s %9s %11s %7s %7s"
                 % ("program", "flops", "bytes", "flop/B", "peak_mem",
                    "compile", "measured", "mfu", "bw")]
        for r in self.rows():
            pc = self.programs[r["fn"]]
            if not pc.available:
                lines.append("%-20s %s" % (r["fn"], pc.note
                                           or "unavailable"))
                continue
            def pct(v):
                # CPU runs against TPU peaks sit far below 0.01%; an
                # adaptive format keeps them readable instead of 0.00%
                return "%.2f%%" % (100 * v) if v >= 1e-4 \
                    else "%.1e" % v
            ms = "%.3fms" % r["measured_ms"] if r["measured_ms"] > 0 \
                else "-"
            mfu = pct(r["mfu"]) if r["measured_ms"] > 0 \
                and r["flops"] > 0 else "-"
            bw = pct(r["bw_frac"]) \
                if r["measured_ms"] > 0 and r["bytes"] > 0 else "-"
            lines.append(
                "%-20s %10s %10s %8.1f %10s %8.2fs %11s %7s %7s"
                % (r["fn"], _fmt_qty(r["flops"]),
                   _fmt_qty(r["bytes"], "B"), r["intensity"],
                   _fmt_qty(r["peak_bytes"], "B"), r["compile_s"], ms,
                   mfu, bw))
            if pc.note:
                lines.append("%-20s   (%s)" % ("", pc.note))
        return "\n".join(lines)


# process-wide extraction cache: AOT lower+compile of the SAME program
# at the SAME abstract signature yields the same cost row, and a server
# restarting (or a test building many servers over one config) must not
# pay XLA again for a number that cannot have changed. Program identity
# is the jit OBJECT itself (held by weakref, id-checked): two different
# programs can share a label and arg shapes — a remat=1 net's update
# step, a different-n_head engine's tick with identical fused weight
# shapes — so (label, signature) alone would alias their rows. The
# engine's module-level lru_cached program constructors return one
# stable object per config, which is exactly the restart case the
# cache exists for; a rebuilt Net gets fresh jit objects and honestly
# re-extracts.
# key -> (weakref(fn), row)
_COST_CACHE: Dict[tuple, tuple] = {}        # guarded_by: _COST_CACHE_LOCK
_COST_CACHE_LOCK = make_lock("devprof._COST_CACHE_LOCK")


def _signature_of(args) -> tuple:
    from ..analysis.recompile import abstract_signature
    return abstract_signature(tuple(args))


def extract_program(fn, args, label: str,
                    use_cache: bool = True) -> Tuple[ProgramCost, object]:
    """AOT lower+compile ``fn`` at ``args`` and extract its cost row.
    Returns ``(cost, compiled)``; ``compiled`` is None on a cache hit
    (the executable is only rebuilt when a caller needs to RUN it —
    pass ``use_cache=False`` to force one). Compile time is recorded in
    the row and attributed to the ``devprof`` label in the compile
    accounting (it is observatory overhead, not the run's own compile
    cost)."""
    import weakref
    sig = _signature_of(args)
    key = (label, id(fn), sig)
    try:
        ref = weakref.ref(fn)
    except TypeError:               # unweakrefable wrapper: no caching
        ref = None
    if use_cache and ref is not None:
        with _COST_CACHE_LOCK:
            hit = _COST_CACHE.get(key)
        # valid only while the SAME fn object is alive — a dead object
        # whose id was recycled must not serve another program's row
        if hit is not None and hit[0]() is fn:
            return dataclasses.replace(hit[1]), None
    t0 = time.perf_counter()
    with compile_attribution("devprof"):
        lowered = fn.lower(*args)
        compiled = lowered.compile()
    compile_s = time.perf_counter() - t0
    pc = _cost_from_compiled(label, compiled, signature=str(hash(sig)))
    pc.compile_s = compile_s
    if ref is not None:
        with _COST_CACHE_LOCK:
            # prune rows whose program died (their ids may be recycled)
            for k in [k for k, (r, _) in _COST_CACHE.items()
                      if r() is None]:
                del _COST_CACHE[k]
            _COST_CACHE[key] = (ref, dataclasses.replace(pc))
    return pc, compiled


def _materialize(args, static_argnums=()):
    """Concrete zero-filled twins of abstract/real args (static argnums
    dropped — an AOT executable is called without them). Real arrays
    are replaced by fresh zeros too: a donating executable DELETES its
    donated input buffers on every backend, so the caller's live params
    or KV pool must never be handed to a timing run."""
    import jax
    import jax.numpy as jnp

    def leaf(x):
        if x is None:
            return None
        shape = getattr(x, "shape", None)
        dtype = getattr(x, "dtype", None)
        if shape is None or dtype is None:
            return x
        z = jnp.zeros(tuple(shape), dtype)
        # match the executable's expected input shardings exactly: an
        # AOT compiled call rejects arrays on the wrong placement (the
        # specs carry real mesh shardings — step_audit.net_step_specs)
        sh = getattr(x, "sharding", None)
        if sh is not None:
            z = jax.device_put(z, sh)
        return z

    return [jax.tree_util.tree_map(leaf, a)
            for i, a in enumerate(args) if i not in static_argnums]


def _time_compiled(compiled, margs, reps: int,
                   feedback: Optional[Dict[int, int]] = None) -> float:
    """Best-of-``reps`` wall seconds for one execution of an AOT
    compiled program (one warm-up first). ``feedback`` maps output
    index -> argument index for donated buffers — the executable
    deletes those inputs, so each rep feeds the matching outputs back
    (run_steps' idiom, generalized)."""
    import jax

    def run():
        out = compiled(*margs)
        jax.block_until_ready(out)
        if feedback:
            for oi, ai in feedback.items():
                margs[ai] = out[oi]
        return out

    run()                                   # warm-up / lazy init
    best = float("inf")
    for _ in range(max(1, reps)):
        t0 = time.perf_counter()
        run()
        best = min(best, time.perf_counter() - t0)
    return best


# output index -> donated argument index of the trainer steps (from
# Net._compile_steps' donate_argnums and the step return layouts) —
# what lets the timing loop re-feed donated buffers
_NET_FEEDBACK = {
    "net_update": {0: 0, 1: 1, 2: 2, 3: 3},
    "net_accum": {0: 0, 2: 3},
    "net_apply": {0: 0, 1: 1, 2: 2},
    "net_forward": None,
}


def profile_net(net, registry=None, time_reps: int = 0) -> CostTable:
    """Cost table for the trainer's four jitted steps, from the same
    real-mesh-sharded abstract specs the compiled-step audit uses.
    ``time_reps > 0`` also RUNS each AOT executable on zero-filled
    inputs (best-of-reps, donated buffers fed back) and fills
    ``measured_s`` -> the roofline MFU columns. Publishes into
    ``registry`` when given, and hands the table to the net's live
    sampler (if armed) so ``cxn_mfu{fn=net_*}`` gauges have FLOPs."""
    from ..analysis.step_audit import net_step_specs
    table = CostTable()
    for label, fn, args, _donate, static in net_step_specs(net):
        pc, compiled = extract_program(fn, args, label,
                                       use_cache=time_reps == 0)
        if time_reps > 0:
            if compiled is None:
                _, compiled = extract_program(fn, args, label,
                                              use_cache=False)
            margs = _materialize(args, static_argnums=static)
            pc.measured_s = _time_compiled(compiled, margs, time_reps,
                                           _NET_FEEDBACK.get(label))
        table.add(pc)
    if registry is not None:
        table.publish(registry)
    net._cost_table = table
    sampler = getattr(net, "_prof_sampler", None)
    if sampler is not None and sampler.table is None:
        sampler.table = table
    return table


def profile_engine(engine, registry=None, time_reps: int = 0,
                   n_prompt: int = 8) -> CostTable:
    """Cost table for the serve engine's compiled programs
    (``DecodeEngine.lint_specs`` rows: legacy prefill, the chunk-prefill
    step, the speculative verify step when armed, the shared tick) —
    the engine's OWN variants, donation included, so ``peak_bytes`` is
    the production program's footprint (a non-donated twin would count
    the whole slot pool twice, overstating peak by the aliased K/V).
    ``time_reps > 0`` times the executables on zero-filled inputs
    (never the engine's live caches — a donating executable deletes
    its donated inputs), feeding each rep's output caches back like
    the trainer timing does. The legacy ``serve_prefill`` row is
    marked ``variable_shape``: it is one representative prompt length
    of a per-length program family, so live samples keep its timing
    histogram but skip the MFU/bandwidth gauges."""
    table = CostTable()
    for label, fn, args, nums in engine.lint_specs(n_prompt=n_prompt):
        pc, compiled = extract_program(fn, args, label,
                                       use_cache=time_reps == 0)
        if label == "serve_prefill":
            pc.variable_shape = True
            pc.note = (pc.note + "; " if pc.note else "") + \
                "one compiled program per prompt length — row is " \
                "n_prompt=%d" % n_prompt
        if time_reps > 0:
            if compiled is None:
                _, compiled = extract_program(fn, args, label,
                                              use_cache=False)
            margs = _materialize(args)
            # every engine program returns (cache_k, cache_v, ...) and
            # donates those cache args when donation is armed
            feedback = dict(enumerate(nums)) if nums else None
            pc.measured_s = _time_compiled(compiled, margs, time_reps,
                                           feedback)
        table.add(pc)
    if registry is not None:
        table.publish(registry)
        if getattr(engine, "paged", False):
            # pool geometry next to the program rows: a cost/MFU drift
            # caused by a changed block-table width (kv_mb resize, a
            # different block_size) is attributable from the scrape
            # alone instead of needing the server config
            bprg = registry.gauge(
                "cxn_program_block_table_width",
                "paged block-table width (blocks per row) compiled "
                "into the serve programs", labelnames=("fn",))
            for name in table.names():
                if name.startswith("serve_"):
                    bprg.labels(name).set(engine.bpr)
    return table


# ------------------------------------------------------------ live sampling
class LiveSampler:
    """Cadence-gated device timing for running programs. The owner
    (DecodeEngine / Net.update) brackets each program call with
    ``t0 = sampler.begin(name)`` / ``sampler.end(name, t0)``: ``begin``
    returns a start time only every ``cadence``-th execution (else
    None — one dict increment, the whole hot-path cost), and the owner
    blocks on the program's outputs before ``end`` so the sample spans
    real device time. Each sample feeds the
    ``cxn_program_seconds{fn=}`` histogram, bumps
    ``cxn_prof_samples_total{fn=}``, and — when the cost table knows
    the program's FLOPs/bytes — refreshes ``cxn_mfu{fn=}`` and
    ``cxn_achieved_bw_frac{fn=}`` against :func:`hw_peaks`.

    Single-threaded by design, like the scheduler that drives it; the
    registry children it updates are themselves thread-safe."""

    def __init__(self, registry, cadence: int = DEFAULT_PROF_EVERY,
                 table: Optional[CostTable] = None,
                 peaks: Optional[HWPeaks] = None, tracer=None):
        from .metrics import TIME_BUCKETS
        self.cadence = max(0, int(cadence))
        self.table = table
        self.peaks = peaks or (table.peaks if table else hw_peaks())
        self._tracer = tracer
        self._counts: Dict[str, int] = {}
        self.samples: Dict[str, int] = {}
        self.dropped: Dict[str, int] = {}   # compile-contaminated
        self._sec = registry.histogram(
            "cxn_program_seconds",
            "sampled wall seconds per program execution (one blocking "
            "sample per prof_every executions)", labelnames=("fn",),
            buckets=TIME_BUCKETS)
        self._n = registry.counter(
            "cxn_prof_samples_total",
            "blocking device-time samples taken", labelnames=("fn",))
        self._ndrop = registry.counter(
            "cxn_prof_samples_dropped_total",
            "samples discarded because a compile ran inside the timed "
            "window (first call at a new shape)", labelnames=("fn",))
        self._mfu = registry.gauge(
            "cxn_mfu", "achieved model FLOPs utilization of the last "
            "sampled execution", labelnames=("fn",))
        self._bw = registry.gauge(
            "cxn_achieved_bw_frac", "achieved HBM bandwidth fraction of "
            "the last sampled execution", labelnames=("fn",))

    def executions(self, name: str) -> int:
        return self._counts.get(name, 0)

    def begin(self, name: str) -> Optional[tuple]:
        """Opaque timing token every ``cadence``-th execution, else
        None. The token carries the process compile-seconds total at
        start: a sampled call that happens to be a program's FIRST
        call at a new shape (the legacy prefill's per-length family, a
        trainer recompile boundary) would otherwise record jaxpr-trace
        + XLA-compile time as an execution sample — a ~1000x outlier
        in the histogram — so ``end`` drops any sample whose window
        saw a compile."""
        n = self._counts.get(name, 0) + 1
        self._counts[name] = n
        if self.cadence and n % self.cadence == 0:
            return (time.perf_counter(), _watch.total_seconds())
        return None

    def end(self, name: str, token: tuple) -> float:
        t0, c0 = token
        dt = time.perf_counter() - t0
        if _watch.total_seconds() > c0:
            self.dropped[name] = self.dropped.get(name, 0) + 1
            self._ndrop.labels(name).inc()
            return dt
        self.record(name, dt)
        return dt

    def record(self, name: str, dt: float) -> None:
        self.samples[name] = self.samples.get(name, 0) + 1
        self._sec.labels(name).observe(dt)
        self._n.labels(name).inc()
        pc = self.table.get(name) if self.table is not None else None
        if pc is not None and pc.available and dt > 0 \
                and not pc.variable_shape:
            if pc.flops > 0:
                self._mfu.labels(name).set(pc.mfu(dt, self.peaks))
            if pc.bytes_accessed > 0:
                self._bw.labels(name).set(pc.bw_frac(dt, self.peaks))
        if self._tracer is not None:
            from .trace import TID_ENGINE
            self._tracer.add("prof_sample", time.perf_counter() - dt, dt,
                             TID_ENGINE, cat="prof", args={"fn": name})


# ------------------------------------------------------------------- ledger
class DeviceLedger:
    """Predicted-vs-measured device memory: named pools register a
    callback returning their PREDICTED bytes (the slot pool's formula,
    the prefix trie's accounting, the param tree's leaf sum), published
    as ``cxn_device_bytes{pool=}`` callback gauges with zero hot-path
    cost; ``pool="live_total"`` is the measured ``jax.live_arrays()``
    sum and ``pool="unaccounted"`` the difference — growth there is the
    leak/fragmentation signal no single pool's formula would show."""

    def __init__(self, registry):
        self._pools: Dict[str, Callable[[], float]] = {}
        self._host: set = set()
        self._fam = registry.gauge(
            "cxn_device_bytes",
            "device-memory ledger: predicted bytes per pool, plus the "
            "measured live_total and the unaccounted remainder",
            labelnames=("pool",))
        self._fam.labels("live_total", fn=self.live_total_bytes)
        self._fam.labels("unaccounted",
                         fn=lambda: self.live_total_bytes()
                         - self.accounted_bytes())

    def register(self, pool: str, fn: Callable[[], float],
                 device: bool = True) -> None:
        """``device=False`` marks a HOST-memory pool (e.g. the serve
        engine's ``swap_host`` buffer of preempted rows): it is
        published as a ``cxn_device_bytes{pool=}`` gauge for visibility
        but EXCLUDED from ``accounted`` — ``jax.live_arrays()`` can
        never see it, so counting it would drive ``unaccounted``
        negative and bury the leak signal."""
        self._pools[pool] = fn
        if not device:
            self._host.add(pool)
        self._fam.labels(pool, fn=lambda: float(fn()))

    def pool_bytes(self, pool: str) -> float:
        fn = self._pools.get(pool)
        try:
            return float(fn()) if fn is not None else 0.0
        except Exception:           # a dead provider reads as empty
            return 0.0

    def accounted_bytes(self) -> float:
        return sum(self.pool_bytes(p) for p in self._pools
                   if p not in self._host)

    @staticmethod
    def live_total_bytes() -> float:
        import jax
        total = 0
        for a in jax.live_arrays():
            try:
                total += a.nbytes
            except Exception:       # deleted between list and read
                pass
        return float(total)

    def reconcile(self) -> Dict:
        """One consistent snapshot: per-pool predicted bytes, their sum,
        the measured live total, and the unaccounted remainder
        (``live_total - accounted``; other subsystems' arrays — e.g. a
        second net's params — land there, so it is a floor-zero signal
        only within one owner's process)."""
        pools = {p: self.pool_bytes(p) for p in self._pools}
        accounted = sum(v for p, v in pools.items()
                        if p not in self._host)
        live = self.live_total_bytes()
        return {"pools": pools, "accounted": accounted,
                "live_total": live, "unaccounted": live - accounted}


def register_net_pools(net, registry=None) -> DeviceLedger:
    """The trainer's ledger pools (params / opt_state) in the
    process-global registry. Re-registering (a rebuilt or second Net)
    rebinds the callbacks to the NEWEST net — the registry's
    latest-provider-wins restart semantics. The closures hold the net
    by WEAKREF: a process-lifetime registry must not pin a dropped
    net's params + optimizer state (gigabytes of HBM at flagship
    scale) — a dead net's pools honestly read 0."""
    import weakref
    from .metrics import default_registry
    ledger = DeviceLedger(registry if registry is not None
                          else default_registry())
    ref = weakref.ref(net)

    def pool(attr):
        def read():
            n = ref()
            return tree_nbytes(getattr(n, attr)) if n is not None else 0.0
        return read

    ledger.register("params", pool("params"))
    ledger.register("opt_state", pool("opt_state"))
    return ledger


# ------------------------------------------------- compile-time accounting
class CompileWatch:
    """Process-global compile-time accounting over ``jax.monitoring``
    duration events: every ``/jax/core/compile/*`` duration (jaxpr
    trace + MLIR lowering + backend compile) is summed under the label
    of the program currently being dispatched on that thread
    (:func:`compile_attribution`; ``"unattributed"`` otherwise) and
    fanned out to every attached sink — ``cxn_compile_seconds{fn=}``
    counters per registry, plus one ``compile`` span per backend
    compile on each sink tracer's engine track. The listener installs
    once per process and costs nothing between compiles."""

    def __init__(self):
        self._lock = make_lock("CompileWatch._lock")
        self._installed = False             # guarded_by: self._lock
        self._tls = threading.local()
        # (registry, tracer or None)
        self._sinks: List[tuple] = []       # guarded_by: self._lock
        # label -> seconds (all events)
        self.totals: Dict[str, float] = {}  # guarded_by: self._lock

    # ------------------------------------------------------------ plumbing
    def _install(self) -> None:
        with self._lock:
            if self._installed:
                return
            try:
                from jax import monitoring
                monitoring.register_event_duration_secs_listener(
                    self._on_event)
                self._installed = True
            except Exception:       # jax without monitoring: stay inert
                pass

    def current_label(self) -> str:
        stack = getattr(self._tls, "stack", None)
        return stack[-1] if stack else "unattributed"

    def total_seconds(self) -> float:
        """All compile seconds observed so far, any label — the
        LiveSampler's compile-in-window detector (a changed total
        across a timed region means the region paid a compile)."""
        with self._lock:
            return sum(self.totals.values())

    @contextlib.contextmanager
    def attribute(self, label: str):
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        stack.append(label)
        try:
            yield
        finally:
            stack.pop()

    def add_sink(self, registry, tracer=None) -> None:
        """Attach a registry (and optional tracer) to receive compile
        events; the counter family is pre-created so the series exists
        (empty) before the first compile. Idempotent per registry."""
        self._install()
        registry.counter("cxn_compile_seconds",
                         "seconds spent tracing/lowering/XLA-compiling, "
                         "by the program label being dispatched",
                         labelnames=("fn",))
        with self._lock:
            if not any(r is registry for r, _ in self._sinks):
                self._sinks.append((registry, tracer))

    def remove_sink(self, registry) -> None:
        with self._lock:
            self._sinks = [(r, t) for r, t in self._sinks
                           if r is not registry]

    # -------------------------------------------------------------- events
    def _on_event(self, name: str, duration: float, **kw) -> None:
        if "/jax/core/compile/" not in name:
            return
        label = self.current_label()
        with self._lock:
            self.totals[label] = self.totals.get(label, 0.0) + duration
            sinks = list(self._sinks)
        backend = name.endswith("backend_compile_duration")
        for registry, tracer in sinks:
            try:
                registry.counter("cxn_compile_seconds",
                                 labelnames=("fn",)).labels(label)\
                    .inc(duration)
                if tracer is not None and backend:
                    from .trace import TID_ENGINE
                    tracer.add("compile",
                               time.perf_counter() - duration, duration,
                               TID_ENGINE, cat="compile",
                               args={"fn": label})
            except Exception:       # a dead sink must not break compiles
                pass


_watch = CompileWatch()


def compile_watch() -> CompileWatch:
    """The process-global :class:`CompileWatch` (Net and the serve
    engine attribute through it; servers/CLI attach their registries as
    sinks)."""
    return _watch


def compile_attribution(label: str):
    """``compile_watch().attribute(label)`` shorthand — wrap a jitted
    call so any compile it triggers lands under ``label`` in
    ``cxn_compile_seconds{fn=}``."""
    return _watch.attribute(label)
