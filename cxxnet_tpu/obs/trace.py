"""Request-scoped span tracer: a bounded ring of host-side spans,
exportable as Chrome-trace JSON (Perfetto / chrome://tracing).

The XPlane trace (utils/profiler.py:trace) answers "what did the DEVICE
do" for one bounded capture window; it is far too heavy to leave on
under live traffic, and it knows nothing about requests. This tracer is
the complement: always-on, host-side, request-scoped. Every span is a
``(name, cat, ts, dur, tid, args)`` record appended to a lock-guarded
ring buffer (``collections.deque(maxlen=capacity)`` — old spans fall off
the back, memory is bounded no matter how long the server lives).

Track model (the ``tid`` axis in the exported trace):

* ``TID_TRAIN`` — the training round loop: one ``train_round`` span per
  round with aggregate ``feed_wait`` / ``step_dispatch`` /
  ``metric_sync`` child spans (cli.py records them from StepStats
  totals, so they are per-round AGGREGATES laid end to end, not exact
  intervals).
* ``TID_ENGINE`` — work shared across requests: one ``decode_tick``
  span per batched tick (args: how many rows decoded — NOT one span per
  row, the no-per-token-allocation rule), one ``spec_draft`` span per
  drafter pass, and the ``recovery`` span tree (teardown -> rebuild ->
  replay) an engine restart leaves behind (serve/resilience.py).
* ``TID_CONTROL`` — supervisory events: degradation-ladder rung
  transitions, load-shed batches, per-request replay markers — the
  track an operator reads to see WHY the engine track looks the way it
  does.
* ``REQ_TID_BASE + rid`` — one track per request carrying its span
  tree: ``request`` (submit -> terminal) over ``queue_wait`` ->
  ``prefix_restore`` -> ``prefill_chunk``* -> ``decode`` (covers the
  ticks; args: tokens) with ``spec_verify`` spans inside it ->
  ``retire``. Perfetto nests them by time containment.

Cost discipline: recording is a ``perf_counter`` pair, one tuple, one
lock-guarded deque append — no formatting, no wall-clock syscall, no
allocation proportional to tokens. ``sample = N`` records only every
Nth request's track (engine/train tracks are unaffected); ``enabled =
False`` turns every record call into one attribute check.

Slow-request exemplars: ``note_slow(rid, ...)`` captures the request's
span tree as its own Chrome-trace dict into a small bounded exemplar
deque, optionally auto-writing ``slow-req-<rid>.trace.json`` into a
configured directory — the server calls it for any request whose TTFT
or total latency exceeds ``obs_slow_ms`` (serve/server.py), so the
evidence for a latency spike is saved at the moment it happens instead
of asking the operator to reproduce it.
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..analysis.concurrency import make_lock

__all__ = ["Span", "Tracer", "get_tracer", "configure", "request_tid",
           "spans_to_chrome", "TID_ENGINE", "TID_TRAIN", "TID_CONTROL",
           "REQ_TID_BASE"]

TID_ENGINE = 1
TID_TRAIN = 2
TID_CONTROL = 3
REQ_TID_BASE = 100


class Span(collections.namedtuple("Span",
                                  ["name", "cat", "ts", "dur", "tid",
                                   "args"])):
    """One recorded span: ``ts``/``dur`` in seconds on the tracer's
    monotonic clock (perf_counter; ``ts`` is absolute perf_counter time,
    export rebases onto the tracer epoch). ``dur`` 0.0 renders as an
    instant. ``args`` is a small dict or None."""
    __slots__ = ()


def request_tid(rid: int) -> int:
    return REQ_TID_BASE + int(rid)


def _thread_meta(tids) -> List[Dict]:
    names = {TID_ENGINE: "engine", TID_TRAIN: "train",
             TID_CONTROL: "control"}
    out = []
    for tid in sorted(tids):
        name = names.get(tid, "request %d" % (tid - REQ_TID_BASE)
                         if tid >= REQ_TID_BASE else "track %d" % tid)
        out.append({"ph": "M", "name": "thread_name", "pid": 0,
                    "tid": tid, "args": {"name": name}})
    return out


def spans_to_chrome(spans: List[Dict],
                    other_data: Optional[Dict] = None) -> Dict:
    """Span dicts (``{name, cat, ts, dur, tid, args}``, ts/dur in
    SECONDS — the ``dump_jsonl`` line schema) as a Chrome-trace JSON
    object: complete ("X") events in microseconds plus thread-name
    metadata. The ONE place the event schema is built — both
    ``Tracer.chrome_trace`` and ``tools/cxn_trace.py`` render through
    here, so the two writers cannot drift. Zero spans still yields a
    valid, loadable trace."""
    events = _thread_meta({s["tid"] for s in spans})
    for s in spans:
        ev = {"name": s["name"], "cat": s.get("cat") or "obs", "ph": "X",
              "ts": round(s["ts"] * 1e6, 3),
              "dur": round(s["dur"] * 1e6, 3), "pid": 0, "tid": s["tid"]}
        if s.get("args"):
            ev["args"] = s["args"]
        events.append(ev)
    doc = {"traceEvents": events, "displayTimeUnit": "ms",
           "otherData": {"format": "cxxnet_tpu.obs.trace/1"}}
    if other_data:
        doc["otherData"].update(other_data)
    return doc


class Tracer:
    """Bounded ring of spans; see module docstring."""

    def __init__(self, capacity: int = 65536, enabled: bool = True,
                 sample: int = 1, slow_dir: str = ""):
        self._lock = make_lock("Tracer._lock")
        # guarded_by: self._lock
        self._ring: collections.deque = collections.deque(
            maxlen=max(1, int(capacity)))
        self.enabled = bool(enabled)
        self.sample = max(1, int(sample))
        self.slow_dir = slow_dir
        # export epoch: monotonic origin + the wall time it corresponds
        # to, so exported ts values start near 0 and the trace metadata
        # can still date the capture
        self._epoch = time.perf_counter()
        self._epoch_wall = time.time()
        self.exemplars: collections.deque = collections.deque(maxlen=8)
        # spans pushed out of the ring (approx.; read lockless at export)
        self.dropped = 0        # guarded_by: self._lock
        # slow-dump throttle: under saturation EVERY request can cross
        # obs_slow_ms, and note_slow runs on the scheduler thread — an
        # unthrottled makedirs+json.dump per retire would amplify the
        # very overload it is diagnosing (and write files without
        # bound). The in-memory exemplar deque still records every slow
        # request (bounded by maxlen); only the FILE dump is limited.
        self.slow_write_interval_s = 1.0
        self._last_slow_write = float("-inf")

    # ---------------------------------------------------------- recording
    def configure(self, enabled: Optional[bool] = None,
                  capacity: Optional[int] = None,
                  sample: Optional[int] = None,
                  slow_dir: Optional[str] = None) -> "Tracer":
        """Adjust knobs in place; resizing the ring keeps the newest
        spans. Returns self (so ``get_tracer().configure(...)``
        chains)."""
        with self._lock:
            if enabled is not None:
                self.enabled = bool(enabled)
            if sample is not None:
                self.sample = max(1, int(sample))
            if slow_dir is not None:
                self.slow_dir = slow_dir
            if capacity is not None and \
                    int(capacity) != self._ring.maxlen:
                self._ring = collections.deque(
                    self._ring, maxlen=max(1, int(capacity)))
        return self

    @property
    def capacity(self) -> int:
        return self._ring.maxlen

    def should_sample(self, rid: int) -> bool:
        """Whether request ``rid``'s track is recorded (the scheduler
        checks ONCE at submit/admit and carries the answer on the
        request, not per tick)."""
        return self.enabled and (int(rid) % self.sample == 0)

    def add(self, name: str, ts: float, dur: float, tid: int,
            cat: str = "", args: Optional[Dict] = None) -> None:
        """Record one externally timed span (``ts`` = perf_counter
        start, ``dur`` seconds)."""
        if not self.enabled:
            return
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1
            self._ring.append(Span(name, cat, ts, dur, tid, args))

    def instant(self, name: str, tid: int, cat: str = "",
                args: Optional[Dict] = None) -> None:
        self.add(name, time.perf_counter(), 0.0, tid, cat, args)

    @contextlib.contextmanager
    def span(self, name: str, tid: int, cat: str = "",
             args: Optional[Dict] = None):
        """Measure the enclosed region (no-op-cheap when disabled)."""
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, t0, time.perf_counter() - t0, tid, cat, args)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self.dropped = 0

    # ------------------------------------------------------------ reading
    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def spans(self, tid: Optional[int] = None) -> List[Span]:
        """Snapshot of the ring (oldest first), optionally one track."""
        with self._lock:
            snap = list(self._ring)
        if tid is None:
            return snap
        return [s for s in snap if s.tid == tid]

    def spans_for_request(self, rid: int) -> List[Span]:
        return self.spans(request_tid(rid))

    # ------------------------------------------------------------- export
    def chrome_trace(self, spans: Optional[List[Span]] = None) -> Dict:
        """The ring (or ``spans``) as a Chrome-trace JSON object
        (``spans_to_chrome`` with ts rebased onto the tracer epoch, plus
        the capture's wall-clock epoch in ``otherData``)."""
        if spans is None:
            spans = self.spans()
        return spans_to_chrome(
            [{"name": s.name, "cat": s.cat, "ts": s.ts - self._epoch,
              "dur": s.dur, "tid": s.tid, "args": s.args}
             for s in spans],
            {"epoch_unix_s": self._epoch_wall,
             "dropped_spans": self.dropped})

    def write_chrome(self, path: str,
                     spans: Optional[List[Span]] = None) -> str:
        with open(path, "w") as f:
            json.dump(self.chrome_trace(spans), f)
        return path

    def dump_jsonl(self, path: str) -> int:
        """Raw span dump, one JSON object per line (the input format of
        ``tools/cxn_trace.py export``/``summary``); returns the span
        count written. Line schema: {name, cat, ts, dur, tid, args} with
        ts rebased to the tracer epoch (seconds)."""
        spans = self.spans()
        with open(path, "w") as f:
            for s in spans:
                f.write(json.dumps({
                    "name": s.name, "cat": s.cat or "obs",
                    "ts": s.ts - self._epoch, "dur": s.dur,
                    "tid": s.tid, "args": s.args or {}}) + "\n")
        return len(spans)

    # ---------------------------------------------------- slow exemplars
    def note_slow(self, rid: int, reason: str = "",
                  args: Optional[Dict] = None) -> Optional[Dict]:
        """Capture request ``rid``'s span tree (whatever of it is still
        in the ring) as its own Chrome-trace dict: kept in
        ``self.exemplars`` and auto-written to
        ``<slow_dir>/slow-req-<rid>.trace.json`` when a dump directory
        is configured. Returns the dict (None when tracing is off or
        the request left no spans — e.g. sampled out)."""
        spans = self.spans_for_request(rid)
        if not spans:
            return None
        doc = self.chrome_trace(spans)
        doc["otherData"]["slow_reason"] = reason
        if args:
            doc["otherData"].update(args)
        self.exemplars.append((int(rid), reason, doc))
        path = ""
        now = time.perf_counter()
        if self.slow_dir and \
                now - self._last_slow_write >= self.slow_write_interval_s:
            self._last_slow_write = now
            try:
                os.makedirs(self.slow_dir, exist_ok=True)
                path = os.path.join(self.slow_dir,
                                    "slow-req-%d.trace.json" % rid)
                with open(path, "w") as f:
                    json.dump(doc, f)
            except OSError:
                path = ""           # dump dir gone: keep the exemplar
        from ..utils import profiler
        profiler.log("obs: slow request %d (%s)%s"
                     % (rid, reason,
                        " -> %s" % path if path else ""), level="warn")
        return doc


_tracer = Tracer()


def get_tracer() -> Tracer:
    """The process-global tracer — what the CLI, the wrapper's
    ``Net.trace_export()``, and (by default) every InferenceServer
    record into. Tests wanting isolation construct their own Tracer and
    pass it explicitly."""
    return _tracer


def configure(**kw) -> Tracer:
    """``get_tracer().configure(...)`` shorthand (cli.py's obs_* keys
    land here)."""
    return _tracer.configure(**kw)
