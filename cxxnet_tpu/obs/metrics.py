"""Unified metrics registry: Counter / Gauge / Histogram behind one
thread-safe surface with Prometheus text exposition.

Before this subsystem every serving counter lived in a private dict —
``InferenceServer._counts``, the prefix cache's ``hits``/``misses``,
``SlotScheduler.spec_*``, the RecompileGuard's signature map — visible
only through one-shot ``metrics()`` snapshots a scraper cannot consume.
The registry absorbs them behind three metric kinds:

* **Counter** — monotonically increasing float (``_total`` names).
* **Gauge** — set-to-current value, or a *callback* gauge evaluated at
  collection time (occupancy, queue depth, cache bytes — values that are
  a property of live objects, not an accumulation).
* **Histogram** — observation counts in FIXED log-spaced buckets plus
  sum/count. The boundaries are process-independent constants, so two
  engine replicas' histograms merge by adding bucket counts and the
  merged percentiles stay exact to bucket resolution — the property the
  ROADMAP item-2 router needs to aggregate TTFT across replicas (a
  sample-reservoir p95 cannot be merged; a fixed-bucket one can).

Label support is the minimal Prometheus subset: a metric family created
with ``labelnames`` yields children via ``labels(value, ...)``; children
are created on first touch and live for the registry's lifetime.

Thread-safety: one lock per registry guards family creation; each child
takes its own lock only for the few arithmetic ops of an update. Metric
updates never allocate on the hot path (bucket index is a bisect into a
static tuple).

Exposition: :meth:`Registry.to_prometheus` renders the standard text
format (``# HELP`` / ``# TYPE``, ``_bucket{le=...}`` / ``_sum`` /
``_count`` for histograms); :meth:`Registry.snapshot` returns the same
data as one plain dict for the JSONL flusher (obs/export.py).
"""

from __future__ import annotations

import bisect
import math
import threading
from typing import Callable, Dict, List, Optional, Tuple

from ..analysis.concurrency import make_lock

__all__ = ["Counter", "Gauge", "Histogram", "Registry", "TIME_BUCKETS",
           "BYTES_BUCKETS", "default_registry", "merged_prometheus",
           "registry_state", "registry_from_state"]


def _log_spaced(lo: float, hi: float, per_decade: int) -> Tuple[float, ...]:
    """Fixed log-spaced bucket upper bounds from ``lo`` to >= ``hi`` with
    ``per_decade`` buckets per decade. Pure function of its arguments —
    every process computes the identical tuple, which is what makes
    histograms mergeable across replicas."""
    n = int(math.ceil(per_decade * math.log10(hi / lo))) + 1
    # deterministic 6-sig-fig rounding: the ``le`` labels stay readable
    # and every process still computes bit-identical bounds
    return tuple(float("%.6g" % (lo * 10.0 ** (i / per_decade)))
                 for i in range(n))


# the shared latency geometry: 10 us .. ~158 s, 4 buckets per decade
# (each bound ~1.78x the previous — percentile resolution well under the
# run-to-run noise of any latency this registry observes). One constant
# for every duration histogram in the process, so ANY two histograms
# with these buckets merge.
TIME_BUCKETS = _log_spaced(1e-5, 100.0, 4)

# the shared size geometry: 256 B .. ~1 TiB, 2 buckets per decade. A
# memory/size histogram observed into TIME_BUCKETS lands entirely in
# the +Inf bucket (its top bound is ~158); this is the same mergeable
# fixed-boundary construction at byte scale. ``Registry.histogram``
# takes ``buckets=`` for geometries neither constant fits.
BYTES_BUCKETS = _log_spaced(256.0, 1e12, 2)


def _fmt(v: float) -> str:
    """Prometheus float formatting: integers without the trailing .0;
    non-finite values render as the exposition format's NaN/+Inf/-Inf
    tokens (a dead callback provider yields NaN — it must render, not
    crash the scrape)."""
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _labels_text(names: Tuple[str, ...], values: Tuple[str, ...],
                 extra: str = "") -> str:
    parts = ['%s="%s"' % (n, v) for n, v in zip(names, values)]
    if extra:
        parts.append(extra)
    return "{%s}" % ",".join(parts) if parts else ""


class Counter:
    """Monotonic counter. ``inc`` with a negative amount is an error —
    a counter that can go down is a gauge wearing the wrong name. A
    *callback* counter (``fn``) reads a live monotonic int at
    collection time instead of being incremented — how the registry
    absorbs counters that already exist as plain attributes on hot
    objects (``SlotScheduler.ticks``, the prefix cache's ``hits``)
    with ZERO added cost on their increment paths."""

    kind = "counter"

    def __init__(self, fn: Optional[Callable[[], float]] = None):
        self._lock = make_lock("Counter._lock")
        self._value = 0.0               # guarded_by: self._lock
        self._fn = fn

    def inc(self, amount: float = 1.0) -> None:
        if self._fn is not None:
            raise RuntimeError("callback counter cannot be inc()ed")
        if amount < 0:
            raise ValueError("Counter.inc amount must be >= 0, got %r"
                             % (amount,))
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:       # a dead provider must not kill scrape
                return float("nan")
        with self._lock:
            return self._value


class Gauge:
    """Set-to-current value, or a callback evaluated at collection time
    (``fn``) for values that are properties of live objects."""

    kind = "gauge"

    def __init__(self, fn: Optional[Callable[[], float]] = None):
        self._lock = make_lock("Gauge._lock")
        self._value = 0.0               # guarded_by: self._lock
        self._fn = fn

    def set(self, value: float) -> None:
        if self._fn is not None:
            raise RuntimeError("callback gauge cannot be set")
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        if self._fn is not None:
            raise RuntimeError("callback gauge cannot be inc()ed")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:       # a dead provider must not kill scrape
                return float("nan")
        with self._lock:
            return self._value


class Histogram:
    """Fixed-bucket histogram: cumulative-style exposition, mergeable
    percentile estimates (see module docstring). ``buckets`` are the
    upper bounds of the non-overflow buckets; observations above the
    last bound land in +Inf."""

    kind = "histogram"

    def __init__(self, buckets: Tuple[float, ...] = TIME_BUCKETS):
        if list(buckets) != sorted(buckets) or len(set(buckets)) != \
                len(buckets):
            raise ValueError("histogram buckets must be strictly "
                             "increasing")
        self.buckets = tuple(float(b) for b in buckets)
        self._lock = make_lock("Histogram._lock")
        # bucket counts, [+Inf] bucket last
        self._counts = [0] * (len(self.buckets) + 1)  # guarded_by: self._lock
        self._sum = 0.0                 # guarded_by: self._lock
        self._count = 0                 # guarded_by: self._lock

    def reset(self) -> None:
        """Zero the observations (bench warm-up isolation — an owner
        resetting its window counters must reset the histogram too, or
        the exposition goes internally inconsistent: histogram count >
        the zeroed request counters)."""
        with self._lock:
            self._counts = [0] * (len(self.buckets) + 1)
            self._sum = 0.0
            self._count = 0

    def observe(self, value: float) -> None:
        if not math.isfinite(value):
            return              # the empty-window contract: poison dropped
        i = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._count += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def counts(self) -> List[int]:
        """Per-bucket (non-cumulative) counts, +Inf last."""
        with self._lock:
            return list(self._counts)

    def _snapshot(self) -> Tuple[List[int], float, int]:
        """(counts, sum, count) read under ONE lock acquisition — a
        concurrent observe() between separate reads would hand merge()
        a state where sum(counts) != count, permanently corrupting the
        destination's percentiles."""
        with self._lock:
            return list(self._counts), self._sum, self._count

    def merge(self, other: "Histogram") -> None:
        """Fold another histogram (same buckets) into this one — the
        cross-replica aggregation primitive; safe against concurrent
        observes on ``other`` (its state is read atomically)."""
        if other.buckets != self.buckets:
            raise ValueError("cannot merge histograms with different "
                             "bucket boundaries")
        oc, osum, ocount = other._snapshot()
        with self._lock:
            for i, c in enumerate(oc):
                self._counts[i] += c
            self._sum += osum
            self._count += ocount

    def percentile(self, q: float) -> float:
        """Bucket-resolution percentile estimate: the upper bound of the
        bucket where the cumulative count crosses ``q`` (0 with no
        observations). Mergeable by construction — merging replicas then
        asking for p95 equals asking each replica and combining counts."""
        with self._lock:
            total = self._count
            counts = list(self._counts)
        if total == 0:
            return 0.0
        rank = q * total
        cum = 0
        for i, c in enumerate(counts):
            cum += c
            if cum >= rank and c:
                if i < len(self.buckets):
                    return self.buckets[i]
                return self.buckets[-1]     # +Inf bucket: clamp to last
        return self.buckets[-1]


class _Family:
    """One registered metric name: unlabeled (a single child) or a
    labeled family (children created per label-value tuple)."""

    def __init__(self, name: str, help_: str, kind: str,
                 labelnames: Tuple[str, ...], make: Callable):
        self.name = name
        self.help = help_
        self.kind = kind
        self.labelnames = labelnames
        self._make = make               # guarded_by: self._lock
        self._buckets: Optional[Tuple[float, ...]] = None
        self._lock = make_lock("_Family._lock")
        # guarded_by: self._lock
        self._children: Dict[Tuple[str, ...], object] = {}
        if not labelnames:
            self._children[()] = make()

    def labels(self, *values, fn: Optional[Callable[[], float]] = None
               ) -> object:
        """Get-or-create the child for one label tuple. ``fn`` binds a
        PER-CHILD callback provider (how the device-memory ledger gives
        each ``cxn_device_bytes{pool=...}`` child its own live reader —
        the family-level ``fn`` of ``counter``/``gauge`` applies one
        provider to every child, which only fits unlabeled families);
        re-passing ``fn`` rebinds the child, latest provider wins."""
        if len(values) != len(self.labelnames):
            raise ValueError("metric %s wants labels %s, got %r"
                             % (self.name, self.labelnames, values))
        if fn is not None and self.kind == "histogram":
            raise ValueError("metric %s: histograms cannot be "
                             "callback-backed" % self.name)
        key = tuple(str(v) for v in values)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._make()
            if fn is not None:
                child._fn = fn
            return child

    @property
    def default(self):
        if self.labelnames:
            raise ValueError("metric %s is labeled (%s); use .labels()"
                             % (self.name, self.labelnames))
        return self._children[()]

    def children(self) -> List[Tuple[Tuple[str, ...], object]]:
        with self._lock:
            return sorted(self._children.items())

    def rebind(self, fn: Callable[[], float], make: Callable) -> None:
        """Point a callback family at a new live provider. Registering
        an existing name WITH a new ``fn`` means the LATEST provider
        wins — a restarted server re-registering its catalog into a
        shared registry must not leave the exported names bound to its
        dead predecessor's objects."""
        with self._lock:
            self._make = make
            for child in self._children.values():
                child._fn = fn


def _render_histogram(out: List[str], name: str, labelnames, values,
                      child: "Histogram") -> None:
    """Append one histogram series (cumulative ``_bucket`` lines +
    ``_sum``/``_count``) in the text exposition format — the ONE
    renderer behind ``Registry.to_prometheus`` and both series kinds of
    :func:`merged_prometheus` (a formatting fix applied here cannot
    desynchronize the per-replica and aggregate renderings the merge
    property compares)."""
    lt = _labels_text(labelnames, values)
    counts = child.counts()
    cum = 0
    for bound, c in zip(child.buckets, counts):
        cum += c
        out.append('%s_bucket%s %d' % (
            name, _labels_text(labelnames, values,
                               'le="%s"' % _fmt(bound)), cum))
    cum += counts[-1]
    out.append('%s_bucket%s %d' % (
        name, _labels_text(labelnames, values, 'le="+Inf"'), cum))
    out.append("%s_sum%s %s" % (name, lt, _fmt(child.sum)))
    out.append("%s_count%s %d" % (name, lt, child.count))


class Registry:
    """Get-or-create metric registry. Creating the same name twice with
    the same kind returns the SAME family (so two subsystems can share a
    counter without coordination); a kind mismatch is an error."""

    def __init__(self):
        self._lock = make_lock("Registry._lock")
        # guarded_by: self._lock
        self._families: Dict[str, _Family] = {}

    # ---------------------------------------------------------- creation
    def _register(self, name: str, help_: str, kind: str,
                  labelnames, make, fn=None, buckets=None) -> _Family:
        labelnames = tuple(labelnames or ())
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind or fam.labelnames != labelnames:
                    raise ValueError(
                        "metric %r already registered as %s%s"
                        % (name, fam.kind, fam.labelnames))
                if buckets is not None and fam._buckets != tuple(buckets):
                    # silently keeping the old geometry would break the
                    # mergeability contract the caller asked for
                    raise ValueError(
                        "histogram %r already registered with different "
                        "buckets" % name)
                if fn is not None:
                    fam.rebind(fn, make)
                return fam
            fam = _Family(name, help_, kind, labelnames, make)
            if buckets is not None:
                fam._buckets = tuple(buckets)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help_: str = "", labelnames=(),
                fn: Optional[Callable[[], float]] = None):
        fam = self._register(name, help_, "counter", labelnames,
                             lambda: Counter(fn), fn=fn)
        return fam if fam.labelnames else fam.default

    def gauge(self, name: str, help_: str = "", labelnames=(),
              fn: Optional[Callable[[], float]] = None):
        fam = self._register(name, help_, "gauge", labelnames,
                             lambda: Gauge(fn), fn=fn)
        return fam if fam.labelnames else fam.default

    def histogram(self, name: str, help_: str = "", labelnames=(),
                  buckets: Tuple[float, ...] = TIME_BUCKETS):
        fam = self._register(name, help_, "histogram", labelnames,
                             lambda: Histogram(buckets), buckets=buckets)
        return fam if fam.labelnames else fam.default

    def freeze(self, names) -> None:
        """Convert callback metrics to their CURRENT values: each child
        reads its provider one last time and becomes a plain stored
        value. An owner shutting down calls this so (a) the registry
        stops pinning it — callback closures hold the whole server,
        params and KV pool included — and (b) later scrapes report the
        honest terminal state (final totals, drained gauges) instead of
        evaluating a dead object. A later re-register with a new ``fn``
        rebinds the family live (the shared-registry restart path)."""
        with self._lock:
            fams = [self._families[n] for n in names
                    if n in self._families]
        for fam in fams:
            make = Counter if fam.kind == "counter" else Gauge
            with fam._lock:
                fam._make = make
                for child in fam._children.values():
                    if child._fn is not None:
                        v = child.value
                        child._fn = None
                        child._value = float(v)

    def get(self, name: str) -> Optional[_Family]:
        with self._lock:
            return self._families.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._families)

    # -------------------------------------------------------- exposition
    def to_prometheus(self) -> str:
        """Standard Prometheus text exposition of every registered
        metric (text format version 0.0.4)."""
        out: List[str] = []
        with self._lock:
            fams = [self._families[n] for n in sorted(self._families)]
        for fam in fams:
            if fam.help:
                out.append("# HELP %s %s" % (fam.name, fam.help))
            out.append("# TYPE %s %s" % (fam.name, fam.kind))
            for values, child in fam.children():
                if fam.kind in ("counter", "gauge"):
                    out.append("%s%s %s" % (
                        fam.name, _labels_text(fam.labelnames, values),
                        _fmt(child.value)))
                    continue
                _render_histogram(out, fam.name, fam.labelnames, values,
                                  child)
        return "\n".join(out) + ("\n" if out else "")

    def snapshot(self) -> Dict:
        """The same collection as one plain dict (for the JSONL
        flusher): counters/gauges -> value (non-finite -> None — a
        dead callback provider must not poison the JSONL stream with
        bare NaN tokens strict parsers reject), histograms -> {count,
        sum, p50, p95, p99}. Labeled children key as name{a=x,b=y}."""
        out: Dict[str, object] = {}
        with self._lock:
            fams = [self._families[n] for n in sorted(self._families)]
        for fam in fams:
            for values, child in fam.children():
                key = fam.name + _labels_text(fam.labelnames, values)
                if fam.kind in ("counter", "gauge"):
                    v = child.value
                    out[key] = v if math.isfinite(v) else None
                else:
                    out[key] = {"count": child.count, "sum": child.sum,
                                "p50": child.percentile(0.50),
                                "p95": child.percentile(0.95),
                                "p99": child.percentile(0.99)}
        return out


def registry_state(reg: Registry) -> Dict:
    """One registry as a plain picklable dict — the fleet's scrape
    transport (serve/fleet.py): a worker process serializes its live
    registry here, ships it over RPC, and the router rebuilds a real
    Registry with :func:`registry_from_state` so ONE
    :func:`merged_prometheus` call aggregates the whole fleet exactly
    like it aggregates in-process replicas. Callback-backed children
    are evaluated NOW (the provider lives in the worker; only its
    current value can travel)."""
    fams = []
    with reg._lock:
        objs = [reg._families[n] for n in sorted(reg._families)]
    for fam in objs:
        children = []
        for values, child in fam.children():
            if fam.kind == "histogram":
                counts, s, c = child._snapshot()
                children.append((values, {"counts": counts, "sum": s,
                                          "count": c}))
            else:
                children.append((values, float(child.value)))
        fams.append({"name": fam.name, "help": fam.help,
                     "kind": fam.kind, "labelnames": fam.labelnames,
                     "buckets": (child.buckets
                                 if fam.kind == "histogram" else None),
                     "children": children})
    return {"families": fams}


def registry_from_state(state: Dict) -> Registry:
    """Rebuild a Registry from :func:`registry_state` output. The
    result is a plain value snapshot (no callbacks) with the same
    names, kinds, labels, and bucket geometry — exactly what
    :func:`merged_prometheus` needs from each fleet worker."""
    reg = Registry()
    for f in state.get("families", []):
        kind, lnames = f["kind"], tuple(f["labelnames"])
        if kind == "histogram":
            fam = reg._register(f["name"], f["help"], kind, lnames,
                                lambda b=tuple(f["buckets"]):
                                Histogram(b),
                                buckets=tuple(f["buckets"]))
        else:
            make = Counter if kind == "counter" else Gauge
            fam = reg._register(f["name"], f["help"], kind, lnames,
                                make)
        for values, v in f["children"]:
            child = fam.labels(*values) if lnames else fam.default
            if kind == "histogram":
                child._counts = list(v["counts"])
                child._sum = float(v["sum"])
                child._count = int(v["count"])
            else:
                # direct assignment, not inc()/set(): a dead worker
                # callback can have produced NaN, and a counter's
                # guard rails should not reject an honest snapshot
                child._value = float(v)
    return reg


def merged_prometheus(registries: Dict[str, Registry],
                      label: str = "replica") -> str:
    """Cross-replica Prometheus exposition — the serve router's one
    scrape payload (serve/router.py). ``registries`` maps a label value
    (the replica index) to that replica's registry; the output keeps
    every EXISTING metric name and label set, adds ``label=\"<value>\"``
    to each per-replica series, and — for histograms — additionally
    emits an AGGREGATE series (no replica label) built with
    :meth:`Histogram.merge`, so the merged percentiles equal a single
    histogram that observed the union of every replica's observations
    (the fixed-bucket mergeability contract the module docstring
    promises; pinned end-to-end in tests/test_obs.py). Counters and
    gauges stay per-replica only: their cross-replica sum is one PromQL
    ``sum by`` away, while a histogram's is not — merging buckets is
    exactly what this function exists to do.

    A name registered with different kinds/labels/buckets across
    replicas is skipped with an exposition comment instead of rendering
    a self-contradictory family (replicas are built from one config, so
    this only fires on operator error)."""
    out: List[str] = []
    keys = sorted(registries)
    names: List[str] = []
    for k in keys:
        for n in registries[k].names():
            if n not in names:
                names.append(n)
    names.sort()
    for name in names:
        fams = [(k, registries[k].get(name)) for k in keys
                if registries[k].get(name) is not None]
        first = fams[0][1]
        if any(f.kind != first.kind or f.labelnames != first.labelnames
               or f._buckets != first._buckets for _, f in fams):
            out.append("# %s skipped: kind/label/bucket mismatch "
                       "across replicas" % name)
            continue
        if first.help:
            out.append("# HELP %s %s" % (name, first.help))
        out.append("# TYPE %s %s" % (name, first.kind))
        lnames = first.labelnames + (label,)
        agg: Dict[Tuple[str, ...], Histogram] = {}
        for k, fam in fams:
            for values, child in fam.children():
                lvals = values + (k,)
                if fam.kind in ("counter", "gauge"):
                    out.append("%s%s %s" % (
                        name, _labels_text(lnames, lvals),
                        _fmt(child.value)))
                    continue
                _render_histogram(out, name, lnames, lvals, child)
                a = agg.get(values)
                if a is None:
                    a = agg[values] = Histogram(child.buckets)
                a.merge(child)
        # the aggregate histogram series: same name, NO replica label —
        # the union-of-observations payload
        for values in sorted(agg):
            _render_histogram(out, name, first.labelnames, values,
                              agg[values])
    return "\n".join(out) + ("\n" if out else "")


_default = Registry()
_default_lock = make_lock("metrics._default_lock")


def default_registry() -> Registry:
    """The process-global registry — where the training side, the
    recompile guards of ``nnet.Net``, and anything without its own
    registry record. Servers default to their own registry so two
    servers' gauges cannot fight (see serve/server.py)."""
    return _default
