"""Telemetry export: Prometheus text endpoint material + periodic JSONL
snapshots + end-of-task trace dumps.

Two consumption models, both fed by the same registry/tracer:

* **Pull** — a scraper asks for the current state:
  :meth:`Registry.to_prometheus` (obs/metrics.py) is the payload;
  ``wrapper.Net.metrics_text()`` / ``InferenceServer.metrics_text()``
  hand it to whatever HTTP front end the deployment runs.
* **Push** — :class:`MetricsFlusher`: a background thread appending one
  JSON line (wall timestamp + full registry snapshot) to a file every
  ``interval_s`` seconds. Lines interleave coherently with
  ``profiler.log``'s timestamped human lines because both carry wall
  timestamps. The thread is named ``cxn-obs-flusher-*`` so the
  suite-wide leak fixture (tests/conftest.py) can see one that outlives
  its owner; ``close()`` flushes one final snapshot and joins.

``export_run`` is the end-of-task convenience the CLI uses: given the
``obs_export`` path prefix it writes ``<prefix>.trace.json`` (Chrome
trace of the whole ring), ``<prefix>.spans.jsonl`` (the raw span dump
``tools/cxn_trace.py`` consumes) and ``<prefix>.prom`` (final
Prometheus exposition), returning the paths written.
"""

from __future__ import annotations

import itertools
import json
import threading
import time
from typing import Dict, List, Optional

from .metrics import Registry
from .trace import Tracer

__all__ = ["MetricsFlusher", "export_run"]

_flusher_seq = itertools.count()


class MetricsFlusher:
    """Periodic registry-snapshot-to-JSONL writer (see module doc)."""

    def __init__(self, registry: Registry, path: str,
                 interval_s: float = 10.0, extra=None):
        """``extra``: optional zero-arg callable merged into every
        snapshot line (the CLI passes the task name); an exception in
        it (or an unserializable value) is the caller's bug — it stops
        the flusher with a loud ``profiler.warn`` naming the error, but
        is never re-raised from ``close()`` (which runs in finally
        blocks and must not mask the task's own exception)."""
        if interval_s <= 0:
            raise ValueError("obs_export_interval_s must be > 0, got %g"
                             % interval_s)
        self.registry = registry
        self.path = path
        self.interval_s = float(interval_s)
        self._extra = extra
        self._stop = threading.Event()
        self.flushes = 0
        # fail fast: an unwritable path must error HERE on the caller's
        # thread, not one interval later on the background one
        with open(self.path, "a"):
            pass
        self._thread = threading.Thread(
            target=self._loop,
            name="cxn-obs-flusher-%d" % next(_flusher_seq), daemon=True)
        self._thread.start()

    def _write_snapshot(self) -> None:
        line: Dict = {"ts": time.time(),
                      "metrics": self.registry.snapshot()}
        if self._extra is not None:
            line.update(self._extra() or {})
        with open(self.path, "a") as f:
            f.write(json.dumps(line) + "\n")
        self.flushes += 1

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self._write_snapshot()
            except Exception as e:          # noqa: BLE001
                # disk/dir gone mid-run, a raising extra() callable, an
                # unserializable snapshot value: stop LOUDLY instead of
                # dying with a bare thread traceback and silently
                # ending snapshots
                from ..utils import profiler
                profiler.warn("obs: metrics flusher stopping, cannot "
                              "write %s (%s: %s)"
                              % (self.path, type(e).__name__, e))
                return

    def close(self, final_flush: bool = True) -> None:
        """Stop the thread (idempotent); ``final_flush`` appends one
        last snapshot so the file always ends with the terminal state
        even when the run was shorter than one interval. An error on
        that last snapshot is logged, not raised — close() runs in
        finally blocks and must not mask the original exception."""
        if self._stop.is_set():
            return
        self._stop.set()
        self._thread.join(timeout=10)
        if final_flush:
            try:
                self._write_snapshot()
            except Exception as e:          # noqa: BLE001
                from ..utils import profiler
                profiler.warn("obs: final metrics flush to %s failed "
                              "(%s: %s)" % (self.path,
                                            type(e).__name__, e))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def export_run(prefix: str, registry: Optional[Registry] = None,
               tracer: Optional[Tracer] = None) -> List[str]:
    """End-of-task dump under ``prefix`` (see module doc); skips the
    pieces whose source is None. Returns the written paths."""
    out: List[str] = []
    if tracer is not None:
        out.append(tracer.write_chrome(prefix + ".trace.json"))
        tracer.dump_jsonl(prefix + ".spans.jsonl")
        out.append(prefix + ".spans.jsonl")
    if registry is not None:
        with open(prefix + ".prom", "w") as f:
            f.write(registry.to_prometheus())
        out.append(prefix + ".prom")
    return out
