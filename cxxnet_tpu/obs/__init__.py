"""Observability subsystem (doc/observability.md): request-scoped span
tracing with Chrome-trace export (obs/trace.py), the unified
Counter/Gauge/Histogram metrics registry with Prometheus text
exposition (obs/metrics.py), the export plumbing — periodic JSONL
snapshots plus end-of-task dumps (obs/export.py) — and the device &
compiler observatory (obs/devprof.py: per-program XLA cost/memory
model, live MFU/bandwidth sampling, the device-memory ledger, and
compile-time accounting; imported as a submodule —
``from cxxnet_tpu.obs import devprof`` — so the base package stays
light).

Surfaces: CLI ``obs_trace`` / ``obs_trace_buffer`` / ``obs_slow_ms`` /
``obs_export`` / ``obs_export_interval_s`` / ``prof_every`` /
``prof_reps`` keys (doc/config.md), ``task=prof``,
``wrapper.Net.trace_export()`` / ``metrics_text()`` / ``profile()``,
``tools/cxn_trace.py export|summary`` for offline trace files, and
``tools/cxn_prof.py`` for the roofline report + bench regression gate.
"""

from .metrics import (BYTES_BUCKETS, Counter, Gauge, Histogram, Registry,
                      TIME_BUCKETS, default_registry)
from .trace import (REQ_TID_BASE, TID_ENGINE, TID_TRAIN, Span, Tracer,
                    configure, get_tracer, request_tid)
from .export import MetricsFlusher, export_run

__all__ = ["Counter", "Gauge", "Histogram", "Registry", "TIME_BUCKETS",
           "BYTES_BUCKETS", "default_registry", "Span", "Tracer",
           "configure", "get_tracer", "request_tid", "TID_ENGINE",
           "TID_TRAIN", "REQ_TID_BASE", "MetricsFlusher", "export_run"]
