"""Observability subsystem (doc/observability.md): request-scoped span
tracing with Chrome-trace export (obs/trace.py), the unified
Counter/Gauge/Histogram metrics registry with Prometheus text
exposition (obs/metrics.py), and the export plumbing — periodic JSONL
snapshots plus end-of-task dumps (obs/export.py).

Surfaces: CLI ``obs_trace`` / ``obs_trace_buffer`` / ``obs_slow_ms`` /
``obs_export`` / ``obs_export_interval_s`` keys (doc/config.md),
``wrapper.Net.trace_export()`` / ``wrapper.Net.metrics_text()``, and
``tools/cxn_trace.py export|summary`` for offline trace files.
"""

from .metrics import (Counter, Gauge, Histogram, Registry, TIME_BUCKETS,
                      default_registry)
from .trace import (REQ_TID_BASE, TID_ENGINE, TID_TRAIN, Span, Tracer,
                    configure, get_tracer, request_tid)
from .export import MetricsFlusher, export_run

__all__ = ["Counter", "Gauge", "Histogram", "Registry", "TIME_BUCKETS",
           "default_registry", "Span", "Tracer", "configure",
           "get_tracer", "request_tid", "TID_ENGINE", "TID_TRAIN",
           "REQ_TID_BASE", "MetricsFlusher", "export_run"]
