"""Serving resilience: fault injection, deterministic replay, watchdog,
and the graceful-degradation ladder.

The serving stack (engine -> scheduler -> server) is a long-lived
process multiplexing many requests over one set of device buffers — a
single unhandled exception or hang inside a tick, swap, or drafter pass
used to kill the engine and every in-flight request with it. This
module is the host-side half of the fix; the wiring lives in
serve/server.py (supervisor, watchdog, recovery), serve/scheduler.py
(journal bookkeeping, fault containment for optional work), and
serve/engine.py (injection points, swap checksums).

**Why recovery is cheap here**: served tokens are pinned bit-identical
to solo ``gpt_decode`` via the deterministic per-request
``fold_in(key, token_index)`` schedule, so every request is fully
re-executable from ``(prompt, SamplingParams, emitted-token count)``
alone — no KV snapshotting, no logit checkpoints. The
:class:`ReplayJournal` records exactly that, and recovery = tear the
pool down, rebuild the engine cold, and push the journaled requests
back through the normal admit path. Already-emitted tokens are verified
bit-identical as they are regenerated (``replay_expect`` on the
request; greedy is exact, sampled resumes on the pinned key schedule so
the distribution is unchanged — the same key indices produce the same
draws).

**Fault injection** (:class:`FaultInjector`): named chaos points at
every hazard the stack already has, armed by ``serve_chaos=<spec>`` /
the ``CXN_CHAOS`` env var with a deterministic per-point seed. Spec
grammar (comma-separated, ``:`` separates key and value)::

    point:prob      arm `point` at probability `prob` per call
    point@N         fire exactly on the Nth call to `point` (one-shot)
    all:prob        arm every point at `prob`
    seed:N          deterministic RNG seed (default 0)
    hang_ms:N       how long an injected hang stalls (default 2000)

Points: ``reserve`` (BlockPoolExhausted mid-reserve), ``swap_out`` /
``swap_in`` (host round-trip I/O failure / buffer corruption),
``drafter`` (drafter exception), ``prefix_restore`` (restore failure),
``tick_raise`` (tick raising), ``tick_hang`` (tick stalling),
``admit`` (a fault inside the admission/quota path — fails that ONE
submit typed, the server and every other request are untouched). An
empty spec yields no injector at all — ``serve_chaos`` off is a true
no-op (the hot path pays one ``is not None`` check).

**Degradation ladder** (:class:`DegradationLadder`): overload is met
with targeted load-shedding instead of collapse, driven by the gauges
the server already keeps (queue depth, block headroom, reserve stalls,
optionally p95 tick) with hysteresis so the rungs do not flap:

    rung 1  disable speculative decode (optional work, costs verifies)
    rung 2  stop prefix-cache admission (no new trie inserts/donations)
    rung 3  deadline-aware shedding of queued requests, and rejections
            carry a ``retry_after_ms`` hint
    rung 4  EMERGENCY (tenancy-armed servers only): even guaranteed-
            class requests become sheddable

With ``serve_tenants`` armed the ladder is tenant-aware: shedding
walks classes in inverse priority (all best-effort requests are
considered before any standard one; guaranteed only at rung 4), and
climbing past rung 3 requires pressure from the PROTECTED classes
alone — a best-effort flood can never push paying tenants onto the
emergency rung (``class_queue_frac`` in :meth:`~DegradationLadder
.evaluate`; :meth:`~DegradationLadder.shed_classes` exposes which
classes the current rung touches). Untenanted servers keep the
original 3-rung ladder bit-identically (``max_rung`` stays 3).

The server surfaces the state as SERVING / DEGRADED / DRAINING /
FAILED in ``health()``, the ``cxn_serve_state`` gauge, and the obs
trace's ``control`` track (doc/serving.md "Resilience").
"""

from __future__ import annotations

import time
import weakref
import zlib
from typing import Dict, List, Optional

from ..analysis.concurrency import make_condition

__all__ = ["FaultInjector", "ReplayJournal", "DegradationLadder",
           "InjectedFault", "SwapCorruptionError", "EngineFailedError",
           "SupersededError", "reset_for_replay", "live_journals",
           "STATE_SERVING", "STATE_DEGRADED", "STATE_DRAINING",
           "STATE_FAILED", "STATE_CODES"]

STATE_SERVING = "SERVING"
STATE_DEGRADED = "DEGRADED"
STATE_DRAINING = "DRAINING"
STATE_FAILED = "FAILED"
# numeric encoding for the cxn_serve_state gauge (doc/observability.md)
STATE_CODES = {STATE_SERVING: 0, STATE_DEGRADED: 1, STATE_DRAINING: 2,
               STATE_FAILED: 3}


class InjectedFault(RuntimeError):
    """A fault raised by the chaos harness (never by real code paths) —
    distinguishable in logs from an organic bug, handled identically by
    the recovery machinery (that is the point of injecting it)."""


class SwapCorruptionError(RuntimeError):
    """A swapped-out row's host buffer failed its checksum at swap-in.
    The row's K/V is untrusted and must NOT be resumed; the scheduler
    routes the request to a journal replay instead (serve/scheduler.py
    ``resume_swapped``)."""


class EngineFailedError(RuntimeError):
    """The engine faulted more than ``serve_max_restarts`` times; the
    server is permanently failed. In-flight requests finish with status
    ``error`` carrying this message, and further submits raise it."""


class SupersededError(RuntimeError):
    """Raised inside a scheduler that a recovery has marked dead: a
    previously-hung loop thread woke up after the watchdog already
    rebuilt the stack, and must unwind without mutating shared request
    state (its engine, slots, and caches were all discarded)."""


# ------------------------------------------------------------------ chaos
class FaultInjector:
    """Deterministic chaos harness; see the module docstring for the
    spec grammar. Single-threaded discipline like the rest of serve
    host state (only the scheduler thread calls :meth:`fire`);
    :meth:`release_hangs` is the one cross-thread entry point and is
    condition-guarded."""

    POINTS = ("reserve", "swap_out", "swap_in", "drafter",
              "prefix_restore", "tick_raise", "tick_hang", "admit")

    def __init__(self, seed: int = 0, hang_ms: float = 2000.0):
        self.spec = ""
        self.seed = int(seed)
        self.hang_ms = float(hang_ms)
        self.armed = True           # tests disarm around warmup passes
        self._prob: Dict[str, float] = {}
        self._at: Dict[str, int] = {}
        self._calls = {p: 0 for p in self.POINTS}
        self.counts = {p: 0 for p in self.POINTS}
        self._rngs: Dict[str, object] = {}
        # injected hangs wait on this condition so a recovery (or
        # shutdown) can interrupt them instead of sleeping out the
        # full hang_ms on an abandoned thread
        self._cv = make_condition("FaultInjector._cv")
        self._release_gen = 0   # guarded_by: self._cv

    @classmethod
    def from_spec(cls, spec: str) -> Optional["FaultInjector"]:
        """Parse a ``serve_chaos`` / ``CXN_CHAOS`` spec; empty -> None
        (chaos fully off costs nothing — no object, no checks beyond
        ``is not None``)."""
        spec = (spec or "").strip()
        if not spec:
            return None
        inj = cls()
        inj.spec = spec
        for item in spec.split(","):
            item = item.strip()
            if not item:
                continue
            if "@" in item:
                point, _, n = item.partition("@")
                point = point.strip()
                if point not in cls.POINTS:
                    raise ValueError(
                        "serve_chaos: unknown injection point %r "
                        "(points: %s)" % (point, ", ".join(cls.POINTS)))
                inj._at[point] = int(n)
                continue
            key, sep, val = item.partition(":")
            key = key.strip()
            if not sep:
                raise ValueError("serve_chaos: malformed item %r "
                                 "(want point:prob, point@N, seed:N, "
                                 "hang_ms:N or all:prob)" % item)
            if key == "seed":
                inj.seed = int(val)
            elif key == "hang_ms":
                inj.hang_ms = float(val)
            elif key == "all":
                p = float(val)
                for point in cls.POINTS:
                    inj._prob[point] = p
            elif key in cls.POINTS:
                inj._prob[key] = float(val)
            else:
                raise ValueError(
                    "serve_chaos: unknown injection point %r "
                    "(points: %s)" % (key, ", ".join(cls.POINTS)))
        return inj

    def _rng(self, point: str):
        rng = self._rngs.get(point)
        if rng is None:
            import numpy as np
            # stable per-point stream: independent of how points
            # interleave at runtime, and of python's salted hash()
            rng = self._rngs[point] = np.random.RandomState(
                (self.seed * 1000003 + zlib.crc32(point.encode()))
                & 0x7FFFFFFF)
        return rng

    def fire(self, point: str) -> bool:
        """One roll of the dice for ``point``; True = inject the fault
        now. The CALL SITE decides the manifestation (raise, corrupt a
        buffer, stall) — this method only counts and decides."""
        if not self.armed:
            return False
        at = self._at.get(point)
        prob = self._prob.get(point, 0.0)
        if at is None and prob <= 0.0:
            return False
        self._calls[point] += 1
        hit = at is not None and self._calls[point] == at
        if not hit and prob > 0.0 \
                and float(self._rng(point).random_sample()) < prob:
            hit = True
        if hit:
            self.counts[point] += 1
        return hit

    def hang(self) -> None:
        """An injected stall: block up to ``hang_ms``. If a recovery
        (or shutdown) releases hangs first, raise :class:`InjectedFault`
        so the abandoned thread UNWINDS instead of resuming mid-pass on
        a scheduler that no longer owns the engine; an undisturbed
        timeout returns normally — a transient stall, not a fault."""
        with self._cv:
            gen = self._release_gen
            deadline = time.perf_counter() + self.hang_ms / 1e3
            while self._release_gen == gen:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    return
                self._cv.wait(remaining)
        raise InjectedFault("injected hang interrupted by recovery")

    def release_hangs(self) -> None:
        """Wake every in-flight injected hang (they raise). Called by
        the supervisor at recovery and at shutdown."""
        with self._cv:
            self._release_gen += 1
            self._cv.notify_all()


# ---------------------------------------------------------------- journal
_journals: "weakref.WeakSet" = weakref.WeakSet()


def live_journals() -> List["ReplayJournal"]:
    """Journals still alive in this process (tests/conftest.py leak
    fixture: a non-empty journal after teardown means a server died
    without finishing — or finalizing — its admitted requests)."""
    return list(_journals)


class ReplayJournal:
    """The server's record of every admitted-but-unfinished request,
    in admission order. One entry = one request object, which already
    carries everything a bit-exact replay needs: the prompt, the
    SamplingParams (seed included), and the tokens emitted so far.
    Single-threaded discipline (scheduler thread), except for reads
    under the server's recovery lock."""

    def __init__(self):
        self._entries: Dict[int, object] = {}   # rid -> Request, ordered
        _journals.add(self)

    def add(self, req) -> None:
        self._entries[req.rid] = req

    def remove(self, req) -> None:
        self._entries.pop(req.rid, None)

    def requests(self) -> List[object]:
        """Live entries in admission order."""
        return list(self._entries.values())

    def clear(self) -> None:
        self._entries.clear()

    def __len__(self) -> int:
        return len(self._entries)


def reset_for_replay(req) -> None:
    """Rewind a journaled request for re-execution through the normal
    admit path.

    GREEDY requests (temperature 0) get a hard token pin: the longest
    stream ever produced becomes ``replay_expect`` and the regenerated
    stream is verified against it token by token before any NEW token
    extends it — greedy output is the argmax chain, bit-identical no
    matter how the replayed run batches, speculates, or pages.

    SAMPLED requests resume on the pinned per-token fold_in key
    schedule (same seed, same key indices), so the output DISTRIBUTION
    is unchanged — but they are not token-pinned: a speculative verify
    may accept a different draft prefix on replay (draft windows depend
    on occupancy and pool pressure), emitting a different —
    distribution-equal — token where the rejection lands, exactly as
    two independent serves of the same sampled request may differ on a
    spec-enabled server.

    The queue deadline is cleared either way: the request was already
    admitted once, and expiring it for the engine's fault would punish
    the caller for the server's failure."""
    if req.params.temperature > 0:
        req.replay_expect = None
    else:
        prev = getattr(req, "replay_expect", None)
        if prev is None or len(req.tokens) >= len(prev):
            # a second crash mid-replay keeps the ORIGINAL (longer)
            # pin: the tokens regenerated so far were verified against
            # it, so both prefixes agree
            req.replay_expect = list(req.tokens)
    req.tokens = []
    req.status = "queued"
    req.slot = None
    req.deadline = None


def swap_checksum(*bufs) -> int:
    """Cheap host-buffer checksum for the swap round trip (crc32 chained
    over every contiguous buffer, Nones skipped) — a corrupted buffer
    fails loudly at resume instead of resuming a garbage bit-stream.
    Quantized (int8) swap records pass four buffers — K/V payloads plus
    their scale planes — so the crc covers exactly the stored
    representation the scatter restores."""
    import numpy as np
    crc = 0
    for b in bufs:
        if b is not None:
            crc = zlib.crc32(np.ascontiguousarray(b), crc)
    return crc


# ----------------------------------------------------------------- ladder
class DegradationLadder:
    """Graceful-degradation state machine with hysteresis; see the
    module docstring for the rungs. ``evaluate`` is called once per
    scheduler pass with the gauges the server already keeps — a few
    float compares, no allocation.

    Hysteresis: a rung is climbed only after ``up_hold`` consecutive
    hot evaluations and descended only after ``down_hold`` consecutive
    cool ones; the band between ``*_lo`` and ``*_hi`` thresholds resets
    both streaks, so the ladder neither flaps on a noisy gauge nor
    relaxes while pressure is merely catching its breath."""

    MAX_RUNG = 3            # the untenanted ceiling (shedding)
    EMERGENCY_RUNG = 4      # tenant-aware servers only: guaranteed
    #                         requests become sheddable

    def __init__(self, enabled: bool = True, queue_hi: float = 0.85,
                 queue_lo: float = 0.30, headroom_lo: float = 0.05,
                 headroom_hi: float = 0.25, up_hold: int = 3,
                 down_hold: int = 16, tick_budget_ms: float = 0.0,
                 max_rung: int = 0):
        self.enabled = bool(enabled)
        # 0 = the classic 3-rung ladder; a tenancy-armed server raises
        # this to EMERGENCY_RUNG (4) — rung 4 is only reachable when
        # the PROTECTED classes are themselves hot (evaluate)
        self.max_rung = int(max_rung) if max_rung > 0 else self.MAX_RUNG
        self.queue_hi = float(queue_hi)
        self.queue_lo = float(queue_lo)
        self.headroom_lo = float(headroom_lo)
        self.headroom_hi = float(headroom_hi)
        self.up_hold = int(up_hold)
        self.down_hold = int(down_hold)
        # p95 decode-tick budget in ms (0 = signal off); the server
        # samples its StepStats percentile periodically when armed
        self.tick_budget_ms = float(tick_budget_ms)
        self.rung = 0
        self.sheds = 0              # requests shed at rung 3 (server inc)
        self.transitions = 0
        self._up = 0
        self._down = 0
        self._stall = False

    def note_stall(self) -> None:
        """A reserve/admission stall (the 50 ms park) since the last
        evaluation — a hot signal regardless of queue depth: the pool
        cannot place the queue head even though a slot is free."""
        self._stall = True

    def evaluate(self, queue_frac: float, headroom: Optional[float],
                 tick_p95_ms: Optional[float] = None,
                 class_queue_frac: Optional[Dict[str, float]] = None
                 ) -> int:
        """One hysteresis step; returns the (possibly new) rung.
        ``queue_frac`` = queue depth / capacity; ``headroom`` = free +
        reclaimable blocks / usable pool (None for the dense engine);
        ``tick_p95_ms`` only participates when ``tick_budget_ms`` > 0
        and a fresh sample is passed. ``class_queue_frac`` (tenancy-
        armed servers) maps priority class -> that class's queue
        fraction: climbing from rung 3 to the emergency rung requires
        the PROTECTED (non-best-effort) classes alone to be over
        ``queue_hi`` — rung 3's best-effort shedding must have failed
        to relieve the paying tenants before guaranteed traffic is
        ever touched."""
        if not self.enabled:
            return 0
        stall = self._stall
        self._stall = False
        hot = queue_frac >= self.queue_hi or stall \
            or (headroom is not None and headroom <= self.headroom_lo) \
            or (self.tick_budget_ms > 0 and tick_p95_ms is not None
                and tick_p95_ms > self.tick_budget_ms)
        cool = queue_frac <= self.queue_lo and not stall \
            and (headroom is None or headroom >= self.headroom_hi) \
            and (self.tick_budget_ms <= 0 or tick_p95_ms is None
                 or tick_p95_ms <= self.tick_budget_ms)
        protected = sum(v for k, v in (class_queue_frac or {}).items()
                        if k != "best_effort")
        if hot:
            self._up += 1
            self._down = 0
            limit = self.max_rung
            if self.rung >= self.MAX_RUNG and limit > self.MAX_RUNG \
                    and protected < self.queue_hi:
                limit = self.MAX_RUNG
            if self._up >= self.up_hold and self.rung < limit:
                self.rung += 1
                self.transitions += 1
                self._up = 0
        elif cool:
            self._down += 1
            self._up = 0
            if self._down >= self.down_hold and self.rung > 0:
                self.rung -= 1
                self.transitions += 1
                self._down = 0
        else:
            self._up = 0
            self._down = 0
        # the emergency rung is HELD only while the protected classes
        # are themselves hot: a lingering best-effort flood (global
        # pressure still high) must not keep guaranteed requests
        # sheddable once the paying tenants' own pressure subsided —
        # demotion to rung 3 is immediate (shedding best-effort there
        # is the correct and sufficient response), while re-climbing
        # pays the full up_hold hysteresis again.
        if self.rung >= self.EMERGENCY_RUNG and protected < self.queue_hi:
            self.rung = self.MAX_RUNG
            self.transitions += 1
        return self.rung

    # ------------------------------------------------------- the effects
    @property
    def spec_enabled(self) -> bool:
        """Rung 1 disables speculative decoding (optional work: greedy
        identity is untouched, only tokens-per-forward drops)."""
        return self.rung < 1

    @property
    def prefix_admission(self) -> bool:
        """Rung 2 stops prefix-cache admission (no new trie inserts or
        live-row donations; existing nodes still serve hits and remain
        evictable under pool pressure)."""
        return self.rung < 2

    @property
    def shedding(self) -> bool:
        """Rung 3 sheds queued requests that cannot meet their deadline
        and attaches ``retry_after_ms`` hints to rejections."""
        return self.rung >= 3

    @staticmethod
    def classes_for(rung: int):
        """Which priority classes the given rung's SHEDDING touches, in
        the order they are walked (inverse priority): rungs 1-2 shed
        nothing (their effects — spec off, prefix admission off — are
        class-global), rung 3 sheds best-effort then standard, rung 4
        (emergency) adds guaranteed. Untenanted requests are class
        ``standard``, so the classic rung-3 behavior is unchanged."""
        if rung >= DegradationLadder.EMERGENCY_RUNG:
            return ("best_effort", "standard", "guaranteed")
        if rung >= 3:
            return ("best_effort", "standard")
        return ()

    def shed_classes(self):
        """The classes the CURRENT rung may shed (see
        :meth:`classes_for`)."""
        return self.classes_for(self.rung)
