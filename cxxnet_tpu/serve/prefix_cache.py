"""Shared-prefix KV reuse: a ref-counted token-trie at chunk granularity.

Real traffic repeats prompt prefixes — system prompts, few-shot
templates, conversation history — and the whole-prompt prefill recomputed
every one of them from scratch on every request. This cache retains the
K/V of retired slot rows' complete prompt chunks, keyed by the chunk's
token ids in a trie (so two prompts sharing 3 chunks share 3 nodes), and
restores the longest cached prefix into a fresh slot row at admit in ONE
jitted call — the matched chunks are concatenated and written with one
``dynamic_update_slice`` per cache (engine.insert_row_prefix, no
recompute); chunked prefill then resumes at the first divergent chunk.

Correctness: K/V at position i depends only on tokens 0..i (causal), so
a chunk computed once for a token prefix is bit-for-bit the chunk any
other request with the same prefix would compute through the same chunk
program — restoring it is a pure copy, and token identity with the solo
``gpt_decode`` path is preserved exactly (pinned by
tests/test_serve_chunked.py's prefix-hit-vs-cold test). The match is
capped at the last complete chunk STRICTLY before the prompt's final
token, so the final chunk always runs and samples token 0 with the
request's own key. Only enabled together with chunked prefill: the
legacy whole-prompt program is a different compiled formulation whose
low-order bits are not contractually identical to the chunk step's.

Memory: every node holds one (n_layer, n_head, chunk, head_dim) K/V pair
(``2 * n_layer * n_head * chunk * head_dim * itemsize`` bytes). The trie
is bounded by a byte budget (``serve_prefix_mb``); going over evicts
least-recently-used EVICTABLE nodes — refcount 0, i.e. no child chunks
and no in-flight copy — so an interior node can never be evicted from
under its children and a chain stays contiguous. Budget 0 disables reuse
entirely (match/insert become no-ops).

Two trie flavors share this module: :class:`PrefixCache` (dense engine —
nodes hold K/V COPIES extracted from retired rows, restored by a jitted
dus at admit) and :class:`PagedPrefixCache` (paged engine — nodes hold
BLOCK IDS with refcounts: hits append shared blocks to the admitting
row's table with zero copies, donation happens at prefill completion so
LIVE rows share too, and copy-on-write protects the shared blocks).

Adapter correctness (batched multi-LoRA, serve/lora.py): LoRA deltas
land on the QKV projection, so K/V computed under adapter A is NOT the
K/V any other adapter (or the base model) would compute for the same
tokens. Both tries therefore key their ROOT by the request's adapter
NAME — one independent trie per adapter, with ``adapter=""`` (base)
keeping the exact pre-LoRA root dict, byte-identical behavior when LoRA
is unarmed. A cross-adapter lookup can never hit (pinned by
tests/test_serve_lora.py).

Thread-safety: all methods run on the server's single scheduler thread
(the same discipline as serve/scheduler.py); the unit tests drive it
directly from one thread.

Observability (doc/observability.md): the traffic counters below
(``hits`` / ``misses`` / ``hit_tokens`` / ``prompt_tokens`` /
``evictions`` / ``inserted_chunks``) plus ``nbytes`` / ``chunks`` are
read at collection time by the server's obs registry as the
``cxn_prefix_*`` metric family — plain attribute increments here, zero
added cost on the admit path. The first LRU eviction logs once
(``profiler.warn``): steady-state churn is normal, but the moment the
budget first binds is the operational signal that ``serve_prefix_mb``
is sized below the working set.
"""

from __future__ import annotations

from typing import Dict, List, Optional

__all__ = ["PrefixCache", "PagedPrefixCache"]


class _Node:
    """One cached chunk: trie edge label = the chunk's token tuple."""

    __slots__ = ("tokens", "k", "v", "parent", "children", "refs",
                 "last_used", "nbytes", "adapter")

    def __init__(self, tokens: tuple, k, v, parent: Optional["_Node"],
                 adapter: str = ""):
        self.tokens = tokens
        self.k = k
        self.v = v
        self.parent = parent
        self.children: Dict[tuple, "_Node"] = {}
        self.refs = 0               # children + in-flight borrows
        self.last_used = 0
        self.nbytes = int(k.nbytes) + int(v.nbytes)
        self.adapter = adapter      # which per-adapter root owns it


class PrefixCache:
    """Token-trie over cached prompt chunks; see module docstring."""

    def __init__(self, engine, budget_bytes: int):
        if not engine.chunk:
            raise ValueError("PrefixCache needs chunked prefill "
                             "(engine prefill_chunk > 0)")
        self.engine = engine
        self.chunk = int(engine.chunk)
        self.budget = int(budget_bytes)
        # bytes of one cached chunk node (K + V), from the engine's
        # geometry — the insert cap below needs it BEFORE any copy-out
        cfg = engine.cfg
        import numpy as _np
        self.node_bytes = (2 * cfg.n_layer * cfg.n_head * self.chunk
                           * (cfg.feat // cfg.n_head)
                           * _np.dtype(engine.dtype).itemsize)
        self._children: Dict[tuple, _Node] = {}     # base-adapter root
        # one independent root per adapter name (LoRA changes K/V, so
        # prefixes only ever match within one adapter); "" — the base
        # model — IS self._children, the exact pre-LoRA root
        self._roots: Dict[str, Dict[tuple, _Node]] = {"": self._children}
        # flat node index for eviction: a dict (insertion-ordered) so
        # removal is O(1) — a list's .remove() turns an eviction burst
        # quadratic on the scheduler thread
        self._nodes: Dict[_Node, None] = {}
        self._clock = 0
        self._bytes = 0
        self.reset_counters()

    # ------------------------------------------------------------- state
    @property
    def enabled(self) -> bool:
        return self.budget > 0

    @property
    def nbytes(self) -> int:
        return self._bytes

    @property
    def chunks(self) -> int:
        return len(self._nodes)

    def reset_counters(self) -> None:
        """Zero the traffic counters (bench warm-up); cached chunks and
        their LRU clocks are kept — steady-state is the point."""
        self.hits = 0               # admits that restored >= 1 chunk
        self.misses = 0             # admits that restored none
        self.hit_tokens = 0         # prompt tokens restored from cache
        self.prompt_tokens = 0      # prompt tokens across all lookups
        self.evictions = 0
        self.inserted_chunks = 0
        self._budget_warned = False

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def _chunk_key(self, prompt, i: int) -> tuple:
        c = self.chunk
        return tuple(int(t) for t in prompt[i * c:(i + 1) * c])

    def _root(self, adapter: str) -> Dict[tuple, _Node]:
        """The trie root for one adapter name ("" = base model — the
        original root dict, so unarmed servers are byte-identical)."""
        return self._roots.setdefault(adapter, {})

    # ------------------------------------------------------------- match
    def match(self, prompt, adapter: str = "") -> List[_Node]:
        """Longest chain of cached complete chunks prefixing ``prompt``,
        capped at ``(len(prompt) - 1) // chunk`` chunks so at least the
        prompt's final token is always recomputed (the final chunk must
        run to sample the request's first generated token). Matching is
        scoped to ``adapter``'s own trie — K/V differs across adapters,
        so a cross-adapter hit would be silent corruption."""
        if not self.enabled:
            return []
        out: List[_Node] = []
        children = self._root(adapter)
        for i in range((len(prompt) - 1) // self.chunk):
            node = children.get(self._chunk_key(prompt, i))
            if node is None:
                break
            out.append(node)
            children = node.children
        return out

    def copy_into(self, slot: int, prompt, adapter: str = "") -> int:
        """Restore the longest cached prefix of ``prompt`` into ``slot``'s
        cache row; returns the number of tokens restored (chunked prefill
        resumes there). Matched nodes are pinned (refs) for the duration
        of the copy and LRU-refreshed."""
        if not self.enabled:
            return 0
        self.prompt_tokens += len(prompt)
        nodes = self.match(prompt, adapter)
        if not nodes:
            self.misses += 1
            return 0
        now = self._tick()
        for n in nodes:
            n.refs += 1
        try:
            # one jitted call restores the whole contiguous prefix (one
            # dus per cache total — per-chunk calls would rewrite the
            # cache once per chunk on backends without donation)
            self.engine.insert_row_prefix(slot, [n.k for n in nodes],
                                          [n.v for n in nodes])
            for n in nodes:
                n.last_used = now
        finally:
            for n in nodes:
                n.refs -= 1
        self.hits += 1
        restored = len(nodes) * self.chunk
        self.hit_tokens += restored
        return restored

    # ------------------------------------------------------------ insert
    def insert_from_row(self, slot: int, prompt,
                        adapter: str = "") -> int:
        """Offer a retired row's complete prompt chunks to the trie:
        uncached chunks are copied out of the row on device, existing
        ones are LRU-refreshed. Returns the number of chunks added. Must
        run BEFORE the slot is recycled (the scheduler calls it inside
        retire)."""
        if not self.enabled:
            return 0
        n_chunks = len(prompt) // self.chunk
        # cap the chain at what the budget could ever retain: inserting
        # a chain larger than the whole budget would flush every warm
        # entry only for evict_to_budget to trim the chain's own tail
        # right back — pay the copy-out only for chunks that can stay.
        # (Deliberately NOT headroom-based: at steady state the cache
        # sits at budget, and LRU churn of older entries is the point.)
        n_chunks = min(n_chunks, self.budget // self.node_bytes)
        if not n_chunks:
            return 0
        now = self._tick()
        keys = [self._chunk_key(prompt, i) for i in range(n_chunks)]
        children = self._root(adapter)
        parent: Optional[_Node] = None
        i = 0
        while i < n_chunks:                 # walk the already-cached part
            node = children.get(keys[i])
            if node is None:
                break
            node.last_used = now
            parent = node
            children = node.children
            i += 1
        if i == n_chunks:
            return 0
        # the uncached chunks are a contiguous SUFFIX of this chain
        # (nodes are only ever created parent-first), so one dispatch
        # copies them all out — retire runs on the scheduler thread,
        # where a per-chunk dispatch chain would stall active rows
        ks, vs = self.engine.extract_row_chunks(slot, i * self.chunk,
                                                n_chunks - i)
        added = n_chunks - i
        for j in range(i, n_chunks):
            node = _Node(keys[j], ks[j - i], vs[j - i], parent,
                         adapter=adapter)
            node.last_used = now
            children[keys[j]] = node
            if parent is not None:
                parent.refs += 1
            self._nodes[node] = None
            self._bytes += node.nbytes
            self.inserted_chunks += 1
            parent = node
            children = node.children
        self.evict_to_budget()
        return added

    # ----------------------------------------------------------- evict
    def evict_to_budget(self) -> int:
        """LRU-evict refcount-0 nodes (leaves with no in-flight borrow)
        until the byte budget holds; returns how many were dropped.
        Evicting a leaf un-refs its parent, so a cold chain unwinds tail
        first and an interior node never orphans its children. One
        sorted sweep over the evictable snapshot per round (parents
        freed mid-sweep join the NEXT round's snapshot), so an eviction
        burst costs O(rounds * n log n) instead of a per-victim scan."""
        n = 0
        if self._bytes > self.budget and not self._budget_warned:
            self._budget_warned = True
            from ..utils import profiler
            profiler.warn(
                "prefix cache reached its %.1f MiB budget (%d chunks "
                "resident); LRU eviction begins — raise serve_prefix_mb "
                "if the hit rate drops" % (self.budget / 2.0 ** 20,
                                           self.chunks))
        while self._bytes > self.budget:
            sweep = sorted((nd for nd in self._nodes if nd.refs == 0),
                           key=lambda nd: nd.last_used)
            if not sweep:               # everything pinned: over-budget
                break                   # but nothing is safely droppable
            for node in sweep:
                if self._bytes <= self.budget:
                    break
                self._remove(node)
                self.evictions += 1
                n += 1
        return n

    def _remove(self, node: _Node) -> None:
        parent = node.parent
        siblings = parent.children if parent is not None \
            else self._roots[node.adapter]
        del siblings[node.tokens]
        if parent is not None:
            parent.refs -= 1
        del self._nodes[node]
        self._bytes -= node.nbytes
        node.k = node.v = None          # drop the device buffers

    def clear(self) -> None:
        """Drop every cached chunk (server shutdown)."""
        for node in self._nodes:
            node.k = node.v = None
            node.children = {}
            node.parent = None
        self._nodes = {}
        self._children = {}
        self._roots = {"": self._children}
        self._bytes = 0


class _PagedNode:
    """One cached chunk — or partial-tail — in the PAGED trie: the
    payload is a tuple of physical block IDS the trie holds an
    ownership ref on, never a K/V copy. ``valid`` is how many leading
    tokens of the node's blocks hold real K/V: a complete chunk node
    has ``valid == chunk`` and whole blocks; a TAIL node (the prompt
    suffix beyond the last complete chunk) has ``valid < chunk`` and
    its last block only partially filled — positions past ``valid`` in
    that block are garbage the attention position mask renders inert,
    which is what makes sub-block sharing free (doc/serving.md)."""

    __slots__ = ("tokens", "blocks", "parent", "children", "refs",
                 "last_used", "valid", "nbytes", "adapter")

    def __init__(self, tokens: tuple, blocks: tuple,
                 parent: Optional["_PagedNode"], valid: int,
                 nbytes: int, adapter: str = ""):
        self.tokens = tokens
        self.blocks = blocks
        self.parent = parent
        self.children: Dict[tuple, "_PagedNode"] = {}
        self.refs = 0               # child chunks
        self.last_used = 0
        self.valid = int(valid)
        self.nbytes = int(nbytes)
        self.adapter = adapter      # which per-adapter root owns it


class PagedPrefixCache:
    """Zero-copy shared-prefix reuse over the paged block pool: the same
    chunk-granular token-trie as :class:`PrefixCache`, but each node
    holds BLOCK IDS instead of host K/V copies. The trie is therefore
    dtype-agnostic: under ``serve_kv_dtype=int8`` its ids point into
    the quantized (values, scales) pool, node byte accounting follows
    ``engine.block_bytes()``'s stored-dtype formula, and the same
    ``serve_prefix_mb`` budget holds ~2x the cached prefix tokens
    (doc/serving.md "Quantized serving").

    * **Hit** (``copy_into``): the matched chain's block ids are
      appended to the admitting row's block table with one refcount bump
      per block — zero device copies, zero recompute. The row and the
      trie (and any other live row that hit the same prefix) now share
      physical blocks; copy-on-write in engine.reserve_window keeps the
      sharing safe if a write window ever lands in one.
    * **Partial tails** (sub-block sharing): the prompt suffix beyond
      the last complete chunk is donated too, as one terminal node with
      a per-node ``valid`` token count — its last block is only
      partially filled, and the garbage beyond ``valid`` is inert under
      the attention position mask (the same invariant recycled rows and
      the fused kernel's garbage-block reads lean on). A hit on a tail
      restores a NON-aligned prefix; chunk prefill resumes mid-block
      and the row's first write COW-faults the shared tail block.
    * **Donation** (``donate_from_row``): at PREFILL COMPLETION — not
      retire — the row's complete prompt chunks are offered to the trie,
      which takes one ownership ref per block. Donating from a LIVE row
      is what extends prefix sharing to concurrent traffic: a burst of
      same-prefix requests hits the first request's blocks the moment
      its prefill lands, instead of waiting for it to retire.
    * **Eviction**: LRU over refcount-0 leaf nodes, under the
      ``serve_prefix_mb`` byte budget (``node_bytes`` per node, the
      blocks' pool bytes) — plus ``evict_blocks(n)``, the pool-pressure
      path the scheduler calls before preempting a row: it ignores the
      byte budget and frees LRU nodes until ``n`` pool blocks actually
      returned to the free list. A node whose blocks are still
      borrowed by live rows frees nothing immediately (the rows keep
      their refs) but stops retaining them once those rows release.

    Counter semantics (hits / misses / hit_tokens / prompt_tokens /
    evictions / inserted_chunks) match :class:`PrefixCache`, so the
    server's ``cxn_prefix_*`` metric family and ``prefix_hit_rate``
    gauge read identically in both modes."""

    def __init__(self, engine, budget_bytes: int):
        if not getattr(engine, "paged", False):
            raise ValueError("PagedPrefixCache needs a paged engine "
                             "(num_blocks > 0); dense engines use "
                             "PrefixCache")
        self.engine = engine
        self.chunk = int(engine.chunk)
        self.cpb = self.chunk // engine.block_size   # blocks per chunk
        self.budget = int(budget_bytes)
        self.node_bytes = engine.block_bytes() * self.cpb
        self._children: Dict[tuple, _PagedNode] = {}    # base root
        # per-adapter roots, exactly as in PrefixCache: "" (base) IS
        # self._children, so unarmed serving is byte-identical
        self._roots: Dict[str, Dict[tuple, _PagedNode]] = \
            {"": self._children}
        self._nodes: Dict[_PagedNode, None] = {}
        self._clock = 0
        self._bytes = 0
        self.reset_counters()

    # ------------------------------------------------------------- state
    @property
    def enabled(self) -> bool:
        return self.budget > 0

    @property
    def nbytes(self) -> int:
        """Pool bytes RETAINED by the trie (nodes * node_bytes); a
        subset of the block pool's total, not memory on top of it."""
        return self._bytes

    @property
    def chunks(self) -> int:
        return len(self._nodes)

    def reset_counters(self) -> None:
        self.hits = 0
        self.misses = 0
        self.hit_tokens = 0
        self.prompt_tokens = 0
        self.evictions = 0
        self.inserted_chunks = 0
        self._budget_warned = False

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def _chunk_key(self, prompt, i: int) -> tuple:
        c = self.chunk
        return tuple(int(t) for t in prompt[i * c:(i + 1) * c])

    def _root(self, adapter: str) -> Dict[tuple, _PagedNode]:
        """The trie root for one adapter name ("" = base model — the
        original root dict, so unarmed servers are byte-identical)."""
        return self._roots.setdefault(adapter, {})

    # ------------------------------------------------------------- match
    def match(self, prompt, adapter: str = "") -> List[_PagedNode]:
        """Longest cached chain prefixing ``prompt`` — complete chunk
        nodes, optionally terminated by one partial-TAIL node — capped
        strictly before the final token (the final chunk must run to
        sample the request's first token with its own key). A tail
        node's K/V is valid for any prompt it PREFIXES: K/V at
        position i depends only on tokens 0..i, so exact-tuple child
        lookup is right for whole chunks but the tail wants the longest
        stored suffix that prefixes the remainder."""
        if not self.enabled:
            return []
        out: List[_PagedNode] = []
        children = self._root(adapter)
        matched = 0
        for i in range((len(prompt) - 1) // self.chunk):
            node = children.get(self._chunk_key(prompt, i))
            if node is None:
                break
            out.append(node)
            children = node.children
            matched += self.chunk
        tail = self._match_tail(children, prompt, matched)
        if tail is not None:
            out.append(tail)
        return out

    def _match_tail(self, children: Dict, prompt,
                    matched: int) -> Optional[_PagedNode]:
        """Longest partial-tail node under ``children`` whose tokens
        prefix ``prompt[matched:]``, leaving at least the final prompt
        token to recompute. Tail nodes carry fewer than ``chunk``
        tokens, so they can never collide with a chunk key; the scan is
        linear over the (few) children — tails are terminal leaves, so
        there is no chain to walk."""
        cap = len(prompt) - 1 - matched
        if cap < 1:
            return None
        best = None
        for node in children.values():
            v = node.valid
            if v >= self.chunk or v > cap:
                continue                # a chunk node, or too long
            if best is not None and v <= best.valid:
                continue
            if node.tokens == tuple(int(t)
                                    for t in prompt[matched:matched + v]):
                best = node
        return best

    def match_tokens(self, prompt, adapter: str = "") -> int:
        """Tokens a hit would restore (the admission gate's estimate —
        no refcounts are touched)."""
        return sum(nd.valid for nd in self.match(prompt, adapter))

    def copy_into(self, slot: int, prompt, adapter: str = "") -> int:
        """Append the longest cached prefix's shared blocks to
        ``slot``'s block table (one incref per block, NO device copy);
        returns tokens restored — NOT necessarily block- or
        chunk-aligned when a partial tail matched: chunk prefill then
        resumes mid-block, and the row's first write there
        copy-on-write-faults the shared tail block (reserve_window).
        The dense method name is kept so the scheduler drives both
        cache kinds identically."""
        if not self.enabled:
            return 0
        self.prompt_tokens += len(prompt)
        nodes = self.match(prompt, adapter)
        if not nodes:
            self.misses += 1
            return 0
        now = self._tick()
        ids = []
        restored = 0
        for nd in nodes:
            nd.last_used = now
            ids.extend(nd.blocks)
            restored += nd.valid
        self.engine.attach_shared(slot, ids)
        self.hits += 1
        self.hit_tokens += restored
        return restored

    # ------------------------------------------------------------ donate
    def donate_from_row(self, slot: int, prompt,
                        adapter: str = "") -> int:
        """Offer ``slot``'s prompt K/V to the trie: one ownership ref
        per block of each not-yet-cached complete chunk, PLUS a
        partial-TAIL node for the suffix beyond the last complete chunk
        (zero copies — the blocks stay exactly where they are). Returns
        nodes added. Safe from a LIVE row: the donated blocks cover
        positions < len(prompt); a later row write past the donated
        region either lands in fresh blocks (block-aligned case) or
        inside the shared tail block, where reserve_window's
        copy-on-write fault privatizes the row's copy FIRST — the
        trie's prefix bytes are immutable either way. Donating the
        partial tail therefore costs the donor at most one COW block
        copy on its next write — the price of sub-block sharing, paid
        once per donation, not per reader."""
        if not self.enabled:
            return 0
        total = len(prompt) // self.chunk
        n_chunks = min(total, self.budget // max(1, self.node_bytes))
        now = self._tick()
        keys = [self._chunk_key(prompt, i) for i in range(n_chunks)]
        children = self._root(adapter)
        parent: Optional[_PagedNode] = None
        i = 0
        while i < n_chunks:
            node = children.get(keys[i])
            if node is None:
                break
            node.last_used = now
            parent = node
            children = node.children
            i += 1
        added = 0
        for j in range(i, n_chunks):
            blocks = tuple(self.engine.row_block_ids(
                slot, j * self.cpb, (j + 1) * self.cpb))
            node = self._add_node(keys[j], blocks, parent, self.chunk,
                                  now, adapter)
            children[keys[j]] = node
            self.inserted_chunks += 1
            added += 1
            parent = node
            children = node.children
        # partial tail: the suffix beyond the last complete chunk joins
        # as ONE terminal node with a per-node valid length (its last
        # block only partially filled — masked garbage beyond). Only
        # when the complete chain is fully resident (a budget-capped
        # chain would dangle the tail mid-prompt) and the tail leaves
        # the final token to recompute on a future hit.
        tail = len(prompt) - total * self.chunk
        bs = self.engine.block_size
        nblk = (tail + bs - 1) // bs
        if n_chunks == total and 1 <= tail < self.chunk \
                and nblk * self.engine.block_bytes() <= self.budget:
            key = tuple(int(t) for t in prompt[total * self.chunk:])
            node = children.get(key)
            if node is not None:
                node.last_used = now
            else:
                blocks = tuple(self.engine.row_block_ids(
                    slot, total * self.cpb, total * self.cpb + nblk))
                node = self._add_node(key, blocks, parent, tail, now,
                                      adapter)
                children[key] = node
                self.inserted_chunks += 1
                added += 1
        self.evict_to_budget()
        return added

    def _add_node(self, key: tuple, blocks: tuple,
                  parent: Optional[_PagedNode], valid: int,
                  now: int, adapter: str = "") -> _PagedNode:
        """Ref the blocks and wire one node under ``parent`` (the
        caller links it into the right children dict)."""
        m = self.engine.manager
        for b in blocks:
            m.incref(b)
        node = _PagedNode(key, blocks, parent, valid,
                          len(blocks) * self.engine.block_bytes(),
                          adapter=adapter)
        node.last_used = now
        if parent is not None:
            parent.refs += 1
        self._nodes[node] = None
        self._bytes += node.nbytes
        return node

    # ------------------------------------------------------------- evict
    def evict_to_budget(self) -> int:
        """LRU-evict refcount-0 leaf nodes until the byte budget holds
        (same sweep discipline as the dense trie)."""
        n = 0
        if self._bytes > self.budget and not self._budget_warned:
            self._budget_warned = True
            from ..utils import profiler
            profiler.warn(
                "paged prefix trie reached its %.1f MiB budget (%d "
                "chunks retained); LRU eviction begins — raise "
                "serve_prefix_mb if the hit rate drops"
                % (self.budget / 2.0 ** 20, self.chunks))
        while self._bytes > self.budget:
            sweep = sorted((nd for nd in self._nodes if nd.refs == 0),
                           key=lambda nd: nd.last_used)
            if not sweep:
                break
            for node in sweep:
                if self._bytes <= self.budget:
                    break
                self._remove(node)
                self.evictions += 1
                n += 1
        return n

    def evict_blocks(self, n_blocks: int) -> int:
        """Pool-pressure eviction: free LRU nodes (budget ignored) until
        ``n_blocks`` blocks actually hit the free list or nothing
        evictable remains; returns blocks freed. Borrowed nodes (live
        rows still hold refs on their blocks) free nothing now — the
        scheduler falls through to preemption in that case."""
        freed = 0
        m = self.engine.manager
        while freed < n_blocks:
            # only evict nodes whose removal actually frees a block:
            # a node whose blocks are ALL borrowed by live rows yields
            # nothing now, and dropping it would annihilate the cache
            # (and every future hit on that chain) for zero reclaimed
            # memory — leave it, fall through to preemption instead
            sweep = sorted(
                (nd for nd in self._nodes if nd.refs == 0
                 and any(m.ref[b] == 1 for b in nd.blocks)),
                key=lambda nd: nd.last_used)
            if not sweep:
                break
            for node in sweep:
                if freed >= n_blocks:
                    break
                before = m.free_count
                self._remove(node)
                self.evictions += 1
                freed += m.free_count - before
        return freed

    def reclaimable_blocks(self) -> int:
        """Blocks eviction could eventually free (every block the trie
        ALONE owns — the sweep cascades tail-first, so interior nodes
        count too once their leaves go) — the admission gate's headroom
        estimate. Blocks borrowed by live rows are excluded: evicting
        their nodes frees nothing until the rows release."""
        m = self.engine.manager
        n = 0
        for nd in self._nodes:
            n += sum(1 for b in nd.blocks if m.ref[b] == 1)
        return n

    def trie_refs(self) -> int:
        """Total block OWNERSHIP refs the trie currently holds (one per
        block per node) — the ``trie_refs`` input of
        :meth:`~cxxnet_tpu.serve.paged.BlockManager.check_consistency`,
        the chaos soak's refcount-leak oracle."""
        return sum(len(nd.blocks) for nd in self._nodes)

    def _remove(self, node: _PagedNode) -> None:
        parent = node.parent
        siblings = parent.children if parent is not None \
            else self._roots[node.adapter]
        del siblings[node.tokens]
        if parent is not None:
            parent.refs -= 1
        del self._nodes[node]
        self._bytes -= node.nbytes
        m = self.engine.manager
        for b in node.blocks:
            m.decref(b)
        node.blocks = ()

    def clear(self) -> None:
        """Release every trie block ref (server shutdown)."""
        m = self.engine.manager
        for node in self._nodes:
            for b in node.blocks:
                m.decref(b)
            node.blocks = ()
            node.children = {}
            node.parent = None
        self._nodes = {}
        self._children = {}
        self._roots = {"": self._children}
        self._bytes = 0
