"""Paged KV allocator: fixed-size blocks, refcounts, copy-on-write.

The dense slot pool charges every admitted request for the full
chunk-padded ``seq_len`` — a 6-token prompt generating 10 tokens pins
``row_len`` positions of K/V in every layer for its whole lifetime, and
concurrency is hard-capped at ``serve_slots`` no matter how short the
live sequences are. The paged layout (vLLM's PagedAttention idea, re-cut
for this engine's one-compiled-signature discipline) splits the cache
into a global pool of fixed-size **blocks**

    (n_layer, num_blocks, n_head, block_size, head_dim)

and gives each slot row an ``int32`` **block table** mapping logical
block index -> physical block id. Occupancy then scales with *tokens in
flight*: a row holds ``ceil(tokens / block_size)`` blocks, not
``row_len`` positions, and the same physical block can back several
rows' tables at once (shared prompt prefixes, the trie in
serve/prefix_cache.py).

This module is the HOST side only — pure bookkeeping, no jax imports.
:class:`BlockManager` owns the free list, the per-block refcounts, and
the per-slot tables; the device side (gather/scatter through traced
block indices, the COW block copy, swap in/out) lives in
serve/engine.py, and the *policy* (when to evict the trie, whom to
preempt) in serve/scheduler.py.

The manager is deliberately DTYPE-AGNOSTIC: under quantized serving
(``serve_kv_dtype=int8``, doc/serving.md "Quantized serving") the
device pool a block id points into becomes a ``(values int8, scales)``
pair instead of one compute-dtype array, and every id here simply
indexes both leaves — refcounts, COW, and swap semantics are unchanged
while each block holds ~2x the tokens per MiB.

Invariants the rest of the serving stack leans on:

* **Block 0 is the garbage block.** It is never handed out by
  :meth:`BlockManager.alloc`; every unallocated table entry points at
  it, so the batched tick's unconditional parked-row write and a padded
  swap-in scatter always have a harmless landing spot, and the paged
  gather always reads in-bounds memory (masked to an exact 0.0
  contribution by the attention's position mask, the same invariant
  dense recycled rows lean on).
* **A block with ``ref > 1`` is shared and therefore read-only.** Every
  write window must run :meth:`~cxxnet_tpu.serve.engine.DecodeEngine.
  reserve_window` first, which faults shared blocks to private copies
  (copy-on-write) BEFORE the program writes — never after, which is why
  a speculative verify whose drafts are rejected needs no rollback copy
  (the window was privately owned before the forward ran).
* **Refcounts are ownership counts**: one per row table referencing the
  block plus one per prefix-trie node holding it. ``decref`` to zero
  returns the block to the free list; nothing else ever does. At server
  drain every row is released and the trie cleared, so
  ``free_count == num_blocks - 1`` (all but the garbage block) — pinned
  by tests/test_serve_paged.py.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

__all__ = ["BlockManager", "BlockPoolExhausted"]


class BlockPoolExhausted(RuntimeError):
    """An allocation needed more free blocks than the pool holds right
    now. ``short`` is how many blocks the request is short by — the
    scheduler uses it to size trie eviction / preemption before
    retrying. Raised BEFORE any state is mutated, so a caught exhaustion
    leaves the manager and the device pool consistent."""

    def __init__(self, short: int, what: str = "allocation"):
        super().__init__(
            "KV block pool exhausted: %s needs %d more free block(s) "
            "(evict prefix-cache blocks, preempt a row, or raise "
            "serve_num_blocks / serve_kv_mb)" % (what, short))
        self.short = int(short)


class BlockManager:
    """Free list + refcounts + per-slot block tables for one engine's
    block pool. Single-threaded by design (the server's scheduler
    thread), like every other piece of serve host state."""

    def __init__(self, num_blocks: int, slots: int, blocks_per_row: int):
        if num_blocks < blocks_per_row + 1:
            raise ValueError(
                "serve_num_blocks=%d cannot hold one full row: need >= "
                "blocks_per_row + 1 = %d (the +1 is the reserved garbage "
                "block; raise serve_num_blocks / serve_kv_mb or shrink "
                "seq_len)" % (num_blocks, blocks_per_row + 1))
        self.num_blocks = int(num_blocks)
        self.bpr = int(blocks_per_row)
        self.slots = int(slots)
        # block 0 reserved: parked writes / padded swap scatters land
        # there, and a ref of 1 keeps it permanently off the free list
        self.ref = np.zeros(self.num_blocks, np.int32)
        self.ref[0] = 1
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))
        # logical -> physical per slot row; unallocated entries stay 0
        # (the garbage block), which keeps every traced gather in-bounds
        self.table = np.zeros((self.slots, self.bpr), np.int32)
        self.nblocks = [0] * self.slots     # valid entries per row
        # traffic counters (read by the obs registry at collection time)
        self.cow_faults = 0
        self.allocated_total = 0

    # ------------------------------------------------------------ state
    @property
    def free_count(self) -> int:
        return len(self._free)

    def counts(self) -> Dict[str, int]:
        """{"free", "private", "shared"} block counts (garbage block
        excluded). ``shared`` = referenced by more than one owner (rows
        and/or trie nodes) — the blocks copy-on-write protects."""
        shared = int((self.ref[1:] > 1).sum())
        private = int((self.ref[1:] == 1).sum())
        return {"free": len(self._free), "private": private,
                "shared": shared}

    def used_tokens_capacity(self, block_size: int) -> int:
        """Token capacity of the allocatable pool (garbage excluded)."""
        return (self.num_blocks - 1) * int(block_size)

    # ------------------------------------------------------ alloc / ref
    def alloc(self, what: str = "allocation") -> int:
        if not self._free:
            raise BlockPoolExhausted(1, what)
        b = self._free.pop()
        self.ref[b] = 1
        self.allocated_total += 1
        return b

    def require(self, n: int, what: str = "allocation") -> None:
        """Raise :class:`BlockPoolExhausted` unless ``n`` blocks are
        free — the pre-flight check that keeps multi-block operations
        all-or-nothing."""
        if n > len(self._free):
            raise BlockPoolExhausted(n - len(self._free), what)

    def incref(self, b: int) -> None:
        assert b != 0, "the garbage block is never shared"
        self.ref[b] += 1

    def decref(self, b: int) -> bool:
        """Drop one ownership ref; returns True when the block was freed
        (refcount reached zero)."""
        assert b != 0 and self.ref[b] > 0, "bad decref of block %d" % b
        self.ref[b] -= 1
        if self.ref[b] == 0:
            self._free.append(b)
            return True
        return False

    # ------------------------------------------------------- row tables
    def append(self, slot: int, b: int) -> None:
        """Append an (already ref-owned) block to ``slot``'s table."""
        i = self.nblocks[slot]
        assert i < self.bpr, "row %d table full" % slot
        self.table[slot, i] = b
        self.nblocks[slot] = i + 1

    def append_new(self, slot: int, what: str = "row growth") -> int:
        b = self.alloc(what)
        self.append(slot, b)
        return b

    def append_shared(self, slot: int, ids) -> None:
        """Append shared blocks (a prefix-cache hit) to ``slot``'s
        table: one ref per block for this row, zero K/V copies."""
        for b in ids:
            self.incref(int(b))
            self.append(slot, int(b))

    def row_blocks(self, slot: int, lo: int = 0, hi: int = -1) -> List[int]:
        """Physical block ids of ``slot``'s logical blocks [lo, hi)."""
        if hi < 0:
            hi = self.nblocks[slot]
        return [int(b) for b in self.table[slot, lo:hi]]

    def release_row(self, slot: int) -> int:
        """Drop every block ref this row holds (retire / swap-out /
        cancel); shared blocks survive through their other owners.
        Returns how many blocks were actually freed."""
        freed = 0
        for i in range(self.nblocks[slot]):
            freed += bool(self.decref(int(self.table[slot, i])))
        self.table[slot, :] = 0
        self.nblocks[slot] = 0
        return freed

    # ------------------------------------------------------------ audit
    def check_consistency(self, trie_refs: int = 0) -> None:
        """Refcount/free-list audit (the chaos soak's leak oracle,
        tests/test_resilience.py): every non-garbage block is either on
        the free list with ref 0, or off it with ref equal to its owner
        count — ``sum(row table refs) + trie_refs``. Raises
        AssertionError naming the first inconsistent block. ``trie_refs``
        is the total ownership refs the prefix trie holds (0 after
        ``clear()``)."""
        free = set(self._free)
        assert len(free) == len(self._free), "free list has duplicates"
        row_refs = np.zeros(self.num_blocks, np.int64)
        for slot in range(self.slots):
            for i in range(self.nblocks[slot]):
                row_refs[self.table[slot, i]] += 1
        for b in range(1, self.num_blocks):
            if b in free:
                assert self.ref[b] == 0, \
                    "block %d on the free list with ref %d" % (b,
                                                               self.ref[b])
                assert row_refs[b] == 0, \
                    "block %d on the free list but in %d row table(s)" \
                    % (b, row_refs[b])
            else:
                assert self.ref[b] > 0, \
                    "block %d neither free nor referenced" % b
        total_refs = int(self.ref[1:].sum())
        assert total_refs == int(row_refs[1:].sum()) + int(trie_refs), \
            "refcount drift: %d refs held vs %d row refs + %d trie refs" \
            % (total_refs, int(row_refs[1:].sum()), trie_refs)
