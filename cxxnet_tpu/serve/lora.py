"""Batched multi-LoRA serving: a paged adapter pool plus the ragged
grouped delta dispatch (round 20, doc/serving.md "Batched multi-LoRA").

One base model, many products: every request may name a rank-r LoRA
adapter, and ONE batched decode tick serves all of them — the adapter
population is paged like KV. The device footprint is a fixed pool of
``P`` adapter slots per block matmul site (qkv, proj, mlp1, mlp2):
slot 0 is all-zeros and reserved for "base model" (adapter id 0's
delta is an exact +0.0 in f32, so base rows ride the armed programs
unperturbed), slots 1..P-1 hold the factor pages of whichever
registered adapters are currently resident. Residency is refcounted by
the scheduler's admissions, eviction is LRU over unreferenced slots,
and swap-in re-verifies the host buffers' crc32 recorded at load —
the PR 8 swap idiom, so a corrupted adapter fails loudly
(:class:`~cxxnet_tpu.serve.resilience.SwapCorruptionError`) instead of
silently serving garbage weights.

The delta itself is ``(x @ A_a) @ B_a * s`` per row — the per-adapter
scale is folded into the stored B factor at load, so the traced math
is two dots through the rank bottleneck with f32 accumulation, added
to the base projection in f32 and cast once. Three formulations, one
bit-contract:

- the XLA reference (:func:`lora_delta`'s ragged path): rows are
  segment-sorted by adapter id (ops/moe.py :func:`grouped_order` — the
  MoE dropless-dispatch machinery) and the two factor matmuls run as
  grouped GEMMs over the ragged segments (``lax.ragged_dot``). Every
  row's product is a full contraction regardless of its neighbours, so
  per-row results are bit-identical across batch compositions — the
  property the solo-oracle identity pins lean on;
- the fused kernel (ops/pallas_kernels.py :func:`lora_bgmv`): adapter
  ids scalar-prefetched, each row's A/B tiles gathered straight into
  VMEM by the index_map (sorted rows make consecutive fetches hit the
  resident tile), pinned bit-exact against the reference in interpret
  mode and gated by ``lora_bgmv_supported``;
- unset ``serve_lora``: no pool, no operands, a pinned STRUCTURAL
  no-op — the lora hook is a trace-time ``None`` check in
  models/gpt.py, so unarmed programs keep their exact jaxpr.
"""

from __future__ import annotations

import os
import zlib
from typing import Dict, Optional

import numpy as np

from ..ops.moe import grouped_order

# the four matmul sites of the fused-QKV decode block, with their
# (in, out) dims as functions of (feat, hidden) — the single source for
# the adapter file format, the pool page shapes, and the delta hooks
# models/gpt.py applies (_block_core_fusedqkv / _mlp_core)
LORA_SITES = ("qkv", "proj", "mlp1", "mlp2")


def lora_site_dims(feat: int, hidden: int) -> Dict[str, tuple]:
    """(in, out) of each adapted matmul site."""
    return {"qkv": (feat, 3 * feat), "proj": (feat, feat),
            "mlp1": (feat, hidden), "mlp2": (hidden, feat)}


def parse_lora_spec(spec: str) -> Dict[str, str]:
    """``serve_lora = name:path;name2:path2`` -> {name: path}. Names
    must be unique and non-empty ("" is the reserved base-model id);
    a bare ``name`` with no colon maps to ``name.npz`` in the cwd."""
    reg: Dict[str, str] = {}
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        name, _, path = part.partition(":")
        name = name.strip()
        if not name:
            raise ValueError("serve_lora adapter name must be non-empty "
                             "(the empty name is the reserved base id)")
        if name in reg:
            raise ValueError("serve_lora adapter %r listed twice" % name)
        reg[name] = path.strip() or (name + ".npz")
    return reg


def make_adapter(cfg, rank: int, seed: int = 0,
                 scale: Optional[float] = None) -> Dict[str, np.ndarray]:
    """A random rank-``rank`` adapter for ``cfg``'s geometry (tests and
    the bench cell; real adapters come out of a fine-tune). Both
    factors are non-zero (N(0, 0.02)) so the delta is observable —
    the classic B=0 init is a training-time choice, useless for
    pinning serve-path identity. ``scale`` defaults to the classic
    alpha/r with alpha = 2r, i.e. 2.0."""
    rs = np.random.RandomState(seed)
    L, f, hidden = cfg.n_layer, cfg.feat, cfg.mlp_ratio * cfg.feat
    ad: Dict[str, np.ndarray] = {
        "rank": np.int32(rank),
        "scale": np.float32(2.0 if scale is None else scale),
    }
    for site, (d_in, d_out) in lora_site_dims(f, hidden).items():
        ad["a_" + site] = rs.normal(
            0, 0.02, (L, d_in, rank)).astype(np.float32)
        ad["b_" + site] = rs.normal(
            0, 0.02, (L, rank, d_out)).astype(np.float32)
    return ad


def save_adapter(path: str, adapter: Dict[str, np.ndarray]) -> None:
    """Write an adapter dict (``make_adapter``'s format) as an npz."""
    np.savez(path, **adapter)


def load_adapter(path: str) -> Dict[str, np.ndarray]:
    """Load an adapter npz, validating the key set."""
    if not os.path.exists(path):
        raise FileNotFoundError("LoRA adapter file not found: %s" % path)
    with np.load(path) as z:
        ad = {k: np.asarray(z[k]) for k in z.files}
    want = {"rank", "scale"} | {p + s for p in ("a_", "b_")
                                for s in LORA_SITES}
    missing = want - set(ad)
    if missing:
        raise ValueError("LoRA adapter %s is missing arrays: %s"
                         % (path, ", ".join(sorted(missing))))
    return ad


def adapter_checksum(adapter: Dict[str, np.ndarray]) -> int:
    """crc32 chained over the factor planes in site order — recorded at
    load, re-verified before every device swap-in (the PR 8 host-buffer
    checksum discipline applied to adapter pages)."""
    crc = 0
    for site in LORA_SITES:
        for pre in ("a_", "b_"):
            crc = zlib.crc32(
                np.ascontiguousarray(adapter[pre + site]), crc)
    return crc


def _delta_ragged(a, b, ids, x, y, n_slots: int):
    """XLA reference delta: segment-sort tokens by adapter id, run both
    factor matmuls as ragged grouped GEMMs, unsort, and fold into the
    base projection in f32. Mirrors the bgmv kernel OP FOR OP (f32
    ``preferred_element_type`` through the rank bottleneck, B cast to
    f32 for the second dot, one final cast) so interpret-mode
    bit-identity is structural, not a tolerance."""
    import jax.numpy as jnp
    from jax import lax

    rows, n, d_in = x.shape
    tok_ids = jnp.repeat(ids, n)                        # (rows*n,)
    xt = x.reshape(rows * n, d_in)
    order, gs = grouped_order(tok_ids, n_slots)
    t = lax.ragged_dot(xt[order], a, gs,
                       preferred_element_type=jnp.float32)
    d = lax.ragged_dot(t, b.astype(jnp.float32), gs,
                       preferred_element_type=jnp.float32)
    d = jnp.zeros_like(d).at[order].set(d)              # unsort
    d = d.reshape(rows, n, -1)
    return (y.astype(jnp.float32) + d).astype(y.dtype)


def lora_delta(pool: Dict, ids, layer: int, site: str, x, y):
    """The per-site delta hook the engine's program builders close over
    (models/gpt.py ``lora(site, x, y)``): ``x`` (rows, n, in) the
    matmul input, ``y`` (rows, n, out) the base projection, ``ids``
    (rows,) int32 pool slots. Routes to the bgmv kernel when the
    geometry gate admits it (rows pre-sorted by id so consecutive grid
    steps reuse the resident factor tile), else the ragged XLA
    reference — a trace-time decision, one formulation per program."""
    import jax.numpy as jnp
    from ..ops import pallas_kernels as _pk

    a = pool["a_" + site][:, layer]                     # (P, in, r)
    b = pool["b_" + site][:, layer]                     # (P, r, out)
    n_slots = int(a.shape[0])
    rows, n, d_in = x.shape
    r, d_out = int(a.shape[-1]), int(y.shape[-1])
    if _pk.lora_bgmv_supported(n, d_in, r, d_out,
                               itemsize=x.dtype.itemsize):
        order, _ = grouped_order(ids, n_slots)
        out = _pk.lora_bgmv(x[order], y[order], a, b, ids[order])
        return jnp.zeros_like(out).at[order].set(out)   # unsort
    return _delta_ragged(a, b, ids, x, y, n_slots)


class AdapterPool:
    """Fixed device pool of LoRA factor pages, paged like KV blocks.

    ``P = size`` slots per site; slot 0 is the all-zeros base page.
    The host side keeps every registered adapter loaded exactly once
    (with its crc32 recorded); the device side holds whichever subset
    is resident. :meth:`acquire` is the scheduler's admission gate —
    a non-resident adapter swaps in first (evicting the LRU
    unreferenced slot), and a pool whose every slot is pinned by
    active rows simply refuses, leaving the request queued exactly
    like a full KV pool does.

    The per-adapter ``scale`` is folded into the stored B pages, so
    the traced programs never see it — mixed scales cost nothing."""

    def __init__(self, cfg, registry: Dict[str, str], rank: int = 8,
                 pool_mb: float = 0.0, dtype=None,
                 adapters: Optional[Dict[str, Dict]] = None):
        import jax.numpy as jnp

        self.cfg = cfg
        self.rank = int(rank)
        self.registry = dict(registry)
        self.dtype = jnp.dtype(dtype) if dtype is not None \
            else jnp.dtype(jnp.float32)
        f, hidden = cfg.feat, cfg.mlp_ratio * cfg.feat
        self.site_dims = lora_site_dims(f, hidden)
        itemsize = self.dtype.itemsize
        self.slot_bytes = sum(
            cfg.n_layer * (d_in * self.rank + self.rank * d_out) * itemsize
            for d_in, d_out in self.site_dims.values())
        if pool_mb and pool_mb > 0:
            by_budget = int(pool_mb * 2 ** 20) // max(1, self.slot_bytes)
            self.size = max(2, min(len(registry) + 1, by_budget))
        else:
            self.size = len(registry) + 1       # everything resident
        # host pages: name -> adapter dict + crc (loaded once, verified
        # at every swap-in); ``adapters`` lets tests/bench inject
        # in-memory adapters without touching disk
        self._host: Dict[str, Dict] = {}
        self._crc: Dict[str, int] = {}
        for name in self.registry:
            ad = (adapters or {}).get(name)
            if ad is None:
                ad = load_adapter(self.registry[name])
            if int(ad["rank"]) != self.rank:
                raise ValueError(
                    "adapter %r has rank %d, pool is rank %d (set "
                    "serve_lora_rank to match)"
                    % (name, int(ad["rank"]), self.rank))
            self._validate_dims(name, ad)
            self._host[name] = ad
            self._crc[name] = adapter_checksum(ad)
        # device pool: slot 0 zeros = base; B pages stored pre-scaled
        L = cfg.n_layer
        self.pool = {}
        for site, (d_in, d_out) in self.site_dims.items():
            self.pool["a_" + site] = jnp.zeros(
                (self.size, L, d_in, self.rank), self.dtype)
            self.pool["b_" + site] = jnp.zeros(
                (self.size, L, self.rank, d_out), self.dtype)
        self._slot_name = [""] * self.size      # "" = empty/base
        self._refs = [0] * self.size
        self._stamp = [0] * self.size           # LRU clock
        self._clock = 0
        self.hits = 0
        self.evictions = 0
        self.swap_ins = 0
        self.acquire_fails = 0

    def _validate_dims(self, name: str, ad: Dict) -> None:
        L = self.cfg.n_layer
        for site, (d_in, d_out) in self.site_dims.items():
            wa, wb = ad["a_" + site].shape, ad["b_" + site].shape
            if wa != (L, d_in, self.rank) or wb != (L, self.rank, d_out):
                raise ValueError(
                    "adapter %r site %s has shapes %s/%s, engine "
                    "geometry wants %s/%s"
                    % (name, site, wa, wb, (L, d_in, self.rank),
                       (L, self.rank, d_out)))

    # ------------------------------------------------------ residency
    def slot_of(self, name: str) -> int:
        """Resident slot of ``name`` (0 = base, -1 = not resident)."""
        if not name:
            return 0
        try:
            return self._slot_name.index(name)
        except ValueError:
            return -1

    def _evictable(self) -> int:
        """LRU slot that can take a new page (empty first, then the
        least-recently-used unreferenced resident); -1 if every slot
        is pinned."""
        best, best_stamp = -1, None
        for s in range(1, self.size):
            if self._refs[s] > 0:
                continue
            if not self._slot_name[s]:
                return s
            if best_stamp is None or self._stamp[s] < best_stamp:
                best, best_stamp = s, self._stamp[s]
        return best

    def can_acquire(self, name: str) -> bool:
        """Would :meth:`acquire` succeed right now? (The scheduler's
        admission check — a queued request waits, never faults.)"""
        if not name:
            return True
        if name not in self._host:
            return False
        return self.slot_of(name) >= 0 or self._evictable() >= 0

    def headroom(self) -> int:
        """Unreferenced pool slots. The server's admission pass budgets
        one against every distinct adapter name it pops that is not
        already pinned: the acquires run later in pop order, and any
        one of them may evict any unpinned slot — including one a
        later pop in the same batch wants as a hit — so headroom >=
        names-charged guarantees every acquire in the batch lands
        (a clobbered hit degrades to a swap-in, never a fault)."""
        return sum(1 for s in range(1, self.size) if self._refs[s] == 0)

    def pinned(self, name: str) -> bool:
        """Is ``name`` resident with live references? (Pinned pages
        cost the admission pass no headroom — another request for the
        same adapter is a free hit on the already-held slot.)"""
        s = self.slot_of(name)
        return s > 0 and self._refs[s] > 0

    def acquire(self, name: str) -> int:
        """Pin ``name``'s page and return its pool slot; swaps the
        adapter in first when non-resident (crc-verified). Raises
        ``KeyError`` for an unregistered name and ``RuntimeError``
        when every slot is pinned (callers gate on can_acquire)."""
        if not name:
            return 0
        if name not in self._host:
            raise KeyError("unknown LoRA adapter %r" % name)
        self._clock += 1
        slot = self.slot_of(name)
        if slot >= 0:
            self.hits += 1
            self._refs[slot] += 1
            self._stamp[slot] = self._clock
            return slot
        slot = self._evictable()
        if slot < 0:
            self.acquire_fails += 1
            raise RuntimeError(
                "adapter pool exhausted: all %d slots pinned "
                "(raise serve_lora_pool_mb)" % (self.size - 1))
        if self._slot_name[slot]:
            self.evictions += 1
        self._swap_in(slot, name)
        self._slot_name[slot] = name
        self._refs[slot] = 1
        self._stamp[slot] = self._clock
        return slot

    def release(self, name: str) -> None:
        """Unpin one reference; the page stays resident until evicted
        (the next acquire is a free hit — the whole point of paging)."""
        if not name:
            return
        slot = self.slot_of(name)
        if slot > 0 and self._refs[slot] > 0:
            self._refs[slot] -= 1

    def _swap_in(self, slot: int, name: str) -> None:
        from .resilience import SwapCorruptionError
        import jax.numpy as jnp

        ad = self._host[name]
        if adapter_checksum(ad) != self._crc[name]:
            raise SwapCorruptionError(
                "adapter %r host pages failed their load-time crc32; "
                "swapping them in would serve corrupted weights" % name)
        s = float(ad["scale"])
        for site in LORA_SITES:
            a = jnp.asarray(ad["a_" + site], self.dtype)
            b = jnp.asarray(ad["b_" + site] * s, self.dtype)
            self.pool["a_" + site] = \
                self.pool["a_" + site].at[slot].set(a)
            self.pool["b_" + site] = \
                self.pool["b_" + site].at[slot].set(b)
        self.swap_ins += 1

    # ------------------------------------------------------- plumbing
    def device_pool(self) -> Dict:
        """The traced pool operand of the armed serve programs."""
        return dict(self.pool)

    def abstract_pool(self) -> Dict:
        """ShapeDtypeStruct mirror for the abstract lint/AOT specs."""
        import jax

        return {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                for k, v in self.pool.items()}

    @property
    def sig(self) -> str:
        """RecompileGuard / AOT-key suffix: mixed-adapter traffic is
        ONE signature — ids are traced, only (rank, pool) are static."""
        return "/lora=r%d/pool=%d" % (self.rank, self.size)

    def resident(self) -> int:
        return sum(1 for s in range(1, self.size) if self._slot_name[s])

    def refs_held(self) -> int:
        return sum(self._refs[1:])

    def check_refs(self, expected: int) -> None:
        """Audit hook (tests, scheduler consistency checks): the pinned
        reference count must equal the scheduler's live admissions."""
        held = self.refs_held()
        if held != expected:
            raise AssertionError(
                "adapter pool refcount audit: pool holds %d refs, "
                "scheduler accounts %d" % (held, expected))

    def metrics(self) -> Dict[str, float]:
        return {"hits": self.hits, "evictions": self.evictions,
                "swap_ins": self.swap_ins,
                "acquire_fails": self.acquire_fails,
                "resident": self.resident(),
                "size": self.size, "rank": self.rank,
                "slot_bytes": self.slot_bytes}


__all__ = ["AdapterPool", "LORA_SITES", "lora_site_dims",
           "parse_lora_spec", "make_adapter", "save_adapter",
           "load_adapter", "adapter_checksum", "lora_delta"]
