"""Multi-tenant SLO policy: per-tenant quotas, priority classes, and
rate limits for the serving stack (doc/serving.md "Multi-tenant SLOs").

One server multiplexes many products over one block pool and one
admission queue; before this module every overload decision was
*global* — one FIFO, one headroom gate, one degradation ladder — so a
burst of best-effort traffic degraded paying tenants first-come-first-
served. This module makes tenancy a first-class scheduler dimension:

* :class:`TenantPolicy` — one tenant's contract: a **priority class**
  (``guaranteed`` > ``standard`` > ``best_effort``) that orders
  admission, preemption, and shedding; a **queue quota** (max requests
  resident in the admission queue); a **slot quota** (max concurrently
  admitted scheduler slots); a **KV-block quota** (absolute blocks or a
  ``%`` of the usable pool, charged at admission); a **token-bucket
  rate limit** (``qps`` + ``burst``) whose rejections carry a
  ``retry_after_ms`` computed from the bucket's refill time; and a
  **default deadline** applied to requests that submit without one.

* :class:`TenantRegistry` — the parsed ``serve_tenants`` spec plus an
  untenanted ``default`` policy (standard priority, no quotas), so a
  request with no — or an unknown — tenant label is still governed.
  **An empty spec yields no registry at all**: ``serve_tenants`` unset
  is a pinned no-op (the scheduler and server skip every tenancy
  branch; existing suites are bit-identical).

Spec grammar (tenants separated by ``;``, fields by ``,``)::

    serve_tenants = gold:prio=G,blocks=40%,qps=50;free:prio=B,queue=4

    name:field=value,...      one tenant's policy
    prio=G|S|B                guaranteed | standard | best_effort
                              (full names accepted)
    blocks=N | blocks=P%      KV-block quota: absolute, or percent of
                              the usable pool
    qps=R [,burst=N]          token-bucket rate limit (burst defaults
                              to max(1, ceil(R)))
    queue=N                   max queued (not yet admitted) requests
    slots=N                   max concurrently admitted slots
    timeout_ms=X              default queue deadline for the tenant's
                              requests (a request's own timeout wins)

A tenant literally named ``default`` REPLACES the untenanted policy —
how an operator assigns a class/quota to unlabeled traffic.

Enforcement sites: rate + queue quotas at ``InferenceServer.submit``
(typed ``QuotaExceededError``); slot + block quotas inside the
scheduler pass (a tenant at quota is *skipped*, never blocking peers
behind it in the queue); the preemption victim order and rung-3/4
shedding walk classes inverse-priority (serve/scheduler.py,
serve/server.py, serve/resilience.py).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Tuple

__all__ = ["TenantPolicy", "TenantRegistry", "TokenBucket",
           "PRIORITIES", "PRIORITY_RANK", "DEFAULT_TENANT"]

# priority classes, best first; rank orders preemption/shedding — a
# HIGHER rank is sacrificed first (best_effort before standard before
# guaranteed)
PRIORITIES = ("guaranteed", "standard", "best_effort")
PRIORITY_RANK = {p: i for i, p in enumerate(PRIORITIES)}

DEFAULT_TENANT = "default"

_PRIO_ALIASES = {
    "g": "guaranteed", "guaranteed": "guaranteed",
    "s": "standard", "standard": "standard",
    "b": "best_effort", "be": "best_effort",
    "best_effort": "best_effort", "besteffort": "best_effort",
}


class TokenBucket:
    """Deterministic token bucket: ``rate`` tokens/sec refill up to
    ``burst`` capacity; one token per admitted request. The caller
    supplies ``now`` (seconds, any monotonic clock), which makes the
    bucket exactly reproducible on a fake clock — the property the
    rate-limit tests pin. ``rate <= 0`` admits everything."""

    def __init__(self, rate: float, burst: float = 0.0):
        self.rate = float(rate)
        self.burst = float(burst) if burst > 0 else max(1.0,
                                                        math.ceil(rate))
        self.tokens = self.burst
        self._t: Optional[float] = None

    def take(self, now: float) -> Tuple[bool, float]:
        """Try to take one token at time ``now``. Returns
        ``(admitted, retry_after_ms)``: on rejection the hint is the
        exact refill time until one whole token is available — the
        honest back-off, not a guess."""
        if self.rate <= 0:
            return True, 0.0
        if self._t is not None and now > self._t:
            self.tokens = min(self.burst,
                              self.tokens + (now - self._t) * self.rate)
        self._t = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True, 0.0
        return False, (1.0 - self.tokens) / self.rate * 1e3


@dataclasses.dataclass
class TenantPolicy:
    """One tenant's SLO contract (module docstring). Zero means
    'unlimited' for every quota field."""
    name: str
    priority: str = "standard"
    queue: int = 0              # max queued (unadmitted) requests
    slots: int = 0              # max concurrently admitted slots
    blocks: float = 0.0         # KV-block quota (absolute count)
    blocks_frac: float = 0.0    # ...or a fraction of the usable pool
    qps: float = 0.0            # token-bucket rate (requests/sec)
    burst: float = 0.0          # bucket capacity (0 = auto)
    timeout_ms: float = 0.0     # default queue deadline

    def __post_init__(self):
        if self.priority not in PRIORITIES:
            raise ValueError("tenant %r: priority must be one of %s, "
                             "got %r" % (self.name, "/".join(PRIORITIES),
                                         self.priority))

    @property
    def rank(self) -> int:
        """Sacrifice order: higher rank is preempted/shed first."""
        return PRIORITY_RANK[self.priority]

    def block_limit(self, usable: int) -> int:
        """The tenant's block quota against a pool of ``usable``
        allocatable blocks (0 = unlimited)."""
        if self.blocks_frac > 0:
            return max(1, int(self.blocks_frac * usable))
        return int(self.blocks)


def _parse_policy(item: str) -> TenantPolicy:
    name, sep, body = item.partition(":")
    name = name.strip()
    if not sep or not name:
        raise ValueError("serve_tenants: malformed tenant %r (want "
                         "name:field=value,...)" % item)
    kw: Dict[str, object] = {}
    for field in body.split(","):
        field = field.strip()
        if not field:
            continue
        key, fsep, val = field.partition("=")
        key = key.strip().lower()
        val = val.strip()
        if not fsep:
            raise ValueError("serve_tenants: tenant %r: malformed "
                             "field %r (want key=value)" % (name, field))
        if key in ("prio", "priority"):
            prio = _PRIO_ALIASES.get(val.lower())
            if prio is None:
                raise ValueError(
                    "serve_tenants: tenant %r: unknown priority %r "
                    "(want G/S/B or %s)" % (name, val,
                                            "/".join(PRIORITIES)))
            kw["priority"] = prio
        elif key == "blocks":
            if val.endswith("%"):
                frac = float(val[:-1]) / 100.0
                if not 0.0 < frac <= 1.0:
                    raise ValueError("serve_tenants: tenant %r: blocks "
                                     "percent must be in (0, 100], got "
                                     "%r" % (name, val))
                kw["blocks_frac"] = frac
            else:
                kw["blocks"] = float(val)
        elif key == "qps":
            kw["qps"] = float(val)
        elif key == "burst":
            kw["burst"] = float(val)
        elif key == "queue":
            kw["queue"] = int(val)
        elif key == "slots":
            kw["slots"] = int(val)
        elif key == "timeout_ms":
            kw["timeout_ms"] = float(val)
        else:
            raise ValueError("serve_tenants: tenant %r: unknown field "
                             "%r (fields: prio, blocks, qps, burst, "
                             "queue, slots, timeout_ms)" % (name, key))
    return TenantPolicy(name=name, **kw)


class TenantRegistry:
    """The parsed tenant catalog + per-tenant token buckets. Requests
    whose tenant label matches no policy resolve to the ``default``
    policy (standard priority, no quotas, unless the spec overrides the
    ``default`` tenant explicitly). Bucket state is guarded by the
    server's admission lock — the registry itself adds none."""

    def __init__(self, policies: List[TenantPolicy]):
        self.policies: Dict[str, TenantPolicy] = {}
        for pol in policies:
            if pol.name in self.policies:
                raise ValueError("serve_tenants: duplicate tenant %r"
                                 % pol.name)
            self.policies[pol.name] = pol
        if DEFAULT_TENANT not in self.policies:
            self.policies[DEFAULT_TENANT] = TenantPolicy(DEFAULT_TENANT)
        self._buckets = {name: TokenBucket(p.qps, p.burst)
                         for name, p in self.policies.items()}

    @classmethod
    def from_spec(cls, spec) -> Optional["TenantRegistry"]:
        """Parse a ``serve_tenants`` spec; empty -> None (tenancy fully
        off costs nothing — no object, no checks beyond ``is not
        None``). A TenantRegistry instance passes through."""
        if isinstance(spec, TenantRegistry):
            return spec
        spec = (spec or "").strip()
        if not spec:
            return None
        return cls([_parse_policy(item) for item in spec.split(";")
                    if item.strip()])

    # ------------------------------------------------------------ lookup
    def policy_for(self, name: str) -> TenantPolicy:
        return self.policies.get(name or DEFAULT_TENANT,
                                 self.policies[DEFAULT_TENANT])

    def resolve(self, name: str) -> str:
        """The label value a request carries: its own tenant name when
        registered, else ``default`` — so metric labels and scheduler
        accounting never key on unknown strings."""
        return self.policy_for(name).name

    def rank_of(self, name: str) -> int:
        return self.policy_for(name).rank

    def class_of(self, name: str) -> str:
        return self.policy_for(name).priority

    def take(self, name: str, now: float) -> Tuple[bool, float]:
        """One token-bucket roll for ``name``'s resolved policy (caller
        holds the admission lock)."""
        return self._buckets[self.resolve(name)].take(now)

    def label_names(self) -> List[str]:
        """Every label value this registry can emit, sorted — the
        stable metric catalog (pre-touched at registration)."""
        return sorted(self.policies)
