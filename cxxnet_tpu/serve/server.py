"""InferenceServer: the online serving front door.

``submit(prompt, params) -> handle`` / ``result(handle)`` over a bounded
admission queue, with a dedicated scheduler thread driving the
continuous-batching loop (serve/scheduler.py) against the decode engine
(serve/engine.py) — by default the PAGED engine: a global KV block pool
with per-row block tables, zero-copy copy-on-write prefix sharing, and
preemption/swap of rows to host memory under pool pressure, so admitted
concurrency scales with tokens in flight instead of being hard-capped
at ``slots * seq_len`` worth of dense rows (doc/serving.md "Paged KV
cache"; ``paged=False`` restores the dense pool). Prefill runs CHUNKED
by default
(``prefill_chunk`` tokens per jitted step, at most ``prefill_budget``
chunks interleaved with each decode tick) with shared-prefix KV reuse
(serve/prefix_cache.py, ``prefix_mb`` byte budget); ``prefill_chunk=0``
selects the legacy whole-prompt admit. Backpressure is explicit: a full
queue rejects at submit time with a reason (``QueueFullError``) instead
of buffering unboundedly — the caller decides whether to retry, shed, or
block (``block=True``, what the CLI's stdin loop uses).

Observability (doc/observability.md): per-request TTFT / per-token
latency and the scheduler's prefill / decode_tick / queue_wait phases
(utils/profiler.py) are summarized as p50/p95/p99 by :meth:`metrics`,
alongside queue-depth, slot-occupancy and batch-efficiency gauges. The
same signals feed the unified obs registry — :meth:`metrics_text` is
the Prometheus exposition — and every request's lifecycle is recorded
as a span tree in the obs tracer (queue_wait -> prefix_restore ->
prefill chunks -> decode -> spec verifies -> retire), exportable as
Chrome-trace JSON; ``slow_ms`` auto-dumps the tree of any request that
crosses the latency threshold.

Shutdown: ``shutdown(drain=True)`` stops admissions, finishes every
queued + in-flight request, then joins the thread and drops the caches;
``drain=False`` cancels queued and in-flight work first. Either way no
slot stays occupied and no thread outlives the call (pinned by test and
by the suite-wide thread-leak fixture — the thread is named
``cxn-serve-scheduler-*`` so tests/conftest.py can see it).
"""

from __future__ import annotations

import collections
import itertools
import os
import threading
import time
from dataclasses import dataclass, replace
from typing import Dict, List, Optional

import numpy as np

from ..analysis.concurrency import make_condition, make_rlock
from ..obs import devprof
from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from ..obs.trace import TID_CONTROL, TID_ENGINE
from ..utils import profiler
from .engine import DecodeEngine
from .resilience import (STATE_CODES, STATE_DEGRADED, STATE_DRAINING,
                         STATE_FAILED, STATE_SERVING, DegradationLadder,
                         EngineFailedError, FaultInjector, InjectedFault,
                         ReplayJournal, SupersededError, reset_for_replay)
from .scheduler import Request, SamplingParams, SlotScheduler
from .tenancy import DEFAULT_TENANT, TenantRegistry

__all__ = ["InferenceServer", "ServeResult", "AdmissionError",
           "QueueFullError", "QuotaExceededError", "EngineFailedError"]

# monotonic scheduler counters that survive an engine rebuild: recovery
# replaces the SlotScheduler, but the obs registry's callback counters
# must never go backwards (serve/resilience.py)
_SCHED_CARRY = ("ticks", "active_row_ticks", "tokens_generated",
                "prefill_chunks", "requests_prefilled", "spec_forwards",
                "spec_drafted", "spec_accepted", "spec_emitted",
                "spec_rollbacks", "spec_backoffs", "swaps_out",
                "swaps_in", "swap_corruptions", "drafter_faults",
                "prefix_restore_faults", "replay_mismatches",
                "migrations_out", "migrations_in")

_server_seq = itertools.count()
# rids are PROCESS-unique, not per-server: the span tracer keys request
# tracks by rid (obs/trace.py request_tid), and the default tracer is
# the process-global one whose ring outlives any single server — a
# per-server counter would land two servers' (or a restarted server's)
# different requests on the same exported track and corrupt slow-request
# exemplars
_rid_seq = itertools.count()


class AdmissionError(RuntimeError):
    """A request the server refused to accept; ``reason`` says why."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


class QueueFullError(AdmissionError):
    """Backpressure: the bounded admission queue is at capacity (or the
    degradation ladder shed the request at the door). ``retry_after_ms``
    > 0 is the server's back-off hint — the estimated time for the
    current backlog to drain enough to admit a retry."""

    def __init__(self, reason: str, retry_after_ms: float = 0.0):
        if retry_after_ms > 0:
            reason += " (retry_after_ms=%d)" % int(retry_after_ms)
        super().__init__(reason)
        self.retry_after_ms = float(retry_after_ms)


class QuotaExceededError(QueueFullError):
    """A tenant-quota rejection (serve/tenancy.py): the request's
    TENANT is over its rate limit or queue quota — the server itself
    has capacity. Distinct from plain :class:`QueueFullError` so the
    router spills the request to a peer replica (per-replica quota
    state) instead of treating the whole fleet as saturated, and so
    callers can back off ONE tenant's traffic without throttling the
    rest. ``tenant`` is the resolved policy name, ``kind`` the quota
    that fired (``rate`` | ``queue`` | ``blocks``)."""

    def __init__(self, reason: str, retry_after_ms: float = 0.0,
                 tenant: str = "", kind: str = ""):
        super().__init__(reason, retry_after_ms)
        self.tenant = tenant
        self.kind = kind


@dataclass
class ServeResult:
    """Terminal state of one request. ``tokens`` is the FULL sequence
    (prompt + generated), matching ``gpt_decode``'s return layout;
    empty for non-ok statuses. Statuses: ``ok`` | ``timeout`` |
    ``cancelled`` | ``shed`` (degradation-ladder load shedding —
    ``retry_after_ms`` carries the back-off hint) | ``error`` (typed
    failure: replay divergence, swap corruption with no replay hook, or
    a permanently-failed engine — serve/resilience.py)."""
    status: str
    tokens: np.ndarray
    error: str = ""
    ttft_ms: float = 0.0            # submit -> first token (incl. queue)
    ms_per_token: float = 0.0       # mean inter-token gap after the first
    queue_ms: float = 0.0           # submit -> admit
    retry_after_ms: float = 0.0     # shed/rejected: back-off hint


class InferenceServer:
    """Slot-based continuous-batching server over the GPT decode path.

    ``cfg``/``params`` are the models/gpt.py config + parameter tree (a
    config-DSL Net serves through ``nnet.lm.net_gpt_export`` — that is
    what ``task=serve`` and ``wrapper.Net.serve_start`` do).
    """

    def __init__(self, cfg, params, *, slots: int = 8, queue: int = 32,
                 timeout_ms: float = 0.0,
                 defaults: Optional[SamplingParams] = None,
                 prefill_chunk: int = 64, prefill_budget: int = 1,
                 prefix_mb: float = 32.0, recompile_limit: int = 0,
                 recompile_strict: bool = True, spec_mode: str = "off",
                 spec_len: int = 4, spec_model=None, tracer=None,
                 registry=None, slow_ms: float = 0.0,
                 prof_every: int = 0, paged: bool = True,
                 block_size: int = 0, num_blocks: int = 0,
                 kv_mb: float = 0.0, fused_attn: bool = True,
                 chaos: str = "", max_restarts: int = 3,
                 watchdog_ms: float = 0.0, degrade: bool = True,
                 tp: int = 0, mesh=None, tenants: str = "",
                 int8_weights: bool = False, int4_weights: bool = False,
                 int4_group: int = 64, kv_dtype: str = "",
                 aot_cache: str = "", lora: str = "",
                 lora_rank: int = 8, lora_pool_mb: float = 0.0,
                 lora_adapters=None):
        """``prefill_chunk``: chunked-prefill unit in tokens (0 = the
        legacy whole-prompt prefill, one compiled program per prompt
        length); ``prefill_budget``: max chunk steps interleaved with
        each decode tick; ``prefix_mb``: shared-prefix KV cache byte
        budget in MiB (0 disables reuse; only active with chunking);
        ``recompile_limit``: cap on distinct compiled prefill/chunk AND
        verify signatures (0 = uncounted; see analysis/recompile.py).

        Speculative decoding (serve/speculative.py): ``spec_mode``
        selects the draft source — ``"off"`` (default; a true no-op on
        the serve path), ``"ngram"`` (host-side prompt lookup), or
        ``"model"`` (a small draft model, ``spec_model=(draft_cfg,
        draft_params)``, which also makes the ngram drafter available
        for per-request overrides); ``spec_len`` is the verify window
        (max draft tokens per forward, one compiled verify signature
        server-wide). Greedy speculative output is bit-identical to the
        non-speculative path; sampled output is identical in
        distribution (doc/serving.md).

        Observability (doc/observability.md): ``tracer`` is the span
        recorder — None uses the process-global
        ``obs.trace.get_tracer()`` (on by default, ring-bounded); pass
        a private Tracer for isolation or one with ``enabled=False``
        to opt out. ``registry`` is the obs metrics registry — None
        gives this server its OWN Registry (two servers' gauges must
        not fight over one name); :meth:`metrics_text` exposes it as
        Prometheus text. ``slow_ms`` > 0 arms the slow-request
        exemplar hook: any request whose TTFT or total latency exceeds
        it has its span tree auto-dumped (``Tracer.note_slow``).
        Paged KV cache (the default; doc/serving.md "Paged KV cache"):
        ``paged=True`` with chunking replaces the dense slot pool by a
        global block pool + per-row block tables — occupancy scales
        with tokens in flight, prefix sharing is zero-copy
        (copy-on-write protected), and under pool pressure the
        scheduler preempts rows to a host swap buffer and resumes them
        bit-identically. ``block_size`` is the block's token width
        (0 = the prefill chunk; must divide it; -1 = ``auto``: load
        the persisted ``task=autotune`` winner for this device kind +
        model geometry from the AOT cache, falling back to the chunk
        default when none exists — engine.resolve_block_size),
        ``num_blocks`` the pool size (0 = auto: dense-equivalent
        ``slots`` rows plus trie headroom, or ``kv_mb`` MiB when given
        — the explicit budget wins over the formula). ``paged=False`` or
        ``prefill_chunk=0`` keeps the dense pool (one row per slot —
        still the better layout when every request runs near seq_len).
        ``fused_attn`` (paged only, default on): route the tick/verify
        attention reads through the fused Pallas block-table-walk
        kernel wherever ``ops.pallas_kernels.paged_attention_supported``
        holds — it auto-resolves off on unsupported backends (the CPU
        test mesh) and geometries, and ``serve_fused_attn=0`` /
        ``CXN_FUSED_ATTN=0`` force the XLA gather formulation (the
        bit-reference path; doc/serving.md "Fused paged attention").

        ``prof_every`` > 0 arms the device/compiler observatory
        (obs/devprof.py): the engine's per-program cost table is
        extracted once at construction (AOT, no execution) and ONE
        blocking device-time sample is taken every ``prof_every``
        executions of each program, publishing ``cxn_program_*`` /
        ``cxn_mfu`` / ``cxn_achieved_bw_frac`` gauges; 0 (default)
        leaves the hot path entirely untouched. The device-memory
        ledger (``cxn_device_bytes{pool=}``) and compile-time
        accounting (``cxn_compile_seconds{fn=}``) are always on — both
        are collection-time callbacks with zero steady-state cost.

        Resilience (serve/resilience.py, doc/serving.md "Resilience"):
        an engine-fatal fault (a tick/prefill/swap raising, or — with
        ``watchdog_ms`` > 0 — the loop stalling that long) tears the
        pool down, rebuilds the engine COLD, and replays every admitted
        request from its journal record through the normal admit path,
        already-emitted tokens verified bit-identical as they
        regenerate; ``max_restarts`` bounds the rebuilds (beyond it
        in-flight requests fail with a typed
        :class:`~cxxnet_tpu.serve.resilience.EngineFailedError` and
        further submits raise it). ``chaos`` arms the
        :class:`~cxxnet_tpu.serve.resilience.FaultInjector` (grammar in
        resilience.py; the ``CXN_CHAOS`` env var overrides); empty =
        true no-op. ``degrade`` enables the graceful-degradation
        ladder: under sustained overload it disables speculation, then
        prefix-cache admission, then sheds deadline-doomed queued
        requests with ``retry_after_ms`` hints; :meth:`health` and the
        ``cxn_serve_state`` gauge surface SERVING / DEGRADED /
        DRAINING / FAILED.

        Multi-tenant SLOs (serve/tenancy.py, doc/serving.md
        "Multi-tenant SLOs"): ``tenants`` is the ``serve_tenants``
        policy spec (or a pre-built TenantRegistry) — per-tenant
        priority classes, queue/slot/KV-block quotas, token-bucket
        rate limits with honest ``retry_after_ms`` refill hints, and
        default deadlines. Armed, requests carry a ``tenant=`` label
        through submit; admission enforces rate + queue quotas with
        typed :class:`QuotaExceededError`; the scheduler admits by
        (priority class, arrival), skips at-quota tenants without
        blocking peers, and preempts best-effort rows first; the
        degradation ladder sheds classes inverse-priority and gains an
        emergency rung 4 (guaranteed sheddable) reachable only under
        protected-class pressure; request counters/histograms gain a
        ``tenant=`` label. Unset (the default) is a pinned no-op —
        the whole layer is skipped and every surface is bit-identical
        to the untenanted server.

        Quantized serving (doc/serving.md "Quantized serving"):
        ``int8_weights`` quantizes the block matmul weights once at
        engine build (per-out-column symmetric int8, the offline
        decode's exact scheme) and streams them through chunk prefill,
        tick AND the speculative verify — halving the weight traffic
        the decode step is bound by. ``kv_dtype="int8"`` (paged only)
        stores the KV block pool per-block-scaled int8 — ``(values,
        scales)`` pairs, quantize-on-scatter / dequantize-on-gather —
        so ``kv_blocks``, the prefix trie's shared blocks, and
        ``swap_host`` all hold ~2x tokens per MiB and swap bandwidth
        halves (checksums verify the quantized round trip bit-exactly).
        Accuracy is pinned by ``serve.engine.kv_int8_tolerance``; both
        default OFF and are pinned no-ops there. ``int4_weights``
        (doc/serving.md "Int4 weights") packs the fused block weights
        to two nibbles per byte with group-wise symmetric scales
        (``int4_group`` in-rows per scale group, 0 = one scale per out
        column) and streams them through every serve program via the
        fused Pallas dequant-matmul where supported — ~4x weight bytes
        vs bf16, accuracy pinned by ``serve.engine.w_int4_tolerance``;
        mutually exclusive with ``int8_weights``.

        Tensor-parallel serving (doc/serving.md "Sharded & replicated
        serving"): ``tp`` > 1 builds a ``model``-axis mesh over the
        first ``tp`` local devices and shards the decode engine across
        it — weights on their output dims, the KV pool on the head
        axis, served tokens bit-identical to the single-device engine
        (gather-form TP, serve/engine.py module docstring). Requires
        chunked prefill and ``n_head`` divisible by ``tp``; the fused
        paged-attention kernel resolves to the gather fallback under
        TP. Pass ``mesh`` to serve over an explicit pre-built mesh
        instead (``tp`` is then ignored).

        AOT executable cache (doc/performance.md "AOT executable
        cache"): ``aot_cache`` is a directory (or the ``CXN_AOT_CACHE``
        env var; the explicit parameter wins) holding serialized
        compiled serve programs. At build — and on every
        watchdog/fault ``_build_stack()`` rebuild — the engine's
        prefill-chunk / verify / tick executables are LOADED from it
        when their full key matches (zero XLA compilation, sub-second
        cold start; the ``cxn_aot_cache_*`` counters and ``aot_load``
        spans witness it) and compiled-then-persisted otherwise. A
        corrupt entry or an unwritable directory degrades to compiling
        with one logged warning. Unset (the default) is a pinned
        no-op.

        Batched multi-LoRA (serve/lora.py, doc/serving.md "Batched
        multi-LoRA"): ``lora`` is the ``serve_lora`` adapter registry
        spec (``name:path;...``); armed, every request may name an
        adapter (``submit(..., adapter=...)``) and ONE batched tick
        serves the whole mixed population — per-request adapter ids are
        a traced operand, so mixed traffic is a single compiled
        signature. The adapter population is paged: a fixed device pool
        of factor slots (``lora_pool_mb`` MiB budget, 0 = size for the
        whole registry), refcounted by admissions, LRU-evicted,
        crc-verified at swap-in; admission defers a request whose
        adapter cannot get a slot without blocking peers. Requires the
        paged engine; ``lora_rank`` must match the adapter files;
        ``lora_adapters`` optionally injects in-memory adapter dicts
        (tests/bench) instead of loading the registry paths. Unset (the
        default) is a pinned STRUCTURAL no-op — the serve programs
        carry no adapter operand and their jaxprs are unchanged."""
        if queue < 1:
            raise ValueError("serve_queue must be >= 1, got %d" % queue)
        if prefill_budget < 1:
            raise ValueError("serve_prefill_budget must be >= 1, got %d"
                             % prefill_budget)
        if spec_mode not in ("off", "ngram", "model"):
            raise ValueError("spec_mode must be 'off', 'ngram' or "
                             "'model', got %r" % (spec_mode,))
        if spec_mode != "off" and spec_len < 1:
            raise ValueError("spec_len must be >= 1 with spec_mode=%s, "
                             "got %d" % (spec_mode, spec_len))
        if spec_mode == "model" and spec_model is None:
            raise ValueError("spec_mode='model' needs spec_model="
                             "(draft_cfg, draft_params)")
        if max_restarts < 0:
            raise ValueError("serve_max_restarts must be >= 0, got %d"
                             % max_restarts)
        if watchdog_ms < 0:
            raise ValueError("serve_watchdog_ms must be >= 0, got %g"
                             % watchdog_ms)
        self._defaults = defaults or SamplingParams()
        if timeout_ms and not self._defaults.timeout_ms:
            self._defaults = replace(self._defaults, timeout_ms=timeout_ms)
        self._tracer = tracer if tracer is not None \
            else obs_trace.get_tracer()
        self._registry = registry if registry is not None \
            else obs_metrics.Registry()
        self._slow_ms = float(slow_ms)
        self._paged = bool(paged) and prefill_chunk > 0
        if lora:
            if not self._paged:
                raise ValueError(
                    "serve_lora requires the paged engine (serve_paged=1 "
                    "with chunked prefill)")
            if int(lora_rank) < 1:
                raise ValueError("serve_lora_rank must be >= 1, got %d"
                                 % lora_rank)
        # resilience state (serve/resilience.py): the chaos injector
        # (CXN_CHAOS env wins over the config spec — the operator's
        # override), the replay journal, the degradation ladder, and
        # the supervisor's restart accounting. `_gen` is the loop
        # generation: the watchdog bumps it when it abandons a hung
        # scheduler thread and starts a fresh one — the abandoned
        # thread sees the mismatch and unwinds without touching state.
        self._inj = FaultInjector.from_spec(
            os.environ.get("CXN_CHAOS", "") or chaos)
        self._max_restarts = int(max_restarts)
        self._watchdog_ms = float(watchdog_ms)
        self._journal = ReplayJournal()
        # multi-tenant SLOs (serve/tenancy.py): None when serve_tenants
        # is unset — the pinned no-op; armed, the ladder gains the
        # emergency rung (guaranteed sheddable only under
        # protected-class pressure)
        self._tenancy = TenantRegistry.from_spec(tenants)
        self._ladder = DegradationLadder(
            enabled=bool(degrade),
            max_rung=(DegradationLadder.EMERGENCY_RUNG
                      if self._tenancy is not None else 0))
        self._restarts = 0
        self._replayed = 0              # guarded_by: self._cond
        self._reserve_stalls = 0
        self._lora_defers = 0           # pops deferred on pool headroom
        self._failed: Optional[EngineFailedError] = None
        self._ema_req_s = 0.0           # EMA of admit->done, feeds the
        #                                 retry_after_ms / shed estimates
        self._gen = 0
        self._recover_lock = make_rlock("InferenceServer._recover_lock")
        self._heartbeat = time.perf_counter()
        # loop idle-parked (watchdog skips)
        self._parked = False            # guarded_by: self._cond
        if mesh is None and tp and int(tp) > 1:
            import jax as _jax

            from ..parallel.mesh import make_mesh
            devs = _jax.devices()
            if len(devs) < int(tp):
                raise ValueError(
                    "serve_tp=%d needs %d devices, found %d (on CPU, "
                    "set XLA_FLAGS=--xla_force_host_platform_device_"
                    "count=%d before jax initializes)"
                    % (tp, tp, len(devs), tp))
            mesh = make_mesh(devices=devs[:int(tp)],
                             model_parallel=int(tp))
        from .engine import serve_tp_size
        self._tp = serve_tp_size(mesh)
        nb = 0
        if self._paged and int(block_size) < 0:
            # serve_block_size=auto (-1): resolve through the persisted
            # geometry-autotune winner BEFORE the pool is sized — the
            # tuned block width changes block_bytes and with it every
            # auto_num_blocks budget below
            from .engine import resolve_block_size, weight_stream_tag
            block_size = resolve_block_size(
                cfg, prefill_chunk, block_size, kv_dtype=kv_dtype,
                tp=self._tp,
                aot=(str(aot_cache or "")
                     or os.environ.get("CXN_AOT_CACHE", "") or None),
                weights=weight_stream_tag(bool(int8_weights),
                                          bool(int4_weights),
                                          int(int4_group)))
        if self._paged:
            from .engine import auto_num_blocks
            # auto-sizing is dtype-aware: the same serve_kv_mb budget
            # buys ~2x the blocks under serve_kv_dtype=int8 (the
            # quantized block itemsize — doc/serving.md "Quantized
            # serving")
            nb = int(num_blocks) if num_blocks > 0 else auto_num_blocks(
                cfg, slots, prefill_chunk, block_size=block_size,
                prefix_mb=prefix_mb, kv_mb=kv_mb, kv_dtype=kv_dtype)
        # everything the recovery supervisor needs to rebuild the
        # device-facing stack from scratch (engine, prefix cache,
        # drafters, scheduler) — _build_stack() reads only this
        self._build = dict(
            cfg=cfg, params=params, slots=slots,
            prefill_chunk=prefill_chunk, recompile_limit=recompile_limit,
            recompile_strict=recompile_strict, spec_mode=spec_mode,
            spec_len=spec_len, spec_model=spec_model, prefix_mb=prefix_mb,
            nb=nb, block_size=block_size, prof_every=prof_every,
            fused_attn=bool(fused_attn), mesh=mesh,
            int8_weights=bool(int8_weights),
            int4_weights=bool(int4_weights), int4_group=int(int4_group),
            kv_dtype=kv_dtype, lora=str(lora), lora_rank=int(lora_rank),
            lora_pool_mb=float(lora_pool_mb),
            lora_adapters=lora_adapters)
        self._prefill_budget = int(prefill_budget)
        # device/compiler observatory (obs/devprof.py): compile-time
        # accounting always (this registry becomes a CompileWatch sink,
        # so every compile the server triggers lands in
        # cxn_compile_seconds{fn=} + a `compile` span on the engine
        # track); the cost table + live MFU sampler only when armed —
        # extraction AOT-compiles every engine program once, which is
        # startup cost a prof_every=0 server must not pay
        devprof.compile_watch().add_sink(self._registry, self._tracer)
        # AOT executable cache (analysis/aot_cache.py): armed by the
        # aot_cache param or CXN_AOT_CACHE; every _build_stack() — the
        # first one AND every recovery rebuild — resolves the serve
        # programs through it (load on key hit, compile-and-persist on
        # miss), with hits/misses/stale/bytes counted in this server's
        # registry and aot_load spans on the engine trace track
        self._aot = None
        aot_path = str(aot_cache or "") or os.environ.get(
            "CXN_AOT_CACHE", "")
        if aot_path:
            from ..analysis.aot_cache import get_cache
            self._aot = get_cache(aot_path)
            self._aot.add_sink(self._registry, self._tracer)
        # StepStats feeds the registry (utils/profiler.py observer):
        # every phase sample lands in the mergeable per-phase histogram
        # as well as the StepStats percentile window
        self._phase_h = self._registry.histogram(
            "cxn_serve_phase_seconds",
            "per-phase scheduler durations (queue_wait, prefill_chunk, "
            "prefix_copy, decode_tick, spec_draft, spec_verify)",
            labelnames=("phase",))
        # every admitted request observes queue_wait, so the series must
        # exist (count 0) even before the first observation — overload
        # monitors alert on its absence, not just its value
        self._phase_h.labels(profiler.QUEUE_WAIT)
        self._stats = profiler.StepStats(
            observer=lambda name, s: self._phase_h.labels(name).observe(s))
        self._queue: collections.deque = collections.deque()  # guarded_by: self._cond
        self._queue_cap = queue
        # disaggregated fleet (serve/fleet.py): migration records
        # adopted from a prefill-tier worker, parked here by the RPC
        # thread (adopt_swapped) and drained onto the scheduler's
        # resume list at the top of each pass — the scheduler thread is
        # the only mutator of its own swap state
        self._adopted: collections.deque = collections.deque()  # guarded_by: self._cond
        self._cond = make_condition("InferenceServer._cond")
        self._rid = _rid_seq
        # no new submits
        self._closing = False           # guarded_by: self._cond
        self._drain = True              # finish queued work on shutdown?
        self._stopped = threading.Event()
        # counters + per-request latency samples for metrics(); the
        # sample reservoirs are bounded so a long-lived server's memory
        # does not grow with requests served (percentiles then describe
        # the most recent window)
        self._counts = {"submitted": 0, "completed": 0,  # guarded_by: self._cond
                        "rejected": 0, "timeout": 0, "cancelled": 0,
                        "expired": 0, "shed": 0, "error": 0}
        if self._tenancy is not None:
            # quota rejections only exist under tenancy; the key is
            # ADDED rather than unconditional so the untenanted
            # metrics() surface stays bit-identical
            self._counts["quota"] = 0
            self._tcounts = {t: dict.fromkeys(self._counts, 0)
                             for t in self._tenancy.label_names()}
        else:
            self._tcounts = None
        self._ttft_s: collections.deque = collections.deque(maxlen=4096)
        self._tok_gap_s: collections.deque = collections.deque(maxlen=4096)
        self._queue_depth_max = 0       # guarded_by: self._cond
        self._build_stack()
        self._register_obs()
        self._idx = next(_server_seq)
        self._watch_stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, args=(0,),
            name="cxn-serve-scheduler-%d" % self._idx, daemon=True)
        self._thread.start()
        self._watch_thread = None
        if self._watchdog_ms > 0:
            self._watch_thread = threading.Thread(
                target=self._watch,
                name="cxn-serve-watchdog-%d" % self._idx, daemon=True)
            self._watch_thread.start()

    def _build_stack(self) -> None:
        """Build — or, after an engine-fatal fault, REBUILD — the
        device-facing stack: engine, prefix cache, drafters, scheduler.
        Recovery restarts COLD by design (empty slots, free block pool,
        empty trie): correctness never depends on cache contents, only
        capacity and latency do, and a cold trie refills from the
        replayed traffic itself. The jitted programs are module-level
        lru caches keyed by config, so a rebuild reuses every compiled
        executable — teardown + rebuild is host bookkeeping plus one
        pool allocation, not a recompile. With the AOT executable cache
        armed the same holds ACROSS processes: a supervisor-restarted
        server (cold lru caches) re-resolves every program from disk
        instead of compiling (analysis/aot_cache.py)."""
        b = self._build
        cfg, slots, spec_mode = b["cfg"], b["slots"], b["spec_mode"]
        prefill_chunk, prefix_mb = b["prefill_chunk"], b["prefix_mb"]
        # LoRA adapter pool (serve/lora.py): rebuilt with the stack —
        # recovery restarts it COLD like the trie (empty device slots,
        # host pages reloaded + re-checksummed from the registry);
        # residency refills from the replayed admissions themselves
        self._lora_pool = None
        if b["lora"]:
            from .lora import AdapterPool, parse_lora_spec
            self._lora_pool = AdapterPool(
                cfg, parse_lora_spec(b["lora"]), rank=b["lora_rank"],
                pool_mb=b["lora_pool_mb"], adapters=b["lora_adapters"])
        self._engine = DecodeEngine(
            cfg, b["params"], slots, prefill_chunk=prefill_chunk,
            recompile_limit=b["recompile_limit"],
            recompile_strict=b["recompile_strict"],
            spec_len=b["spec_len"] if spec_mode != "off" else 0,
            obs_registry=self._registry,
            num_blocks=b["nb"],
            block_size=b["block_size"] if self._paged else 0,
            injector=self._inj, fused_attn=b["fused_attn"],
            mesh=b["mesh"], int8_weights=b["int8_weights"],
            int4_weights=b["int4_weights"], int4_group=b["int4_group"],
            kv_dtype=b["kv_dtype"], lora_pool=self._lora_pool,
            aot=self._aot, tracer=self._tracer)
        self._prefix = None
        if prefill_chunk > 0 and prefix_mb > 0:
            if self._paged:
                from .prefix_cache import PagedPrefixCache
                self._prefix = PagedPrefixCache(
                    self._engine, int(prefix_mb * (1 << 20)))
            else:
                from .prefix_cache import PrefixCache
                self._prefix = PrefixCache(self._engine,
                                           int(prefix_mb * (1 << 20)))
        self._drafters = {}
        if spec_mode != "off":
            from .speculative import ModelDrafter, NgramDrafter
            self._drafters["ngram"] = NgramDrafter(self._engine.spec_len)
            if spec_mode == "model":
                dcfg, dparams = b["spec_model"]
                self._drafters["model"] = ModelDrafter(
                    dcfg, dparams, slots, target_cfg=cfg)
        self._prof_sampler = None
        if b["prof_every"] > 0:
            table = devprof.profile_engine(self._engine,
                                           registry=self._registry)
            self._prof_sampler = devprof.LiveSampler(
                self._registry, cadence=b["prof_every"], table=table,
                tracer=self._tracer)
            self._engine.set_profiler(self._prof_sampler)
        self._sched = SlotScheduler(self._engine, self._stats,
                                    on_finish=self._record_done,
                                    prefix_cache=self._prefix,
                                    drafters=self._drafters,
                                    spec_mode=spec_mode,
                                    spec_len=self._engine.spec_len,
                                    tracer=self._tracer,
                                    injector=self._inj,
                                    on_swap_corrupt=self._replay_one,
                                    tenancy=self._tenancy)
        self._sched.prefix_admission = self._ladder.prefix_admission

    # ----------------------------------------------------------- tenancy
    def _class_of(self, req: Request) -> str:
        """The request's priority class; untenanted requests are
        ``standard``, which keeps every class-gated path (door shed,
        queue shed) bit-identical to the pre-tenancy server."""
        if self._tenancy is None:
            return "standard"
        return self._tenancy.class_of(req.tenant)

    def _bump(self, key: str, req: Optional[Request] = None,
              tenant: str = "") -> None:
        """Increment one request counter, mirrored into the tenant's
        row when tenancy is armed (caller holds the lock or runs on
        the scheduler thread, like every _counts mutation)."""
        self._counts[key] += 1
        if self._tcounts is not None:
            t = req.tenant if req is not None else \
                self._tenancy.resolve(tenant)
            self._tcounts.get(t, self._tcounts[DEFAULT_TENANT])[key] += 1

    def _hist(self, fam, req: Request):
        """The (tenant-labeled when armed) histogram child to observe
        a request's latency into."""
        return fam.labels(req.tenant) if self._tenancy is not None \
            else fam

    def _inc_shed(self, tenant: str) -> None:
        """Count one shed into the (rung[, tenant]) family."""
        if self._tenancy is None:
            self._shed_c.labels(str(self._ladder.rung)).inc()
        else:
            self._shed_c.labels(str(self._ladder.rung), tenant).inc()

    def _tenant_queued(self, tenant: str) -> int:
        """Queued (unadmitted) requests for one tenant — the queue-
        quota denominator and the per-tenant depth gauge."""
        with self._cond:
            return sum(1 for r in self._queue if r.tenant == tenant)

    def _class_queue_frac(self):
        """Per-class queue fractions for the tenant-aware ladder
        (None when untenanted)."""
        if self._tenancy is None:
            return None
        per = {c: 0 for c in ("guaranteed", "standard", "best_effort")}
        with self._cond:
            for r in self._queue:
                per[self._tenancy.class_of(r.tenant)] += 1
        return {c: n / float(self._queue_cap) for c, n in per.items()}

    # --------------------------------------------------------------- obs
    def _register_obs(self) -> None:
        """Register this server's metric catalog (doc/observability.md)
        in the registry. Counters that already exist as monotonic ints
        on the scheduler / prefix cache / request-count dict are
        exposed as CALLBACK counters (obs/metrics.py) — collection-time
        reads, zero added work on the increment paths; the latency
        histograms are real observations (submit/terminal paths only,
        never the tick loop)."""
        r = self._registry
        sc = self._sched
        # every callback-backed name is remembered so shutdown() can
        # freeze it to its terminal value (the registry must not keep
        # the dead server — engine params, KV pool — alive, nor report
        # its stale attributes as live)
        cb = self._obs_cb_names = []

        def cb_counter(name, help_, fn):
            cb.append(name)
            r.counter(name, help_, fn=fn)

        def cb_gauge(name, help_, fn):
            cb.append(name)
            r.gauge(name, help_, fn=fn)

        for key, help_ in (
                ("submitted", "requests accepted into the admission "
                              "queue"),
                ("completed", "requests finished ok"),
                ("rejected", "requests refused at admission "
                             "(bad params or queue full)"),
                ("timeout", "requests that reached a terminal timeout "
                            "(queue-deadline expiry included)"),
                ("expired", "requests whose queue deadline passed "
                            "before a slot freed (subset of timeout)"),
                ("cancelled", "requests cancelled by shutdown/abort"),
                ("error", "requests failed typed (replay divergence, "
                          "swap corruption, engine permanently "
                          "failed)")):
            if self._tenancy is None:
                cb_counter("cxn_serve_%s_total" % key, help_,
                           lambda k=key: self._counts[k])
            else:
                # tenancy armed: the same names, one child per tenant
                # (the cross-tenant total is a PromQL `sum by` away);
                # pre-touched for every policy so the catalog is
                # stable before the first request
                name = "cxn_serve_%s_total" % key
                cb.append(name)
                fam = r.counter(name, help_, labelnames=("tenant",))
                for t in self._tenancy.label_names():
                    fam.labels(t, fn=(lambda k=key, t=t:
                                      self._tcounts[t][k]))
        if self._tenancy is not None:
            # the tenancy-only catalog: quota rejections by kind, live
            # per-tenant queue/slot/block gauges (doc/observability.md)
            self._quota_c = r.counter(
                "cxn_serve_quota_rejections_total",
                "submits rejected on a tenant quota (typed "
                "QuotaExceededError with a retry_after_ms hint)",
                labelnames=("tenant", "kind"))
            cb.extend(("cxn_serve_tenant_queue_depth",
                       "cxn_serve_tenant_slots",
                       "cxn_serve_tenant_blocks"))
            qd = r.gauge("cxn_serve_tenant_queue_depth",
                         "queued (unadmitted) requests by tenant",
                         labelnames=("tenant",))
            ts = r.gauge("cxn_serve_tenant_slots",
                         "scheduler slots occupied by tenant",
                         labelnames=("tenant",))
            tb = r.gauge("cxn_serve_tenant_blocks",
                         "KV blocks charged to tenant admissions",
                         labelnames=("tenant",))
            for t in self._tenancy.label_names():
                for kind in ("rate", "queue", "blocks"):
                    self._quota_c.labels(t, kind)
                qd.labels(t, fn=lambda t=t: self._tenant_queued(t))
                ts.labels(t,
                          fn=lambda t=t: self._sched.tenant_usage(t)[0])
                tb.labels(t,
                          fn=lambda t=t: self._sched.tenant_usage(t)[1])
        else:
            self._quota_c = None
        for attr, help_ in (
                ("ticks", "batched decode steps run"),
                ("tokens_generated", "tokens emitted across all "
                                     "requests"),
                ("prefill_chunks", "chunk-prefill steps run"),
                ("requests_prefilled", "requests whose prefill "
                                       "completed"),
                ("spec_forwards", "speculative verify forwards run"),
                ("spec_drafted", "draft tokens proposed"),
                ("spec_accepted", "draft tokens accepted"),
                ("spec_emitted", "tokens appended by verify forwards"),
                ("spec_rollbacks", "verify forwards that rejected a "
                                   "suffix"),
                ("spec_backoffs", "requests that stopped speculating "
                                  "(accept-rate back-off)")):
            cb_counter("cxn_serve_%s_total" % attr, help_,
                       lambda a=attr: getattr(sc, a))
        # resilience catalog (serve/resilience.py, doc/observability.md)
        # — registered whether or not chaos / the watchdog is armed, so
        # the exported name set is stable across configurations
        cb_gauge("cxn_serve_state", "serving state (0=SERVING, "
                 "1=DEGRADED, 2=DRAINING, 3=FAILED)",
                 lambda: STATE_CODES[self.health()["state"]])
        cb_gauge("cxn_serve_degrade_rung", "degradation-ladder rung "
                 "(0=normal .. 3=shedding)", lambda: self._ladder.rung)
        cb_counter("cxn_engine_restarts_total", "engine teardown+rebuild "
                   "recoveries (fault or watchdog)",
                   lambda: self._restarts)
        cb_counter("cxn_replayed_requests_total", "admitted requests "
                   "re-queued for deterministic replay after a recovery "
                   "or swap corruption", lambda: self._replayed)
        cb_counter("cxn_reserve_stalls_total", "scheduler passes parked "
                   "because the queue head's blocks could not be placed "
                   "(make-room escapes exhausted)",
                   lambda: self._reserve_stalls)
        cb_counter("cxn_swap_corruptions_total", "swap-in host buffers "
                   "that failed their checksum (row replayed)",
                   lambda: sc.swap_corruptions)
        cb_counter("cxn_drafter_faults_total", "contained drafter "
                   "exceptions (rows ticked plain that pass)",
                   lambda: sc.drafter_faults)
        cb_counter("cxn_prefix_restore_faults_total", "contained prefix-"
                   "restore failures (treated as cache misses)",
                   lambda: sc.prefix_restore_faults)
        cb.append("cxn_faults_injected_total")
        inj = self._inj
        fam = r.counter("cxn_faults_injected_total",
                        "chaos faults injected by point "
                        "(serve_chaos / CXN_CHAOS)",
                        labelnames=("point",))
        for point in FaultInjector.POINTS:
            # pre-touched so the catalog is stable; callback-backed only
            # when an injector is armed
            fam.labels(point, fn=(lambda p=point: inj.counts[p])
                       if inj is not None else None)
        if self._tenancy is None:
            self._shed_c = r.counter(
                "cxn_shed_requests_total",
                "queued requests shed by the degradation ladder",
                labelnames=("rung",))
            self._shed_c.labels("3")    # shedding is the rung-3 effect
        else:
            # tenancy armed: sheds are attributed to the tenant too —
            # the isolation headline ("zero guaranteed sheds under a
            # best-effort flood") is a direct PromQL query
            self._shed_c = r.counter(
                "cxn_shed_requests_total",
                "queued requests shed by the degradation ladder",
                labelnames=("rung", "tenant"))
            for rung in ("3", "4"):
                for t in self._tenancy.label_names():
                    self._shed_c.labels(rung, t)
        cb_gauge("cxn_serve_queue_depth", "requests waiting in the "
                 "admission queue", lambda: len(self._queue))
        cb_gauge("cxn_serve_queue_depth_max", "high-water queue depth "
                 "since start/reset", lambda: self._queue_depth_max)
        cb_gauge("cxn_serve_slots", "KV slot-pool size",
                 lambda: self._engine.slots)
        cb_gauge("cxn_serve_tp", "tensor-parallel shard count of the "
                 "decode engine (1 = single device)", lambda: self._tp)
        cb_gauge("cxn_serve_slot_occupancy", "occupied slot fraction",
                 sc.occupancy)
        cb_gauge("cxn_serve_batch_efficiency", "mean fraction of slot "
                 "rows doing useful work per tick", sc.batch_efficiency)
        cb_gauge("cxn_serve_kv_cache_bytes", "KV cache device bytes "
                 "(dense slot pool, or the whole paged block pool)",
                 self._engine.cache_bytes)
        # token-level utilization alongside row occupancy: the dense
        # gauge charges every row its full row_len, so only the paged
        # engine can push this toward 1.0 (doc/observability.md)
        cb_gauge("cxn_serve_kv_utilization", "live cache tokens / total "
                 "KV token capacity", sc.kv_token_utilization)
        if self._paged:
            mgr = self._engine.manager
            for key, help_ in (
                    ("free", "unallocated KV blocks"),
                    ("shared", "KV blocks with more than one owner "
                               "(rows and/or prefix-trie nodes) — "
                               "copy-on-write protected"),
                    ("private", "KV blocks owned by exactly one row or "
                                "trie node")):
                cb_gauge("cxn_blocks_%s" % key, help_,
                         lambda k=key: mgr.counts()[k])
            cb_counter("cxn_swap_out_total", "rows preempted to the "
                       "host swap buffer", lambda: sc.swaps_out)
            cb_counter("cxn_swap_in_total", "preempted rows resumed "
                       "from the host swap buffer", lambda: sc.swaps_in)
            cb_counter("cxn_cow_faults_total", "shared blocks "
                       "copy-on-write faulted to private copies",
                       lambda: mgr.cow_faults)
            cb_gauge("cxn_swap_host_bytes", "host bytes holding "
                     "swapped-out rows' K/V", lambda: sc.swap_host_bytes)
        if self._lora_pool is not None:
            # adapter-pool economy (serve/lora.py): the callbacks read
            # THROUGH self._lora_pool so a recovery rebuild (fresh pool)
            # is what gets reported
            for key, help_ in (
                    ("hits", "adapter acquires served by a resident "
                             "slot"),
                    ("evictions", "resident adapter pages LRU-evicted"),
                    ("swap_ins", "adapter pages swapped onto the "
                                 "device (crc-verified)"),
                    ("acquire_fails", "acquires faulted on an "
                                      "exhausted pool")):
                cb_counter("cxn_lora_%s_total" % key, help_,
                           lambda k=key: self._lora_pool.metrics()[k])
            cb_counter("cxn_lora_admission_defers_total",
                       "admission pops deferred waiting for "
                       "adapter-pool headroom",
                       lambda: self._lora_defers)
            cb_gauge("cxn_lora_resident", "adapter pages resident on "
                     "the device pool",
                     lambda: self._lora_pool.resident())
            cb_gauge("cxn_lora_refs", "pinned adapter references held "
                     "by admitted rows",
                     lambda: self._lora_pool.refs_held())
            cb_gauge("cxn_lora_pool_slots", "adapter pool slots "
                     "(base slot 0 included)",
                     lambda: self._lora_pool.size)
        pc = self._prefix
        if pc is not None:
            for attr, help_ in (
                    ("hits", "admits that restored >= 1 cached chunk"),
                    ("misses", "admits that restored none"),
                    ("hit_tokens", "prompt tokens restored from the "
                                   "prefix cache"),
                    ("prompt_tokens", "prompt tokens across all "
                                      "lookups"),
                    ("evictions", "cached chunks LRU-evicted"),
                    ("inserted_chunks", "chunks copied into the trie")):
                cb_counter("cxn_prefix_%s_total" % attr, help_,
                           lambda a=attr: getattr(pc, a))
            cb_gauge("cxn_prefix_cache_bytes", "prefix-trie K/V bytes",
                     lambda: pc.nbytes)
            cb_gauge("cxn_prefix_cache_chunks", "chunks resident in the "
                     "prefix trie", lambda: pc.chunks)
        # device-memory ledger (doc/observability.md): predicted bytes
        # per pool as callback gauges, reconciled against the measured
        # jax.live_arrays() total at collection time. `params` covers
        # the ENGINE's weight copies (the fused block dict + outer
        # tree), not the caller's original export — the caller's tree
        # shows up in `unaccounted` until it is dropped.
        cb.append("cxn_device_bytes")
        eng = self._engine
        self._ledger = devprof.DeviceLedger(r)
        self._ledger.register(
            "params", lambda: devprof.tree_nbytes((eng._blocks,
                                                   eng._outer)))
        if self._paged:
            # `kv_blocks` is the WHOLE block pool (trie-resident blocks
            # included — they live inside it, so a separate prefix pool
            # would double-count); `swap_host` is HOST memory holding
            # preempted rows, published for visibility but excluded
            # from the device reconciliation (device=False)
            self._ledger.register("kv_blocks", eng.cache_bytes)
            self._ledger.register("swap_host",
                                  lambda: self._sched.swap_host_bytes,
                                  device=False)
            if self._lora_pool is not None:
                self._ledger.register(
                    "lora_pool",
                    lambda: devprof.tree_nbytes(self._lora_pool.pool))
        else:
            self._ledger.register("kv_slots", eng.cache_bytes)
            if pc is not None:
                self._ledger.register("prefix_cache", lambda: pc.nbytes)
        md = self._drafters.get("model")
        if md is not None:
            self._ledger.register(
                "spec_draft",
                lambda: md.engine.cache_bytes() + devprof.tree_nbytes(
                    (md.engine._blocks, md.engine._outer)))
        # latency histograms (fixed log-spaced buckets -> mergeable
        # across replicas); cxn_serve_phase_seconds was registered with
        # the StepStats observer in __init__
        if self._tenancy is None:
            self._ttft_h = r.histogram(
                "cxn_serve_ttft_seconds",
                "submit -> first token (queue wait included)")
            self._gap_h = r.histogram(
                "cxn_serve_token_gap_seconds",
                "mean inter-token gap per completed request")
        else:
            # per-tenant latency series (same names + tenant label,
            # fixed mergeable buckets): the per-class SLO gauges —
            # guaranteed p95 TTFT under overload is read straight off
            # cxn_serve_ttft_seconds{tenant="gold"}
            self._ttft_h = r.histogram(
                "cxn_serve_ttft_seconds",
                "submit -> first token (queue wait included)",
                labelnames=("tenant",))
            self._gap_h = r.histogram(
                "cxn_serve_token_gap_seconds",
                "mean inter-token gap per completed request",
                labelnames=("tenant",))
            for t in self._tenancy.label_names():
                self._ttft_h.labels(t)
                self._gap_h.labels(t)
        # the recompile-trip family always exists (pre-touched at 0) so
        # the exported catalog is stable whether or not a guard is armed
        from ..analysis.recompile import trip_counter
        trips = trip_counter(r)
        trips.labels("serve_prefill")
        trips.labels("serve_verify_chunk")

    @property
    def registry(self):
        """The obs metrics registry this server reports into."""
        return self._registry

    @property
    def tracer(self):
        """The span tracer this server records into."""
        return self._tracer

    @property
    def fault_injector(self):
        """The armed chaos injector (None when ``serve_chaos`` is off).
        Tests disarm it (``.armed = False``) around warm-up passes so
        compile-time passes don't consume deterministic `@N` shots."""
        return self._inj

    @property
    def ladder(self):
        """The degradation ladder (serve/resilience.py)."""
        return self._ladder

    @property
    def tenancy(self):
        """The tenant-policy registry (serve/tenancy.py; None when
        ``serve_tenants`` is unset — the pinned no-op)."""
        return self._tenancy

    @property
    def lora_pool(self):
        """The LoRA adapter pool (serve/lora.py; None when
        ``serve_lora`` is unset — the pinned no-op)."""
        return self._lora_pool

    def metrics_text(self) -> str:
        """Prometheus text exposition of the full serving catalog
        (serving + prefix-cache + speculative + recompile-guard
        metrics) — the scrape payload."""
        return self._registry.to_prometheus()

    # ------------------------------------------------------------ submit
    @property
    def slots(self) -> int:
        return self._engine.slots

    @property
    def tp(self) -> int:
        """Tensor-parallel shard count of the decode engine (1 =
        single-device)."""
        return self._tp

    @property
    def queue_capacity(self) -> int:
        """The admission queue bound (the router's load-signal
        denominator)."""
        return self._queue_cap

    def queue_depth(self) -> int:
        with self._cond:
            return len(self._queue)

    def adopt(self, req: Request) -> None:
        """Admit an EXISTING Request object — the router's failover /
        drain migration path (serve/router.py): the request was rewound
        with :func:`~cxxnet_tpu.serve.resilience.reset_for_replay` (its
        verified greedy prefix pinned in ``replay_expect``), and this
        server regenerates it through the normal admit path exactly
        like PR 9's single-node replay. Migrations bypass the queue cap
        (the request already held — and lost — capacity on another
        replica) and count into ``cxn_replayed_requests_total``."""
        if self._tenancy is not None:
            # re-resolve against THIS server's registry (the dead peer
            # may have been untenanted or carried labels this fleet
            # does not know); migrations bypass quotas — the request
            # already held, and lost, capacity elsewhere
            req.tenant = self._tenancy.resolve(req.tenant)
        self._check_adoptable(req)
        with self._cond:
            if self._failed is not None:
                raise EngineFailedError(str(self._failed))
            if self._closing:
                raise AdmissionError("server is shutting down")
            self._queue.append(req)
            self._bump("submitted", req)
            self._replayed += 1
            self._queue_depth_max = max(self._queue_depth_max,
                                        len(self._queue))
            self._cond.notify_all()

    def export_migrated(self, handle: Request,
                        timeout: Optional[float] = None):
        """Fleet prefill-tier hook (serve/fleet.py): wait for ``handle``
        to leave this worker, then hand its parked migration record to
        the caller for wire transport. Returns the record when the
        request migrated, ``None`` when it is terminal here (finished
        during prefill — the normal :meth:`result` has the answer — or
        the record was lost to an engine recovery and the router must
        replay instead). The journal entry leaves WITH the record: from
        this moment the request is the adopting worker's (and the fleet
        router's) to replay."""
        if not handle.done.wait(timeout):
            raise TimeoutError("request %d still in flight"
                               % handle.rid)
        if handle.status != "migrated":
            return None
        rec = self._sched.pop_migrated(handle.rid)
        self._journal.remove(handle)
        return rec

    def adopt_swapped(self, req: Request, rec: dict) -> None:
        """Fleet decode-tier hook (serve/fleet.py): adopt a migrated
        row — ``rec`` is the wire-transported swap record (crc still
        unverified; the scheduler's resume path checks it) and ``req``
        the rebuilt Request it belongs to. Parked on the adoption queue
        for the scheduler thread to inject; journaled first, so a fault
        between adoption and resume replays the request here from
        scratch, bit-identically."""
        self._check_adoptable(req)
        rec["req"] = req
        with self._cond:
            if self._failed is not None:
                raise EngineFailedError(str(self._failed))
            if self._closing:
                raise AdmissionError("server is shutting down")
            self._journal.add(req)
            self._bump("submitted", req)
            self._adopted.append(rec)
            self._cond.notify_all()

    def _check_adoptable(self, req: Request) -> None:
        """Fleet/failover entry gate: a migrated request naming a LoRA
        adapter this replica cannot serve must be refused AT ADOPTION —
        admitted, it would silently regenerate with the base model
        (wrong tokens, and the replay-divergence check would fire only
        after emitting them)."""
        if req.adapter and (self._lora_pool is None
                            or req.adapter
                            not in self._lora_pool.registry):
            with self._cond:
                self._bump("rejected", req)
            raise AdmissionError(
                "migrated request %d names LoRA adapter %r this "
                "replica cannot serve" % (req.rid, req.adapter))

    def _reject(self, reason: str) -> None:
        """Count + raise an unservable-request rejection, so the
        'rejected' metric agrees with the ERR lines callers emit. No
        queue-wait sample here: a bad-params rejection never interacted
        with the queue, and a misbehaving client spamming invalid
        requests must not flood the wait histogram with zeros (only the
        queue-FULL shed path in submit() records the zero-wait sample —
        that one really was turned away at the door by load)."""
        with self._cond:
            self._bump("rejected")
        raise AdmissionError(reason)

    def submit(self, prompt, params: Optional[SamplingParams] = None,
               block: bool = False, tenant: str = "",
               rid: Optional[int] = None, migrate: bool = False,
               adapter: str = "", **overrides) -> Request:
        """Enqueue one generation request; returns an opaque handle for
        :meth:`result`. ``params``/keyword overrides fill a
        SamplingParams on top of the server defaults. ``tenant`` is the
        request's tenant label (serve/tenancy.py) — resolved against
        the ``serve_tenants`` registry when armed (unknown names get
        the ``default`` policy), ignored otherwise. ``adapter`` names
        the request's LoRA adapter (serve/lora.py; "" = base model) —
        requires ``serve_lora`` armed and the name registered; with
        tenancy armed and no explicit tenant, the adapter name doubles
        as the tenant label, so per-adapter quotas/SLOs compose for
        free. Raises :class:`QueueFullError` when the admission queue
        is at capacity (``block=True`` waits for space instead),
        :class:`QuotaExceededError` when the tenant is over its rate or
        queue quota (quotas are hard — they apply to blocking submits
        too), and :class:`AdmissionError` for unservable prompts."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        seq_len = self._engine.cfg.seq_len
        if prompt.size < 1:
            self._reject("empty prompt")
        if prompt.size >= seq_len:
            self._reject("prompt length %d leaves no room to generate "
                         "within seq_len %d" % (prompt.size, seq_len))
        p = params if params is not None else self._defaults
        if overrides:
            p = replace(p, **overrides)
        if p.max_tokens < 1:
            self._reject("max_tokens must be >= 1, got %d" % p.max_tokens)
        if p.top_k < 0 or not 0.0 < p.top_p <= 1.0:
            self._reject("bad sampling params: top_k=%r top_p=%r"
                         % (p.top_k, p.top_p))
        if p.spec_len < 0:
            self._reject("spec_len must be >= 0, got %d" % p.spec_len)
        if p.spec_mode not in (None, "off") \
                and p.spec_mode not in self._drafters:
            self._reject("spec_mode %r not available on this server "
                         "(server spec drafters: %s)"
                         % (p.spec_mode,
                            ", ".join(sorted(self._drafters)) or "none"))
        if adapter:
            # a request naming an adapter the server cannot serve is
            # PERMANENTLY unservable — rejected typed at the door, never
            # queued to stall the admission walk
            if self._lora_pool is None:
                self._reject("request names LoRA adapter %r but "
                             "serve_lora is not armed on this server"
                             % adapter)
            if adapter not in self._lora_pool.registry:
                self._reject(
                    "unknown LoRA adapter %r (registered: %s)"
                    % (adapter,
                       ", ".join(sorted(self._lora_pool.registry))
                       or "none"))
            if not tenant:
                # adapter-as-tenant composition: per-adapter quotas and
                # SLO series fall out of the existing tenancy layer
                tenant = adapter
        pol = None
        if self._tenancy is not None:
            pol = self._tenancy.policy_for(tenant)
            tenant = pol.name
            if pol.timeout_ms > 0 and p.timeout_ms <= 0:
                # the tenant's default deadline; the request's own
                # timeout always wins
                p = replace(p, timeout_ms=pol.timeout_ms)
            if self._paged:
                limit = pol.block_limit(self._engine.num_blocks - 1)
                if limit > 0 and \
                        self._engine.blocks_for(prompt.size + 1) > limit:
                    # a prompt no amount of waiting fits under the
                    # tenant's block quota would park in the queue
                    # forever — reject it NOW, typed, hint 0 (permanent)
                    with self._cond:
                        self._bump("rejected", tenant=tenant)
                        self._bump("quota", tenant=tenant)
                    self._quota_c.labels(tenant, "blocks").inc()
                    raise QuotaExceededError(
                        "tenant %r: prompt needs %d KV blocks, over the "
                        "tenant block quota of %d"
                        % (tenant, self._engine.blocks_for(
                            prompt.size + 1), limit),
                        tenant=tenant, kind="blocks")
        if self._inj is not None and self._inj.fire("admit"):
            # chaos point 'admit': the admission/quota path itself
            # faults — contained to THIS submit (typed rejection), the
            # server and every other request are untouched
            with self._cond:
                self._bump("rejected", tenant=tenant)
            raise AdmissionError(
                str(InjectedFault("chaos point 'admit' fired inside "
                                  "the admission path")))
        cls = pol.priority if pol is not None else "standard"

        def _queue_quota_locked():
            # re-checked after every blocking wait below: N submits of
            # one tenant parked at the global cap must not ALL append
            # past the tenant's queue quota as capacity frees
            if pol is not None and pol.queue > 0 and sum(
                    1 for r in self._queue
                    if r.tenant == tenant) >= pol.queue:
                self._bump("rejected", tenant=tenant)
                self._bump("quota", tenant=tenant)
                self._quota_c.labels(tenant, "queue").inc()
                raise QuotaExceededError(
                    "tenant %r at its queue quota (%d queued)"
                    % (tenant, pol.queue),
                    retry_after_ms=self._retry_after_ms(),
                    tenant=tenant, kind="queue")

        with self._cond:
            if self._failed is not None:
                self._bump("rejected", tenant=tenant)
                raise EngineFailedError(str(self._failed))
            if self._closing:
                raise self._draining_error()
            _queue_quota_locked()
            if self._ladder.shedding and not block and p.timeout_ms > 0 \
                    and self._ema_req_s > 0 \
                    and cls in self._ladder.shed_classes():
                # non-blocking submits only: a block=True caller (the
                # CLI stdin loop) asked to WAIT, and the queue-resident
                # shed still protects it if its deadline turns hopeless
                # rung-3 door check: a deadline the current backlog
                # cannot possibly meet is shed NOW with a back-off
                # hint, not queued to expire after wasting queue space.
                # Tenant-aware: the door walks classes with the ladder
                # — guaranteed requests pass until the emergency rung.
                eta_ms = ((len(self._queue) + 1) * self._ema_req_s
                          / max(1, self._engine.slots)) * 1e3
                if eta_ms > p.timeout_ms:
                    self._bump("rejected", tenant=tenant)
                    self._bump("shed", tenant=tenant)
                    self._inc_shed(tenant if pol is not None
                                   else DEFAULT_TENANT)
                    self._ladder.sheds += 1
                    self._phase_h.labels(profiler.QUEUE_WAIT).observe(0.0)
                    raise QueueFullError(
                        "overload shed at admission: estimated queue "
                        "wait %.0f ms exceeds timeout_ms=%.0f"
                        % (eta_ms, p.timeout_ms),
                        retry_after_ms=self._retry_after_ms())
            while len(self._queue) >= self._queue_cap:
                if not block:
                    self._bump("rejected", tenant=tenant)
                    self._phase_h.labels(profiler.QUEUE_WAIT).observe(0.0)
                    raise QueueFullError(
                        "admission queue full (%d queued, %d/%d slots "
                        "busy); retry later or submit(block=True)"
                        % (len(self._queue), self._sched.active,
                           self._engine.slots),
                        retry_after_ms=self._retry_after_ms())
                self._cond.wait()
                if self._failed is not None:
                    raise EngineFailedError(str(self._failed))
                if self._closing:
                    raise self._draining_error()
                _queue_quota_locked()
            if pol is not None:
                # rate limit LAST, once nothing structural can reject:
                # one token per ADMITTED request (TokenBucket's
                # contract) — queue-full / quota / shed rejections must
                # not silently drain the tenant's bucket
                ok, retry = self._tenancy.take(tenant,
                                              time.perf_counter())
                if not ok:
                    self._bump("rejected", tenant=tenant)
                    self._bump("quota", tenant=tenant)
                    self._quota_c.labels(tenant, "rate").inc()
                    raise QuotaExceededError(
                        "tenant %r over its rate limit (%g qps)"
                        % (tenant, pol.qps), retry_after_ms=retry,
                        tenant=tenant, kind="rate")
            # rid/migrate are the fleet hooks (serve/fleet.py): a fleet
            # worker serves requests under the ROUTER's request id (the
            # cross-process journal and failover accounting key on it),
            # and migrate=True sends the row to a decode-tier worker at
            # prefill completion. Both default to the pre-fleet path.
            req = Request(next(self._rid) if rid is None else rid,
                          prompt, p, time.perf_counter(), tenant=tenant,
                          adapter=adapter)
            req.migrate = migrate
            self._queue.append(req)
            self._bump("submitted", req)
            self._queue_depth_max = max(self._queue_depth_max,
                                        len(self._queue))
            self._cond.notify_all()
        return req

    def _draining_error(self):
        """The admission rejection while shutting down: a DRAINING
        server (graceful preemption — SIGTERM, drain_replica) answers
        with a back-off hint so clients retry elsewhere or later; an
        aborting one answers plain (nothing to wait for)."""
        if self._drain and not self._stopped.is_set():
            return QueueFullError(
                "server is draining (graceful shutdown); retry "
                "elsewhere", retry_after_ms=self._retry_after_ms())
        return AdmissionError("server is shutting down")

    def result(self, handle: Request,
               timeout: Optional[float] = None) -> ServeResult:
        """Block until ``handle`` reaches a terminal state (or ``timeout``
        seconds pass — then raises TimeoutError) and return its
        ServeResult."""
        if not handle.done.wait(timeout):
            raise TimeoutError("request %d still in flight" % handle.rid)
        if handle.status == "ok":
            tokens = np.concatenate(
                [handle.prompt,
                 np.asarray(handle.tokens, np.int32)])
            ttft = (handle.first_token_t - handle.submit_t) * 1e3
            gaps = ((handle.done_t - handle.first_token_t)
                    / max(1, len(handle.tokens) - 1) * 1e3
                    if len(handle.tokens) > 1 else 0.0)
            return ServeResult("ok", tokens, ttft_ms=ttft,
                               ms_per_token=gaps,
                               queue_ms=(handle.admit_t
                                         - handle.submit_t) * 1e3)
        return ServeResult(handle.status, np.zeros((0,), np.int32),
                           error=handle.error,
                           retry_after_ms=handle.retry_after_ms)

    # -------------------------------------------------------------- loop
    def _expire_queued_locked(self, now: float) -> List[Request]:
        """Finish queued requests whose deadline passed (FIFO order is
        preserved for the survivors). Returns the expired requests so
        the caller can run the slow-exemplar hook on them OUTSIDE the
        lock (``note_slow`` does file I/O) — an expired request is
        exactly the kind of worst offender ``obs_slow_ms`` exists to
        capture."""
        if not any(r.deadline is not None for r in self._queue):
            return []
        keep = collections.deque()
        expired: List[Request] = []
        for req in self._queue:
            if req.deadline is not None and now > req.deadline:
                expired.append(req)
                self._bump("timeout", req)
                self._bump("expired", req)
                # an expired request DID wait — record its full queue
                # time, or overload reads as low queue-wait percentiles
                # (only the admitted survivors would contribute). Runs
                # on the scheduler thread, so StepStats is safe here;
                # the observer forwards it to the registry histogram.
                self._stats.record(profiler.QUEUE_WAIT,
                                   now - req.submit_t)
                self._stats.end_step()
                req.finish("timeout",
                           "expired after %.0f ms in queue"
                           % ((now - req.submit_t) * 1e3))
                if self._tracer.should_sample(req.rid):
                    # the span tree of a request that never got a slot:
                    # queue_wait + the terminal root, nothing else
                    tid = obs_trace.request_tid(req.rid)
                    self._tracer.add(profiler.QUEUE_WAIT, req.submit_t,
                                     now - req.submit_t, tid,
                                     cat="serve")
                    self._tracer.add("request", req.submit_t,
                                     req.done_t - req.submit_t, tid,
                                     cat="serve",
                                     args={"rid": req.rid,
                                           "status": "timeout",
                                           "expired": True})
            else:
                keep.append(req)
        if len(keep) != len(self._queue):
            self._queue = keep
            self._cond.notify_all()
        return expired

    def _loop(self, gen: int) -> None:
        """The scheduler loop for one engine GENERATION. A fault on
        this thread recovers in place (same generation); a watchdog
        recovery bumps ``self._gen`` and starts a fresh thread — this
        one then unwinds without finalizing (the new thread owns the
        state, and this one's engine/scheduler references were already
        discarded)."""
        try:
            while self._gen == gen:
                try:
                    if not self._pass():
                        break
                except Exception as e:
                    if self._gen != gen or isinstance(e, SupersededError):
                        return          # superseded by a watchdog restart
                    if self._closing and not self._drain:
                        break           # aborting anyway: don't rebuild
                    if not self._recover(
                            "%s: %s" % (type(e).__name__, e), gen):
                        break           # restart budget exhausted
                    if self._gen != gen:
                        return
        finally:
            if self._gen == gen:
                self._finalize()

    def _pass(self) -> bool:
        """One scheduler pass (expire / shed / admit / resume / prefill
        / speculate / tick / ladder); returns False when the loop
        should exit. Every device call runs OUTSIDE the admission
        lock."""
        sched = self._sched
        admitted = []
        expired = []
        shed = []
        try:
            with self._cond:
                now = time.perf_counter()
                # fleet adoptions first (serve/fleet.py): migrated rows
                # parked by the RPC thread join the scheduler's resume
                # list here, on the scheduler thread — swapped_pending
                # then both skips the idle park below and gives them
                # resume priority over fresh admissions
                while self._adopted:
                    sched.inject_swapped(self._adopted.popleft())
                expired = self._expire_queued_locked(now)
                if self._closing and not self._drain:
                    return False
                if self._ladder.shedding:
                    shed = self._shed_queued_locked(now)
                n_free = sched.free_slots   # slots shrink only when
                #   admit() runs below, outside this lock
                # swapped (preempted) requests resume with strict
                # priority over fresh admissions — and the paged
                # admissible() gate stops popping at the first queue
                # head whose blocks don't fit, so overload waits in the
                # queue instead of thrashing the pool with admit/preempt
                # cycles. `claimed` carries the blocks promised to
                # requests popped EARLIER IN THIS PASS (their
                # allocations run later, outside this lock), so a burst
                # can't over-admit against a free_count that hasn't
                # moved yet. Tenancy (serve/tenancy.py): candidates are
                # walked in (priority class, arrival) order — per-tenant
                # sub-queues under the FIFO — and a tenant at its
                # slot/block quota is SKIPPED without blocking other
                # tenants queued behind it (`t_claims` mirrors `claimed`
                # per tenant); untenanted, every rank ties and the walk
                # IS the original FIFO pop.
                claimed = 0
                t_claims: Dict[str, tuple] = {}
                l_names: set = set()    # distinct adapter names charged
                #   a pool slot by pops earlier in THIS pass (their
                #   acquires run later, outside this lock)
                if not sched.swapped_pending and n_free > 0 \
                        and self._queue:
                    q = list(self._queue)
                    if self._tenancy is None:
                        order = range(len(q))
                    else:
                        order = sorted(
                            range(len(q)),
                            key=lambda i: (sched._rank(q[i]), i))
                    taken = set()
                    for i in order:
                        if n_free <= 0:
                            break
                        req = q[i]
                        if not sched.admissible(req, claimed):
                            # the first globally-inadmissible candidate
                            # ends the walk: admission stays orderly
                            # waiting, never a search for smaller work
                            break
                        if sched.tenant_blocked(req, t_claims):
                            continue        # THIS tenant waits; peers
                            #                 behind it do not
                        lp = self._lora_pool
                        if lp is not None and req.adapter \
                                and req.adapter not in l_names \
                                and not lp.pinned(req.adapter):
                            # adapter residency is an admission gate
                            # exactly like tenant quotas: a request
                            # whose adapter cannot get a pool slot
                            # WAITS without blocking peers. The budget
                            # is one unreferenced slot per distinct
                            # un-pinned name popped this pass — the
                            # acquires run later in pop order and any
                            # one may evict any unpinned slot, so
                            # headroom >= names-charged keeps every
                            # acquire in the batch from faulting
                            # (lora.AdapterPool.headroom)
                            if not lp.can_acquire(req.adapter) \
                                    or lp.headroom() <= len(l_names):
                                self._lora_defers += 1
                                continue
                            l_names.add(req.adapter)
                        # journal BEFORE any device work: from this
                        # moment until its terminal state, the request
                        # is replayed after an engine-fatal fault
                        # (serve/resilience.py)
                        self._journal.add(req)
                        claimed += sched.admission_claim(req)
                        if self._tenancy is not None:
                            cs, cb = t_claims.get(req.tenant, (0, 0))
                            t_claims[req.tenant] = (
                                cs + 1, cb + sched.admission_claim(req))
                        taken.add(i)
                        admitted.append(req)
                        n_free -= 1
                    if taken:
                        self._queue = collections.deque(
                            r for i, r in enumerate(q) if i not in taken)
                        self._cond.notify_all()  # space for blocked
                        #                          submits
                if not admitted and sched.active == 0 \
                        and not sched.swapped_pending:
                    if self._closing and not self._queue:
                        return False
                    # truly idle: active == 0 means every slot is free
                    # and (queue empty) nothing can expire while we
                    # sleep; every mutation path (submit, shutdown)
                    # notifies, so an untimed wait parks the thread
                    # instead of polling. An inadmissible queue head
                    # with every slot free is the make-room loop's
                    # terminal stall — all three escapes (trie evict,
                    # preempt, swap) exhausted — so it is COUNTED
                    # (cxn_reserve_stalls_total) and fed to the
                    # degradation ladder instead of silently parked;
                    # the 50 ms wait keeps it a poll, never a deadlock.
                    # A pass that just expired/shed requests skips the
                    # park so their exemplar dump isn't deferred to the
                    # next submit.
                    if self._queue:
                        self._reserve_stalls += 1
                        self._ladder.note_stall()
                        self._evaluate_ladder()
                        self._cond.wait(0.05)
                    elif not expired and not shed:
                        self._evaluate_ladder()
                        self._parked = True
                        try:
                            # not a predicate loop BY DESIGN: the caller
                            # re-enters _pass, which re-derives all
                            # state — a spurious wakeup just costs one
                            # scan (see the park rationale above)
                            self._cond.wait()   # cxn-lint: disable=CXN305
                        finally:
                            # beat BEFORE unparking: the watchdog must
                            # never observe parked=False with a stale
                            # heartbeat on a just-woken healthy loop
                            self._beat()
                            self._parked = False
                    self._beat()
                    return True
        finally:
            # slow-exemplar hook outside the lock (note_slow does file
            # I/O); a finally so the early returns above cannot skip it
            # — expired/shed requests are exactly the worst offenders
            # obs_slow_ms exists to capture
            for req in expired:
                self._maybe_slow(req)
            for req in shed:
                self._maybe_slow(req)
        # preempted requests come back FIRST (strict priority — the pop
        # loop above did not admit while any were pending), then fresh
        # admissions; both are device work and run outside the lock
        if sched.swapped_pending:
            sched.resume_swapped()
        for req in admitted:                # device work outside the
            sched.admit(req)                # lock
        # at most prefill_budget chunk steps per pass, so a long
        # prompt's prefill cannot stall the decode tick for more than
        # one chunk's duration (whole-prompt admits already ran inside
        # admit() when chunking is off)
        for _ in range(self._prefill_budget):
            if not sched.prefill_step():
                break
        # draft-and-verify before the tick: each eligible row banks up
        # to spec_len + 1 tokens from ONE verify forward, then the
        # shared tick advances every decoding row (verified rows
        # included) by one more. Degradation rung 1 skips speculation:
        # it is optional work whose verifies cost dispatches the
        # saturated engine needs for ticks.
        if self._drafters and sched.decoding \
                and self._ladder.spec_enabled:
            sched.spec_steps()
        if sched.decoding:
            sched.tick()
        self._evaluate_ladder()
        self._beat()
        return True

    def _beat(self) -> None:
        """Heartbeat: one completed scheduler pass (the watchdog's
        liveness signal)."""
        self._heartbeat = time.perf_counter()

    def _finalize(self) -> None:
        """Terminal shutdown: stop accepting, resolve EVERY outstanding
        request exactly once, drop the caches, release the stopped
        event. Reached on drain/abort shutdown and — with the typed
        EngineFailedError status — when the restart budget is
        exhausted."""
        err = self._failed
        status = "error" if err is not None else "cancelled"
        msg = str(err) if err is not None else "server shutdown"
        with self._cond:
            self._closing = True
            for req in self._queue:
                self._bump(status, req)
                req.finish(status, msg)
            self._queue.clear()
            # adopted-but-never-injected migration records: the
            # requests are journaled (swept below); the host buffers
            # just drop
            self._adopted.clear()
            self._cond.notify_all()
        # retire every scheduler-tracked request FIRST (counted via
        # _record_done, which also drops them from the journal), so the
        # journal sweep below only touches requests the scheduler never
        # took ownership of — popped but not admit()ed, or crashed
        # mid-admit — and nothing is finished (or counted) twice
        # (Request.finish is first-wins)
        self._sched.cancel_active(status, msg)
        for req in self._journal.requests():
            if not req.done.is_set():
                self._bump(status, req)
                req.finish(status, msg)
        self._journal.clear()
        if self._prefix is not None:
            self._prefix.clear()        # drop the cached chunk K/V
        for d in self._drafters.values():
            d.close()                   # drop the draft slot pool
        self._engine.close()
        self._stopped.set()

    # --------------------------------------------------------- recovery
    def _recover(self, reason: str, gen: int) -> bool:
        """Exception-path recovery, on the loop thread itself. Returns
        False when the loop should exit (budget exhausted, or a
        concurrent watchdog recovery superseded this thread)."""
        with self._recover_lock:
            if self._gen != gen:
                return False            # watchdog got here first
            ok = self._do_recover(reason)
            self._beat()                # recovery was progress
            return ok

    def _do_recover(self, reason: str) -> bool:
        """Tear down the pool, rebuild the engine cold, and requeue the
        journaled requests for deterministic replay (module docstring
        of serve/resilience.py). Caller holds ``_recover_lock``.
        Returns False when ``serve_max_restarts`` is exhausted — the
        server is then permanently FAILED and the caller finalizes."""
        t0 = time.perf_counter()
        self._restarts += 1
        if self._inj is not None:
            # wake any injected hang NOW: an abandoned thread sleeping
            # inside the old engine must unwind, not resume a pass on
            # state this recovery is about to discard
            self._inj.release_hangs()
        tr = self._tracer
        if self._restarts > self._max_restarts:
            self._failed = EngineFailedError(
                "engine failed %d time(s), exceeding serve_max_restarts"
                "=%d; last fault: %s"
                % (self._restarts, self._max_restarts, reason))
            profiler.warn("serve: %s" % self._failed)
            # the FAILED path keeps the old scheduler for the terminal
            # sweep, but only THIS thread may drive it — a hung loop
            # thread waking mid-device-call must still unwind instead
            # of re-retiring requests _finalize already failed
            self._sched.supersede()
            if tr.enabled:
                tr.instant("engine_failed", TID_CONTROL,
                           cat="resilience",
                           args={"reason": reason,
                                 "restarts": self._restarts})
            return False
        profiler.warn("serve: engine fault (%s) -- restart %d/%d: "
                      "tearing down and rebuilding cold"
                      % (reason, self._restarts, self._max_restarts))
        old = self._sched
        old.supersede()                 # an abandoned thread that wakes
        #                                 inside this scheduler unwinds
        old_prefix = self._prefix
        old_manager = self._engine.manager if self._paged else None
        t_teardown = time.perf_counter()
        try:
            self._engine.close()
        except Exception:
            pass                        # the engine is being discarded
        if self._prefix is not None:
            try:
                self._prefix.clear()
            except Exception:
                pass
        for d in self._drafters.values():
            try:
                d.close()
            except Exception:
                pass
        t_rebuild = time.perf_counter()
        self._build_stack()
        for attr in _SCHED_CARRY:       # registry counters stay monotone
            setattr(self._sched, attr, getattr(old, attr))
        # the prefix-cache and block-manager traffic counters back other
        # callback counters (cxn_prefix_*_total, cxn_cow_faults_total) —
        # carry them onto the cold-rebuilt objects for the same reason
        if self._prefix is not None and old_prefix is not None:
            for attr in ("hits", "misses", "hit_tokens", "prompt_tokens",
                         "evictions", "inserted_chunks"):
                setattr(self._prefix, attr, getattr(old_prefix, attr))
        if old_manager is not None and self._paged:
            self._engine.manager.cow_faults = old_manager.cow_faults
        self._register_obs()            # rebind callbacks to the new
        #                                 engine/scheduler (latest wins)
        t_replay = time.perf_counter()
        # parked migration records are host-only numpy — they survive
        # the engine rebuild verbatim, so an export racing a recovery
        # still gets its record instead of forcing a router-side replay
        self._sched.migrated.update(old.migrated)
        reqs = [r for r in self._journal.requests()
                if not r.done.is_set()]
        self._journal.clear()
        for req in reqs:
            reset_for_replay(req)
        with self._cond:
            # adopted-but-not-injected records: their requests are in
            # `reqs` (journaled at adoption) and will replay from
            # scratch — draining the records too would admit them twice
            self._adopted.clear()
            # replayed requests go to the FRONT in admission order —
            # they were admitted once and must not requeue behind
            # traffic that arrived after them (cap overflow is fine:
            # they already held their queue slot)
            for req in reversed(reqs):
                self._queue.appendleft(req)
            self._replayed += len(reqs)
            self._cond.notify_all()
        t1 = time.perf_counter()
        if tr.enabled:
            # the recovery span tree on the ENGINE track: a restart is
            # visible in Perfetto exactly where the ticks stop
            tr.add("teardown", t_teardown, t_rebuild - t_teardown,
                   TID_ENGINE, cat="resilience")
            tr.add("rebuild", t_rebuild, t_replay - t_rebuild,
                   TID_ENGINE, cat="resilience")
            tr.add("replay", t_replay, t1 - t_replay, TID_ENGINE,
                   cat="resilience", args={"requests": len(reqs)})
            tr.add("recovery", t0, t1 - t0, TID_ENGINE, cat="resilience",
                   args={"reason": reason, "restart": self._restarts,
                         "replayed": len(reqs)})
        # teardown -> rebuild -> requeue wall of THIS recovery (the
        # bench.py cold-start cell and metrics() read it; with a warm
        # AOT cache the rebuild loads executables instead of compiling)
        self._last_recover_ms = (t1 - t0) * 1e3
        profiler.warn("serve: engine rebuilt cold in %.0f ms (restart "
                      "%d/%d), replaying %d in-flight request(s)"
                      % ((t1 - t0) * 1e3, self._restarts,
                         self._max_restarts, len(reqs)))
        return True

    def _replay_one(self, req: Request) -> None:
        """Single-request replay (the scheduler's swap-corruption hook):
        the row's host buffer was untrusted, so the request is rewound
        and re-queued through the normal admit path — the deterministic
        key schedule regenerates its verified tokens bit-identically."""
        self._journal.remove(req)
        reset_for_replay(req)
        if self._tracer.enabled:
            self._tracer.instant("replay_request", TID_CONTROL,
                                 cat="resilience",
                                 args={"rid": req.rid,
                                       "why": "swap corruption"})
        with self._cond:
            self._replayed += 1
            self._queue.appendleft(req)
            self._cond.notify_all()

    def _watch(self) -> None:
        """Watchdog thread (``cxn-serve-watchdog-*``): a scheduler loop
        that has not completed a pass within ``serve_watchdog_ms``
        while un-parked work exists is declared hung — the generation
        is bumped (abandoning the stuck thread: when its device call
        finally returns, or its injected hang is released, it sees the
        mismatch and unwinds), the stack is rebuilt, and a fresh loop
        thread takes over. Hangs become restarts instead of silent
        deadlocks; the restart budget still applies."""
        thresh = self._watchdog_ms / 1e3
        period = max(0.005, min(thresh / 4.0, 0.25))
        while not self._watch_stop.wait(period):
            if self._stopped.is_set():
                return
            if self._parked:
                continue                # idle park, not a hang
            if time.perf_counter() - self._heartbeat < thresh:
                continue
            with self._recover_lock:
                if self._stopped.is_set() or self._failed is not None:
                    return
                if self._parked or \
                        time.perf_counter() - self._heartbeat < thresh:
                    continue            # progressed while we waited
                self._gen += 1
                gen = self._gen
                if self._do_recover(
                        "watchdog: no scheduler pass completed in "
                        "%.0f ms" % self._watchdog_ms):
                    self._beat()
                    self._thread = threading.Thread(
                        target=self._loop, args=(gen,),
                        name="cxn-serve-scheduler-%d-r%d"
                        % (self._idx, self._restarts), daemon=True)
                    self._thread.start()
                else:
                    self._finalize()
                    return

    # ----------------------------------------------------------- ladder
    def _evaluate_ladder(self) -> None:
        """One degradation-ladder step per scheduler pass (a few float
        compares): queue pressure, paged block headroom (free +
        trie-reclaimable over the usable pool), and any reserve stall
        noted since the last step. Rung transitions are logged, traced
        on the control track, and pushed to the scheduler's
        prefix-admission switch."""
        lad = self._ladder
        if not lad.enabled:
            return
        before = lad.rung
        with self._cond:
            depth = len(self._queue)
        qf = depth / float(self._queue_cap)
        headroom = None
        if self._paged:
            m = self._engine.manager
            usable = max(1, self._engine.num_blocks - 1)
            free = m.free_count
            if self._prefix is not None:
                free += self._prefix.reclaimable_blocks()
            headroom = free / float(usable)
        lad.evaluate(qf, headroom,
                     class_queue_frac=self._class_queue_frac())
        if lad.rung != before:
            self._sched.prefix_admission = lad.prefix_admission
            profiler.warn(
                "serve: degradation rung %d -> %d (queue %.0f%%, "
                "headroom %s) — %s"
                % (before, lad.rung, 100.0 * qf,
                   "%.0f%%" % (100.0 * headroom)
                   if headroom is not None else "n/a",
                   "speculation off" if lad.rung == 1 else
                   "prefix admission off" if lad.rung == 2 else
                   "EMERGENCY (guaranteed sheddable)"
                   if lad.rung >= lad.EMERGENCY_RUNG else
                   "shedding" if lad.rung >= 3 else "recovered"
                   if lad.rung == 0 else "degraded"))
            if self._tracer.enabled:
                self._tracer.instant(
                    "degrade_rung", TID_CONTROL, cat="resilience",
                    args={"from": before, "to": lad.rung,
                          "queue_frac": round(qf, 3),
                          "headroom": (round(headroom, 3)
                                       if headroom is not None
                                       else None)})

    def _retry_after_ms(self) -> float:
        """Back-off hint for a shed/rejected request: the estimated
        time for the current backlog to drain one queue slot's worth of
        work — queue depth x the EMA of admit->done over the slot
        count, floored at 50 ms."""
        ema = self._ema_req_s if self._ema_req_s > 0 else 0.05
        depth = len(self._queue)
        return max(50.0,
                   depth * ema / max(1, self._engine.slots) * 1e3)

    def _shed_queued_locked(self, now: float) -> List[Request]:
        """Rung-3 deadline-aware shedding (caller holds the lock): a
        queued request whose estimated admission time already overruns
        its deadline is finished as ``shed`` NOW, with a
        ``retry_after_ms`` hint, instead of rotting in the queue until
        expiry — the queue space goes to requests that can still make
        it, which is what keeps admitted-request TTFT bounded under
        overload. Requests without deadlines are never shed (they wait
        by contract).

        Tenant-aware (serve/tenancy.py): classes are walked in inverse
        priority — ALL doomed best-effort requests are shed (and their
        queue positions vacated) before any standard request's ETA is
        even re-evaluated, and guaranteed requests are only sheddable
        on the emergency rung 4. Untenanted, every request is class
        ``standard`` and the walk reduces to the original single
        pass."""
        ema = self._ema_req_s
        if ema <= 0 or not any(r.deadline is not None
                               for r in self._queue):
            return []
        shed: List[Request] = []
        slots = max(1, self._engine.slots)
        queue = self._queue
        for cls in self._ladder.shed_classes():
            if not any(r.deadline is not None
                       and self._class_of(r) == cls for r in queue):
                continue
            keep = collections.deque()
            pos = 0
            for req in queue:
                eta = now + (pos + 1) * ema / slots
                if req.deadline is not None and eta > req.deadline \
                        and self._class_of(req) == cls:
                    retry = self._retry_after_ms()
                    req.retry_after_ms = retry
                    self._bump("shed", req)
                    self._ladder.sheds += 1
                    self._inc_shed(req.tenant if self._tenancy
                                   is not None else DEFAULT_TENANT)
                    self._stats.record(profiler.QUEUE_WAIT,
                                       now - req.submit_t)
                    self._stats.end_step()
                    req.finish(
                        "shed",
                        "load shed at degradation rung %d: estimated "
                        "admission %.0f ms past deadline; retry "
                        "after %.0f ms"
                        % (self._ladder.rung,
                           (eta - req.deadline) * 1e3, retry))
                    shed.append(req)
                else:
                    keep.append(req)
                    pos += 1
            queue = keep
        if shed:
            self._queue = queue
            self._cond.notify_all()
            if self._tracer.enabled:
                self._tracer.instant("shed", TID_CONTROL,
                                     cat="resilience",
                                     args={"count": len(shed),
                                           "rung": self._ladder.rung})
        return shed

    def health(self) -> Dict:
        """Liveness + degradation snapshot (doc/serving.md
        "Resilience"): ``state`` is SERVING / DEGRADED (ladder rung >
        0) / DRAINING (shutdown in progress) / FAILED (restart budget
        exhausted — submits raise EngineFailedError); ``retry_after_ms``
        carries the shed hint while rung 3 holds."""
        if self._failed is not None:
            state = STATE_FAILED
        elif self._closing:
            state = STATE_DRAINING
        elif self._ladder.rung > 0:
            state = STATE_DEGRADED
        else:
            state = STATE_SERVING
        return {
            "state": state,
            "rung": self._ladder.rung,
            "restarts": self._restarts,
            "max_restarts": self._max_restarts,
            "replayed": self._replayed,
            "shed": self._ladder.sheds,
            "reserve_stalls": self._reserve_stalls,
            "queue_depth": len(self._queue),
            "retry_after_ms": (self._retry_after_ms()
                               if self._ladder.shedding else 0.0),
            "watchdog_ms": self._watchdog_ms,
            "chaos": self._inj.spec if self._inj is not None else "",
            # tenancy (serve/tenancy.py): which classes the current
            # rung may shed, and per-class queue fractions (None /
            # empty when serve_tenants is unset)
            "shed_classes": list(self._ladder.shed_classes()),
            "class_queue_frac": self._class_queue_frac(),
        }

    def _record_done(self, req: Request) -> None:
        """Scheduler on_finish hook (scheduler-thread only)."""
        self._journal.remove(req)       # terminal: nothing to replay
        if req.status != "ok":
            self._bump("cancelled" if req.status == "cancelled"
                       else req.status, req)
            self._maybe_slow(req)
            return
        self._bump("completed", req)
        if req.admit_t is not None:
            # EMA of admit->done feeds the shed / retry_after estimates
            dur = req.done_t - req.admit_t
            self._ema_req_s = dur if self._ema_req_s <= 0 \
                else 0.2 * dur + 0.8 * self._ema_req_s
        ttft = req.first_token_t - req.submit_t
        self._ttft_s.append(ttft)
        self._hist(self._ttft_h, req).observe(ttft)
        if len(req.tokens) > 1:
            gap = ((req.done_t - req.first_token_t)
                   / (len(req.tokens) - 1))
            self._tok_gap_s.append(gap)
            self._hist(self._gap_h, req).observe(gap)
        self._maybe_slow(req)

    def _maybe_slow(self, req: Request) -> None:
        """The slow-request exemplar hook (obs_slow_ms): a request whose
        TTFT or total latency crossed the threshold gets its span tree
        dumped NOW, while the spans are still in the ring."""
        if self._slow_ms <= 0:
            return
        total_ms = (req.done_t - req.submit_t) * 1e3
        ttft_ms = ((req.first_token_t - req.submit_t) * 1e3
                   if req.first_token_t is not None else total_ms)
        if ttft_ms > self._slow_ms or total_ms > self._slow_ms:
            self._tracer.note_slow(
                req.rid,
                "ttft %.1f ms, total %.1f ms over obs_slow_ms=%g"
                % (ttft_ms, total_ms, self._slow_ms),
                args={"status": req.status})

    # ----------------------------------------------------------- control
    def drain(self, timeout: Optional[float] = None) -> None:
        """Finish everything queued + in flight, keep the server alive is
        NOT supported — drain means shutdown(drain=True)."""
        self.shutdown(drain=True, timeout=timeout)

    def shutdown(self, drain: bool = True,
                 timeout: Optional[float] = None) -> None:
        """Stop the server. ``drain=True`` finishes queued + in-flight
        requests first; ``drain=False`` cancels them. Idempotent; joins
        the scheduler thread and frees every slot + the cache buffers."""
        with self._cond:
            self._closing = True
            self._drain = drain
            self._cond.notify_all()
        if self._inj is not None:
            # an injected hang must not outlive the server: the stalled
            # thread raises, the loop sees closing, and (drain) recovery
            # or (abort) finalize proceeds
            self._inj.release_hangs()
        self._stopped.wait(timeout)
        self._watch_stop.set()
        self._thread.join(timeout)
        if self._watch_thread is not None:
            self._watch_thread.join(timeout)
        # freeze this server's callback metrics at their terminal
        # values: the registry stops pinning the engine/KV pool, and a
        # post-shutdown scrape reports the honest drained state instead
        # of evaluating a dead object (obs/metrics.py:Registry.freeze)
        self._registry.freeze(self._obs_cb_names)
        # and stop routing process compile events into a dead server's
        # registry (the CompileWatch sink holds a reference to it)
        devprof.compile_watch().remove_sink(self._registry)
        if self._aot is not None:
            self._aot.remove_sink(self._registry)

    def close(self) -> None:
        self.shutdown(drain=False)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown(drain=not any(exc))

    # ----------------------------------------------------------- metrics
    def metrics(self) -> Dict:
        """Serving health snapshot: request counters, p50/p95/p99 latency
        summaries (ms), and scheduler gauges."""
        ms = lambda xs: {k: v * 1e3 for k, v in
                         profiler.percentiles(xs).items()}
        with self._cond:
            depth = len(self._queue)
        st = self._stats
        sc = self._sched
        pc = self._prefix
        return {
            # AOT executable cache: resolution source per program +
            # process-wide cache traffic; the key is ADDED only when
            # armed so the uncached metrics() surface stays identical
            **({"aot_cache": dict(self._aot.stats(),
                                  programs=self._engine.aot_status())}
               if self._aot is not None else {}),
            # adapter-pool economy (serve/lora.py): the key is ADDED
            # only when serve_lora is armed so the base metrics()
            # surface stays identical
            **({"lora": dict(self._lora_pool.metrics(),
                             defers=self._lora_defers,
                             refs=self._lora_pool.refs_held())}
               if self._lora_pool is not None else {}),
            "requests": dict(self._counts),
            "ttft_ms": ms(self._ttft_s),
            "token_ms": ms(self._tok_gap_s),
            "queue_wait_ms": ms(st.samples(profiler.QUEUE_WAIT)),
            "prefill_ms": ms(st.samples(profiler.PREFILL)),
            "prefill_chunk_ms": ms(st.samples(profiler.PREFILL_CHUNK)),
            "prefix_copy_ms": ms(st.samples(profiler.PREFIX_COPY)),
            "decode_tick_ms": ms(st.samples(profiler.DECODE_TICK)),
            "spec_draft_ms": ms(st.samples(profiler.SPEC_DRAFT)),
            "spec_verify_ms": ms(st.samples(profiler.SPEC_VERIFY)),
            "queue_depth": {"now": depth, "max": self._queue_depth_max},
            "slot_occupancy": sc.occupancy(),
            # token-level utilization ALONGSIDE row occupancy: the dense
            # pool charges every row its full row_len, so only the paged
            # engine can drive this toward 1.0 — the gauge the paged
            # capacity win shows up in (doc/serving.md)
            "kv_token_utilization": sc.kv_token_utilization(),
            "batch_efficiency": sc.batch_efficiency(),
            # paged-engine health: block economy + preemption/swap
            # traffic (None when the dense pool serves)
            "paged": ({
                "num_blocks": self._engine.num_blocks,
                "block_size": self._engine.block_size,
                "fused_attn": self._engine.fused_attn,
                "fused_formulation": self._engine.fused_formulation,
                "kv_dtype": self._engine.kv_dtype,
                "blocks": self._engine.manager.counts(),
                "cow_faults": self._engine.manager.cow_faults,
                "swaps_out": sc.swaps_out, "swaps_in": sc.swaps_in,
                "swapped_pending": sc.swapped_pending,
                "swap_host_bytes": sc.swap_host_bytes,
            } if self._paged else None),
            # resilience snapshot (serve/resilience.py): restart/replay
            # accounting, fault-containment counters, ladder state
            "resilience": {
                "state": self.health()["state"],
                "rung": self._ladder.rung,
                "restarts": self._restarts,
                "last_recover_ms": getattr(self, "_last_recover_ms", 0.0),
                "replayed": self._replayed,
                "shed": self._ladder.sheds,
                "reserve_stalls": self._reserve_stalls,
                "swap_corruptions": sc.swap_corruptions,
                "drafter_faults": sc.drafter_faults,
                "prefix_restore_faults": sc.prefix_restore_faults,
                "replay_mismatches": sc.replay_mismatches,
                "faults_injected": (dict(self._inj.counts)
                                    if self._inj is not None else {}),
            },
            "ticks": sc.ticks,
            "tokens_generated": sc.tokens_generated,
            "slots": self._engine.slots,
            "tp": self._tp,
            "int8_weights": self._engine.int8_weights,
            "int4_weights": self._engine.int4_weights,
            "int4_group": self._engine.int4_group,
            "int4_formulation": self._engine.int4_formulation,
            "kv_cache_bytes": self._engine.cache_bytes(),
            # device-memory ledger snapshot (obs/devprof.py): predicted
            # bytes per pool vs the measured jax.live_arrays() total
            "device_bytes": self._ledger.reconcile(),
            # chunked prefill + prefix reuse gauges (doc/serving.md):
            # hit rate is FRACTION OF PROMPT TOKENS restored from the
            # prefix cache; chunks/req is the mean chunk steps a request
            # cost (prefix hits lower it below ceil(n/chunk))
            "prefill_chunks_per_req": (sc.prefill_chunks
                                       / max(1, sc.requests_prefilled)),
            "prefix_hit_rate": (pc.hit_tokens / max(1, pc.prompt_tokens)
                                if pc is not None else 0.0),
            # speculative decoding gauges (doc/serving.md): all three
            # report a consistent 0.0 when no verify forward ever ran
            # (spec off, or the drafter never produced a proposal)
            "accept_rate": sc.spec_accepted / max(1, sc.spec_drafted),
            "spec_tokens_per_forward": (
                sc.spec_emitted / float(sc.spec_forwards)
                if sc.spec_forwards else 0.0),
            "spec_rollback_rate": (sc.spec_rollbacks
                                   / max(1, sc.spec_forwards)),
            "spec_forwards": sc.spec_forwards,
            "spec_backoffs": sc.spec_backoffs,
            # multi-tenant SLOs (serve/tenancy.py): per-tenant request
            # counters + live usage, None when serve_tenants is unset
            "tenants": ({t: {
                "priority": self._tenancy.policy_for(t).priority,
                "requests": dict(self._tcounts[t]),
                "queue_depth": self._tenant_queued(t),
                "slots": sc.tenant_usage(t)[0],
                "blocks": sc.tenant_usage(t)[1],
            } for t in self._tenancy.label_names()}
                if self._tenancy is not None else None),
            "prefix_cache_bytes": pc.nbytes if pc is not None else 0,
            "prefix_cache": ({
                "budget_bytes": pc.budget, "bytes": pc.nbytes,
                "chunks": pc.chunks, "hits": pc.hits,
                "misses": pc.misses, "hit_tokens": pc.hit_tokens,
                "prompt_tokens": pc.prompt_tokens,
                "evictions": pc.evictions,
                "inserted_chunks": pc.inserted_chunks,
            } if pc is not None else None),
        }

    def reset_metrics(self) -> None:
        """Zero the latency samples and gauges (bench.py warms the jit
        caches with one pass of the trace, then measures a clean one)."""
        with self._cond:
            self._ttft_s.clear()
            self._tok_gap_s.clear()
            self._queue_depth_max = 0
            self._counts = {k: 0 for k in self._counts}
            if self._tcounts is not None:
                self._tcounts = {t: dict.fromkeys(row, 0)
                                 for t, row in self._tcounts.items()}
        self._stats.clear()
        self._sched.ticks = 0
        self._sched.active_row_ticks = 0
        self._sched.tokens_generated = 0
        self._sched.prefill_chunks = 0
        self._sched.requests_prefilled = 0
        self._sched.spec_forwards = 0
        self._sched.spec_drafted = 0
        self._sched.spec_accepted = 0
        self._sched.spec_emitted = 0
        self._sched.spec_rollbacks = 0
        self._sched.spec_backoffs = 0
        self._sched.swaps_out = 0
        self._sched.swaps_in = 0
        self._sched.swap_corruptions = 0
        self._sched.drafter_faults = 0
        self._sched.prefix_restore_faults = 0
        self._reserve_stalls = 0
        if self._paged:
            # traffic counter only — block refcounts/tables are live
            # state a reset must not touch
            self._engine.manager.cow_faults = 0
        if self._prefix is not None:
            # traffic counters only: cached chunks stay warm — a bench's
            # measured pass is supposed to see the steady state
            self._prefix.reset_counters()
        # the registry histograms must reset WITH the counters they are
        # read against — otherwise a post-reset scrape shows
        # ttft_seconds_count > completed_total (the callback counters
        # read the zeroed dicts, the histograms would still carry the
        # warm pass)
        if self._tenancy is None:
            self._ttft_h.reset()
            self._gap_h.reset()
        else:
            for fam_name in ("cxn_serve_ttft_seconds",
                             "cxn_serve_token_gap_seconds"):
                for _, child in self._registry.get(fam_name).children():
                    child.reset()
        for _, child in self._registry.get(
                "cxn_serve_phase_seconds").children():
            child.reset()
