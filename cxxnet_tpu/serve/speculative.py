"""Speculative decoding: draft-and-verify multi-token decode steps.

Every plain decode tick runs one full model forward per emitted token,
so per-token latency is floored by per-step weight traffic no matter how
good the KV path is. Speculative decoding turns K sequential forwards
into ONE batched verify forward: a cheap *drafter* proposes up to
``spec_len`` continuation tokens, the target model scores all of them
(plus the pending last token) in a single forward
(``serve/engine.py:_verify_fn``), and the longest acceptable prefix is
emitted together with one correction/bonus token — up to
``spec_len + 1`` tokens per forward when the drafter is right, exactly
one (the correction) when it is wrong.

Two draft sources, both DETERMINISTIC proposals (the q ≡ 1 case of the
standard accept-with-min(1, p/q) rule, which makes the rejection test
``u < p(draft)`` and the residual the draft-excluded renormalized
target distribution — ops/sampling.py):

* :class:`NgramDrafter` — zero-cost prompt lookup: match the last few
  tokens of the context (prompt + generated history) against the
  context itself and propose the continuation of the most recent
  earlier occurrence. Pure host NumPy over tokens the scheduler already
  holds; no extra model, no device work. Pays off exactly when the
  output repeats material from the prompt/history (summarization,
  code edits, template-y generations).
* :class:`ModelDrafter` — a small draft model (same GPT stack at
  reduced depth/width) greedy-decoding K tokens ahead through its OWN
  :class:`~cxxnet_tpu.serve.engine.DecodeEngine` slot pool, one draft
  row mirroring each target slot. Catch-up reuses the engine's
  chunk-prefill program (consume the tokens the target emitted since
  the last draft), then K-1 BATCHED draft ticks propose for every
  drafting slot at once. Stale draft-row K/V beyond the synced point is
  unreachable by the same masked-softmax invariant the target's
  recycled slots lean on, and is overwritten by the next catch-up.

Identity contract: greedy (temperature 0) speculative output is
bit-identical to the solo ``gpt_decode`` stream — acceptance is
argmax-prefix matching against logits that are themselves bit-identical
to the tick's (engine._attn_verify), and the keys are never consumed on
the greedy path. Sampled output is identical in DISTRIBUTION (standard
rejection/residual sampling; chi-squared-pinned in
tests/test_sampling.py), with one fold_in index consumed per EMITTED
token so the per-token key schedule never desynchronizes from the
non-speculative path. Either way the drafter only affects SPEED
(accept_rate), never which distribution the tokens come from.

:class:`SpeculativeDecoder` drives the offline
``gpt_decode(speculative=...)`` path: batch prompts admitted into a
b-slot engine, per-iteration draft + verify per row, stragglers and
no-draft rows advanced by the ordinary batched tick. The serving
integration lives in serve/scheduler.py (``spec_steps``), which
interleaves per-slot verify chunks with the shared decode tick.

Paged serving note: under the paged KV cache the scheduler reserves the
verify window's blocks — allocation plus copy-on-write faults for any
shared block — BEFORE the forward dispatches (speculation never
preempts a neighbor for room; an unreservable window just skips the
draft this pass and the row ticks normally). Rollback therefore stays
free: a rejected draft's stale K/V already sits in privately-owned
blocks beyond the accepted position, so no COW fault — and no copy of
any kind — happens on rejection. The offline decoder's engine keeps the
dense pool (equal-length offline batches are its sweet spot), as does
the :class:`ModelDrafter` mirror engine — draft rows are all the same
short horizon, exactly the shape dense rows price correctly.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

import numpy as np

from ..obs.trace import TID_ENGINE, get_tracer
from ..utils import profiler

__all__ = ["NgramDrafter", "ModelDrafter", "SpeculativeDecoder",
           "speculative_decode"]


class NgramDrafter:
    """Prompt-lookup drafter: propose the continuation of the most
    recent earlier occurrence of the context's trailing n-gram.

    Longest-match-first: n-grams from ``max_ngram`` down to
    ``min_ngram`` are tried in order; the LAST (most recent) earlier
    occurrence wins, matching the intuition that recent repetition is
    the best predictor of continued repetition. Returns up to ``k``
    tokens (possibly fewer near the match's end, possibly none when the
    suffix never occurred before) — an empty draft simply means the
    scheduler falls back to a plain tick for that row this pass."""

    name = "ngram"

    def __init__(self, spec_len: int, max_ngram: int = 3,
                 min_ngram: int = 1):
        if spec_len < 1:
            raise ValueError("spec_len must be >= 1, got %d" % spec_len)
        if not 1 <= min_ngram <= max_ngram:
            raise ValueError("need 1 <= min_ngram <= max_ngram, got %d/%d"
                             % (min_ngram, max_ngram))
        self.spec_len = int(spec_len)
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)

    def reset(self, slot: int) -> None:
        """Stateless — the context is passed whole every call."""

    def close(self) -> None:
        """Nothing to release (no device state, no threads)."""

    def draft_one(self, ctx: np.ndarray, k: int) -> np.ndarray:
        """Up to ``k`` proposed continuation tokens for ``ctx``."""
        ctx = np.asarray(ctx, np.int32).reshape(-1)
        n = ctx.size
        k = min(int(k), self.spec_len)
        if k < 1 or n < self.min_ngram + 1:
            return np.zeros((0,), np.int32)
        for g in range(min(self.max_ngram, n - 1), self.min_ngram - 1, -1):
            # candidate starts 0 .. n-g-1: every one has >= 1
            # continuation token and excludes the trailing suffix itself
            win = np.lib.stride_tricks.sliding_window_view(ctx, g)
            hits = np.flatnonzero((win[:n - g] == ctx[n - g:]).all(axis=1))
            if hits.size:
                j = int(hits[-1])
                return ctx[j + g:j + g + k].copy()
        return np.zeros((0,), np.int32)

    def draft(self, contexts: Dict[int, np.ndarray],
              lens: Dict[int, int]) -> Dict[int, np.ndarray]:
        """Per-slot drafts for a batch of contexts (host-only loop)."""
        return {slot: self.draft_one(ctx, lens[slot])
                for slot, ctx in contexts.items()}


class ModelDrafter:
    """Small-draft-model drafter over its own DecodeEngine slot pool.

    One draft cache row mirrors each target slot. ``draft()`` first
    catches each drafting row up to its request's current context via
    the draft engine's chunk-prefill program — the final chunk's greedy
    sample IS the first draft token — then runs K-1 BATCHED draft ticks
    (greedy, temperature 0) to extend every drafting slot's proposal at
    once. Draft-row K/V written by those speculative ticks is stale the
    moment the target rejects; it sits beyond the synced position, so
    it is unreachable (masked) until the next catch-up overwrites it —
    the engine's standard recycled-row invariant.

    The draft model must share the target's vocabulary (its tokens index
    the target's embedding) and cover its sequence length (draft
    positions run up to the target's verify window)."""

    name = "model"

    def __init__(self, cfg, params: Dict, slots: int, target_cfg=None,
                 prefill_chunk: int = 64):
        from .engine import DecodeEngine
        if target_cfg is not None:
            if cfg.vocab_size != target_cfg.vocab_size:
                raise ValueError(
                    "draft model vocab %d != target vocab %d (draft "
                    "tokens index the target embedding)"
                    % (cfg.vocab_size, target_cfg.vocab_size))
            if cfg.seq_len < target_cfg.seq_len:
                raise ValueError(
                    "draft model seq_len %d < target seq_len %d (draft "
                    "rows mirror target positions)"
                    % (cfg.seq_len, target_cfg.seq_len))
        self.engine = DecodeEngine(cfg, params, slots,
                                   prefill_chunk=max(1, prefill_chunk))
        n = slots
        self._synced = [0] * n          # context tokens already consumed
        self._park = self.engine.row_len - 1
        self._zero_key = np.zeros((2,), np.uint32)
        self._keys = np.zeros((n, 2), np.uint32)
        self._fold = np.zeros(n, np.int32)
        self._temp = np.zeros(n, np.float32)    # greedy drafting
        self._topk = np.zeros(n, np.int32)
        self._topp = np.ones(n, np.float32)

    def reset(self, slot: int) -> None:
        """A new request owns ``slot`` — its mirror row restarts from
        position 0 (catch-up rewrites it; stale tail is masked). Also
        the scheduler's fault-containment hook: a draft pass that threw
        mid-catch-up may have advanced ``_synced`` past what the mirror
        row actually holds, so the containing scheduler resets every
        involved slot before the next pass (serve/scheduler.py)."""
        self._synced[slot] = 0

    def close(self) -> None:
        """Idempotent; a closed drafter fails loudly on the next draft
        (the engine recovery path rebuilds drafters from scratch —
        serve/resilience.py — so a draft through a torn-down pool is a
        supervisor bug, not a condition to limp through)."""
        self.closed = True
        self.engine.close()

    closed = False

    def _catch_up(self, slot: int, ctx: np.ndarray) -> int:
        """Consume ``ctx[synced:]`` into the mirror row via the chunk
        program; returns the greedy next token after the full context
        (the first draft). The start is aligned DOWN to a chunk multiple
        — the chunk program writes a full ``chunk``-wide window at its
        offset, and only aligned offsets are guaranteed to fit inside
        ``row_len`` (an unaligned final window could run past the row,
        where dynamic_update_slice start-clamping would silently shift
        the write onto earlier live positions). Re-consumed tokens
        (alignment, or a retry on an ungrown context) just rewrite their
        own K/V rows with identical values — harmless."""
        n = len(ctx)
        s = min(self._synced[slot], n - 1)
        c = self.engine.chunk
        s = (s // c) * c
        tok = None
        while s < n:
            e = min(s + c, n)
            buf = np.zeros(c, np.int32)
            buf[:e - s] = ctx[s:e]
            tok = self.engine.prefill_chunk(slot, buf, s, e - s,
                                            self._zero_key, 0.0, 0, 1.0)
            s = e
        self._synced[slot] = n
        return int(tok)

    def draft(self, contexts: Dict[int, np.ndarray],
              lens: Dict[int, int]) -> Dict[int, np.ndarray]:
        if self.closed:
            raise RuntimeError("ModelDrafter is closed (its slot pool "
                               "was torn down)")
        if not contexts:
            return {}
        drafts: Dict[int, list] = {}
        lim: Dict[int, int] = {}
        tok = np.zeros(self.engine.slots, np.int32)
        pos = np.full(self.engine.slots, self._park, np.int32)
        seq = self.engine.cfg.seq_len
        for slot, ctx in contexts.items():
            ctx = np.asarray(ctx, np.int32).reshape(-1)
            # draft positions run len(ctx) .. len(ctx) + k - 1: cap k so
            # they stay inside the draft model's own position table (the
            # ctor only requires seq_len >= the target's, so a request
            # near the sequence end can ask for more than the table
            # holds — a shorter draft is still useful, none falls back
            # to a plain tick)
            k = min(int(lens[slot]), seq - len(ctx))
            if k < 1:
                continue
            first = self._catch_up(slot, ctx)
            lim[slot] = k
            drafts[slot] = [first]
            tok[slot] = first
            pos[slot] = len(ctx)        # the first draft's position
        if not drafts:
            return {}
        for _ in range(max(lim.values()) - 1):
            # slots whose cap is reached park their row so the batched
            # tick's unconditional write stops touching live positions
            done = True
            for slot in drafts:
                if len(drafts[slot]) >= lim[slot]:
                    pos[slot] = self._park
                else:
                    done = False
            if done:
                break
            nxt = self.engine.tick(tok, pos, self._keys, self._fold,
                                   self._temp, self._topk, self._topp)
            for slot in drafts:
                if len(drafts[slot]) >= lim[slot]:
                    continue
                drafts[slot].append(int(nxt[slot]))
                tok[slot] = nxt[slot]
                pos[slot] += 1
        return {slot: np.asarray(d[:lens[slot]], np.int32)
                for slot, d in drafts.items()}


class SpeculativeDecoder:
    """Offline draft-and-verify decode over a b-slot DecodeEngine — the
    machinery behind ``gpt_decode(speculative=...)``.

    Prefill runs the engine's whole-prompt program (equal-length offline
    batches are its sweet spot, and its ``fold_in(key, 0)`` first-token
    schedule is the solo path's); then each iteration drafts per row,
    verifies rows with non-empty drafts in one ``serve_verify_chunk``
    dispatch each, and advances every remaining unfinished row with one
    BATCHED tick. Greedy output is bit-identical to the non-speculative
    ``gpt_decode`` scan; a drafter only changes how many forwards that
    stream costs."""

    def __init__(self, cfg, params: Dict, batch: int, spec_len: int = 4,
                 mode: str = "ngram", model=None, tracer=None,
                 int8_weights: bool = False, int4_weights: bool = False,
                 int4_group: int = 64):
        """``tracer``: obs span recorder for the offline decode loop
        (doc/observability.md) — None uses the process-global tracer,
        so ``gpt_decode(speculative=...)`` runs show up on the same
        TID_ENGINE track as serving ticks; pass one with
        ``enabled=False`` to opt out.

        ``int8_weights`` streams the target engine's block matmul
        weights int8-quantized (per-out-column, models/gpt.py
        _quantize_decode_blocks) through the verify/tick programs —
        the previously-impossible speculative-plus-int8 combination.
        Greedy output is then bit-identical to the engine's OWN
        non-speculative int8 stream (the verify logits are the int8
        tick's logits); the drafter keeps full-precision weights — it
        only affects accept_rate, never which tokens are emitted.

        ``int4_weights`` / ``int4_group`` do the same with PACKED int4
        weights (group-wise scales, models/gpt.py
        _quantize_decode_blocks_int4): greedy spec-int4 output is
        bit-identical to the engine's own non-speculative int4 stream.
        Mutually exclusive with ``int8_weights`` (the engine ctor
        rejects the pair)."""
        from .engine import DecodeEngine
        if mode not in ("ngram", "model"):
            raise ValueError("speculative mode must be 'ngram' or "
                             "'model', got %r" % (mode,))
        if spec_len < 1:
            raise ValueError("spec_len must be >= 1, got %d" % spec_len)
        self.cfg = cfg
        self.spec_len = min(int(spec_len), max(cfg.seq_len - 1, 1))
        self.engine = DecodeEngine(cfg, params, slots=batch,
                                   prefill_chunk=0, spec_len=self.spec_len,
                                   int8_weights=int8_weights,
                                   int4_weights=int4_weights,
                                   int4_group=int4_group)
        if mode == "model":
            if model is None:
                raise ValueError("speculative mode 'model' needs "
                                 "model=(draft_cfg, draft_params)")
            dcfg, dparams = model
            self.drafter = ModelDrafter(dcfg, dparams, batch,
                                        target_cfg=cfg)
        else:
            self.drafter = NgramDrafter(self.spec_len)
        self.tracer = tracer if tracer is not None else get_tracer()
        # observability: filled per decode() call
        self.stats = {"forwards": 0, "drafted": 0, "accepted": 0,
                      "rollbacks": 0, "ticks": 0, "tokens": 0}

    def close(self) -> None:
        self.drafter.close()
        self.engine.close()

    def decode(self, prompt: np.ndarray, max_new: int,
               temperature: float = 0.0, rng=None, top_k: int = 0,
               top_p: float = 1.0) -> np.ndarray:
        """(b, n_prompt) int32 -> (b, n_prompt + max_new) int32."""
        import jax
        prompt = np.asarray(prompt, np.int32)
        b, n = prompt.shape
        if b != self.engine.slots:
            raise ValueError("decoder built for batch %d, got %d"
                             % (self.engine.slots, b))
        eng = self.engine
        if rng is None:
            rng = jax.random.PRNGKey(0)
        # per-row keys: greedy never reads them; sampled speculative is
        # distribution-level (doc/serving.md), so independent per-row
        # streams (split) are the right semantics for a batch
        keys = (np.asarray(jax.random.split(rng, b), np.uint32) if b > 1
                else np.asarray(rng, np.uint32)[None])
        for s in self.stats:
            self.stats[s] = 0
        toks = [[] for _ in range(b)]
        for i in range(b):
            self.drafter.reset(i)
            toks[i].append(eng.prefill(i, prompt[i], keys[i],
                                       temperature, top_k, top_p))
        park = eng.row_len - 1
        pos = np.full(b, n, np.int32)
        fold = np.ones(b, np.int32)
        last = np.asarray([t[-1] for t in toks], np.int32)
        temp_row = np.full(b, temperature, np.float32)
        topk_row = np.full(b, top_k, np.int32)
        topp_row = np.full(b, top_p, np.float32)
        K = self.spec_len
        while True:
            live = [i for i in range(b) if len(toks[i]) < max_new]
            if not live:
                break
            # draft for rows whose remaining budget and row window admit
            # a verify (the program writes K + 1 rows from pos)
            want = {i: min(K, max_new - len(toks[i]) - 1) for i in live
                    if max_new - len(toks[i]) >= 2
                    and int(pos[i]) + K + 1 <= eng.row_len}
            tr = self.tracer if self.tracer.enabled else None
            t0 = time.perf_counter()
            drafts = self.drafter.draft(
                {i: np.concatenate([prompt[i],
                                    np.asarray(toks[i], np.int32)])
                 for i in want}, want) if want else {}
            if tr is not None and want:
                # mirror the serving scheduler's shared-span discipline:
                # one engine-track span per batched drafter pass / per
                # verify forward / per tick, never one per token
                tr.add(profiler.SPEC_DRAFT, t0, time.perf_counter() - t0,
                       TID_ENGINE, cat="spec_offline",
                       args={"rows": len(want)})
            for i, d in drafts.items():
                nd = len(d)
                if nd < 1:
                    continue
                buf = np.zeros(K + 1, np.int32)
                buf[0] = last[i]
                buf[1:1 + nd] = d
                t0 = time.perf_counter()
                n_acc, emit = eng.verify_chunk(
                    i, buf, int(pos[i]), nd, keys[i], int(fold[i]),
                    temperature, top_k, top_p)
                if tr is not None:
                    tr.add(profiler.SPEC_VERIFY, t0,
                           time.perf_counter() - t0, TID_ENGINE,
                           cat="spec_offline",
                           args={"row": i, "drafted": nd,
                                 "accepted": int(n_acc)})
                emitted = [int(t) for t in d[:n_acc]] + [int(emit)]
                self.stats["forwards"] += 1
                self.stats["drafted"] += nd
                self.stats["accepted"] += n_acc
                self.stats["rollbacks"] += int(n_acc < nd)
                toks[i].extend(emitted)
                pos[i] += len(emitted)
                fold[i] += len(emitted)
                last[i] = emitted[-1]
            # one batched tick advances every still-unfinished row
            # (including just-verified ones — their new position's K/V
            # is written by the tick itself, write-before-attend)
            tick_rows = [i for i in range(b) if len(toks[i]) < max_new]
            if tick_rows:
                t_pos = np.full(b, park, np.int32)
                t_temp = np.zeros(b, np.float32)
                for i in tick_rows:
                    t_pos[i] = pos[i]
                    t_temp[i] = temp_row[i]
                t0 = time.perf_counter()
                nxt = eng.tick(last, t_pos, keys, fold, t_temp, topk_row,
                               topp_row)
                if tr is not None:
                    tr.add(profiler.DECODE_TICK, t0,
                           time.perf_counter() - t0, TID_ENGINE,
                           cat="spec_offline",
                           args={"decoding": len(tick_rows)})
                self.stats["ticks"] += 1
                for i in tick_rows:
                    toks[i].append(int(nxt[i]))
                    last[i] = nxt[i]
                    pos[i] += 1
                    fold[i] += 1
        self.stats["tokens"] = sum(len(t) for t in toks)
        return np.concatenate(
            [prompt, np.asarray(toks, np.int32)], axis=1)


def speculative_decode(params: Dict, prompt, max_new: int, cfg,
                       temperature: float = 0.0, rng=None,
                       top_k: int = 0, top_p: float = 1.0,
                       spec: Optional[dict] = None,
                       int8_weights: bool = False,
                       int4_weights: bool = False,
                       int4_group: int = 64):
    """``gpt_decode(speculative=...)``'s implementation: build a
    one-shot :class:`SpeculativeDecoder`, run it, fill ``spec['stats']``
    (if the caller passed a dict to receive accept_rate & friends), and
    return the (b, n_prompt + max_new) ids. ``spec`` keys: ``mode``
    ('ngram' | 'model'), ``spec_len``, ``model`` ((draft_cfg,
    draft_params) for mode 'model'), ``stats`` (optional out-dict).
    ``int8_weights`` streams the target weights int8-quantized through
    the verify/tick programs; ``int4_weights`` / ``int4_group`` stream
    them packed int4 instead (SpeculativeDecoder docstring)."""
    spec = dict(spec or {})
    stats_out = spec.get("stats")
    prompt = np.asarray(prompt, np.int32)
    dec = SpeculativeDecoder(cfg, params, batch=prompt.shape[0],
                             spec_len=int(spec.get("spec_len", 4)),
                             mode=spec.get("mode", "ngram"),
                             model=spec.get("model"),
                             int8_weights=int8_weights,
                             int4_weights=int4_weights,
                             int4_group=int4_group)
    try:
        out = dec.decode(prompt, max_new, temperature=temperature,
                         rng=rng, top_k=top_k, top_p=top_p)
        if isinstance(stats_out, dict):
            st = dec.stats
            stats_out.update(st)
            stats_out["accept_rate"] = (st["accepted"]
                                        / max(1, st["drafted"]))
            stats_out["spec_tokens_per_forward"] = (
                (st["accepted"] + st["forwards"]) / max(1, st["forwards"])
                if st["forwards"] else 0.0)
    finally:
        dec.close()
    return out
