"""Cross-process serving fleet: disaggregated prefill/decode tiers
behind an out-of-process RPC router.

``serve_replicas=M`` (serve/router.py) is M engines as *threads* in one
process — one GIL, one failure domain, one host. This module is the
same serving contract over *processes*: a :class:`FleetRouter` spawns N
worker processes (each hosting one :class:`InferenceServer` over its
own device block), talks to them over the length-prefixed binary RPC of
serve/rpc.py, and splits them into two tiers:

* **prefill tier** — runs chunked prefill (prefix cache included);
  every request is submitted with ``migrate=True``, so the scheduler
  parks the just-prefilled row as a swap record (``_migrate_out``)
  instead of decoding it;
* **decode tier** — adopts the migrated rows: the router moves the
  crc32-checksummed engine swap record (serve/paged.py
  ``swap_out_row``/``swap_in_row`` — int8 KV stored representation
  included) over the socket, and the decode worker's scheduler resumes
  it through the exact host-RAM preemption path. The checksum verifies
  the wire round trip bit-exactly; a corrupted payload fails typed
  (``SwapCorruptionError``) and replays only that request.

Failure domains are real here: the ROUTER owns the ``ReplayJournal``
(serve/resilience.py), so a SIGKILL'd worker's in-flight requests are
rewound (``rewind_request`` — the same contract the in-process router
uses) and re-adopted on a survivor, bit-identically for greedy streams
and distribution-identically for sampled ones. A replacement worker is
spawned in the background; with a shared AOT executable cache and
device relabeling armed (analysis/aot_cache.py, ``CXN_AOT_RELABEL``)
it loads every serve program instead of compiling — near-free spin-up.

The in-process ``ServeRouter`` remains the single-host fast path and
the oracle the fleet is pinned against (tests/test_fleet.py). With
``serve_fleet`` unset nothing in this module runs: no process, no
thread, no socket.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import os
import pickle
import shutil
import subprocess
import sys
import tempfile
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ..analysis.concurrency import make_lock
from ..obs import metrics as obs_metrics
from .resilience import (EngineFailedError, ReplayJournal,
                         reset_for_replay)
from .router import rewind_request
from .rpc import RpcClient, RpcError, RpcServer, WorkerLostError
from .scheduler import Request, SamplingParams
from .server import (AdmissionError, QueueFullError, QuotaExceededError,
                     ServeResult)

__all__ = ["FleetRouter", "FleetWorker", "WorkerLostError",
           "worker_main", "parse_tiers", "request_to_wire",
           "request_from_wire", "record_to_wire", "record_from_wire"]

_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

READY_SENTINEL = "CXN_FLEET_READY"


def parse_tiers(spec: str) -> Dict[str, int]:
    """Parse a ``serve_fleet`` tier spec — ``"prefill=1,decode=2"`` —
    into ``{"prefill": n, "decode": m}``. A bare integer means that
    many decode workers with no prefill tier (no migration: a plain
    cross-process replica fleet)."""
    spec = (spec or "").strip()
    out = {"prefill": 0, "decode": 0}
    if not spec:
        return out
    if spec.isdigit():
        out["decode"] = int(spec)
        return out
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        tier, sep, n = item.partition("=")
        tier = tier.strip()
        if not sep or tier not in out:
            raise ValueError(
                "serve_fleet: malformed tier spec %r (want e.g. "
                "'prefill=1,decode=2')" % (item,))
        out[tier] = int(n)
    return out


# ----------------------------------------------------------- wire forms
def request_to_wire(req: Request) -> dict:
    return {"rid": req.rid,
            "prompt": np.asarray(req.prompt, np.int32),
            "params": dataclasses.asdict(req.params),
            "tenant": req.tenant,
            "adapter": req.adapter,
            "tokens": list(req.tokens),
            "replay_expect": (None if req.replay_expect is None
                              else list(req.replay_expect))}


def request_from_wire(d: dict) -> Request:
    req = Request(int(d["rid"]), np.asarray(d["prompt"], np.int32),
                  SamplingParams(**d["params"]), time.perf_counter(),
                  tenant=d.get("tenant", ""),
                  adapter=d.get("adapter", ""))
    req.tokens = list(d.get("tokens", ()))
    exp = d.get("replay_expect")
    req.replay_expect = None if exp is None else list(exp)
    return req


_REC_KEYS = ("key", "phase", "tok", "pos", "fold", "spec", "charge",
             "k", "v", "ks", "vs", "n", "nbytes", "crc")


def record_to_wire(rec: dict) -> dict:
    d = {k: rec[k] for k in _REC_KEYS if k in rec}
    d["req"] = request_to_wire(rec["req"])
    return d


def record_from_wire(d: dict):
    rec = dict(d)
    req = request_from_wire(rec.pop("req"))
    # rebase the lifecycle clock: perf_counter values don't compare
    # across processes, and the resume path orders by admit_t
    now = time.perf_counter()
    req.submit_t = req.admit_t = req.first_token_t = now
    req.deadline = None         # already admitted once (replay contract)
    return req, rec


def result_to_wire(res: ServeResult) -> dict:
    return {"status": res.status,
            "tokens": np.asarray(res.tokens, np.int32),
            "error": res.error, "ttft_ms": res.ttft_ms,
            "ms_per_token": res.ms_per_token, "queue_ms": res.queue_ms,
            "retry_after_ms": res.retry_after_ms}


def result_from_wire(d: dict) -> ServeResult:
    return ServeResult(d["status"], np.asarray(d["tokens"], np.int32),
                       error=d.get("error", ""),
                       ttft_ms=d.get("ttft_ms", 0.0),
                       ms_per_token=d.get("ms_per_token", 0.0),
                       queue_ms=d.get("queue_ms", 0.0),
                       retry_after_ms=d.get("retry_after_ms", 0.0))


# typed remote exceptions revived locally: the fleet keeps the single
# server's admission contract — a queue-full worker raises
# QueueFullError (back-off hint included) through the socket
def _revive(e: RpcError) -> BaseException:
    p = e.payload
    msg = p.get("msg", str(e))
    t = e.remote_type
    if t == "QueueFullError":
        return QueueFullError(msg,
                              retry_after_ms=p.get("retry_after_ms", 0.0))
    if t == "QuotaExceededError":
        return QuotaExceededError(msg,
                                  retry_after_ms=p.get("retry_after_ms",
                                                       0.0),
                                  tenant=p.get("tenant", ""),
                                  kind=p.get("kind", ""))
    if t == "AdmissionError":
        return AdmissionError(msg)
    if t == "EngineFailedError":
        return EngineFailedError(msg)
    if t == "TimeoutError":
        return TimeoutError(msg)
    return e


# ------------------------------------------------------- worker process
class FleetWorker:
    """The worker-process side: one InferenceServer behind the RPC verb
    surface. ``handle(verb, payload)`` is the RpcServer handler;
    requests are tracked by the ROUTER's rid (the ``rid=`` submit hook),
    so the cross-process journal and failover accounting share one key
    space."""

    def __init__(self, server):
        self.server = server
        self._handles: Dict[int, Request] = {}  # guarded_by: self._lock
        self._lock = make_lock("FleetWorker._lock")
        self.shutdown_event = threading.Event()
        self.spinup_info: dict = {}

    # every verb below runs on its own RpcServer dispatch thread
    def handle(self, verb: str, p: dict):
        fn = getattr(self, "verb_" + verb, None)
        if fn is None:
            raise AdmissionError("unknown fleet verb %r" % verb)
        return fn(**p)

    def _req(self, rid: int) -> Request:
        with self._lock:
            req = self._handles.get(rid)
        if req is None:
            raise AdmissionError("unknown request id %d on this worker"
                                 % rid)
        return req

    def verb_ping(self):
        return True

    def verb_health(self):
        h = dict(self.server.health())
        h["pid"] = os.getpid()
        return h

    def verb_spinup(self):
        """Spin-up accounting recorded at READY time: compile seconds
        by program label (obs/devprof.py CompileWatch) and the AOT
        cache traffic — the zero-compile replacement-worker pin."""
        return dict(self.spinup_info)

    def verb_metrics(self):
        return self.server.metrics()

    def verb_metrics_state(self):
        return obs_metrics.registry_state(self.server.registry)

    def verb_metrics_text(self):
        return self.server.metrics_text()

    def verb_submit(self, rid: int, prompt, params: dict,
                    tenant: str = "", migrate: bool = False,
                    block: bool = False, adapter: str = ""):
        req = self.server.submit(np.asarray(prompt, np.int32),
                                 params=SamplingParams(**params),
                                 block=block, tenant=tenant, rid=rid,
                                 migrate=migrate, adapter=adapter)
        with self._lock:
            self._handles[rid] = req
        return True

    def verb_result(self, rid: int, wait: Optional[float] = None):
        res = self.server.result(self._req(rid), timeout=wait)
        if res.status == "migrated":
            # the router raced the migration pump; it retries once the
            # decode-tier owner is known
            return {"status": "__migrated__", "tokens": ()}
        return result_to_wire(res)

    def verb_fetch_migrated(self, rid: int,
                            wait: Optional[float] = None):
        req = self._req(rid)
        if not req.done.wait(wait):
            raise TimeoutError("request %d still prefilling" % rid)
        rec = self.server.export_migrated(req, timeout=0)
        if rec is not None:
            return {"kind": "record", "record": record_to_wire(rec)}
        if req.status == "migrated":
            # parked record lost to an engine recovery between park and
            # export — the router replays from its journal
            return {"kind": "lost"}
        return {"kind": "result",
                "result": result_to_wire(self.server.result(req, 0))}

    def verb_adopt_migrated(self, record: dict):
        req, rec = record_from_wire(record)
        self.server.adopt_swapped(req, rec)
        with self._lock:
            self._handles[req.rid] = req
        return True

    def verb_adopt(self, request: dict):
        req = request_from_wire(request)
        now = time.perf_counter()
        req.submit_t = now
        reset_for_replay(req)
        self.server.adopt(req)
        with self._lock:
            self._handles[req.rid] = req
        return True

    def verb_drain(self, wait: Optional[float] = None):
        self.server.drain(timeout=wait)
        return True

    def verb_shutdown(self):
        self.shutdown_event.set()
        return True


def worker_main(spec_path: str, tier: str = "") -> int:
    """Process entry (``python -m cxxnet_tpu.serve.fleet <spec> [tier]``
    / CLI ``task=fleet-worker``): build the InferenceServer from the
    pickled spec, bind the RPC port, print the READY sentinel + port on
    stdout (the router's spawn handshake), and serve until the shutdown
    verb."""
    with open(spec_path, "rb") as f:
        spec = pickle.load(f)
    kw = dict(spec.get("server_kw") or {})
    kw.update((spec.get("tier_kw") or {}).get(tier, {}))
    from .server import InferenceServer
    srv = InferenceServer(spec["cfg"], spec["params"], **kw)
    worker = FleetWorker(srv)
    # spin-up accounting BEFORE serving traffic: compile totals by
    # attributed program label + the AOT cache counters — what the
    # zero-compile replacement-worker test pins
    try:
        from ..obs import devprof
        worker.spinup_info["compile_totals"] = dict(
            devprof.compile_watch().totals)
    except Exception:
        worker.spinup_info["compile_totals"] = {}
    worker.spinup_info["aot"] = srv.metrics().get("aot_cache")
    worker.spinup_info["tier"] = tier
    rpc = RpcServer(worker.handle, port=int(spec.get("port", 0)),
                    name="worker")
    rpc.start()
    print("%s %d" % (READY_SENTINEL, rpc.port), flush=True)
    worker.shutdown_event.wait()
    time.sleep(0.25)            # let the shutdown reply flush
    rpc.close()
    try:
        srv.shutdown(drain=False, timeout=10)
    except Exception:
        pass
    return 0


# ------------------------------------------------------- router process
class _Worker:
    """Router-side handle on one worker process: tier, subprocess,
    stdout drain, RPC client, and liveness."""

    def __init__(self, tier: str, idx: int):
        self.tier = tier
        self.idx = idx
        self.name = "%s%d" % (tier, idx)
        self.proc: Optional[subprocess.Popen] = None
        self.client: Optional[RpcClient] = None
        self.port: Optional[int] = None
        self.ready = threading.Event()
        self.dead = False
        self.lines: collections.deque = collections.deque(maxlen=400)
        self.reader: Optional[threading.Thread] = None

    def call(self, verb: str, timeout: Optional[float] = None,
             **payload):
        if self.dead or self.client is None:
            raise WorkerLostError("worker %s is gone" % self.name)
        try:
            return self.client.call(verb, timeout=timeout, **payload)
        except RpcError as e:
            raise _revive(e)

    def tail(self, n: int = 40) -> str:
        return "\n".join(list(self.lines)[-n:])


class FleetRouter:
    """Spawn and front a cross-process serving fleet. The submit /
    result / drain / metrics surface mirrors ``ServeRouter``; handles
    are plain :class:`Request` mirrors (tokens live worker-side until
    the terminal result crosses back).

    ``prefill``/``decode`` are the tier sizes; with ``prefill == 0``
    the fleet is a plain cross-process replica pool (no migration).
    ``tier_kw`` overlays per-tier server kwargs on ``server_kw`` (e.g.
    chaos on the decode tier only). ``worker_env`` overlays the worker
    process environment — device placement rides it (the CPU CI passes
    a one-device XLA_FLAGS; a TPU rig passes per-tier visible-device
    variables). ``aot_relabel`` (default on when ``aot_cache`` is set)
    arms device relabeling in the workers so one persisted artifact
    serves every worker of a tier."""

    def __init__(self, cfg, params, *, prefill: int = 1,
                 decode: int = 2, worker_env: Optional[dict] = None,
                 tier_kw: Optional[dict] = None,
                 aot_relabel: Optional[bool] = None,
                 restart_workers: bool = True, heartbeat_s: float = 2.0,
                 spawn_timeout: float = 600.0, registry=None,
                 defaults: Optional[SamplingParams] = None,
                 **server_kw):
        if decode < 1:
            raise ValueError("fleet needs decode >= 1 worker, got %d"
                             % decode)
        if prefill < 0:
            raise ValueError("fleet prefill tier size must be >= 0")
        self._heartbeat_s = float(heartbeat_s)
        self._spawn_timeout = float(spawn_timeout)
        self._restart_workers = bool(restart_workers)
        self._worker_env = dict(worker_env or {})
        if aot_relabel is None:
            aot_relabel = bool(server_kw.get("aot_cache"))
        self._aot_relabel = bool(aot_relabel)
        self._defaults = (defaults if defaults is not None
                          else SamplingParams())
        if server_kw.get("timeout_ms") and not self._defaults.timeout_ms:
            self._defaults = dataclasses.replace(
                self._defaults, timeout_ms=server_kw["timeout_ms"])
        # _lock guards the request tables + counters below; _fail_lock
        # serializes ONLY the worker-death latch (_note_lost), so a
        # failover never has to wait on the request tables and the two
        # are never nested — the lint acquisition graph (CXN302) and
        # the CXN_LOCK_WATCH watchdog both check that stays true
        self._lock = make_lock("FleetRouter._lock")
        self._fail_lock = make_lock("FleetRouter._fail_lock")
        self._closing = False               # guarded_by: self._lock
        self._rid = itertools.count()
        self._journal = ReplayJournal()     # guarded_by: self._lock
        # rid -> local mirror / owning worker / wire result
        self._reqs: Dict[int, Request] = {}      # guarded_by: self._lock
        self._owner: Dict[int, _Worker] = {}     # guarded_by: self._lock
        self._results: Dict[int, dict] = {}      # guarded_by: self._lock
        self._mig_done: Dict[int, threading.Event] = {}  # guarded_by: self._lock
        self.migrations = 0                 # guarded_by: self._lock
        self.kv_wire_bytes = 0              # guarded_by: self._lock
        self.replays = 0                    # guarded_by: self._lock
        self.restarts = 0                   # guarded_by: self._lock
        self._final_metrics: Optional[Dict] = None  # drain() snapshot
        # router-owned fleet metrics; worker registries merge with this
        # one (worker="router") in metrics_text()
        self._registry = (registry if registry is not None
                          else obs_metrics.Registry())
        self._registry.gauge(
            "cxn_fleet_workers", "live fleet worker processes",
            fn=lambda: float(len(self._live())))
        self._mig_c = self._registry.counter(
            "cxn_fleet_migrations_total",
            "prefill->decode KV-row migrations completed over the wire")
        self._wire_c = self._registry.counter(
            "cxn_kv_wire_bytes_total",
            "KV swap-record payload bytes moved over fleet sockets")
        self._restart_c = self._registry.counter(
            "cxn_worker_restarts_total",
            "replacement fleet workers spawned after a worker loss")
        self._replay_c = self._registry.counter(
            "cxn_fleet_replays_total",
            "requests replayed on a survivor after a worker loss")
        # one spec file feeds every worker of the fleet (replacements
        # included): config + host-resident params + server kwargs
        self._spec_dir = tempfile.mkdtemp(prefix="cxn-fleet-")
        self._spec_path = os.path.join(self._spec_dir, "spec.pkl")
        import jax
        host_params = jax.tree_util.tree_map(np.asarray, params)
        with open(self._spec_path, "wb") as f:
            pickle.dump({"cfg": cfg, "params": host_params,
                         "server_kw": dict(server_kw),
                         "tier_kw": dict(tier_kw or {})},
                        f, protocol=pickle.HIGHEST_PROTOCOL)
        self.workers: List[_Worker] = []
        self._widx = {"prefill": itertools.count(),
                      "decode": itertools.count()}
        try:
            # sequential spawn: the first worker warms the shared AOT
            # cache, every later worker (relabeling armed) loads its
            # executables instead of compiling
            for _ in range(prefill):
                self._spawn("prefill")
            for _ in range(decode):
                self._spawn("decode")
        except Exception:
            self._teardown(kill=True)
            raise
        self._stop = threading.Event()
        self._monitor_t = threading.Thread(
            target=self._monitor, name="cxn-fleet-monitor", daemon=True)
        self._monitor_t.start()

    # ------------------------------------------------------------ spawn
    def _spawn(self, tier: str) -> _Worker:
        w = _Worker(tier, next(self._widx[tier]))
        env = dict(os.environ)
        env.pop("PYTHONPATH", None)
        env["PYTHONPATH"] = _REPO_ROOT
        env["PYTHONUNBUFFERED"] = "1"
        if self._aot_relabel:
            env["CXN_AOT_RELABEL"] = "1"
        env.update(self._worker_env)
        w.proc = subprocess.Popen(
            [sys.executable, "-m", "cxxnet_tpu.serve.fleet",
             self._spec_path, tier],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            env=env, cwd=_REPO_ROOT, text=True)
        w.reader = threading.Thread(target=self._drain_stdout,
                                    args=(w,),
                                    name="cxn-fleet-stdout-%s" % w.name,
                                    daemon=True)
        w.reader.start()
        if not w.ready.wait(self._spawn_timeout) or w.port is None:
            try:
                w.proc.kill()
            except OSError:
                pass
            raise RuntimeError(
                "fleet worker %s did not come up within %.0fs; last "
                "output:\n%s" % (w.name, self._spawn_timeout, w.tail()))
        w.client = RpcClient("127.0.0.1", w.port, name=w.name)
        with self._lock:
            self.workers.append(w)
        return w

    def _drain_stdout(self, w: _Worker) -> None:
        for line in w.proc.stdout:
            line = line.rstrip("\n")
            w.lines.append(line)
            if line.startswith(READY_SENTINEL):
                try:
                    w.port = int(line.split()[1])
                except (IndexError, ValueError):
                    w.port = None
                w.ready.set()
        w.ready.set()           # EOF: unblock a waiting spawn either way

    def _live(self, tier: Optional[str] = None) -> List[_Worker]:
        with self._lock:
            return [w for w in self.workers
                    if not w.dead and (tier is None or w.tier == tier)]

    def _outstanding(self, w: _Worker) -> int:
        with self._lock:
            return sum(1 for rid, o in self._owner.items()
                       if o is w and rid not in self._results)

    def _pick(self, tier: str, exclude: Optional[_Worker] = None
              ) -> Optional[_Worker]:
        cands = [w for w in self._live(tier) if w is not exclude]
        if not cands and exclude is not None:
            cands = [w for w in self._live(tier)]
        if not cands:
            return None
        return min(cands, key=self._outstanding)

    # ----------------------------------------------------------- submit
    def submit(self, prompt, params: Optional[SamplingParams] = None,
               block: bool = False, tenant: str = "",
               adapter: str = "", **overrides) -> Request:
        if self._closing:
            raise AdmissionError("fleet is shutting down")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        p = params if params is not None else self._defaults
        if overrides:
            p = dataclasses.replace(p, **overrides)
        rid = next(self._rid)
        req = Request(rid, prompt, p, time.perf_counter(), tenant=tenant,
                      adapter=adapter)
        prefill_tier = self._live("prefill")
        migrate = bool(prefill_tier) and bool(self._live("decode"))
        tier = "prefill" if prefill_tier else "decode"
        last_err: Optional[BaseException] = None
        tried: List[_Worker] = []
        while True:
            w = self._pick(tier)
            w = w if w not in tried else next(
                (c for c in self._live(tier) if c not in tried), None)
            if w is None:
                if tier == "prefill":
                    # whole prefill tier gone: the decode tier serves
                    # end-to-end (no migration) until a replacement is up
                    tier, migrate, tried = "decode", False, []
                    continue
                raise last_err or EngineFailedError(
                    "no live fleet worker to accept the request")
            tried.append(w)
            try:
                w.call("submit", rid=rid, prompt=prompt,
                       params=dataclasses.asdict(p), tenant=tenant,
                       migrate=migrate, block=block, adapter=adapter)
                break
            except WorkerLostError as e:
                last_err = e
                self._note_lost(w)
        with self._lock:
            self._journal.add(req)
            self._reqs[rid] = req
            self._owner[rid] = w
            if migrate:
                self._mig_done[rid] = threading.Event()
        if migrate:
            threading.Thread(target=self._pump, args=(rid,),
                             name="cxn-fleet-pump-%d" % rid,
                             daemon=True).start()
        return req

    # -------------------------------------------------------- migration
    def _pump(self, rid: int) -> None:
        """Drive one request's prefill->decode hop: block on the
        prefill worker until the row is exportable, move the swap
        record, and adopt it on the least-loaded decode worker. Runs on
        its own thread so N in-flight requests migrate concurrently
        (a result() caller never serializes the tier hop)."""
        ev = self._mig_done.get(rid)
        w = self._owner.get(rid)
        try:
            try:
                out = w.call("fetch_migrated", rid=rid, timeout=None)
            except WorkerLostError:
                self._note_lost(w)      # failover replays rid for us
                return
            except Exception:
                return                  # result() surfaces the state
            if out["kind"] == "result":
                with self._lock:
                    self._results[rid] = out["result"]
                return
            if out["kind"] == "lost":
                self._replay([rid], why="migration record lost")
                return
            record = out["record"]
            nbytes = int(record.get("nbytes", 0))
            while True:
                d = self._pick("decode", exclude=w)
                if d is None:
                    self._replay([rid], why="no decode worker")
                    return
                try:
                    d.call("adopt_migrated", record=record)
                    break
                except WorkerLostError:
                    self._note_lost(d)
            with self._lock:
                self._owner[rid] = d
                self.migrations += 1
                self.kv_wire_bytes += nbytes
            self._mig_c.inc()
            self._wire_c.inc(nbytes)
        finally:
            if ev is not None:
                ev.set()

    # ----------------------------------------------------------- result
    @staticmethod
    def _remaining(deadline: Optional[float]) -> Optional[float]:
        if deadline is None:
            return None
        rem = deadline - time.monotonic()
        if rem <= 0:
            raise TimeoutError("request still in flight at the fleet "
                               "deadline")
        return rem

    def result(self, handle: Request,
               timeout: Optional[float] = None) -> ServeResult:
        rid = handle.rid
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while True:
            with self._lock:
                wire = self._results.get(rid)
                w = self._owner.get(rid)
                ev = self._mig_done.get(rid)
            if wire is not None:
                return self._finish_local(rid, wire)
            if ev is not None and not ev.is_set():
                if not ev.wait(self._remaining(deadline)):
                    raise TimeoutError(
                        "request %d still migrating between tiers"
                        % rid)
                continue
            if w is None or w.dead:
                # a failover replay is (re)assigning the owner
                time.sleep(0.05)
                self._remaining(deadline)
                continue
            rem = self._remaining(deadline)
            try:
                # worker-side wait carries the user deadline; the RPC
                # wait is padded so the remote TimeoutError wins the race
                wire = w.call("result", rid=rid, wait=rem,
                              timeout=(None if rem is None
                                       else rem + 30.0))
            except WorkerLostError:
                self._note_lost(w)
                continue
            except TimeoutError:
                raise
            if wire.get("status") == "__migrated__":
                continue        # raced the pump; loop to the new owner
            with self._lock:
                self._results[rid] = wire
            return self._finish_local(rid, wire)

    def _finish_local(self, rid: int, wire: dict) -> ServeResult:
        res = result_from_wire(wire)
        with self._lock:
            req = self._reqs.get(rid)
            if req is not None:
                self._journal.remove(req)
        if req is not None and not req.done.is_set():
            if res.status == "ok" and len(res.tokens):
                req.tokens = list(
                    np.asarray(res.tokens)[len(req.prompt):])
            req.finish(res.status, res.error)
        return res

    # --------------------------------------------------------- failover
    def _note_lost(self, w: Optional[_Worker]) -> None:
        """Mark a worker dead exactly once, replay its in-flight
        requests on survivors, and (optionally) spawn a replacement."""
        if w is None:
            return
        with self._fail_lock:
            if w.dead:
                return
            w.dead = True
        if w.client is not None:
            w.client.close()
        try:
            if w.proc is not None and w.proc.poll() is None:
                w.proc.kill()
        except OSError:
            pass
        with self._lock:
            victims = [rid for rid, o in self._owner.items()
                       if o is w and rid not in self._results
                       and rid in self._reqs]
        if victims and not self._closing:
            self._replay(victims, why="worker %s lost" % w.name)
        if self._restart_workers and not self._closing:
            # under _lock: _note_lost runs on monitor AND caller
            # threads, and two concurrent worker deaths must not lose
            # a restart count to a torn read-modify-write
            with self._lock:
                self.restarts += 1
            self._restart_c.inc()
            threading.Thread(target=self._respawn, args=(w.tier,),
                             name="cxn-fleet-respawn",
                             daemon=True).start()

    def _respawn(self, tier: str) -> None:
        try:
            self._spawn(tier)
        except Exception:
            pass                # monitor keeps serving on survivors

    def _replay(self, rids: List[int], why: str = "") -> None:
        """Re-adopt journaled requests on surviving workers: the rewind
        (router.py ``rewind_request``) + deterministic re-execution make
        greedy streams bit-identical and sampled streams distribution-
        identical — PR 9's replay contract, across a process boundary."""
        for rid in rids:
            with self._lock:
                req = self._reqs.get(rid)
                ev = self._mig_done.get(rid)
                if req is None or rid in self._results:
                    continue
            new = rewind_request(req)
            placed = False
            while not placed:
                # prefer the decode tier (end-to-end serve, no second
                # hop), fall back to any live worker
                d = self._pick("decode") or self._pick("prefill")
                if d is None:
                    new.finish("error",
                               "no surviving fleet worker to replay "
                               "request %d (%s)" % (rid, why))
                    with self._lock:
                        self._results[rid] = result_to_wire(
                            ServeResult("error", np.zeros((0,), np.int32),
                                        error=new.error))
                    break
                try:
                    d.call("adopt", request=request_to_wire(new))
                    placed = True
                except WorkerLostError:
                    self._note_lost(d)
            if not placed:
                continue
            with self._lock:
                self._journal.remove(req)
                self._journal.add(new)
                self._reqs[rid] = new
                self._owner[rid] = d
                self.replays += 1
            self._replay_c.inc()
            if ev is not None:
                ev.set()        # the tier hop is moot after a replay

    # ---------------------------------------------------------- monitor
    def _monitor(self) -> None:
        """Heartbeat loop: a worker whose process exited, whose
        connection died, or whose health verb goes silent past the
        timeout is declared lost (typed WorkerLostError for its
        waiters) and its requests replay on survivors."""
        hb_timeout = max(10.0, 5 * self._heartbeat_s)
        while not self._stop.wait(self._heartbeat_s):
            for w in self._live():
                if self._stop.is_set():
                    return
                if w.proc is not None and w.proc.poll() is not None:
                    self._note_lost(w)
                    continue
                try:
                    w.call("ping", timeout=hb_timeout)
                except (WorkerLostError, TimeoutError):
                    self._note_lost(w)

    # ---------------------------------------------------------- metrics
    def metrics(self) -> Dict:
        if self._final_metrics is not None:
            return self._final_metrics
        per = {}
        for w in self._live():
            try:
                per[w.name] = w.call("metrics", timeout=30)
            except (WorkerLostError, TimeoutError):
                pass
        counts: Dict[str, int] = {}
        for m in per.values():
            for k, v in m.get("requests", {}).items():
                counts[k] = counts.get(k, 0) + v
        return {
            "requests": counts,
            "tokens_generated": sum(m.get("tokens_generated", 0)
                                    for m in per.values()),
            "workers": per,
            "fleet": {"live": len(self._live()),
                      "prefill": len(self._live("prefill")),
                      "decode": len(self._live("decode")),
                      "migrations": self.migrations,
                      "kv_wire_bytes": self.kv_wire_bytes,
                      "replays": self.replays,
                      "restarts": self.restarts},
        }

    def metrics_text(self) -> str:
        """ONE merged Prometheus scrape for the whole fleet: every
        worker's registry crosses the wire as a value snapshot
        (obs/metrics.py registry_state), is rebuilt router-side, and
        merges with the router's own fleet counters under ``worker=``
        labels — histograms additionally aggregate, exactly like the
        in-process router's ``replica=`` payload."""
        regs: Dict[str, obs_metrics.Registry] = {}
        for w in self._live():
            try:
                regs[w.name] = obs_metrics.registry_from_state(
                    w.call("metrics_state", timeout=30))
            except (WorkerLostError, TimeoutError):
                pass
        regs["router"] = self._registry
        return obs_metrics.merged_prometheus(regs, label="worker")

    @property
    def registry(self):
        return self._registry

    def health(self) -> Dict:
        per = {}
        for w in self._live():
            try:
                per[w.name] = w.call("health", timeout=30)
            except (WorkerLostError, TimeoutError):
                per[w.name] = {"state": "LOST"}
        live = len(self._live())
        return {"state": ("SERVING" if live else "FAILED"),
                "workers": per, "live": live,
                "replays": self.replays, "restarts": self.restarts}

    # --------------------------------------------------------- shutdown
    def drain(self, timeout: Optional[float] = None) -> None:
        """Zero-lost graceful stop: wait for every tier hop to settle,
        drain every worker (their queues finish), pull every
        outstanding result into the router cache, then tear the
        processes down — ``result()`` keeps answering from the cache
        afterwards."""
        with self._lock:
            events = list(self._mig_done.values())
        for ev in events:
            ev.wait(timeout)
        for w in self._live():
            try:
                w.call("drain", wait=timeout,
                       timeout=(None if timeout is None
                                else timeout + 30.0))
            except (WorkerLostError, TimeoutError):
                pass
        with self._lock:
            pending = [rid for rid in self._reqs
                       if rid not in self._results]
        for rid in pending:
            w = self._owner.get(rid)
            if w is None or w.dead:
                continue
            try:
                wire = w.call("result", rid=rid, wait=30, timeout=60)
                if wire.get("status") != "__migrated__":
                    with self._lock:
                        self._results[rid] = wire
            except (WorkerLostError, TimeoutError):
                pass
        # snapshot the aggregate before the processes go away so the
        # post-drain summary (cli.py task_serve) still has numbers —
        # mirrors result() answering from the cache after teardown
        self._final_metrics = self.metrics()
        self.shutdown(drain=False)

    def shutdown(self, drain: bool = True,
                 timeout: Optional[float] = None) -> None:
        if self._closing:
            return
        if drain:
            self.drain(timeout)
            return
        with self._lock:
            self._closing = True
        self._stop.set()
        self._monitor_t.join(timeout=10)
        self._teardown(kill=False)
        with self._lock:
            for rid, req in self._reqs.items():
                if rid not in self._results and not req.done.is_set():
                    req.finish("cancelled", "fleet shutdown")
            self._journal.clear()
            for ev in self._mig_done.values():
                ev.set()

    def _teardown(self, kill: bool) -> None:
        with self._lock:
            workers = list(self.workers)
        for w in workers:
            if not kill and not w.dead and w.client is not None:
                try:
                    w.call("shutdown", timeout=10)
                except (WorkerLostError, TimeoutError):
                    pass
        for w in workers:
            if w.client is not None:
                w.client.close()
            if w.proc is not None:
                try:
                    w.proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    w.proc.kill()
                    try:
                        w.proc.wait(timeout=10)
                    except subprocess.TimeoutExpired:
                        pass
                except OSError:
                    pass
            if w.reader is not None:
                w.reader.join(timeout=5)
            w.dead = True
        shutil.rmtree(self._spec_dir, ignore_errors=True)

    def __enter__(self) -> "FleetRouter":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown(drain=exc[0] is None)


if __name__ == "__main__":
    sys.exit(worker_main(sys.argv[1],
                         sys.argv[2] if len(sys.argv) > 2 else ""))
