"""Slot-pool decode engine: the device side of continuous batching.

The offline decode (``models/gpt.py:gpt_decode``) compiles prefill + the
whole token scan into one program per (prompt length, generation length)
signature — perfect for equal-length batch generation, useless for a
server where requests arrive at different times with different lengths.
This engine re-cuts the same math at the granularity a scheduler needs:

* a **KV slot pool** — one (n_layer, slots, n_head, row_len, head_dim)
  cache pair; each in-flight request owns one slot row for its lifetime
  (``row_len`` is ``seq_len`` rounded up to a chunk multiple so the last
  — padded — prefill chunk's row write always fits);
* **chunked prefill** — ONE jitted chunk step consuming
  ``prefill_chunk`` tokens into a slot row at a traced offset, attending
  over the row's already-written cache; every prompt of every length
  runs as ceil(n / chunk) calls of the SAME compiled program, so the
  per-prompt-length compile storm of the whole-prompt path cannot
  happen, and the scheduler can interleave decode ticks between a long
  prompt's chunks. The last (possibly partial) chunk pads + masks and
  samples the request's first token;
* **prefill** — the legacy whole-prompt admit program (one compiled
  program PER prompt length; ``prefill_chunk = 0`` selects it — kept as
  the bench baseline and the single-dispatch path for tiny prompts);
* **tick** — ONE jitted batched decode step across ALL slot rows, each
  row at its own position with its own sampling params and PRNG key.
  Rows advance independently, so short and long requests interleave
  instead of convoying behind the longest member of a fixed batch;
* **verify** — ONE jitted draft-and-verify step (``serve_verify_chunk``,
  speculative decoding): ``spec_len`` drafted tokens plus the row's
  pending token run through the model in a single forward, all
  candidate K/V rows written, the accepted prefix and one
  correction/bonus token computed on device — up to ``spec_len + 1``
  tokens per forward instead of one per tick. Slot, position, and the
  real draft count are traced, so mixed n-gram hit lengths share one
  compiled signature (its own RecompileGuard enforces that).

**Paged mode** (``num_blocks > 0``, the server's default): the dense
slot pool is replaced by a global block pool ``(n_layer, num_blocks,
n_head, block_size, head_dim)`` plus per-row ``int32`` block tables
(serve/paged.py). The chunk-prefill / tick / verify programs are re-cut
as scatter/gather through TRACED block indices at a FIXED block size
(default = the prefill chunk), so each keeps exactly one compiled
signature while occupancy scales with tokens in flight instead of
``slots * row_len``. Prefix sharing becomes zero-copy (shared blocks
with refcounts, copy-on-write on first write into a shared block —
serve/prefix_cache.py:PagedPrefixCache), and rows can be preempted to a
host swap buffer and resumed bit-identically (swap_out_row /
swap_in_row; policy in serve/scheduler.py). Served tokens stay
bit-identical to the dense path and to solo ``gpt_decode``: the gather
rebuilds the exact logical (H, row_len, d) rows the dense programs read
— garbage in a table's unallocated tail is masked to an exact 0.0
contribution, the same invariant dense stale rows lean on.

Compiled-program hygiene: every prefill/chunk program fetch is counted
by a :class:`~cxxnet_tpu.analysis.recompile.RecompileGuard` when
``recompile_limit > 0`` — a mixed-length trace through the legacy path
trips it with the drifting dimension named (``n_prompt=...``), while the
chunked path stays at one signature per server. The lru_cache below is
the cache, the guard is the alarm.

Token-identity contract: every numeric building block is shared with the
offline path's XLA form (``_fuse_qkv_blocks`` / ``_block_core_fusedqkv``
/ ``_layernorm`` from models/gpt.py, the masked-softmax cached attention
in the same per-row form, ``ops/sampling.py`` with the per-request
``fold_in(key, token_index)`` schedule), so a request served from any
slot — including a recycled one — produces the same tokens as running it
alone through ``gpt_decode``'s XLA scan path with the same params and
seed (pinned by tests on the CPU mesh). Kernel-vs-XLA numeric contracts
are defined in ONE place, :func:`fused_attn_tolerance` — exact under
interpret mode on CPU, a bounded ULP band on a real TPU — and every
differential test pins through :func:`assert_fused_allclose` instead of
per-test ad-hoc ``allclose`` settings. (The offline ``gpt_decode``
whole-step kernel predates that helper's exact-on-CPU guarantee; its
accelerator band is the same TPU branch of the contract.)

**Fused paged attention** (the paged default wherever
``ops.pallas_kernels.paged_attention_supported`` holds, i.e. on TPU
backends — ``serve_fused_attn=0`` / ``CXN_FUSED_ATTN=0`` restores the
gather formulation, which also remains the fallback for unsupported
geometries and the bit-reference the fused path is pinned against): the
tick and verify programs route their attention reads through one Pallas
pass per layer that walks the block table directly — per-block K/V
tiles DMA from the global pool into a VMEM row image fused with q·K,
the position-masked softmax, and the ·V product — so the gathered
logical caches the XLA formulation materializes in HBM never exist.
The K/V scatter (and with it every cache byte) is shared with the
gather path; garbage block 0 and parked rows mask to an exact 0.0
inside the kernel exactly as they do outside it.

**Quantized serving** (``serve_int8_weights`` / ``serve_kv_dtype=int8``,
doc/serving.md "Quantized serving"; both OFF by default and pinned
no-ops there): weights quantize ONCE at engine build — per-out-column
symmetric int8 with f32 scales, the offline fused decode's exact scheme
(models/gpt.py:_quantize_decode_blocks) — and stream through all three
programs via the scale-aware matmul in ``_block_core_fusedqkv``/
``_qmat``; the paged KV pool can independently store per-block-scaled
int8 as a ``(values, scales)`` pair (one symmetric scale per (layer,
block, head, token)), quantized on scatter and dequantized on gather in
BOTH the gather and the fused attention formulations, so every pool
byte — ``kv_blocks``, the trie's shared blocks, ``swap_host`` — is the
stored int8 representation (~2x tokens per MiB, halved swap bandwidth,
crc-verified bit-exact round trips). Accuracy lives under the ONE
:func:`kv_int8_tolerance` contract; the dequant targets the COMPUTE
dtype, never silently f32 (the CXN209 audit).

Recycled-slot safety: every attention mask admits only positions <= the
querying row's own position, and every admitted position was written by
THIS request — a prefix-cache copy, one of its own prefill chunks, or
one of its own ticks (each tick writes its position's K/V before
attending). The legacy whole-prompt prefill additionally rewrites the
entire row; the chunked path does not need to, because stale positions
beyond the row's current position are unreachable by construction (a
masked score of -1e30 softmaxes to exactly 0.0 in f32, so stale columns
contribute exactly nothing). The scheduler parks free and still-
prefilling rows' tick position at row_len - 1, so the batched tick's
unconditional per-row cache write can never land inside a pending row's
already-prefilled prefix; the parked position itself is safe to dirty
because a decode row ALWAYS writes its own position's K/V before
attending to it — the write-before-attend order in the tick is the
load-bearing half of this invariant (do not reorder it).

The tick runs the XLA scan path (not the fused whole-step Pallas kernel):
slot rows sit at DIFFERENT cache positions, and the fused kernel's
single-position dus/mask layout assumes one shared ``pos``. The measured
fused-kernel batch amortization (ops/pallas_kernels.py) is the obvious
next lever — a per-row-position variant is future work, noted in
doc/serving.md.

**Tensor-parallel serving** (``mesh`` with a > 1 ``model`` axis,
doc/serving.md "Sharded & replicated serving"): the serve programs are
partitioned by GSPMD in the GATHER form of megatron TP — the
``fullc_gather`` descendant (parallel/sharding.py), not the psum form
the pipelined trainer uses inside shard_map. Every block matmul weight
is sharded on its OUTPUT dimension (w_qkv / w_proj / w_mlp1 / w_mlp2
all 1/N per shard), the KV pool is sharded on the HEAD axis — axis 2
of both the dense ``(L, slots, H, row, hd)`` and the paged ``(L,
blocks, H, bs, hd)`` layout, so per-head K/V blocks live whole on one
shard and the host-side block tables stay shard-agnostic — and the
sharded activations are re-replicated (all-gather) at the block-math
boundaries the engine already controls (the ``attn`` callbacks and the
block body's ``reduce`` hook). The row/psum form would split the
contraction of w_proj / w_mlp2 into per-shard partial sums whose f32
accumulation order differs from the single-device dot; the gather form
keeps every contraction whole on every shard, so collectives move data
but never re-associate arithmetic — TP-sharded decode is BIT-IDENTICAL
to the single-device engine (greedy and sampled), pinned by
tests/test_serve_tp.py on the forced multi-device CPU mesh. Cost: one
all-gather per matmul boundary (~4 per layer, plus the qkv-split
reshards) and the embedding/LM head replicated. The fused paged-
attention kernel is a Mosaic custom call GSPMD cannot partition on its
own, so it rides inside a ``shard_map`` wrap
(ops/pallas_kernels.py:paged_attention_sharded): each shard runs the
kernel on its LOCAL head slice (q / pools head-sharded, block tables
replicated), and the head-sharded output is re-replicated by the SAME
``gather`` hook the gather formulation pays — no extra collective, and
still zero all-reduces on the decode hot path. The support gate
evaluates the local head count ``n_head // tp``, so fused resolves ON
under TP wherever the per-shard geometry fits. Per-shard outputs are
bit-identical to the corresponding head slice of the single-device
kernel whenever the local head count is >= 2 (XLA lowers a batch-1
head contraction through a different codepath whose low-order f32
bits can differ — a one-head shard is numerically fine but not
bitwise-pinned). RecompileGuard signatures carry the mesh shape — the
same program traced over two mesh shapes is two compiled executables
and must count as such.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..models.gpt import (GPTConfig, INT4_GROUP_DEFAULT, _block_core_fusedqkv,
                          _fuse_qkv_blocks, _int4_groups, _layernorm,
                          _quantize_decode_blocks,
                          _quantize_decode_blocks_int4)
from ..obs.devprof import compile_attribution
from ..ops.attention import local_attention
from ..ops.sampling import (accept_draft_rows, residual_sample_rows,
                            sample_rows)
from .paged import BlockPoolExhausted
from .resilience import InjectedFault, SwapCorruptionError, swap_checksum

__all__ = ["DecodeEngine", "auto_num_blocks", "fused_attn_tolerance",
           "assert_fused_allclose", "kv_int8_tolerance",
           "w_int4_tolerance", "weight_stream_tag",
           "serve_param_shardings", "serve_kv_sharding", "serve_tp_size",
           "resolve_block_size", "clear_program_caches"]


def fused_attn_tolerance(dtype=None,
                         formulation: str = "resident") -> Dict[str, float]:
    """The ONE fused-vs-gather numeric contract (every differential test
    pins through :func:`assert_fused_allclose`; nothing defines its own
    ad-hoc ``allclose`` settings).

    * **Interpret mode / CPU** (``pallas_kernels._INTERPRET``, or any
      non-TPU backend), RESIDENT formulation: EXACT — ``rtol = atol =
      0``, any dtype. The fused kernel's compute step reproduces the
      gather reference's arithmetic op for op (head-batched f32 dots,
      the same mask constant, the same ``jax.nn.softmax``), so the
      interpret-mode lowering is bit-identical by construction.
    * **STREAMING formulation** (``formulation="streaming"``): bounded
      even in interpret mode. Online-softmax accumulates per KV block
      with running max/sum rescaling, so its f32 reductions are
      RE-ASSOCIATED relative to the single-pass softmax of the gather
      reference (and of the resident kernel) — mathematically equal,
      bitwise a few f32 ULP apart. The band covers that reassociation
      (measured ~1e-7 on O(1) values; bf16 outputs still round both
      arms to 8 mantissa bits, so the bf16 band already covers it).
    * **TPU**: bounded ULP in the COMPARED dtype — the Mosaic lowering
      of the same ops may round differently in the last bits (dot
      tiling, transcendental tables). For f32 outputs that is a few
      f32 ULP on O(1) values; bf16 outputs round both arms to 8
      mantissa bits, so a last-bit disagreement is one bf16 ULP
      (~2^-8 relative) and the band must be sized in bf16 ULPs, not
      f32's. ``dtype`` selects the band (None = f32's).

    This replaces the per-path prose caveat the serve module used to
    carry: the contract is now executable, in one place."""
    import jax as _jax
    from ..ops import pallas_kernels as _pk
    if dtype is not None and jnp.dtype(dtype) == jnp.bfloat16:
        if formulation == "streaming" \
                or not (_pk._INTERPRET
                        or _jax.default_backend() != "tpu"):
            # two bf16 ULP relative (2^-8 each), atol for near-zero
            return {"rtol": 2.0 / 256, "atol": 2.0 / 256}
        return {"rtol": 0.0, "atol": 0.0}
    if _pk._INTERPRET or _jax.default_backend() != "tpu":
        if formulation == "streaming":
            # f32 online-softmax reassociation band (see above)
            return {"rtol": 1e-5, "atol": 1e-6}
        return {"rtol": 0.0, "atol": 0.0}
    return {"rtol": 2e-6, "atol": 2e-6}


def assert_fused_allclose(actual, desired, err_msg: str = "",
                          formulation: str = "resident") -> None:
    """Assert fused-vs-gather agreement under the shared tolerance
    contract (exact in interpret mode / on CPU for the resident
    formulation, bounded ULP — in the compared dtype — on TPU and for
    the streaming online-softmax formulation)."""
    tol = fused_attn_tolerance(getattr(desired, "dtype", None),
                               formulation=formulation)
    np.testing.assert_allclose(
        np.asarray(actual, np.float64 if tol["rtol"] else None),
        np.asarray(desired, np.float64 if tol["rtol"] else None),
        err_msg=err_msg, **tol)


def kv_int8_tolerance() -> Dict[str, float]:
    """The ONE numeric contract of per-block-scaled int8 KV (the
    ``serve_kv_dtype=int8`` pool), the quantized analogue of
    :func:`fused_attn_tolerance` — every int8-KV differential test
    pins through THESE numbers instead of ad-hoc settings:

    * ``rtol`` / ``atol`` — per-op band for a dequantized attention
      read against the full-precision reference. Symmetric per-(head,
      token) scaling bounds the element error by ``scale / 2`` =
      ``max|v| / 254`` per stored value; softmax averaging keeps the
      attention output inside ~1% of the reference on O(1) values.
    * ``greedy_flip`` — the bounded greedy-divergence budget: the max
      fraction of LOCKSTEP decode steps (both engines fed the same
      context) whose argmax may differ between the int8-KV engine and
      the full-precision engine. Tiny random-init test models sit near
      the uniform-logits worst case, so the budget is deliberately
      loose; a plumbing bug (wrong scale axis, swapped K/V) flips far
      more than this.
    * ``chi2_sig`` — significance level for the sampled-mode
      chi-squared pin (int8-engine sample distribution vs the
      full-precision engine's at matched sample sizes).

    With quantization OFF (``serve_kv_dtype`` unset) nothing here
    applies: the pools hold the compute dtype and every bit-identity
    suite pins the no-op."""
    return {"rtol": 2e-2, "atol": 2e-2, "greedy_flip": 0.35,
            "chi2_sig": 1e-3}


def w_int4_tolerance() -> Dict[str, float]:
    """The ONE numeric contract of packed-int4 weight streaming
    (``serve_int4_weights=1``), the weight-side sibling of
    :func:`kv_int8_tolerance` — every int4-weight differential test
    pins through THESE numbers:

    * ``rtol`` / ``atol`` — per-op band for an int4-dequantized matmul
      against the full-precision reference. A symmetric group scale
      bounds each weight's error by ``scale / 2`` = ``max|w| / 14``
      over its group, ~9x the int8 bound — residual streams and LN keep
      activations O(1), so logits land within a few percent.
    * ``greedy_flip`` — max fraction of LOCKSTEP decode steps whose
      argmax may differ from the full-precision engine's. 3-bit
      mantissas on a tiny random-init model (near-uniform logits) flip
      often and harmlessly; a plumbing bug (nibble order, group axis,
      scale placement) flips essentially every step.
    * ``chi2_sig`` — significance level for the sampled-mode
      chi-squared pin at matched sample sizes.

    The band is deliberately wider than int8's: int4 halves the bits,
    it does not halve the error. With ``serve_int4_weights`` unset
    nothing here applies — the unquantized programs stay pinned
    byte-for-byte."""
    return {"rtol": 8e-2, "atol": 8e-2, "greedy_flip": 0.5,
            "chi2_sig": 1e-3}


def weight_stream_tag(int8: bool, int4: bool,
                      int4_group: int = INT4_GROUP_DEFAULT) -> str:
    """Canonical weight-stream component for autotune/AOT keys:
    ``"int8"``, ``"int4:g<group>"``, or ``""`` for full precision —
    the ONE spelling shared by resolve_block_size, the autotune task,
    and the bench cells, so a winner tuned under one weight dtype can
    never be served to another."""
    if int4:
        return "int4:g%d" % int(int4_group)
    return "int8" if int8 else ""


# fused-fallback observability (one line per distinct reason per
# process — engine rebuilds and replica spin-ups over the same config
# must not spam the log; the counter still ticks every resolution)
_FALLBACK_LOGGED = set()


def _note_fused_fallback(reason: str, registry=None) -> None:
    """Record one fused-attention fallback resolution: the support gate
    rejected the Pallas kernel (``reason`` from
    ``paged_attention_fallback_reason`` — "backend", "geometry",
    "env_off") and the engine is keeping the XLA gather formulation.
    Logs the reason ONCE per process via the profiler and counts every
    occurrence in ``cxn_fused_fallback_total{reason=}`` when a registry
    is armed — the resolution used to be silent, which made "why is
    this replica slow" a source-diving exercise."""
    if not reason:
        return
    if reason not in _FALLBACK_LOGGED:
        _FALLBACK_LOGGED.add(reason)
        from ..utils import profiler
        profiler.log("serve: fused paged attention unavailable "
                     "(reason=%s) — decoding on the XLA gather "
                     "formulation" % reason)
    if registry is not None:
        registry.counter(
            "cxn_fused_fallback_total",
            "fused paged-attention fallback resolutions by reason",
            labelnames=("reason",)).labels(reason).inc()


def _note_int4_fallback(reason: str, registry=None) -> None:
    """Record one int4 dequant-matmul fallback resolution: the support
    gate (``int4_matmul_fallback_reason`` — "backend", "geometry",
    "env_off") rejected the Pallas kernel for the tick's hot matmul
    geometry and the engine's programs stream packed weights through
    the XLA reference instead. Same once-per-process logging /
    always-counting contract as :func:`_note_fused_fallback`, under
    ``cxn_int4_fallback_total{reason=}``."""
    if not reason:
        return
    key = "int4:" + reason
    if key not in _FALLBACK_LOGGED:
        _FALLBACK_LOGGED.add(key)
        from ..utils import profiler
        profiler.log("serve: int4 dequant-matmul kernel unavailable "
                     "(reason=%s) — streaming packed weights through "
                     "the XLA reference formulation" % reason)
    if registry is not None:
        registry.counter(
            "cxn_int4_fallback_total",
            "int4 dequant-matmul fallback resolutions by reason",
            labelnames=("reason",)).labels(reason).inc()


def _kv_itemsizes(cfg, kv_int8: bool):
    """(value itemsize, per-token-per-head scale overhead bytes) of one
    stored KV position — the dtype-aware half of the paged-geometry
    formula. int8 pools store 1-byte values plus one compute-dtype
    scale per (layer, block, head, token); full-precision pools store
    compute-dtype values and no scales."""
    citem = 2 if cfg.dtype == "bfloat16" else 4
    return (1, citem) if kv_int8 else (citem, 0)


def _paged_geometry(cfg, prefill_chunk: int, block_size: int,
                    kv_dtype: str = ""):
    """The ONE source of paged-cache geometry — ``(chunk, block_size,
    row_len, blocks_per_row, block_bytes)`` — shared by
    :func:`auto_num_blocks`, the :class:`DecodeEngine` ctor, and
    :meth:`DecodeEngine.block_bytes`, so a sizing budget can never
    desynchronize from the engine's actual block layout. Validates the
    paged preconditions (chunked prefill on, block size divides the
    seq_len-clamped chunk). ``kv_dtype`` makes ``block_bytes``
    dtype-aware: ``"int8"`` prices the per-block-scaled int8 layout
    (1-byte values + one compute-dtype scale per head per token), so a
    ``serve_kv_mb`` budget buys ~2x the blocks and the DeviceLedger's
    ``kv_blocks`` prediction still reconciles bit-for-bit."""
    chunk = min(int(prefill_chunk), cfg.seq_len)
    if chunk <= 0:
        raise ValueError(
            "paged KV cache requires chunked prefill "
            "(serve_prefill_chunk > 0); the legacy whole-prompt path "
            "is dense-only")
    bs = int(block_size) or chunk
    if bs < 1 or chunk % bs:
        raise ValueError(
            "serve_block_size=%d must be >= 1 and divide the prefill "
            "chunk %d (chunk windows and prefix-cache nodes must cover "
            "whole blocks; with seq_len=%d the chunk is clamped to "
            "min(serve_prefill_chunk, seq_len))"
            % (int(block_size), chunk, cfg.seq_len))
    row_len = (cfg.seq_len + chunk - 1) // chunk * chunk
    itemsize, scale_bytes = _kv_itemsizes(
        cfg, str(kv_dtype).lower() == "int8")
    block_bytes = (2 * cfg.n_layer * cfg.n_head * bs
                   * ((cfg.feat // cfg.n_head) * itemsize + scale_bytes))
    return chunk, bs, row_len, row_len // bs, block_bytes


def auto_num_blocks(cfg, slots: int, prefill_chunk: int,
                    block_size: int = 0, prefix_mb: float = 0.0,
                    kv_mb: float = 0.0, kv_dtype: str = "") -> int:
    """Block-pool sizing for the paged engine — the ONE formula the
    server, the CLI, and the lint tool share (geometry from
    :func:`_paged_geometry`, the same helper the engine ctor uses). An
    explicit ``kv_mb`` MiB budget wins: ``floor(kv_mb MiB /
    block_bytes)`` blocks (the DecodeEngine ctor rejects a budget that
    cannot hold one full row plus the garbage block). Otherwise:
    dense-equivalent capacity (``slots`` full rows) plus prefix-trie
    headroom (``prefix_mb`` worth of blocks, capped at another
    ``slots`` rows so a huge trie budget cannot balloon the pool) plus
    the reserved garbage block — a strict superset of what the dense
    pool could ever hold, so the default upgrade never loses capacity
    (doc/serving.md memory formula). ``kv_dtype="int8"`` sizes by the
    QUANTIZED block itemsize: the same ``serve_kv_mb`` budget yields
    ~2x the blocks (doc/serving.md "Quantized serving")."""
    _, _, _, bpr, block_bytes = _paged_geometry(cfg, prefill_chunk,
                                                block_size,
                                                kv_dtype=kv_dtype)
    if kv_mb > 0:
        return int(kv_mb * (1 << 20) // block_bytes)
    prefix_blocks = int(prefix_mb * (1 << 20) // block_bytes)
    return slots * bpr + min(prefix_blocks, slots * bpr) + 1


def resolve_block_size(cfg, prefill_chunk: int, block_size: int,
                       kv_dtype: str = "", tp: int = 1,
                       aot=None, weights: str = "") -> int:
    """Resolve ``serve_block_size=auto`` (the ``-1`` sentinel) through
    the persisted geometry-autotune winner — the ONE lookup the
    server, the CLI, and the lint tool share. A non-negative
    ``block_size`` passes through untouched (0 keeps the
    block-size-defaults-to-chunk behavior). ``-1`` consults the AOT
    cache (``aot``: an AotCache, a path, or None for the
    process-default :func:`~cxxnet_tpu.analysis.aot_cache.active`
    cache) under the :func:`~cxxnet_tpu.analysis.aot_cache
    .tuned_components` key — device kind + model geometry + chunk +
    KV dtype + TP + weight stream (``weights``: the
    :func:`weight_stream_tag` spelling — int4's matmul route changes
    which block size wins). A hit returns the tuned winner (tuning ran once per
    fleet; every replica loads it here); a miss logs once and falls
    back to 0 = the chunk default, so ``auto`` without a tuning run
    is never an error."""
    bs = int(block_size)
    if bs >= 0:
        return bs
    from ..analysis import aot_cache as aot_mod
    from ..utils import profiler
    cache = aot_mod.get_cache(aot) if isinstance(aot, str) \
        else (aot if aot is not None else aot_mod.active())
    chunk = min(int(prefill_chunk), cfg.seq_len)
    if cache is not None:
        comp = aot_mod.tuned_components(
            aot_mod.config_hash(dataclasses.astuple(cfg)), chunk,
            kv_dtype, tp, weights)
        rec = cache.load_tuned(comp)
        if rec is not None:
            profiler.log(
                "serve: serve_block_size=auto -> %d (tuned winner, "
                "formulation=%s, %.3f ms/tick when tuned)"
                % (int(rec["block_size"]), rec.get("formulation", "?"),
                   float(rec.get("tick_ms", 0.0))))
            return int(rec["block_size"])
    profiler.log("serve: serve_block_size=auto found no tuned winner "
                 "for this geometry%s — using the chunk default "
                 "(run task=autotune with an aot_cache to persist one)"
                 % ("" if cache is not None else " (no aot cache armed)"))
    return 0


# ------------------------------------------------------------------ TP
# Gather-form tensor parallelism for the serve programs (module
# docstring): weights sharded on OUTPUT dims, KV pools on the head
# axis, activations re-replicated at the boundaries below. The helpers
# all degrade to identity with mesh=None, so the single-device programs
# are byte-for-byte the ones this PR inherited.


def serve_tp_size(mesh) -> int:
    """The model-axis size of ``mesh`` (1 for None / no model axis) —
    the one definition of "is this engine tensor-parallel"."""
    if mesh is None:
        return 1
    from ..parallel.mesh import MODEL_AXIS
    return int(mesh.shape.get(MODEL_AXIS, 1))


def serve_param_shardings(mesh, int4: bool = False):
    """NamedShardings for the engine's fused block dict + outer tree —
    the gather form: every matmul weight sharded on its OUTPUT dim
    (full contractions per shard — the bit-identity invariant), biases
    sharded to match their matmul's output, LN params and the
    embedding/head replicated. One table so the engine ctor, the
    abstract (audit) engine, and tests cannot drift.

    ``int4``: the packed-nibble weight planes are still (L, k, n/2)
    with the out dim last (the shard-aware packing keeps each shard's
    bytes self-contained — models/gpt.py _pack_int4), so the col spec
    holds; the dequant scales become 3-D (L, G, n) group planes whose
    OUT dim is axis 2, so they take the col spec instead of the int8
    bias-shaped vec spec."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ..parallel.mesh import MODEL_AXIS
    ns = lambda *spec: NamedSharding(mesh, P(*spec))
    rep = ns()
    col = ns(None, None, MODEL_AXIS)        # (L, in, out): out sharded
    vec = ns(None, MODEL_AXIS)              # (L, out) bias
    scale = col if int4 else vec            # int4: (L, G, out) planes
    blocks = {"w_qkv": col, "b_qkv": vec, "w_proj": col,
              "w_mlp1": col, "b_mlp1": vec, "w_mlp2": col,
              "ln1_g": rep, "ln1_b": rep, "ln2_g": rep, "ln2_b": rep,
              "b_proj": rep, "b_mlp2": rep,
              # int8/int4 weight streaming: the dequant scales shard
              # with their matmul's OUTPUT dim — the scale multiply is
              # elementwise on the sharded dim, applied BEFORE the
              # gather re-replication
              "s_qkv": scale, "s_proj": scale, "s_mlp1": scale,
              "s_mlp2": scale}
    outer = {k: rep for k in ("emb", "pos", "lnf_g", "lnf_b", "head")}
    return blocks, outer


def serve_kv_sharding(mesh):
    """The KV pool's NamedSharding: head axis (axis 2 of BOTH the dense
    (L, slots, H, row, hd) and the paged (L, blocks, H, bs, hd)
    layout) over the model axis, everything else replicated — per-head
    K/V blocks live whole on one shard, and the host-side block tables
    index physical blocks exactly as on one device."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ..parallel.mesh import MODEL_AXIS
    return NamedSharding(mesh, P(None, None, MODEL_AXIS, None, None))


def _tp_ops(mesh):
    """``(gather, pin_kv)`` constraint hooks for one program build:
    ``gather`` re-replicates an activation (an all-gather — pure data
    movement, bit-exact; it doubles as the block body's ``reduce``
    hook, constraining each output-sharded matmul product back to
    replicated), ``pin_kv`` keeps a cache/pool head-sharded through
    its scatter update (and pins the donated output's sharding to the
    input's, so donation aliasing survives partitioning). Both are
    identity with mesh=None."""
    if mesh is None:
        ident = lambda t: t
        return ident, ident
    from jax.sharding import NamedSharding, PartitionSpec as P
    rep = NamedSharding(mesh, P())
    kv = serve_kv_sharding(mesh)
    gather = lambda t: lax.with_sharding_constraint(t, rep)
    pin_kv = lambda t: lax.with_sharding_constraint(t, kv)
    return gather, pin_kv


def _attn_cached_rows(q, ck, cv, pos):
    """Per-row cached attention: q (b, 1, H, d) against head-major caches
    (b, H, S, d), each row masked at its OWN position ``pos`` (b,) —
    the multi-position form of models/gpt.py:_attn_cached's jnp path
    (same einsums, same f32 softmax, same -1e30 mask), row-independent
    so each slot reproduces the batch-1 offline computation exactly."""
    d = q.shape[-1]
    qh = jnp.swapaxes(q, 1, 2)                          # (b, h, 1, d)
    s = jnp.einsum("bhqd,bhkd->bhqk", qh.astype(jnp.float32),
                   ck.astype(jnp.float32)) / (d ** 0.5)
    mask = jnp.arange(ck.shape[2])[None, None, None, :] \
        <= pos[:, None, None, None]
    w = jax.nn.softmax(jnp.where(mask, s, -1e30), axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", w,
                     cv.astype(jnp.float32)).astype(q.dtype)
    return jnp.swapaxes(out, 1, 2)                      # (b, 1, h, d)


@functools.lru_cache(maxsize=16)
def _tick_fn(cfg_key: tuple, donate: bool, mesh=None):
    """Jitted batched decode tick for one model config — module-level and
    lru-cached (the models/gpt.py:_decode_fn idiom) so every server over
    the same config shares one compiled program; the slot count is a
    traced dimension, not part of the key. ``mesh`` (part of the key —
    two mesh shapes are two compiled programs) arms the gather-form TP
    constraints; None leaves the program untouched."""
    cfg = GPTConfig(*cfg_key)
    dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
    gather, pin_kv = _tp_ops(mesh)

    def impl(blocks, outer, cache_k, cache_v, tok, pos, keys, fold, temp,
             top_k, top_p):
        # explicit clip, not implicit XLA gather clamping: free and
        # still-prefilling rows are parked at row_len - 1, which is past
        # the pos table when the chunk does not divide seq_len; real
        # decode rows always sit < seq_len, so the clip is an identity
        # for every row whose output is kept
        h = (outer["emb"][tok][:, None, :]
             + outer["pos"][jnp.minimum(pos, cfg.seq_len - 1)][:, None, :]
             ).astype(dtype)
        # python-unrolled layer loop (n_layer is static) with per-row
        # dynamic_update_slice writes STRAIGHT into the stacked caches:
        # the lax.scan form instead streams both caches through xs->ys,
        # which XLA materializes as a full cache copy per layer per token
        # — measured at 87% of the decode step (doc/performance.md round
        # 4). With the caches donated, the dus chain can update in place.
        for l in range(cfg.n_layer):
            p = {k: w[l] for k, w in blocks.items()}

            def attn(q, k, v, l=l):
                kh = jnp.swapaxes(k, 1, 2)[:, None]     # (b, 1, h, 1, d)
                vh = jnp.swapaxes(v, 1, 2)[:, None]
                # vmap over the slot axis: each row writes (h, 1, d) at
                # (layer l, its OWN position)
                upd = jax.vmap(
                    lambda c, u, pp: lax.dynamic_update_slice(
                        c, u, (l, 0, pp, 0)),
                    in_axes=(1, 0, 0), out_axes=1)
                ck = pin_kv(upd(cache_k, kh, pos))
                cv = pin_kv(upd(cache_v, vh, pos))
                return gather(_attn_cached_rows(q, ck[l], cv[l], pos)), \
                    (ck, cv)

            h, (cache_k, cache_v) = _block_core_fusedqkv(
                p, h, cfg.n_head, attn, gather)
        hl = _layernorm(h, outer["lnf_g"], outer["lnf_b"])
        logits = hl[:, 0] @ outer["head"].astype(hl.dtype)      # (b, V)
        keys_t = jax.vmap(jax.random.fold_in)(keys, fold)
        nxt = sample_rows(logits, keys_t, temp, top_k, top_p)
        return cache_k, cache_v, nxt

    return jax.jit(impl, donate_argnums=(2, 3) if donate else ())


@functools.lru_cache(maxsize=256)
def _prefill_fn(cfg_key: tuple, n_prompt: int, row_len: int, donate: bool):
    """Jitted admit program for one (config, prompt length): full-prompt
    forward, whole-slot-row cache write (traced slot index — one program
    serves every slot), first-token sample. ``row_len`` is the engine's
    (possibly chunk-padded) cache row length."""
    cfg = GPTConfig(*cfg_key)
    dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
    identity = lambda t: t

    def impl(blocks, outer, cache_k, cache_v, prompt, slot, key, temp,
             top_k, top_p):
        h = (outer["emb"][prompt]
             + outer["pos"][None, :n_prompt]).astype(dtype)

        def prefill_layer(carry, p):
            def attn(q, k, v):
                return local_attention(q, k, v, causal=True), (k, v)
            out, (k, v) = _block_core_fusedqkv(p, carry, cfg.n_head, attn,
                                               identity)
            # head-major (1, H, S, d) row, zero-padded to the FULL slot
            # length: the dus below replaces the whole row, so a recycled
            # slot keeps nothing of its previous occupant
            kh = jnp.transpose(k, (0, 2, 1, 3))
            vh = jnp.transpose(v, (0, 2, 1, 3))
            pad = ((0, 0), (0, 0), (0, row_len - n_prompt), (0, 0))
            return out, (jnp.pad(kh, pad), jnp.pad(vh, pad))

        h, (ck_row, cv_row) = lax.scan(prefill_layer, h, blocks)
        hl = _layernorm(h[:, -1:], outer["lnf_g"], outer["lnf_b"])
        logits = hl[:, 0] @ outer["head"].astype(hl.dtype)      # (1, V)
        # first generated token: fold index 0 — the same schedule as
        # gpt_decode's pick(logits, fold_in(rng, 0))
        k0 = jax.random.fold_in(key, 0)
        tok = sample_rows(logits, k0[None], temp[None], top_k[None],
                          top_p[None])
        cache_k = lax.dynamic_update_slice(cache_k, ck_row,
                                           (0, slot, 0, 0, 0))
        cache_v = lax.dynamic_update_slice(cache_v, cv_row,
                                           (0, slot, 0, 0, 0))
        return cache_k, cache_v, tok[0]

    return jax.jit(impl, donate_argnums=(2, 3) if donate else ())


def _attn_chunk(q, ck, cv, start):
    """Chunk-prefill attention: q (1, C, H, d) token-major against the
    row's head-major caches (1, H, S, d), causal at absolute positions
    ``start + i`` — the multi-key form of ops/attention.py:full_attention
    (same einsum contractions with f32 accumulation, the same -1e30 mask,
    p cast back to v.dtype before the PV product), so a chunk's
    activations reproduce the whole-prompt prefill position for
    position. Masked cache columns (future positions, pad writes, a
    recycled slot's stale tail) softmax to exactly 0.0 in f32 and
    contribute exactly nothing to the output."""
    d = q.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    s = jnp.einsum("bqhd,bhkd->bhqk", q, ck,
                   preferred_element_type=jnp.float32) * scale
    qpos = start + jnp.arange(q.shape[1])[:, None]
    kpos = jnp.arange(ck.shape[2])[None, :]
    s = jnp.where(qpos >= kpos, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bqhd", p.astype(cv.dtype), cv,
                     preferred_element_type=jnp.float32)
    return out.astype(cv.dtype)


@functools.lru_cache(maxsize=16)
def _prefill_chunk_fn(cfg_key: tuple, chunk: int, donate: bool, mesh=None):
    """Jitted chunk-prefill step: consume ``chunk`` tokens into a slot
    row starting at a traced offset ``start``, attending over the row's
    already-written cache — ONE compiled program serves every prompt
    length (ceil(n / chunk) calls), every slot, and every chunk index.
    The caller pads the final chunk to ``chunk`` tokens and passes
    ``n_valid``; the first generated token is sampled from position
    ``n_valid - 1``'s logits with the offline ``fold_in(key, 0)``
    schedule (only the final chunk's sample is meaningful — earlier
    chunks' returned token is a mid-prompt sample the host discards).
    Layer loop python-unrolled with per-layer dus straight into the
    stacked caches, the tick's idiom — a lax.scan would stream both
    caches through xs->ys as a full copy per layer."""
    cfg = GPTConfig(*cfg_key)
    dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
    gather, pin_kv = _tp_ops(mesh)
    hd = cfg.feat // cfg.n_head

    def impl(blocks, outer, cache_k, cache_v, toks, slot, start, n_valid,
             key, temp, top_k, top_p):
        # position rows by gather, index clamped into the table: pad
        # positions of the final chunk can point past seq_len - 1 (the
        # table's extent) — their rows are masked garbage either way,
        # while every VALID position start+i < seq_len fetches exactly
        # the row the whole-prompt prefill adds at that position
        pidx = jnp.clip(start + jnp.arange(chunk), 0, cfg.seq_len - 1)
        h = (outer["emb"][toks] + outer["pos"][pidx][None]).astype(dtype)
        row_len = cache_k.shape[3]
        for l in range(cfg.n_layer):
            p = {k: w[l] for k, w in blocks.items()}

            def attn(q, k, v, l=l):
                # write this chunk's K/V at (layer l, slot, start), then
                # attend the chunk's queries over the updated row
                kh = jnp.transpose(k, (0, 2, 1, 3))[None]   # (1,1,H,C,d)
                vh = jnp.transpose(v, (0, 2, 1, 3))[None]
                ck = pin_kv(lax.dynamic_update_slice(
                    cache_k, kh, (l, slot, 0, start, 0)))
                cv = pin_kv(lax.dynamic_update_slice(
                    cache_v, vh, (l, slot, 0, start, 0)))
                size = (1, 1, cfg.n_head, row_len, hd)
                row_k = lax.dynamic_slice(ck, (l, slot, 0, 0, 0), size)[0]
                row_v = lax.dynamic_slice(cv, (l, slot, 0, 0, 0), size)[0]
                return gather(_attn_chunk(q, row_k, row_v, start)), (ck, cv)

            h, (cache_k, cache_v) = _block_core_fusedqkv(
                p, h, cfg.n_head, attn, gather)
        last = lax.dynamic_slice_in_dim(h, n_valid - 1, 1, axis=1)
        hl = _layernorm(last, outer["lnf_g"], outer["lnf_b"])
        logits = hl[:, 0] @ outer["head"].astype(hl.dtype)      # (1, V)
        k0 = jax.random.fold_in(key, 0)
        tok = sample_rows(logits, k0[None], temp[None], top_k[None],
                          top_p[None])
        return cache_k, cache_v, tok[0]

    return jax.jit(impl, donate_argnums=(2, 3) if donate else ())


def _attn_verify(q, ck, cv, pos):
    """Multi-query cached attention for the draft-and-verify step: q
    (1, K+1, H, d) token-major against the row's head-major caches
    (1, H, S, d), query i masked at absolute position ``pos + i``. This
    is _attn_cached_rows' EXACT arithmetic (f32-cast einsums, the same
    ``/ d ** 0.5`` scaling, -1e30 mask, f32 softmax) with the query
    count widened from 1 to K+1 — query rows are independent through
    every op here (batch dims of the einsums, row-wise softmax), so row
    i reproduces bit for bit what the batched tick would compute for
    the same token at the same position. That equality is the greedy
    identity contract of speculative decoding: an accepted draft
    token's logits ARE the tick's logits."""
    d = q.shape[-1]
    qh = jnp.swapaxes(q, 1, 2)                          # (1, h, K+1, d)
    s = jnp.einsum("bhqd,bhkd->bhqk", qh.astype(jnp.float32),
                   ck.astype(jnp.float32)) / (d ** 0.5)
    kpos = jnp.arange(ck.shape[2])[None, None, None, :]
    qpos = (pos + jnp.arange(q.shape[1]))[None, None, :, None]
    w = jax.nn.softmax(jnp.where(kpos <= qpos, s, -1e30), axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", w,
                     cv.astype(jnp.float32)).astype(q.dtype)
    return jnp.swapaxes(out, 1, 2)                      # (1, K+1, h, d)


@functools.lru_cache(maxsize=16)
def _verify_fn(cfg_key: tuple, spec_len: int, donate: bool, mesh=None):
    """Jitted draft-and-verify step (``serve_verify_chunk``): process
    ``spec_len + 1`` tokens — the row's last emitted token plus
    ``spec_len`` (padded) draft tokens — through the target model in ONE
    forward, writing all K+1 candidate K/V rows at a traced position,
    then compute the accepted prefix and the one emitted
    correction/bonus token on device. Slot, position, draft count, and
    sampling params are all traced, so ONE compiled program serves every
    slot, every position, and every draft hit length (mixed n-gram hit
    lengths included — fewer real drafts just lower ``n_draft``).

    Acceptance preserves the solo decode's output exactly: greedy
    accepts the longest prefix matching the target argmax (row i's
    logits are bit-identical to the tick's at that position, see
    _attn_verify) and emits the argmax at the first divergence — the
    greedy stream is the argmax chain either way. Sampled rows use the
    standard rejection/residual rule (ops/sampling.py) so the output
    DISTRIBUTION is unchanged. The fold_in key schedule consumes one
    index per EMITTED token — row i derives its accept/emit keys from
    ``fold_in(key, fold + i)`` and the verify advances ``fold`` by the
    emitted count — so a speculative stream and a tick-by-tick stream
    stay on the same per-token schedule (greedy never touches the keys
    at all, which is why greedy is bit-identical, not just
    distributionally identical).

    Rejected draft rows need no rollback copy: the row's new position
    stops at the last accepted token, and stale K/V beyond a row's own
    position is unreachable by construction (the same masked-softmax
    invariant recycled slots lean on); the next forward overwrites the
    rejected rows in place. Layer loop python-unrolled with per-layer
    dus straight into the stacked caches — the tick/chunk idiom."""
    cfg = GPTConfig(*cfg_key)
    dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
    gather, pin_kv = _tp_ops(mesh)
    hd = cfg.feat // cfg.n_head
    rows = spec_len + 1

    def impl(blocks, outer, cache_k, cache_v, toks, slot, pos, n_draft,
             key, fold, temp, top_k, top_p):
        # position rows by gather, clipped into the table: pad drafts
        # past seq_len - 1 produce masked garbage the accept logic never
        # reads (n_draft caps acceptance; the caller gates dispatch so
        # pos + spec_len + 1 <= row_len and real positions stay valid)
        pidx = jnp.clip(pos + jnp.arange(rows), 0, cfg.seq_len - 1)
        h = (outer["emb"][toks] + outer["pos"][pidx][None]).astype(dtype)
        row_len = cache_k.shape[3]
        for l in range(cfg.n_layer):
            p = {k: w[l] for k, w in blocks.items()}

            def attn(q, k, v, l=l):
                # write all K+1 candidate rows at (layer l, slot, pos),
                # then attend the queries over the updated row
                kh = jnp.transpose(k, (0, 2, 1, 3))[None]   # (1,1,H,K+1,d)
                vh = jnp.transpose(v, (0, 2, 1, 3))[None]
                ck = pin_kv(lax.dynamic_update_slice(
                    cache_k, kh, (l, slot, 0, pos, 0)))
                cv = pin_kv(lax.dynamic_update_slice(
                    cache_v, vh, (l, slot, 0, pos, 0)))
                size = (1, 1, cfg.n_head, row_len, hd)
                row_k = lax.dynamic_slice(ck, (l, slot, 0, 0, 0), size)[0]
                row_v = lax.dynamic_slice(cv, (l, slot, 0, 0, 0), size)[0]
                return gather(_attn_verify(q, row_k, row_v, pos)), (ck, cv)

            h, (cache_k, cache_v) = _block_core_fusedqkv(
                p, h, cfg.n_head, attn, gather)
        hl = _layernorm(h, outer["lnf_g"], outer["lnf_b"])
        logits = hl[0] @ outer["head"].astype(hl.dtype)     # (K+1, V)
        # one fold index per candidate emitted token; greedy ignores keys
        folds = fold + jnp.arange(rows)
        keys_r = jax.vmap(jax.random.fold_in, in_axes=(None, 0))(key, folds)
        draft = toks[0, 1:]                                 # (spec_len,)
        bshape = (spec_len,)
        acc_keys = jax.vmap(lambda kk: jax.random.fold_in(kk, 1))(
            keys_r[:spec_len])
        acc = accept_draft_rows(
            logits[:spec_len], draft, acc_keys,
            jnp.broadcast_to(temp, bshape), jnp.broadcast_to(top_k, bshape),
            jnp.broadcast_to(top_p, bshape))
        acc = acc & (jnp.arange(spec_len) < n_draft)
        # accepted-prefix length = index of the first rejected row (the
        # appended False makes an all-accepted window resolve to n_draft)
        n_acc = jnp.argmin(jnp.concatenate(
            [acc, jnp.zeros((1,), bool)])).astype(jnp.int32)
        # the emitted token comes from row n_acc's logits: residual
        # (draft token excluded) on a rejection, a plain filtered draw
        # (exclusion disabled via draft = -1) on the all-accepted bonus
        la = jnp.take(logits, n_acc, axis=0)[None]
        da = jnp.where(n_acc >= n_draft, -1,
                       jnp.take(draft, jnp.minimum(n_acc, spec_len - 1)))
        ke = jax.random.fold_in(jnp.take(keys_r, n_acc, axis=0), 2)
        emit = residual_sample_rows(la, da[None], ke[None],
                                    jnp.asarray(temp)[None],
                                    jnp.asarray(top_k)[None],
                                    jnp.asarray(top_p)[None])[0]
        return cache_k, cache_v, n_acc, emit

    return jax.jit(impl, donate_argnums=(2, 3) if donate else ())


@functools.lru_cache(maxsize=256)
def _extract_chunks_fn(cfg_key: tuple, chunk: int, n_chunks: int):
    """Jitted chunk copy-out for the prefix cache: ``n_chunks``
    contiguous chunks sliced from a slot row at a traced offset in ONE
    dispatch, returned chunk-major (n_chunks, n_layer, n_head, chunk,
    head_dim) so the caller can index per-chunk trie buffers out of it.
    Compiled per chunk count — bounded by row_len / chunk, which the
    maxsize covers up to seq_len 16k at the default chunk 64 (these
    small copy programs sit outside the RecompileGuard: their signature
    count is config-bounded, not traffic-driven). The caches are NOT
    donated — the row keeps serving."""
    cfg = GPTConfig(*cfg_key)
    hd = cfg.feat // cfg.n_head
    size = (cfg.n_layer, 1, cfg.n_head, n_chunks * chunk, hd)

    def grab(cache, slot, start):
        blk = lax.dynamic_slice(cache, (0, slot, 0, start, 0), size)[:, 0]
        blk = blk.reshape(cfg.n_layer, cfg.n_head, n_chunks, chunk, hd)
        return jnp.transpose(blk, (2, 0, 1, 3, 4))

    def impl(cache_k, cache_v, slot, start):
        return grab(cache_k, slot, start), grab(cache_v, slot, start)

    return jax.jit(impl)


@functools.lru_cache(maxsize=256)
def _insert_prefix_fn(cfg_key: tuple, n_tokens: int, donate: bool):
    """Jitted whole-prefix copy-in: a matched prefix is CONTIGUOUS at
    the row start, so the cache's chunk nodes are concatenated once and
    restored with ONE dus per cache — the admit-time fast path (N
    separate per-chunk dus calls each rewrite the whole cache on
    backends without donation; one call pays that once). Compiled per
    restored-prefix length in chunks — bounded by row_len / chunk, which
    the maxsize covers up to seq_len 16k at the default chunk 64."""
    def impl(cache_k, cache_v, ks, vs, slot):
        # ks/vs: n_chunks-tuples of (L, H, chunk, hd); concat -> one
        # (L, 1, H, n_tokens, hd) block at position 0 of the slot row
        k = jnp.concatenate(ks, axis=2)[:, None]
        v = jnp.concatenate(vs, axis=2)[:, None]
        ck = lax.dynamic_update_slice(cache_k, k, (0, slot, 0, 0, 0))
        cv = lax.dynamic_update_slice(cache_v, v, (0, slot, 0, 0, 0))
        return ck, cv

    return jax.jit(impl, donate_argnums=(0, 1) if donate else ())


# --------------------------------------------------------------- paged
# The paged programs re-cut the three dense serve programs over a global
# block pool (n_layer, num_blocks, n_head, block_size, head_dim) plus
# traced int32 block tables (serve/paged.py). Every K/V write becomes a
# position-wise SCATTER — position p lands at physical block
# table[p // bs], offset p % bs — and every attention read a GATHER of
# the row's blocks back into the same logical (H, row_len, d) layout the
# dense programs use, so the arithmetic downstream of the gather is the
# dense path's bit for bit (same einsums, same f32 softmax, same -1e30
# mask; garbage blocks in a table's unallocated tail are masked to an
# exact 0.0 contribution exactly like a dense row's stale tail). Block
# size, blocks-per-row and the table SHAPES are static — slot, position
# and the table VALUES are traced — so each program keeps exactly one
# compiled signature across mixed lengths, occupancy, and any block
# placement (the RecompileGuard pins it).


# int8 KV codec (serve_kv_dtype=int8): a quantized pool is the pytree
# (values int8, scales compute-dtype) instead of one compute-dtype
# array — scales shaped like the values minus the head_dim axis, one
# symmetric scale per (layer, block, head, token). Tuple-ness is part
# of jit's abstract signature, so the SAME program builders serve both
# layouts (a quantized engine is a different compiled program, counted
# as such — the RecompileGuard signature carries /kv=int8). Quantize
# happens ON SCATTER (the one place a position's K/V is produced),
# dequantize ON GATHER (the one place it is consumed), so the stored
# representation IS the int8 payload — which is what lets the swap
# crc32 checksums of PR 9 verify a quantized round trip bit-exactly.


def _kv_quant(val, sdtype):
    """Per-(…, head, token) symmetric int8 quantization of a K/V write:
    ``scale = max|v| / 127`` over head_dim, rounded to the STORED scale
    dtype first so dequant uses exactly the scale quantization used
    (values clipped to ±127 — a scale that rounded down must not wrap
    the int8 payload)."""
    a = val.astype(jnp.float32)
    s = (jnp.max(jnp.abs(a), axis=-1) / 127.0).astype(sdtype)
    sf = jnp.maximum(s.astype(jnp.float32), 1e-12)
    q = jnp.clip(jnp.round(a / sf[..., None]),
                 -127.0, 127.0).astype(jnp.int8)
    return q, s


def _kv_dequant(q, s):
    """Inverse of :func:`_kv_quant` in the COMPUTE dtype (``s.dtype``):
    int8 values are exact in bf16's 8 mantissa bits, so the product is
    one rounding step — never a silent f32 promotion (CXN209)."""
    return q.astype(s.dtype) * s[..., None]


def _layer_pool(pool, l):
    """Layer ``l``'s slice of a pool in either layout (array or the
    int8 (values, scales) pair)."""
    if isinstance(pool, tuple):
        return pool[0][l], pool[1][l]
    return pool[l]


def _scatter_kv(pool, l, blk, off, val):
    """Scatter one K/V write — ``val`` (…, H, d) at (layer ``l``, block
    ``blk``, offset ``off``) — into either pool layout, quantizing on
    the way in for an int8 pool."""
    if isinstance(pool, tuple):
        qp, sp = pool
        q, s = _kv_quant(val, sp.dtype)
        return (qp.at[l, blk, :, off, :].set(q),
                sp.at[l, blk, :, off].set(s))
    return pool.at[l, blk, :, off, :].set(val)


def _gather_row(pool, table, n_head, bs):
    """One row's logical K or V cache (1, H, row_len, d) gathered from
    the (layer-sliced) pool through its (bpr,) block table,
    dequantized on the way out for an int8 pool."""
    if isinstance(pool, tuple):
        qp, sp = pool
        blk = _kv_dequant(qp[table], sp[table])     # (bpr, H, bs, d)
    else:
        blk = pool[table]
    hd = blk.shape[-1]
    return jnp.transpose(blk, (1, 0, 2, 3)).reshape(
        n_head, table.shape[0] * bs, hd)[None]


def _gather_rows(pool, table, n_head, bs):
    """All slot rows' logical caches (slots, H, row_len, d) gathered
    from the (layer-sliced) pool through the (slots, bpr) block table,
    dequantized on the way out for an int8 pool."""
    if isinstance(pool, tuple):
        qp, sp = pool
        blk = _kv_dequant(qp[table], sp[table])     # (b, bpr, H, bs, d)
    else:
        blk = pool[table]
    b, bpr = table.shape
    hd = blk.shape[-1]
    return jnp.transpose(blk, (0, 2, 1, 3, 4)).reshape(
        b, n_head, bpr * bs, hd)


def _paged_attn(q, pool_k, pool_v, table, pos, l, bs, mesh=None,
                streaming=False):
    """Route the fused Pallas block-table-walk attention over either
    pool layout: an int8 pool hands the kernel its scale planes too, so
    the in-VMEM dequant mirrors :func:`_kv_dequant` op for op (the
    interpret-mode differential pins it bit-exact against the gather
    formulation). A TP ``mesh`` (model axis > 1) routes through the
    shard_map wrap — each shard runs the kernel on its local head
    slice of q and the pools, tables replicated; the returned output
    is still HEAD-SHARDED and the caller re-replicates it with the
    same ``gather`` hook the gather formulation uses. ``streaming``
    selects the online-softmax grid formulation (row images past the
    resident VMEM gate)."""
    from ..ops.pallas_kernels import (paged_attention,
                                      paged_attention_sharded)
    sk = sv = None
    if isinstance(pool_k, tuple):
        (pool_k, sk), (pool_v, sv) = pool_k, pool_v
    if mesh is not None:
        return paged_attention_sharded(q, pool_k, pool_v, table, pos,
                                       l, bs, mesh, scale_k=sk,
                                       scale_v=sv, streaming=streaming)
    return paged_attention(q, pool_k, pool_v, table, pos, l, bs,
                           scale_k=sk, scale_v=sv, streaming=streaming)


@functools.lru_cache(maxsize=16)
def _tick_paged_fn(cfg_key: tuple, bs: int, bpr: int, donate: bool,
                   fused="", mesh=None, lora: bool = False):
    """Paged batched decode tick: same math as ``_tick_fn`` with the
    per-row dus replaced by a block scatter and the cache row reads by a
    table gather. Parked rows scatter into whatever their table's last
    entry points at — the garbage block for free/prefilling rows — and
    their output is discarded; a decode row always writes its own
    position before attending to it (write-before-attend, the invariant
    every reuse argument leans on).

    ``fused`` (the formulation string — ``"resident"`` /
    ``"streaming"``, falsy = gather; a legacy ``True`` means resident)
    replaces the XLA gather + attention by ONE Pallas pass per layer
    (ops/pallas_kernels.py:paged_attention): the kernel walks the
    block table directly, so the gathered logical rows are never
    materialized in HBM — streaming additionally carries online-
    softmax scratch across the block walk so row images past the
    resident VMEM gate stay fused. Under a TP mesh the kernel rides
    the shard_map wrap per head shard and its output is re-replicated
    by the same ``gather`` hook the gather formulation pays. The
    scatter (and with it the cache bytes) is IDENTICAL either way;
    only the attention read path changes, under the
    fused_attn_tolerance contract. The formulation is part of this lru
    key — a fused and a gather engine over one config are different
    compiled programs — but deliberately NOT part of any RecompileGuard
    signature string (the guard counts traffic-driven drift, and the
    formulation is fixed at engine construction).

    ``lora`` arms the per-row adapter delta: the impl grows two traced
    operands — the (b,) adapter-id vector and the device pool dict —
    and every block matmul site routes through serve/lora.py's grouped
    dispatch. The adapter ids are TRACED, so mixed-adapter traffic is
    one signature; unarmed builders pass lora=None into the block core
    and keep their exact jaxpr (the pinned structural no-op)."""
    cfg = GPTConfig(*cfg_key)
    dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
    gather, pin_kv = _tp_ops(mesh)
    tp_mesh = mesh if serve_tp_size(mesh) > 1 else None
    streaming = (fused == "streaming")
    shards = serve_tp_size(mesh)
    if lora:
        from .lora import lora_delta

    def impl(blocks, outer, pool_k, pool_v, table, tok, pos, keys, fold,
             temp, top_k, top_p, *lrest):
        h = (outer["emb"][tok][:, None, :]
             + outer["pos"][jnp.minimum(pos, cfg.seq_len - 1)][:, None, :]
             ).astype(dtype)
        # physical write target per row: block table[pos // bs] at
        # offset pos % bs (pos <= row_len - 1 always, so the logical
        # block index stays inside the table)
        blk = jnp.take_along_axis(table, (pos // bs)[:, None],
                                  axis=1)[:, 0]
        off = pos % bs
        for l in range(cfg.n_layer):
            p = {k: w[l] for k, w in blocks.items()}

            def attn(q, k, v, l=l):
                # scatter each row's (H, d) K/V into its own block
                # (quantize-on-scatter for an int8 pool), then attend:
                # fused = the Pallas block-table walk; gather =
                # materialize the logical rows and reuse the dense math
                pk = pin_kv(_scatter_kv(pool_k, l, blk, off, k[:, 0]))
                pv = pin_kv(_scatter_kv(pool_v, l, blk, off, v[:, 0]))
                if fused:
                    return gather(_paged_attn(
                        q, pk, pv, table, pos, l, bs, mesh=tp_mesh,
                        streaming=streaming)), (pk, pv)
                ck = _gather_rows(_layer_pool(pk, l), table, cfg.n_head,
                                  bs)
                cv = _gather_rows(_layer_pool(pv, l), table, cfg.n_head,
                                  bs)
                return gather(_attn_cached_rows(q, ck, cv, pos)), (pk, pv)

            hook = None
            if lora:
                aid, lpool = lrest
                hook = lambda site, x, y, l=l: \
                    lora_delta(lpool, aid, l, site, x, y)
            h, (pool_k, pool_v) = _block_core_fusedqkv(
                p, h, cfg.n_head, attn, gather, lora=hook,
                int4_shards=shards)
        hl = _layernorm(h, outer["lnf_g"], outer["lnf_b"])
        logits = hl[:, 0] @ outer["head"].astype(hl.dtype)      # (b, V)
        keys_t = jax.vmap(jax.random.fold_in)(keys, fold)
        nxt = sample_rows(logits, keys_t, temp, top_k, top_p)
        return pool_k, pool_v, nxt

    return jax.jit(impl, donate_argnums=(2, 3) if donate else ())


@functools.lru_cache(maxsize=16)
def _prefill_chunk_paged_fn(cfg_key: tuple, chunk: int, bs: int,
                            bpr: int, donate: bool, mesh=None,
                            lora: bool = False):
    """Paged chunk-prefill step: ``_prefill_chunk_fn``'s math with the
    row dus/slice replaced by a per-position block scatter and a table
    gather. The caller (engine.reserve_window) has already allocated —
    and COW-privatized — every block covering [start, start + chunk),
    so the scatter only ever lands in blocks this row owns alone.
    ``lora``: as in :func:`_tick_paged_fn`, but the adapter id is a
    traced SCALAR (one row prefills per dispatch)."""
    cfg = GPTConfig(*cfg_key)
    dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
    gather, pin_kv = _tp_ops(mesh)
    shards = serve_tp_size(mesh)
    if lora:
        from .lora import lora_delta

    def impl(blocks, outer, pool_k, pool_v, table, toks, start, n_valid,
             key, temp, top_k, top_p, *lrest):
        pidx = jnp.clip(start + jnp.arange(chunk), 0, cfg.seq_len - 1)
        h = (outer["emb"][toks] + outer["pos"][pidx][None]).astype(dtype)
        # write positions clamped INTO the row: a partial-tail prefix
        # hit resumes prefill at a non-block-aligned start, so the
        # final chunk's pad positions can run past row_len — clamping
        # the POSITION (not just the block index) parks those writes at
        # the row's last slot (beyond every live position, rewritten
        # before any read — the standard write-before-attend argument)
        # instead of aliasing offset-of-overflow onto a live block
        wpos = jnp.minimum(start + jnp.arange(chunk), bpr * bs - 1)
        blkw = table[wpos // bs]                            # (chunk,)
        offw = wpos % bs
        for l in range(cfg.n_layer):
            p = {k: w[l] for k, w in blocks.items()}

            def attn(q, k, v, l=l):
                pk = pin_kv(_scatter_kv(pool_k, l, blkw, offw, k[0]))
                pv = pin_kv(_scatter_kv(pool_v, l, blkw, offw, v[0]))
                row_k = _gather_row(_layer_pool(pk, l), table,
                                    cfg.n_head, bs)
                row_v = _gather_row(_layer_pool(pv, l), table,
                                    cfg.n_head, bs)
                return gather(_attn_chunk(q, row_k, row_v, start)), \
                    (pk, pv)

            hook = None
            if lora:
                aid, lpool = lrest
                hook = lambda site, x, y, l=l: \
                    lora_delta(lpool, aid[None], l, site, x, y)
            h, (pool_k, pool_v) = _block_core_fusedqkv(
                p, h, cfg.n_head, attn, gather, lora=hook,
                int4_shards=shards)
        last = lax.dynamic_slice_in_dim(h, n_valid - 1, 1, axis=1)
        hl = _layernorm(last, outer["lnf_g"], outer["lnf_b"])
        logits = hl[:, 0] @ outer["head"].astype(hl.dtype)      # (1, V)
        k0 = jax.random.fold_in(key, 0)
        tok = sample_rows(logits, k0[None], temp[None], top_k[None],
                          top_p[None])
        return pool_k, pool_v, tok[0]

    return jax.jit(impl, donate_argnums=(2, 3) if donate else ())


@functools.lru_cache(maxsize=16)
def _verify_paged_fn(cfg_key: tuple, spec_len: int, bs: int, bpr: int,
                     donate: bool, fused="", mesh=None,
                     lora: bool = False):
    """Paged draft-and-verify step: ``_verify_fn``'s math over block
    scatter/gather. All K+1 candidate positions were reserved (and
    COW-privatized) before dispatch, which is exactly why a rejected
    draft needs no rollback copy: the stale candidate K/V sits in
    privately-owned blocks beyond the row's accepted position,
    unreachable by the position mask until overwritten.

    ``fused`` (the formulation string, as in :func:`_tick_paged_fn`)
    routes the attention read through the same Pallas block-table
    kernel as the tick, widened to K+1 query rows (query r masked at
    ``pos + r`` — exactly ``_attn_verify``'s semantics), sharded per
    head under a TP mesh; the scatter and the accept/emit logic are
    untouched."""
    cfg = GPTConfig(*cfg_key)
    dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
    gather, pin_kv = _tp_ops(mesh)
    tp_mesh = mesh if serve_tp_size(mesh) > 1 else None
    streaming = (fused == "streaming")
    rows = spec_len + 1
    shards = serve_tp_size(mesh)
    if lora:
        from .lora import lora_delta

    def impl(blocks, outer, pool_k, pool_v, table, toks, pos, n_draft,
             key, fold, temp, top_k, top_p, *lrest):
        pidx = jnp.clip(pos + jnp.arange(rows), 0, cfg.seq_len - 1)
        h = (outer["emb"][toks] + outer["pos"][pidx][None]).astype(dtype)
        wpos = pos + jnp.arange(rows)
        blkw = table[jnp.clip(wpos // bs, 0, bpr - 1)]      # (K+1,)
        offw = wpos % bs
        for l in range(cfg.n_layer):
            p = {k: w[l] for k, w in blocks.items()}

            def attn(q, k, v, l=l):
                pk = pin_kv(_scatter_kv(pool_k, l, blkw, offw, k[0]))
                pv = pin_kv(_scatter_kv(pool_v, l, blkw, offw, v[0]))
                if fused:
                    return gather(_paged_attn(
                        q, pk, pv, table[None], jnp.reshape(pos, (1,)),
                        l, bs, mesh=tp_mesh,
                        streaming=streaming)), (pk, pv)
                row_k = _gather_row(_layer_pool(pk, l), table,
                                    cfg.n_head, bs)
                row_v = _gather_row(_layer_pool(pv, l), table,
                                    cfg.n_head, bs)
                return gather(_attn_verify(q, row_k, row_v, pos)), \
                    (pk, pv)

            hook = None
            if lora:
                aid, lpool = lrest
                hook = lambda site, x, y, l=l: \
                    lora_delta(lpool, aid[None], l, site, x, y)
            h, (pool_k, pool_v) = _block_core_fusedqkv(
                p, h, cfg.n_head, attn, gather, lora=hook,
                int4_shards=shards)
        hl = _layernorm(h, outer["lnf_g"], outer["lnf_b"])
        logits = hl[0] @ outer["head"].astype(hl.dtype)     # (K+1, V)
        folds = fold + jnp.arange(rows)
        keys_r = jax.vmap(jax.random.fold_in, in_axes=(None, 0))(key, folds)
        draft = toks[0, 1:]                                 # (spec_len,)
        bshape = (spec_len,)
        acc_keys = jax.vmap(lambda kk: jax.random.fold_in(kk, 1))(
            keys_r[:spec_len])
        acc = accept_draft_rows(
            logits[:spec_len], draft, acc_keys,
            jnp.broadcast_to(temp, bshape), jnp.broadcast_to(top_k, bshape),
            jnp.broadcast_to(top_p, bshape))
        acc = acc & (jnp.arange(spec_len) < n_draft)
        n_acc = jnp.argmin(jnp.concatenate(
            [acc, jnp.zeros((1,), bool)])).astype(jnp.int32)
        la = jnp.take(logits, n_acc, axis=0)[None]
        da = jnp.where(n_acc >= n_draft, -1,
                       jnp.take(draft, jnp.minimum(n_acc, spec_len - 1)))
        ke = jax.random.fold_in(jnp.take(keys_r, n_acc, axis=0), 2)
        emit = residual_sample_rows(la, da[None], ke[None],
                                    jnp.asarray(temp)[None],
                                    jnp.asarray(top_k)[None],
                                    jnp.asarray(top_p)[None])[0]
        return pool_k, pool_v, n_acc, emit

    return jax.jit(impl, donate_argnums=(2, 3) if donate else ())


@functools.lru_cache(maxsize=16)
def _copy_block_fn(cfg_key: tuple, bs: int, donate: bool):
    """Jitted copy-on-write fault: duplicate one physical block's K/V
    (all layers) into a freshly-allocated block — traced src/dst, one
    compiled signature no matter which blocks fault."""
    cfg = GPTConfig(*cfg_key)
    hd = cfg.feat // cfg.n_head
    size = (cfg.n_layer, 1, cfg.n_head, bs, hd)

    def impl(pool_k, pool_v, src, dst):
        def cp(pool):
            if isinstance(pool, tuple):
                # int8 pool: the COW copy moves the STORED
                # representation — payload and scales — so the private
                # copy is bit-identical to the shared original
                q, s = pool
                bq = lax.dynamic_slice(q, (0, src, 0, 0, 0), size)
                bsc = lax.dynamic_slice(s, (0, src, 0, 0), size[:-1])
                return (lax.dynamic_update_slice(q, bq, (0, dst, 0, 0, 0)),
                        lax.dynamic_update_slice(s, bsc, (0, dst, 0, 0)))
            b = lax.dynamic_slice(pool, (0, src, 0, 0, 0), size)
            return lax.dynamic_update_slice(pool, b, (0, dst, 0, 0, 0))

        return cp(pool_k), cp(pool_v)

    return jax.jit(impl, donate_argnums=(0, 1) if donate else ())


@functools.lru_cache(maxsize=16)
def _gather_blocks_fn(cfg_key: tuple, bs: int, bpr: int):
    """Jitted swap-out copy: gather ``bpr`` blocks (padded id vector —
    pad entries read the garbage block, the host slices them off) out of
    the pool in one dispatch. Fixed gather width = one compiled
    signature for every row size; pools NOT donated (the pool keeps
    serving)."""
    def impl(pool_k, pool_v, ids):
        def g(pool):                            # (L, bpr, H, bs, d)
            if isinstance(pool, tuple):
                # int8 pool: the swap buffer carries the stored
                # representation (payload + scales), so the round trip
                # — and PR 9's crc32 over it — is bit-exact
                return pool[0][:, ids], pool[1][:, ids]
            return pool[:, ids]

        return g(pool_k), g(pool_v)

    return jax.jit(impl)


@functools.lru_cache(maxsize=16)
def _scatter_blocks_fn(cfg_key: tuple, bs: int, bpr: int, donate: bool):
    """Jitted swap-in restore: scatter a padded (L, bpr, H, bs, d) host
    buffer back into freshly-allocated blocks — the paged analogue of
    the dense dus-per-cache restore path. Pad entries target the
    garbage block (id 0), which exists to absorb exactly this kind of
    write."""
    def impl(pool_k, pool_v, bufk, bufv, ids):
        def sc(pool, buf):
            if isinstance(pool, tuple):
                return (pool[0].at[:, ids].set(buf[0]),
                        pool[1].at[:, ids].set(buf[1]))
            return pool.at[:, ids].set(buf)

        return sc(pool_k, bufk), sc(pool_v, bufv)

    return jax.jit(impl, donate_argnums=(0, 1) if donate else ())


def clear_program_caches() -> None:
    """Drop every module-level compiled-program cache AND the AOT
    cache's in-memory executable memos. Tests and the cold-start bench
    use this to simulate a fresh process: the next program fetch
    re-resolves — from the AOT executable cache's DISK artifacts when
    one is armed (analysis/aot_cache.py), else by tracing + compiling."""
    for f in (_tick_fn, _prefill_fn, _prefill_chunk_fn, _verify_fn,
              _extract_chunks_fn, _insert_prefix_fn, _tick_paged_fn,
              _prefill_chunk_paged_fn, _verify_paged_fn, _copy_block_fn,
              _gather_blocks_fn, _scatter_blocks_fn):
        f.cache_clear()
    from ..analysis.aot_cache import clear_memory_caches
    clear_memory_caches()


class DecodeEngine:
    """Owns the KV cache — the dense slot pool, or the paged block pool
    plus block tables (``num_blocks > 0``) — and drives the jitted
    programs (one chunk-prefill step, legacy prefill per prompt length,
    one shared tick, one verify step, plus the paged COW/swap copies).
    Host-side state is the caller's job (serve/scheduler.py); this
    class only moves tensors and owns the
    :class:`~cxxnet_tpu.serve.paged.BlockManager` bookkeeping."""

    def __init__(self, cfg: GPTConfig, params: Dict, slots: int,
                 prefill_chunk: int = 64, recompile_limit: int = 0,
                 recompile_strict: bool = True, abstract: bool = False,
                 spec_len: int = 0, obs_registry=None,
                 num_blocks: int = 0, block_size: int = 0,
                 injector=None, fused_attn: bool = True, mesh=None,
                 int8_weights: bool = False, kv_dtype: str = "",
                 int4_weights: bool = False,
                 int4_group: int = INT4_GROUP_DEFAULT,
                 aot=None, tracer=None, lora_pool=None):
        """``num_blocks`` > 0 selects the PAGED cache: a global block
        pool of that many fixed-size blocks (``block_size`` tokens each;
        0 = the prefill chunk) indexed by per-row block tables, with
        copy-on-write prefix sharing and host swap support. 0 (the
        engine-level default) keeps the dense slot pool. Paging requires
        chunked prefill (``prefill_chunk`` > 0) and a ``block_size``
        that divides the (seq_len-clamped) chunk, so chunk windows and
        prefix-trie nodes always cover whole blocks.

        ``fused_attn`` (paged only): arm the fused Pallas
        block-table-walk attention for the tick and verify programs
        wherever ``paged_attention_formulation`` resolves one — the
        RESIDENT whole-row-image formulation when it fits the VMEM
        gate, the STREAMING online-softmax formulation (one KV block
        resident at a time) for longer rows, so long-context serving
        stays fused. It auto-resolves OFF on unsupported
        backends/geometries (the XLA gather formulation then runs,
        bit-reference semantics — the reason is logged once and counted
        in ``cxn_fused_fallback_total{reason=}``), and
        ``CXN_FUSED_ATTN=0`` force-disables it process-wide. The
        resolved state is ``self.fused_attn`` /
        ``self.fused_formulation``; under TP the kernel runs per head
        shard through the shard_map wrap (module docstring).

        ``mesh`` (a ``jax.sharding.Mesh`` whose ``model`` axis is > 1)
        arms gather-form tensor-parallel serving (module docstring):
        weights sharded on output dims, the KV pool on the head axis,
        decode bit-identical to the single-device engine. Requires
        chunked prefill and ``n_head`` divisible by the model-axis
        size. A mesh WITHOUT a > 1 model axis is placement-only: the
        single-device programs run untouched, but the engine's params
        and caches are committed to that mesh's device — how the
        router places replica i on its own device block instead of
        every replica defaulting onto device 0.

        Quantized serving (doc/serving.md "Quantized serving"):
        ``int8_weights`` quantizes the fused block matmul weights ONCE
        at engine build (per-out-column symmetric int8,
        models/gpt.py:_quantize_decode_blocks) and streams them through
        every program — chunk prefill, tick, AND the speculative
        verify — halving the per-token weight traffic the decode step
        is bound by. ``kv_dtype="int8"`` (paged engines only) stores
        the block pool per-block-scaled int8: each pool becomes a
        ``(values int8, scales)`` pair with one symmetric scale per
        (layer, block, head, token), quantized on scatter and
        dequantized on gather inside the same fused/gather attention
        formulations — ~2x tokens per MiB in ``kv_blocks``, the trie's
        shared blocks, and ``swap_host`` (the swap record carries the
        stored int8 representation, so PR 9's crc32 checksums verify
        the quantized round trip bit-exactly). Accuracy is pinned by
        :func:`kv_int8_tolerance`; both knobs default OFF and are
        pinned no-ops there (every bit-identity suite runs against
        the unquantized programs).

        ``int4_weights`` (round 19) quantizes the same fused block
        dict to PACKED int4 instead — two nibbles per byte along the
        out-column dim, group-wise symmetric scales over
        ``int4_group`` in-rows (0 = one group = per-out-column;
        models/gpt.py:_quantize_decode_blocks_int4) — quartering the
        resident weight pool and the per-token stream. Every program
        routes its hot matmuls through _qmat's uint8 dispatch: the
        fused Pallas dequant-matmul (``int4_matmul`` — unpack + scale
        inside the tile, the unpacked weight never in HBM) where the
        geometry gate passes, the op-for-op XLA reference elsewhere
        (resolution in ``self.int4_formulation``, fallbacks counted in
        ``cxn_int4_fallback_total{reason=}``). Mutually exclusive with
        ``int8_weights``; accuracy pinned by :func:`w_int4_tolerance`;
        OFF is the same byte-for-byte no-op contract. Composes with
        ``serve_tp > 1``: the nibbles are packed PER output-dim shard
        (pairs never straddle a shard boundary), so GSPMD splits the
        packed plane on its halved axis and every shard unpacks a
        self-contained weight slice — bit-identical to the
        single-device int4 engine; the in-tile Pallas unpack assumes
        the single-segment layout, so sharded engines stream the XLA
        reference (``int4_formulation == ""``, reason ``"tp"``).

        ``lora_pool`` (an :class:`~cxxnet_tpu.serve.lora.AdapterPool`)
        arms batched multi-LoRA serving: every paged program grows a
        traced per-row adapter-id operand plus the pool's device
        factors, and applies the rank-r delta at the four block matmul
        sites via ragged grouped dispatch (serve/lora.py) — mixed
        adapter traffic decodes in ONE tick under ONE compiled
        signature (the pool geometry rides ``_sig_suffix``). None (the
        default) is a pinned STRUCTURAL no-op: the unarmed programs
        trace the exact pre-LoRA jaxpr."""
        if slots < 1:
            raise ValueError("serve_slots must be >= 1, got %d" % slots)
        if cfg.feat % cfg.n_head:
            raise ValueError("feat %d not divisible by n_head %d"
                             % (cfg.feat, cfg.n_head))
        kv = str(kv_dtype or "").lower()
        if kv in ("", "auto", "bf16", "bfloat16", "f32", "float32"):
            if kv in ("bf16", "bfloat16") and cfg.dtype != "bfloat16":
                raise ValueError(
                    "serve_kv_dtype=bf16 under an f32 model config: the "
                    "full-precision pool always stores the COMPUTE "
                    "dtype (leave serve_kv_dtype unset, or set "
                    "dtype=bfloat16)")
            if kv in ("f32", "float32") and cfg.dtype == "bfloat16":
                raise ValueError(
                    "serve_kv_dtype=f32 under a bfloat16 model config: "
                    "the full-precision pool always stores the COMPUTE "
                    "dtype (leave serve_kv_dtype unset)")
            self.kv_int8 = False
        elif kv == "int8":
            if int(num_blocks) <= 0:
                raise ValueError(
                    "serve_kv_dtype=int8 requires the paged KV cache "
                    "(serve_paged=1 with chunked prefill): the dense "
                    "slot pool keeps the compute dtype")
            self.kv_int8 = True
        else:
            raise ValueError(
                "serve_kv_dtype must be one of '', 'auto', 'bf16', "
                "'f32', 'int8', got %r" % (kv_dtype,))
        self.int8_weights = bool(int8_weights)
        self.int4_weights = bool(int4_weights)
        self.int4_group = int(int4_group)
        if self.int4_weights and self.int8_weights:
            raise ValueError(
                "serve_int4_weights and serve_int8_weights are mutually "
                "exclusive — pick one weight stream")
        if self.int4_group < 0:
            raise ValueError(
                "serve_int4_group must be >= 0 (0 = per-out-column), "
                "got %d" % int4_group)
        self.tp = serve_tp_size(mesh)
        self.mesh = mesh if self.tp > 1 else None
        if self.kv_int8 and self.tp > 1:
            raise ValueError(
                "serve_kv_dtype=int8 does not compose with serve_tp>1 "
                "yet: the (values, scales) pool pair needs per-leaf "
                "head-axis shardings the TP constraint hooks don't "
                "carry — shard OR quantize the KV pool, not both")
        if self.tp > 1:
            if cfg.n_head % self.tp:
                raise ValueError(
                    "serve_tp: n_head %d must be divisible by the "
                    "model-axis size %d (the KV pool shards whole "
                    "heads)" % (cfg.n_head, self.tp))
            if int(prefill_chunk) <= 0:
                raise ValueError(
                    "serve_tp requires chunked prefill "
                    "(serve_prefill_chunk > 0): the legacy whole-"
                    "prompt prefill compiles one program per prompt "
                    "length, which a sharded engine must not multiply "
                    "by mesh shapes")
        if prefill_chunk < 0:
            raise ValueError("serve_prefill_chunk must be >= 0 "
                             "(0 = whole-prompt prefill), got %d"
                             % prefill_chunk)
        if spec_len < 0:
            raise ValueError("spec_len must be >= 0 (0 = no speculative "
                             "verify program), got %d" % spec_len)
        self.cfg = cfg
        self._cfg_key = dataclasses.astuple(cfg)
        self.slots = slots
        # a chunk beyond seq_len buys nothing (no prompt can fill it —
        # submit rejects prompts >= seq_len) but would inflate row_len,
        # and with it every slot row's HBM; clamp instead of erroring so
        # the default chunk 64 composes with tiny-seq_len configs
        self.chunk = min(int(prefill_chunk), cfg.seq_len)
        # cache rows rounded UP to a chunk multiple: the final (padded)
        # chunk's row write at start = floor((n-1)/chunk)*chunk always
        # fits without jax's dynamic_update_slice start-clamping silently
        # shifting it onto earlier chunks. Decode positions stay < seq_len
        # (submit rejects prompts that leave no room), so the pad tail is
        # only ever written — by padded chunks and parked dummy ticks —
        # never read.
        c = self.chunk
        self.row_len = ((cfg.seq_len + c - 1) // c * c) if c else cfg.seq_len
        # default verify window for the speculative path: drafts beyond
        # seq_len - 1 could never all be verified inside one row anyway
        # (the verify writes spec_len + 1 rows from a decode position)
        self.spec_len = min(int(spec_len), max(cfg.seq_len - 1, 0))
        # paged cache geometry: block_size defaults to the prefill
        # chunk, and must divide it so every chunk window and every
        # prefix-trie node covers whole blocks (sub-chunk block sizes
        # buy finer-grained occupancy at the same alignment guarantees).
        # _paged_geometry is the shared source of this layout — the
        # same helper auto_num_blocks sizes budgets with, so a kv_mb
        # pool can never disagree with the engine's actual blocks.
        self.paged = int(num_blocks) > 0
        self.num_blocks = int(num_blocks) if self.paged else 0
        if self.paged:
            _, self.block_size, row_len_g, _, self._block_bytes = \
                _paged_geometry(cfg, prefill_chunk, block_size,
                                kv_dtype="int8" if self.kv_int8 else "")
            assert row_len_g == self.row_len
        else:
            self.block_size = 0
            self._block_bytes = 0
        self.dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
        # fused QKV once per server lifetime (models/gpt.py does this once
        # per decode CALL; a server amortizes it over every request); an
        # abstract engine fuses shapes only — no device concat
        self._blocks = (jax.eval_shape(_fuse_qkv_blocks, params["blocks"])
                        if abstract else _fuse_qkv_blocks(params["blocks"]))
        if self.int8_weights:
            # quantize ONCE at engine build (per-out-column symmetric
            # int8 + f32 scales, the offline decode's exact scheme) —
            # the engine then holds ONLY the int8 weights, so resident
            # weight memory halves along with the per-token stream; the
            # programs pick the scale keys up statically in
            # _block_core_fusedqkv/_qmat (models/gpt.py)
            self._blocks = (jax.eval_shape(_quantize_decode_blocks,
                                           self._blocks)
                            if abstract
                            else _quantize_decode_blocks(self._blocks))
        elif self.int4_weights:
            # same build-once contract, packed nibbles + group scales:
            # the engine holds ONLY the packed representation (a
            # quarter of bf16's weight bytes), and _qmat's uint8
            # dispatch routes every program's hot matmuls through
            # _qmat4 (kernel or XLA reference, resolved below)
            _q4 = functools.partial(_quantize_decode_blocks_int4,
                                    group=self.int4_group,
                                    shards=self.tp)
            self._blocks = (jax.eval_shape(_q4, self._blocks)
                            if abstract else _q4(self._blocks))
        self._outer = {k: params[k] for k in ("emb", "pos", "lnf_g",
                                              "lnf_b", "head")}
        if self.tp > 1:
            # gather-form TP placement (module docstring): weights on
            # their output-dim shardings, embedding/head replicated. An
            # abstract (audit-only) engine attaches the SAME shardings
            # to ShapeDtypeStructs, so the AOT audit lowers exactly the
            # partitioned programs a real TP engine runs.
            bsh, osh = serve_param_shardings(self.mesh,
                                             int4=self.int4_weights)
            if abstract:
                self._blocks = {
                    k: jax.ShapeDtypeStruct(v.shape, v.dtype,
                                            sharding=bsh[k])
                    for k, v in self._blocks.items()}
                self._outer = {
                    k: jax.ShapeDtypeStruct(jnp.shape(v),
                                            jnp.result_type(v),
                                            sharding=osh[k])
                    for k, v in self._outer.items()}
            else:
                self._blocks = {k: jax.device_put(v, bsh[k])
                                for k, v in self._blocks.items()}
                self._outer = {k: jax.device_put(v, osh[k])
                               for k, v in self._outer.items()}
        elif mesh is not None and not abstract:
            # placement-only mesh (model axis 1): commit the weights to
            # the mesh's device so this engine computes there — jit
            # follows its committed inputs, no program change
            from jax.sharding import NamedSharding, PartitionSpec
            rep = NamedSharding(mesh, PartitionSpec())
            self._blocks = jax.device_put(self._blocks, rep)
            self._outer = jax.device_put(self._outer, rep)
        # RecompileGuard signatures carry the mesh shape AND the
        # quantization dtypes: the same program traced over two mesh
        # shapes — or over int8 vs full-precision operands — is two
        # compiled executables, and the guard must count it as such (an
        # int8 and a bf16 engine in one process are distinct single
        # signatures; unlike the fused/gather flag, dtype changes the
        # abstract signature for real, so it belongs in the string)
        self._sig_suffix = ("/mesh=%s" % "x".join(
            str(s) for s in self.mesh.devices.shape)) if self.tp > 1 \
            else ""
        if self.int8_weights:
            self._sig_suffix += "/w=int8"
        if self.int4_weights:
            self._sig_suffix += "/w=int4/g=%d" % self.int4_group
        if self.kv_int8:
            self._sig_suffix += "/kv=int8"
        # batched multi-LoRA (serve/lora.py): the pool geometry (rank,
        # slot count) joins the signature — mixed adapter ids inside
        # one pool are ONE executable (the ids are a traced operand),
        # but a different rank/pool shape is honestly a different one
        self.lora_pool = lora_pool
        if lora_pool is not None:
            if not self.paged:
                raise ValueError(
                    "serve_lora requires the paged engine (serve_paged=1 "
                    "with chunked prefill): the adapter pool pages its "
                    "factor slots alongside the KV block pool, and only "
                    "the paged programs carry the adapter-id operand")
            self._sig_suffix += lora_pool.sig
        hd = cfg.feat // cfg.n_head
        # int4 matmul route, resolved ONCE on the tick's hot QKV
        # geometry (m = slots decode rows, k = feat, n = 3*feat, the
        # largest per-token matmul): "fused" when the Pallas dequant-
        # matmul's gate passes there, "" when programs stream packed
        # weights through the XLA reference. Chunk-prefill matmuls
        # re-gate per shape inside _qmat4 — this field is the
        # observability/audit verdict for the steady-state decode path.
        self.int4_formulation = ""
        if self.int4_weights and self.tp > 1:
            # sharded engines stream the XLA reference: the kernel's
            # in-tile unpack assumes the single-segment halves layout,
            # and pallas_call is not GSPMD-partitionable over the
            # packed plane's halved axis — counted, not silent
            _note_int4_fallback("tp", obs_registry)
        elif self.int4_weights:
            from ..ops.pallas_kernels import (int4_matmul_fallback_reason,
                                              int4_matmul_supported)
            citem = 2 if cfg.dtype == "bfloat16" else 4
            g_qkv = _int4_groups(cfg.feat, self.int4_group)
            if int4_matmul_supported(slots, cfg.feat, 3 * cfg.feat,
                                     g_qkv, itemsize=citem):
                self.int4_formulation = "fused"
            else:
                _note_int4_fallback(
                    int4_matmul_fallback_reason(
                        slots, cfg.feat, 3 * cfg.feat, g_qkv,
                        itemsize=citem),
                    obs_registry)
        if self.paged:
            self.bpr = self.row_len // self.block_size
            # fused paged attention: requested AND the backend/geometry
            # supports the kernel (TPU, or interpret mode under test) —
            # anything else keeps the gather formulation, so a CPU test
            # mesh and an odd geometry degrade to the bit-reference
            # path instead of failing to compile, and the resolution is
            # no longer silent: the reason is logged once and counted
            # in cxn_fused_fallback_total{reason=}. The gate sees the
            # LOCAL head count (each shard holds n_head / tp whole
            # heads, the shard_map wrap runs the kernel per shard), and
            # picks the FORMULATION: "resident" when the whole row
            # image fits the VMEM gate, "streaming" (online-softmax
            # scratch across the block walk) when only a single block
            # does — long rows stay fused instead of degrading to
            # gather.
            from ..ops.pallas_kernels import (
                paged_attention_fallback_reason,
                paged_attention_formulation)
            itemsize = 1 if self.kv_int8 \
                else (2 if cfg.dtype == "bfloat16" else 4)
            form = paged_attention_formulation(
                cfg.n_head // self.tp, self.bpr, self.block_size, hd,
                itemsize)
            self.fused_formulation = form if bool(fused_attn) else ""
            self.fused_attn = bool(self.fused_formulation)
            if bool(fused_attn) and not self.fused_attn:
                _note_fused_fallback(
                    paged_attention_fallback_reason(
                        cfg.n_head // self.tp, self.bpr,
                        self.block_size, hd, itemsize),
                    obs_registry)
            shape = (cfg.n_layer, self.num_blocks, cfg.n_head,
                     self.block_size, hd)
            # host-side bookkeeping (free list, refcounts, tables);
            # validates num_blocks >= bpr + 1 so one full row always
            # fits. The abstract (audit-only) engine still builds it —
            # the manager is pure host state, and lint_specs wants bpr.
            from .paged import BlockManager
            self.manager = BlockManager(self.num_blocks, slots, self.bpr)
        else:
            self.bpr = 0
            self.manager = None
            self.fused_attn = False
            self.fused_formulation = ""
            shape = (cfg.n_layer, slots, cfg.n_head, self.row_len, hd)
        kv_sh = serve_kv_sharding(self.mesh) if self.tp > 1 else None
        if kv_sh is None and mesh is not None and not abstract:
            # placement-only mesh: the caches live with the weights
            from jax.sharding import NamedSharding, PartitionSpec
            kv_sh = NamedSharding(mesh, PartitionSpec())
        if abstract:
            # audit-only engine (tools/cxn_lint.py --compile): the cache
            # leaves are ShapeDtypeStructs, so lint_specs can AOT-lower
            # every program without allocating a single device byte;
            # prefill/tick calls on such an engine are a usage error
            if self.kv_int8:
                sshape = shape[:-1]
                self.cache_k = (jax.ShapeDtypeStruct(shape, jnp.int8),
                                jax.ShapeDtypeStruct(sshape, self.dtype))
                self.cache_v = (jax.ShapeDtypeStruct(shape, jnp.int8),
                                jax.ShapeDtypeStruct(sshape, self.dtype))
            else:
                self.cache_k = jax.ShapeDtypeStruct(shape, self.dtype,
                                                    sharding=kv_sh)
                self.cache_v = jax.ShapeDtypeStruct(shape, self.dtype,
                                                    sharding=kv_sh)
        elif kv_sh is not None:
            # head-sharded pool: each shard holds n_head / tp whole
            # heads of every block/row — 1/tp of the KV bytes per chip,
            # the serving-memory lever TP exists for (int8 pools are
            # rejected with tp > 1 above; a placement-only mesh commits
            # the pair wholesale, P() fits any rank)
            if self.kv_int8:
                sshape = shape[:-1]
                self.cache_k = jax.device_put(
                    (jnp.zeros(shape, jnp.int8),
                     jnp.zeros(sshape, self.dtype)), kv_sh)
                self.cache_v = jax.device_put(
                    (jnp.zeros(shape, jnp.int8),
                     jnp.zeros(sshape, self.dtype)), kv_sh)
            else:
                self.cache_k = jax.device_put(jnp.zeros(shape, self.dtype),
                                              kv_sh)
                self.cache_v = jax.device_put(jnp.zeros(shape, self.dtype),
                                              kv_sh)
        elif self.kv_int8:
            # per-block-scaled int8 pool: (values, scales) pair — one
            # symmetric scale per (layer, block, head, token) in the
            # compute dtype, quantize-on-scatter / dequantize-on-gather
            # (_scatter_kv / _gather_row[s])
            sshape = shape[:-1]
            self.cache_k = (jnp.zeros(shape, jnp.int8),
                            jnp.zeros(sshape, self.dtype))
            self.cache_v = (jnp.zeros(shape, jnp.int8),
                            jnp.zeros(sshape, self.dtype))
        else:
            self.cache_k = jnp.zeros(shape, self.dtype)
            self.cache_v = jnp.zeros(shape, self.dtype)
        # donating the caches halves peak HBM on real chips; CPU (the test
        # mesh) ignores donation with a warning, so gate on the backend
        self._donate = jax.default_backend() != "cpu"
        # live per-program device timing (obs/devprof.py): the server
        # arms this with a LiveSampler when `prof_every` > 0 — one
        # blocking sample every N executions of each program, a dict
        # increment otherwise; None (the default) costs one attribute
        # check per call
        self._prof = None
        # chaos harness (serve/resilience.py FaultInjector, armed via
        # serve_chaos / CXN_CHAOS): None when off — every injection
        # point below costs exactly one `is not None` check
        self._inj = injector
        # compiled prefill/chunk signature counting (lint_recompile_limit
        # for the serve engine): the lru_caches above silently absorb a
        # per-prompt-length compile storm; the guard makes it loud
        self._guard = None
        self._vguard = None
        self._tguard = None
        if recompile_limit > 0:
            from ..analysis.recompile import RecompileGuard
            from ..utils import profiler
            on_trip = None
            if obs_registry is not None:
                # every trip — strict or log-only — lands in the unified
                # registry, so a scraper sees compiled-signature churn
                # without parsing the human log
                from ..analysis.recompile import trip_counter
                trips = trip_counter(obs_registry)
                on_trip = lambda name: trips.labels(name).inc()
            self._guard = RecompileGuard(
                lambda sig: None, "serve_prefill", recompile_limit,
                strict=bool(recompile_strict), log=profiler.warn,
                on_trip=on_trip)
            # the verify program gets its OWN signature count: its one
            # legitimate signature must not share headroom with the
            # prefill/chunk programs', and a trip should name spec_len —
            # the only dimension that can drift there
            self._vguard = RecompileGuard(
                lambda sig: None, "serve_verify_chunk", recompile_limit,
                strict=bool(recompile_strict), log=profiler.warn,
                on_trip=on_trip)
            if self.paged:
                # the paged tick's one legitimate signature is pinned
                # separately: its block-table shape (slots x bpr) is
                # part of the counted signature, so a drifting table
                # shape trips CXN205 naming the drift instead of
                # silently compiling a second program
                self._tguard = RecompileGuard(
                    lambda sig: None, "serve_tick", recompile_limit,
                    strict=bool(recompile_strict), log=profiler.warn,
                    on_trip=on_trip)
        # AOT executable cache (analysis/aot_cache.py, doc/performance.md
        # "AOT executable cache"): ``aot`` is an AotCache (or a dir
        # path); the serve programs resolve through it at build —
        # deserialize-and-load on a key hit (ZERO XLA compilation),
        # AOT-compile-then-persist on a miss — so every later engine
        # build, _build_stack() recovery, and replica spin-up over the
        # same key starts in milliseconds. None (the default) is a
        # pinned no-op: the lazy module-level jit path runs untouched.
        self._aot = None
        self._aot_progs: Dict[str, object] = {}
        self._aot_src: Dict[str, str] = {}
        if aot is not None and not abstract:
            self.warm_aot(aot, tracer=tracer)

    def set_profiler(self, prof) -> None:
        """Arm live per-program device timing (an
        ``obs.devprof.LiveSampler`` or None to disarm). Each program
        call asks the sampler once; only every Nth execution is timed —
        the timed call blocks on the program's outputs (the tick and
        verify already do; a sampled prefill chunk gives up its
        pipelining for that one call), the rest are untouched."""
        self._prof = prof

    def _count_program(self, sig: str) -> None:
        """Register one prefill/chunk program fetch with the guard; the
        signature string carries the drifting dimension's name, so a
        CXN205 trip reads e.g. \"leaf 0: 'n_prompt=17' -> 'n_prompt=23'\".
        A TP engine's signatures additionally carry the mesh shape
        (``/mesh=1x1x1x1x2``): two mesh shapes are two executables."""
        if self._guard is not None:
            self._guard(sig + self._sig_suffix)

    @property
    def prefill_signatures(self) -> tuple:
        """Distinct compiled prefill/chunk program signatures seen so far
        (empty when the guard is off)."""
        return self._guard.signatures if self._guard is not None else ()

    @property
    def verify_signatures(self) -> tuple:
        """Distinct compiled verify program signatures seen so far
        (empty when the guard is off). One fixed ``spec_len`` = one
        signature no matter how draft hit lengths mix — the speculative
        acceptance bound, pinned by tests/test_speculative.py."""
        return self._vguard.signatures if self._vguard is not None else ()

    @property
    def tick_signatures(self) -> tuple:
        """Distinct compiled paged-tick signatures seen so far (empty
        when the guard is off or the engine is dense). One fixed
        (slots x bpr) block-table shape = one signature across every
        occupancy mix — pinned by tests/test_serve_paged.py."""
        return self._tguard.signatures if self._tguard is not None else ()

    def aot_extra(self, label: str) -> str:
        """The AOT-cache key's ``extra`` component for one program:
        every builder constant that selects a different executable
        WITHOUT changing the abstract signature (the fused/gather
        resolution, geometry constants, the guard-suffix flags). The
        artifact validator (analysis/step_audit.py:audit_aot_artifacts)
        must derive the same string, so it lives here, next to the
        builders it describes. The streaming formulation is a distinct
        executable and gets its own ``/form=streaming`` component;
        resident keeps the historical key shape, so every artifact
        written before the streaming formulation existed still
        resolves."""
        return "%s/chunk=%d/bs=%d/bpr=%d/spec=%d/fused=%d%s%s" % (
            label, self.chunk, self.block_size, self.bpr, self.spec_len,
            int(self.fused_attn),
            "/form=streaming" if self.fused_formulation == "streaming"
            else "", self._sig_suffix)

    def warm_aot(self, cache=None, tracer=None) -> Dict[str, str]:
        """Resolve the serve programs through the AOT executable cache:
        for each program the engine will run (the same abstract specs
        the compiled-step audit lowers), deserialize-and-load the
        artifact for its exact key, or AOT-compile once and persist it.
        Returns ``{label: "aot_load" | "compiled"}`` (also kept as
        :meth:`aot_status`). The legacy whole-prompt prefill is skipped
        — one program per prompt length has no single spec to warm; its
        signatures stay on the lazy jit path."""
        from ..analysis import aot_cache as aot_mod
        cache = cache if cache is not None else self._aot
        if cache is None:
            return {}
        if isinstance(cache, str):
            cache = aot_mod.get_cache(cache)
        self._aot = cache
        cfg_hash = aot_mod.config_hash(self._cfg_key)
        for label, fn, args, donate_nums in self.lint_specs(donate=None):
            if label == "serve_prefill":
                continue
            comp = cache.components(label, args,
                                    donate_argnums=donate_nums,
                                    extra=self.aot_extra(label),
                                    config=cfg_hash, mesh=self.mesh)
            compiled = cache.load(comp, tracer=tracer)
            if compiled is None:
                with compile_attribution(label):
                    compiled = fn.lower(*args).compile()
                cache.store(comp, compiled)
                src = "compiled"
            else:
                src = "aot_load"
            self._aot_progs[label] = aot_mod.ResolvedProgram(
                compiled, label, src, (lambda f=fn: f))
            self._aot_src[label] = src
        return dict(self._aot_src)

    def aot_status(self) -> Dict[str, str]:
        """How each serve program was resolved at the last
        :meth:`warm_aot` — ``"aot_load"`` (deserialized from the cache)
        or ``"compiled"`` (compiled, then persisted); empty when the
        cache is off (``task=prof`` reports this table)."""
        return dict(self._aot_src)

    def lint_specs(self, n_prompt: int = 8, donate: Optional[bool] = None):
        """(label, jitted fn, abstract args, donate_argnums) rows for the
        compiled-step audit (analysis/step_audit.py): prefill at one
        representative prompt length, the chunk-prefill step (when
        chunking is enabled), plus the shared tick. ``donate`` overrides
        the backend-gated donation choice so tests can pin the aliasing
        contract on the CPU mesh too. Pure AOT — nothing runs, nothing
        is allocated."""
        from jax import ShapeDtypeStruct as SDS
        don = self._donate if donate is None else bool(donate)
        nums = (2, 3) if don else ()
        f32, i32, key = jnp.float32, jnp.int32, SDS((2,), jnp.uint32)
        b = self.slots
        if self.paged:
            # the paged engine's three programs, audited with abstract
            # block-table inputs (the tables are traced data, so the
            # audit sees exactly the one compiled signature each holds)
            row_t = SDS((self.bpr,), i32)
            # an armed adapter pool appends its abstract (id, factor
            # pool) operands, so the audit/AOT lowers exactly the
            # adapter-carrying executables the engine runs
            lora_on = self.lora_pool is not None
            lrow = (SDS((), i32), self.lora_pool.abstract_pool()) \
                if lora_on else ()
            lbat = (SDS((b,), i32), self.lora_pool.abstract_pool()) \
                if lora_on else ()
            chunk_args = (self._blocks, self._outer, self.cache_k,
                          self.cache_v, row_t, SDS((1, self.chunk), i32),
                          SDS((), i32), SDS((), i32), key, SDS((), f32),
                          SDS((), i32), SDS((), f32)) + lrow
            # the audited tick/verify are the engine's OWN variants —
            # fused when self.fused_attn resolved on (the Pallas call
            # AOT-lowers like any op), gather otherwise — so the audit
            # pins the donation aliasing of the programs that actually
            # serve
            specs = [
                ("serve_prefill_chunk",
                 _prefill_chunk_paged_fn(self._cfg_key, self.chunk,
                                         self.block_size, self.bpr, don,
                                         mesh=self.mesh, lora=lora_on),
                 chunk_args, nums)]
            if self.spec_len:
                verify_args = (self._blocks, self._outer, self.cache_k,
                               self.cache_v, row_t,
                               SDS((1, self.spec_len + 1), i32),
                               SDS((), i32), SDS((), i32), key,
                               SDS((), i32), SDS((), f32), SDS((), i32),
                               SDS((), f32)) + lrow
                specs.append(
                    ("serve_verify_chunk",
                     _verify_paged_fn(self._cfg_key, self.spec_len,
                                      self.block_size, self.bpr, don,
                                      self.fused_formulation,
                                      mesh=self.mesh, lora=lora_on),
                     verify_args, nums))
            tick_args = (self._blocks, self._outer, self.cache_k,
                         self.cache_v, SDS((b, self.bpr), i32),
                         SDS((b,), i32), SDS((b,), i32),
                         SDS((b, 2), jnp.uint32), SDS((b,), i32),
                         SDS((b,), f32), SDS((b,), i32),
                         SDS((b,), f32)) + lbat
            specs.append(
                ("serve_tick",
                 _tick_paged_fn(self._cfg_key, self.block_size, self.bpr,
                                don, self.fused_formulation,
                                mesh=self.mesh, lora=lora_on),
                 tick_args, nums))
            return specs
        tick_args = (self._blocks, self._outer, self.cache_k, self.cache_v,
                     SDS((b,), i32), SDS((b,), i32),
                     SDS((b, 2), jnp.uint32), SDS((b,), i32),
                     SDS((b,), f32), SDS((b,), i32), SDS((b,), f32))
        specs = []
        if self.tp == 1:
            # the legacy whole-prompt admit is single-device-only (a TP
            # engine mandates chunked prefill — see the ctor), so a
            # sharded audit must not lower an unsharded lookalike
            prefill_args = (self._blocks, self._outer, self.cache_k,
                            self.cache_v, SDS((1, n_prompt), i32),
                            SDS((), i32), key, SDS((), f32), SDS((), i32),
                            SDS((), f32))
            specs.append(
                ("serve_prefill",
                 _prefill_fn(self._cfg_key, n_prompt, self.row_len, don),
                 prefill_args, nums))
        if self.chunk:
            chunk_args = (self._blocks, self._outer, self.cache_k,
                          self.cache_v, SDS((1, self.chunk), i32),
                          SDS((), i32), SDS((), i32), SDS((), i32), key,
                          SDS((), f32), SDS((), i32), SDS((), f32))
            specs.append(
                ("serve_prefill_chunk",
                 _prefill_chunk_fn(self._cfg_key, self.chunk, don,
                                   mesh=self.mesh),
                 chunk_args, nums))
        if self.spec_len:
            verify_args = (self._blocks, self._outer, self.cache_k,
                           self.cache_v, SDS((1, self.spec_len + 1), i32),
                           SDS((), i32), SDS((), i32), SDS((), i32), key,
                           SDS((), i32), SDS((), f32), SDS((), i32),
                           SDS((), f32))
            specs.append(
                ("serve_verify_chunk",
                 _verify_fn(self._cfg_key, self.spec_len, don,
                            mesh=self.mesh),
                 verify_args, nums))
        specs.append(
            ("serve_tick", _tick_fn(self._cfg_key, don, mesh=self.mesh),
             tick_args, nums))
        return specs

    @property
    def kv_dtype(self) -> str:
        """The pool's STORED dtype name — ``"int8"`` for the quantized
        (values, scales) layout, else the compute dtype."""
        if self.kv_int8:
            return "int8"
        return "bf16" if self.cfg.dtype == "bfloat16" else "f32"

    def cache_bytes(self) -> int:
        """KV-cache device bytes. Dense: 2 * layers * slots * heads *
        row_len * head_dim * itemsize (row_len is chunk-padded seq_len),
        with the prefix cache's copies on top (``prefix_cache_bytes``).
        Paged: 2 * layers * num_blocks * heads * block_size * head_dim *
        itemsize — the WHOLE pool, prefix-cache-resident blocks
        included, since the trie's shared blocks live inside it
        (doc/serving.md memory formula). An int8 pool sums its stored
        leaves — 1-byte values plus the compute-dtype scale planes — so
        the DeviceLedger's ``kv_blocks`` prediction reconciles against
        ``jax.live_arrays()`` under quantization too."""
        if self.cache_k is None:        # closed (metrics after shutdown)
            return 0
        total = 0
        for cache in (self.cache_k, self.cache_v):
            for leaf in (cache if isinstance(cache, tuple) else (cache,)):
                total += int(leaf.size) * jnp.dtype(leaf.dtype).itemsize
        return total

    def close(self) -> None:
        """Drop the cache buffers (the server calls this at shutdown)."""
        self.cache_k = self.cache_v = None

    def _lora_args(self, aid, batched: bool) -> tuple:
        """The appended ``(adapter-ids, device-pool)`` operand pair for
        an armed engine's program call — empty when LoRA is off, so
        every call site stays a pinned structural no-op. ``aid`` is the
        (slots,) per-row id vector for the batched tick, a scalar for
        the single-row chunk/verify programs; None means base (id 0,
        the pool's pinned all-zero slot)."""
        if self.lora_pool is None:
            return ()
        if batched:
            ids = np.zeros(self.slots, np.int32) if aid is None \
                else np.asarray(aid, np.int32).reshape(self.slots)
            return (jnp.asarray(ids), self.lora_pool.device_pool())
        return (jnp.asarray(0 if aid is None else int(aid), jnp.int32),
                self.lora_pool.device_pool())

    def prefill(self, slot: int, prompt: np.ndarray, key: np.ndarray,
                temperature: float, top_k: int, top_p: float) -> int:
        """Admit one request into ``slot``: full forward over ``prompt``
        (1-D int array), write its K/V row, return the first generated
        token (synchronized — the host needs it for EOS/TTFT anyway).
        The legacy whole-prompt path: one compiled program PER prompt
        length."""
        if self.paged:
            raise RuntimeError("whole-prompt prefill is dense-only; the "
                               "paged engine admits through "
                               "prefill_chunk")
        n = int(len(prompt))
        self._count_program("n_prompt=%d" % n)
        fn = _prefill_fn(self._cfg_key, n, self.row_len, self._donate)
        t0 = self._prof.begin("serve_prefill") \
            if self._prof is not None else None
        with compile_attribution("serve_prefill"):
            self.cache_k, self.cache_v, tok = fn(
                self._blocks, self._outer, self.cache_k, self.cache_v,
                jnp.asarray(np.asarray(prompt, np.int32))[None],
                jnp.asarray(slot, jnp.int32), jnp.asarray(key),
                jnp.asarray(temperature, jnp.float32),
                jnp.asarray(top_k, jnp.int32),
                jnp.asarray(top_p, jnp.float32))
        tok = int(tok)                      # host fetch: the sync point
        if t0 is not None:
            self._prof.end("serve_prefill", t0)
        return tok

    def prefill_chunk(self, slot: int, toks: np.ndarray, start: int,
                      n_valid: int, key: np.ndarray, temperature: float,
                      top_k: int, top_p: float, aid=None):
        """One chunk of prefill work for ``slot``: ``toks`` is exactly
        ``prefill_chunk`` tokens (the caller zero-pads the final chunk
        and passes ``n_valid``); ``start`` is the chunk's offset in the
        row. Returns the sampled token as a DEVICE value — meaningful
        only on the final chunk (fold_in(key, 0) on position n_valid-1's
        logits, the offline first-token schedule), and left unsynced so
        a long prompt's chunk steps pipeline on device instead of
        paying one host round-trip each; the scheduler fetches it only
        when the final chunk lands."""
        toks = np.asarray(toks, np.int32).reshape(-1)
        if toks.size != self.chunk:
            raise ValueError("prefill_chunk expects exactly %d tokens, "
                             "got %d" % (self.chunk, toks.size))
        if self.paged:
            m = self.manager
            if (int(start) + self.chunk) > m.nblocks[slot] \
                    * self.block_size:
                raise RuntimeError(
                    "prefill window [%d, %d) not reserved for slot %d "
                    "(call reserve_window first)"
                    % (int(start), int(start) + self.chunk, slot))
            # the block-table shape rides in the counted signature: a
            # drifting table shape would be a second compiled program
            self._count_program("chunk=%d/table=%d" % (self.chunk,
                                                       self.bpr))
            fn = _prefill_chunk_paged_fn(self._cfg_key, self.chunk,
                                         self.block_size, self.bpr,
                                         self._donate, mesh=self.mesh,
                                         lora=self.lora_pool is not None)
            args = (jnp.asarray(m.table[slot]),)
        else:
            self._count_program("chunk=%d" % self.chunk)
            fn = _prefill_chunk_fn(self._cfg_key, self.chunk,
                                   self._donate, mesh=self.mesh)
            args = ()
        # AOT-cache-resolved executable (load-instead-of-compile) when
        # the engine was warmed; the lazy jit above is its fallback
        fn = self._aot_progs.get("serve_prefill_chunk", fn)
        t0 = self._prof.begin("serve_prefill_chunk") \
            if self._prof is not None else None
        with compile_attribution("serve_prefill_chunk"):
            self.cache_k, self.cache_v, tok = fn(
                self._blocks, self._outer, self.cache_k, self.cache_v,
                *args,
                jnp.asarray(toks)[None],
                *(() if self.paged else (jnp.asarray(slot, jnp.int32),)),
                jnp.asarray(start, jnp.int32),
                jnp.asarray(n_valid, jnp.int32),
                jnp.asarray(key), jnp.asarray(temperature, jnp.float32),
                jnp.asarray(top_k, jnp.int32),
                jnp.asarray(top_p, jnp.float32),
                *self._lora_args(aid, batched=False))
        if t0 is not None:
            # the one sampled call pays the sync the unsampled path
            # deliberately avoids — that IS the measurement
            jax.block_until_ready(tok)
            self._prof.end("serve_prefill_chunk", t0)
        return tok

    def verify_chunk(self, slot: int, toks: np.ndarray, pos: int,
                     n_draft: int, key: np.ndarray, fold: int,
                     temperature: float, top_k: int, top_p: float,
                     aid=None):
        """One draft-and-verify step for ``slot``: ``toks`` is
        ``spec_len + 1`` tokens — the row's last emitted token followed
        by ``n_draft`` real draft tokens (rest padding); ``pos`` is the
        position the last emitted token will be written at, ``fold`` the
        fold_in index of the NEXT emitted token. Returns
        ``(n_accepted, emitted)`` synchronized — the host must know the
        accepted prefix to advance the row. The caller guarantees
        ``pos + spec_len + 1 <= row_len`` (all candidate rows fit
        without dynamic_update_slice start-clamping shifting the write
        onto earlier, live positions)."""
        toks = np.asarray(toks, np.int32).reshape(-1)
        k = toks.size - 1
        if k < 1:
            raise ValueError("verify_chunk needs >= 1 draft token slot, "
                             "got %d tokens" % toks.size)
        if int(pos) + k + 1 > self.row_len:
            raise ValueError("verify window [%d, %d) exceeds row_len %d"
                             % (int(pos), int(pos) + k + 1, self.row_len))
        if self.paged:
            m = self.manager
            if (int(pos) + k + 1) > m.nblocks[slot] * self.block_size:
                raise RuntimeError(
                    "verify window [%d, %d) not reserved for slot %d "
                    "(call reserve_window first)"
                    % (int(pos), int(pos) + k + 1, slot))
            if self._vguard is not None:
                # NB the counted signature string deliberately does NOT
                # carry the fused/gather flag: it is fixed at engine
                # construction, not traffic-driven drift (the mesh
                # shape rides along — see _count_program)
                self._vguard("spec_len=%d/table=%d%s"
                             % (k, self.bpr, self._sig_suffix))
            fn = _verify_paged_fn(self._cfg_key, k, self.block_size,
                                  self.bpr, self._donate,
                                  self.fused_formulation,
                                  mesh=self.mesh,
                                  lora=self.lora_pool is not None)
            args = (jnp.asarray(m.table[slot]),)
        else:
            if self._vguard is not None:
                self._vguard("spec_len=%d%s" % (k, self._sig_suffix))
            fn = _verify_fn(self._cfg_key, k, self._donate,
                            mesh=self.mesh)
            args = ()
        if k == self.spec_len:
            # the one full-window signature the cache holds; a narrower
            # ad-hoc window keeps the lazy jit path
            fn = self._aot_progs.get("serve_verify_chunk", fn)
        t0 = self._prof.begin("serve_verify_chunk") \
            if self._prof is not None else None
        with compile_attribution("serve_verify_chunk"):
            self.cache_k, self.cache_v, n_acc, emit = fn(
                self._blocks, self._outer, self.cache_k, self.cache_v,
                *args,
                jnp.asarray(toks)[None],
                *(() if self.paged else (jnp.asarray(slot, jnp.int32),)),
                jnp.asarray(pos, jnp.int32),
                jnp.asarray(n_draft, jnp.int32),
                jnp.asarray(key), jnp.asarray(fold, jnp.int32),
                jnp.asarray(temperature, jnp.float32),
                jnp.asarray(top_k, jnp.int32),
                jnp.asarray(top_p, jnp.float32),
                *self._lora_args(aid, batched=False))
        out = int(n_acc), int(emit)         # host fetch: the sync point
        if t0 is not None:
            self._prof.end("serve_verify_chunk", t0)
        return out

    def extract_row_chunks(self, slot: int, start: int, n_chunks: int):
        """Copy ``n_chunks`` contiguous chunks' K/V out of ``slot``'s row
        from offset ``start`` in one dispatch (the prefix cache's
        copy-out at retire); returns chunk-major stacked (n_chunks,
        n_layer, n_head, chunk, head_dim) arrays. Dense-only: the paged
        trie shares blocks by id (PagedPrefixCache) and never copies."""
        if self.paged:
            raise RuntimeError("extract_row_chunks is dense-only; the "
                               "paged prefix cache shares blocks by id")
        fn = _extract_chunks_fn(self._cfg_key, self.chunk, int(n_chunks))
        return fn(self.cache_k, self.cache_v, jnp.asarray(slot, jnp.int32),
                  jnp.asarray(start, jnp.int32))

    def insert_row_prefix(self, slot: int, ks, vs) -> None:
        """Restore a whole matched prefix (``ks``/``vs``: equal-length
        sequences of chunk K/V pairs, contiguous from position 0) into
        ``slot``'s row in ONE jitted call — one dus per cache total
        instead of one per chunk. Dense-only (see extract_row_chunks)."""
        if self.paged:
            raise RuntimeError("insert_row_prefix is dense-only; the "
                               "paged prefix cache shares blocks by id")
        fn = _insert_prefix_fn(self._cfg_key, len(ks) * self.chunk,
                               self._donate)
        self.cache_k, self.cache_v = fn(
            self.cache_k, self.cache_v, tuple(ks), tuple(vs),
            jnp.asarray(slot, jnp.int32))

    def tick(self, tok: np.ndarray, pos: np.ndarray, keys: np.ndarray,
             fold: np.ndarray, temp: np.ndarray, top_k: np.ndarray,
             top_p: np.ndarray, aid=None) -> np.ndarray:
        """One batched decode step across every slot row (free and
        still-prefilling rows run too, on dummy state — the scheduler
        parks their position at row_len - 1, past every readable
        position, so their unconditional cache write can never land
        inside real data, and their tokens are discarded). ``fold`` is each row's
        token index in ITS OWN request — the fold_in schedule that makes
        a slot row's sample stream identical to the offline path's.
        Returns the (slots,) next tokens, synchronized."""
        if self._inj is not None:
            if self._inj.fire("tick_hang"):
                # stalls up to hang_ms; raises InjectedFault instead if
                # a recovery releases hangs first (the watchdog path)
                self._inj.hang()
            if self._inj.fire("tick_raise"):
                raise InjectedFault("chaos point 'tick_raise': injected "
                                    "decode-tick exception")
        if self.paged:
            if self._tguard is not None:
                # fused/gather is NOT in the counted signature (fixed at
                # construction; only traffic-driven drift should count)
                self._tguard("slots=%d/table=%d%s"
                             % (self.slots, self.bpr, self._sig_suffix))
            fn = _tick_paged_fn(self._cfg_key, self.block_size, self.bpr,
                                self._donate, self.fused_formulation,
                                mesh=self.mesh,
                                lora=self.lora_pool is not None)
            args = (jnp.asarray(self.manager.table),)
        else:
            fn = _tick_fn(self._cfg_key, self._donate, mesh=self.mesh)
            args = ()
        fn = self._aot_progs.get("serve_tick", fn)
        t0 = self._prof.begin("serve_tick") \
            if self._prof is not None else None
        with compile_attribution("serve_tick"):
            self.cache_k, self.cache_v, nxt = fn(
                self._blocks, self._outer, self.cache_k, self.cache_v,
                *args,
                jnp.asarray(tok), jnp.asarray(pos), jnp.asarray(keys),
                jnp.asarray(fold), jnp.asarray(temp), jnp.asarray(top_k),
                jnp.asarray(top_p), *self._lora_args(aid, batched=True))
        out = np.asarray(nxt)               # host fetch: the sync point —
        #                                     a sampled tick adds only
        #                                     the perf_counter pair
        if t0 is not None:
            self._prof.end("serve_tick", t0)
        return out

    # --------------------------------------------------- paged plumbing
    def block_bytes(self) -> int:
        """Device bytes of ONE K/V block pair (all layers) — from the
        shared _paged_geometry, the same figure auto_num_blocks sizes
        budgets with."""
        return self._block_bytes

    def blocks_for(self, n_tokens: int) -> int:
        """Blocks needed to hold ``n_tokens`` cache positions."""
        bs = self.block_size
        return (int(n_tokens) + bs - 1) // bs

    def reserve_window(self, slot: int, p0: int, p1: int,
                       what: str = "write window") -> None:
        """Make positions [p0, p1) of ``slot``'s row writable: allocate
        the missing blocks and copy-on-write-fault any SHARED block the
        window touches (a prefix-cache hit's blocks, or any block
        another owner still references). All-or-nothing: the total
        allocation is pre-flighted, so a
        :class:`~cxxnet_tpu.serve.paged.BlockPoolExhausted` leaves both
        the manager and the device pool untouched — the scheduler
        evicts / preempts and retries. Runs BEFORE the write program
        dispatches; this ordering is what makes speculative rollback
        free (rejected drafts sit in already-private blocks)."""
        if self._inj is not None and self._inj.fire("reserve"):
            # chaos: exhaust the pool mid-reserve — exercises the
            # make-room escapes (trie evict, preempt, swap) for real
            raise BlockPoolExhausted(1, "fault injection "
                                        "(chaos point 'reserve')")
        m = self.manager
        bs = self.block_size
        first, last = int(p0) // bs, (int(p1) - 1) // bs
        have = m.nblocks[slot]
        grow = max(0, last + 1 - have)
        cow = [bi for bi in range(first, min(last, have - 1) + 1)
               if m.ref[m.table[slot, bi]] > 1]
        m.require(grow + len(cow), what)
        don = self._donate
        for bi in cow:
            src = int(m.table[slot, bi])
            dst = m.alloc("copy-on-write fault")
            fn = _copy_block_fn(self._cfg_key, bs, don)
            self.cache_k, self.cache_v = fn(
                self.cache_k, self.cache_v, jnp.asarray(src, jnp.int32),
                jnp.asarray(dst, jnp.int32))
            m.table[slot, bi] = dst
            m.decref(src)
            m.cow_faults += 1
        for _ in range(grow):
            m.append_new(slot, what)

    def attach_shared(self, slot: int, block_ids) -> None:
        """Append shared blocks (a prefix-cache hit) to ``slot``'s
        table: refcount bumps only, zero K/V copies."""
        self.manager.append_shared(slot, block_ids)

    def row_block_ids(self, slot: int, lo: int, hi: int):
        """Physical ids of ``slot``'s logical blocks [lo, hi) — what the
        paged prefix cache takes ownership refs on at donation."""
        return self.manager.row_blocks(slot, lo, hi)

    def release_row(self, slot: int) -> int:
        """Drop every block ref ``slot`` holds (retire / cancel); shared
        blocks live on through the trie or other rows. Returns blocks
        actually freed."""
        return self.manager.release_row(slot)

    def swap_out_row(self, slot: int) -> Dict:
        """Preemption: copy the CONTENT of every block in ``slot``'s
        table to host memory and release the row's refs — shared prefix
        blocks included (the copy makes the resume self-contained even
        if the trie evicts the prefix meanwhile). Returns the swap
        record ``{"k", "v", "n", "nbytes", "crc"}`` that
        :meth:`swap_in_row` restores bit-identically — ``crc`` is the
        host-buffer checksum swap-in verifies, so a corrupted buffer
        fails loudly (typed) instead of resuming a garbage bit-stream."""
        if self._inj is not None and self._inj.fire("swap_out"):
            raise InjectedFault("chaos point 'swap_out': injected "
                                "swap-out I/O failure")
        m = self.manager
        n = m.nblocks[slot]
        ids = np.zeros(self.bpr, np.int32)
        ids[:n] = m.table[slot, :n]
        fn = _gather_blocks_fn(self._cfg_key, self.block_size, self.bpr)
        bk, bv = fn(self.cache_k, self.cache_v, jnp.asarray(ids))
        if self.kv_int8:
            # the swap record carries the STORED representation — the
            # int8 payload plus its scale planes — so the host round
            # trip moves half the bytes and the crc covers exactly the
            # bits swap-in scatters back (bit-exact by construction)
            qk = np.asarray(bk[0])[:, :n].copy()
            sk = np.asarray(bk[1])[:, :n].copy()
            qv = np.asarray(bv[0])[:, :n].copy()
            sv = np.asarray(bv[1])[:, :n].copy()
            m.release_row(slot)
            return {"k": qk, "ks": sk, "v": qv, "vs": sv, "n": n,
                    "nbytes": (qk.nbytes + sk.nbytes + qv.nbytes
                               + sv.nbytes),
                    "crc": swap_checksum(qk, sk, qv, sv)}
        bk = np.asarray(bk)[:, :n].copy()
        bv = np.asarray(bv)[:, :n].copy()
        m.release_row(slot)
        return {"k": bk, "v": bv, "n": n,
                "nbytes": bk.nbytes + bv.nbytes,
                "crc": swap_checksum(bk, bv)}

    def swap_in_row(self, slot: int, rec: Dict) -> None:
        """Resume a preempted row: allocate ``rec["n"]`` fresh blocks
        (caller pre-flighted availability), rebuild the table, and
        scatter the host buffers back — the paged analogue of the dense
        dus-per-cache restore path. Every restored block is private
        (ref 1); prefix sharing for a resumed row is rebuilt only by
        its next admission, never mid-flight.

        The host buffers are checksum-verified FIRST — before any
        allocation — so a corrupted buffer raises
        :class:`~cxxnet_tpu.serve.resilience.SwapCorruptionError` with
        the manager untouched; the scheduler then replays the request
        from its journal record instead of resuming garbage."""
        if self._inj is not None and self._inj.fire("swap_in"):
            # chaos: corrupt the host buffer in transit — the checksum
            # below must catch it (the injected flip, not the raise,
            # is the fault: it exercises the detection path)
            rec["k"].view(np.uint8).flat[0] ^= 0xFF
        if "crc" in rec and swap_checksum(
                rec["k"], rec.get("ks"), rec["v"],
                rec.get("vs")) != rec["crc"]:
            raise SwapCorruptionError(
                "swap-in checksum mismatch for a %d-block row (host "
                "buffer corrupted in transit); resuming would replay a "
                "garbage bit-stream — the request is replayed from its "
                "journal record instead" % int(rec["n"]))
        m = self.manager
        n = int(rec["n"])
        m.require(n, "swap-in")
        ids = np.zeros(self.bpr, np.int32)
        for i in range(n):
            b = m.alloc("swap-in")
            m.append(slot, b)
            ids[i] = b
        cfg = self.cfg
        hd = cfg.feat // cfg.n_head
        shape = (cfg.n_layer, self.bpr, cfg.n_head, self.block_size, hd)
        fn = _scatter_blocks_fn(self._cfg_key, self.block_size, self.bpr,
                                self._donate)
        if self.kv_int8:
            # rebuild the padded (values, scales) pair from the stored
            # representation — no requantization, so resume is bit-exact
            sshape = shape[:-1]
            bq_k = np.zeros(shape, np.int8)
            bs_k = np.zeros(sshape, np.dtype(self.dtype))
            bq_v = np.zeros(shape, np.int8)
            bs_v = np.zeros(sshape, np.dtype(self.dtype))
            bq_k[:, :n] = rec["k"]
            bs_k[:, :n] = rec["ks"]
            bq_v[:, :n] = rec["v"]
            bs_v[:, :n] = rec["vs"]
            self.cache_k, self.cache_v = fn(
                self.cache_k, self.cache_v,
                (jnp.asarray(bq_k), jnp.asarray(bs_k)),
                (jnp.asarray(bq_v), jnp.asarray(bs_v)),
                jnp.asarray(ids))
            return
        bufk = np.zeros(shape, np.dtype(self.dtype))
        bufv = np.zeros(shape, np.dtype(self.dtype))
        bufk[:, :n] = rec["k"]
        bufv[:, :n] = rec["v"]
        self.cache_k, self.cache_v = fn(
            self.cache_k, self.cache_v, jnp.asarray(bufk),
            jnp.asarray(bufv), jnp.asarray(ids))
