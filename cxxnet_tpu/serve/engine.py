"""Slot-pool decode engine: the device side of continuous batching.

The offline decode (``models/gpt.py:gpt_decode``) compiles prefill + the
whole token scan into one program per (prompt length, generation length)
signature — perfect for equal-length batch generation, useless for a
server where requests arrive at different times with different lengths.
This engine re-cuts the same math at the granularity a scheduler needs:

* a **KV slot pool** — one (n_layer, slots, n_head, seq_len, head_dim)
  cache pair; each in-flight request owns one slot row for its lifetime;
* **prefill** — a jitted full-prompt forward for ONE request that writes
  its K/V into an arbitrary slot row (traced slot index — one compiled
  program per prompt length, reused for every slot) and samples the
  request's first token;
* **tick** — ONE jitted batched decode step across ALL slot rows, each
  row at its own position with its own sampling params and PRNG key.
  Rows advance independently, so short and long requests interleave
  instead of convoying behind the longest member of a fixed batch.

Token-identity contract: every numeric building block is shared with the
offline path's XLA form (``_fuse_qkv_blocks`` / ``_block_core_fusedqkv``
/ ``_layernorm`` from models/gpt.py, the masked-softmax cached attention
in the same per-row form, ``ops/sampling.py`` with the per-request
``fold_in(key, token_index)`` schedule), so a request served from any
slot — including a recycled one — produces the same tokens as running it
alone through ``gpt_decode``'s XLA scan path with the same params and
seed (pinned by tests on the CPU mesh). Where the offline path engages
its fused Pallas kernel instead (single TPU shard), its low-order logit
bits can differ from any XLA formulation — including gpt_decode's own
fallback — so the cross-path guarantee there is distribution-level, not
bit-level. Prefill
rewrites the WHOLE slot row (real K/V, zero-padded tail), and the decode
mask admits only positions <= the row's own position, every one of which
the row's own prefill/ticks have written — a recycled slot can never see
its previous occupant's cache.

The tick runs the XLA scan path (not the fused whole-step Pallas kernel):
slot rows sit at DIFFERENT cache positions, and the fused kernel's
single-position dus/mask layout assumes one shared ``pos``. The measured
fused-kernel batch amortization (ops/pallas_kernels.py) is the obvious
next lever — a per-row-position variant is future work, noted in
doc/serving.md.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..models.gpt import (GPTConfig, _block_core_fusedqkv, _fuse_qkv_blocks,
                          _layernorm)
from ..ops.attention import local_attention
from ..ops.sampling import sample_rows

__all__ = ["DecodeEngine"]


def _attn_cached_rows(q, ck, cv, pos):
    """Per-row cached attention: q (b, 1, H, d) against head-major caches
    (b, H, S, d), each row masked at its OWN position ``pos`` (b,) —
    the multi-position form of models/gpt.py:_attn_cached's jnp path
    (same einsums, same f32 softmax, same -1e30 mask), row-independent
    so each slot reproduces the batch-1 offline computation exactly."""
    d = q.shape[-1]
    qh = jnp.swapaxes(q, 1, 2)                          # (b, h, 1, d)
    s = jnp.einsum("bhqd,bhkd->bhqk", qh.astype(jnp.float32),
                   ck.astype(jnp.float32)) / (d ** 0.5)
    mask = jnp.arange(ck.shape[2])[None, None, None, :] \
        <= pos[:, None, None, None]
    w = jax.nn.softmax(jnp.where(mask, s, -1e30), axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", w,
                     cv.astype(jnp.float32)).astype(q.dtype)
    return jnp.swapaxes(out, 1, 2)                      # (b, 1, h, d)


@functools.lru_cache(maxsize=16)
def _tick_fn(cfg_key: tuple, donate: bool):
    """Jitted batched decode tick for one model config — module-level and
    lru-cached (the models/gpt.py:_decode_fn idiom) so every server over
    the same config shares one compiled program; the slot count is a
    traced dimension, not part of the key."""
    cfg = GPTConfig(*cfg_key)
    dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
    identity = lambda t: t

    def impl(blocks, outer, cache_k, cache_v, tok, pos, keys, fold, temp,
             top_k, top_p):
        h = (outer["emb"][tok][:, None, :]
             + outer["pos"][pos][:, None, :]).astype(dtype)
        # python-unrolled layer loop (n_layer is static) with per-row
        # dynamic_update_slice writes STRAIGHT into the stacked caches:
        # the lax.scan form instead streams both caches through xs->ys,
        # which XLA materializes as a full cache copy per layer per token
        # — measured at 87% of the decode step (doc/performance.md round
        # 4). With the caches donated, the dus chain can update in place.
        for l in range(cfg.n_layer):
            p = {k: w[l] for k, w in blocks.items()}

            def attn(q, k, v, l=l):
                kh = jnp.swapaxes(k, 1, 2)[:, None]     # (b, 1, h, 1, d)
                vh = jnp.swapaxes(v, 1, 2)[:, None]
                # vmap over the slot axis: each row writes (h, 1, d) at
                # (layer l, its OWN position)
                upd = jax.vmap(
                    lambda c, u, pp: lax.dynamic_update_slice(
                        c, u, (l, 0, pp, 0)),
                    in_axes=(1, 0, 0), out_axes=1)
                ck = upd(cache_k, kh, pos)
                cv = upd(cache_v, vh, pos)
                return _attn_cached_rows(q, ck[l], cv[l], pos), (ck, cv)

            h, (cache_k, cache_v) = _block_core_fusedqkv(
                p, h, cfg.n_head, attn, identity)
        hl = _layernorm(h, outer["lnf_g"], outer["lnf_b"])
        logits = hl[:, 0] @ outer["head"].astype(hl.dtype)      # (b, V)
        keys_t = jax.vmap(jax.random.fold_in)(keys, fold)
        nxt = sample_rows(logits, keys_t, temp, top_k, top_p)
        return cache_k, cache_v, nxt

    return jax.jit(impl, donate_argnums=(2, 3) if donate else ())


@functools.lru_cache(maxsize=256)
def _prefill_fn(cfg_key: tuple, n_prompt: int, donate: bool):
    """Jitted admit program for one (config, prompt length): full-prompt
    forward, whole-slot-row cache write (traced slot index — one program
    serves every slot), first-token sample."""
    cfg = GPTConfig(*cfg_key)
    dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
    identity = lambda t: t

    def impl(blocks, outer, cache_k, cache_v, prompt, slot, key, temp,
             top_k, top_p):
        h = (outer["emb"][prompt]
             + outer["pos"][None, :n_prompt]).astype(dtype)

        def prefill_layer(carry, p):
            def attn(q, k, v):
                return local_attention(q, k, v, causal=True), (k, v)
            out, (k, v) = _block_core_fusedqkv(p, carry, cfg.n_head, attn,
                                               identity)
            # head-major (1, H, S, d) row, zero-padded to the FULL slot
            # length: the dus below replaces the whole row, so a recycled
            # slot keeps nothing of its previous occupant
            kh = jnp.transpose(k, (0, 2, 1, 3))
            vh = jnp.transpose(v, (0, 2, 1, 3))
            pad = ((0, 0), (0, 0), (0, cfg.seq_len - n_prompt), (0, 0))
            return out, (jnp.pad(kh, pad), jnp.pad(vh, pad))

        h, (ck_row, cv_row) = lax.scan(prefill_layer, h, blocks)
        hl = _layernorm(h[:, -1:], outer["lnf_g"], outer["lnf_b"])
        logits = hl[:, 0] @ outer["head"].astype(hl.dtype)      # (1, V)
        # first generated token: fold index 0 — the same schedule as
        # gpt_decode's pick(logits, fold_in(rng, 0))
        k0 = jax.random.fold_in(key, 0)
        tok = sample_rows(logits, k0[None], temp[None], top_k[None],
                          top_p[None])
        cache_k = lax.dynamic_update_slice(cache_k, ck_row,
                                           (0, slot, 0, 0, 0))
        cache_v = lax.dynamic_update_slice(cache_v, cv_row,
                                           (0, slot, 0, 0, 0))
        return cache_k, cache_v, tok[0]

    return jax.jit(impl, donate_argnums=(2, 3) if donate else ())


class DecodeEngine:
    """Owns the slot-pool KV caches and drives the jitted programs
    (prefill per prompt length, one shared tick). Host-side state is the
    caller's job (serve/scheduler.py); this class only moves tensors."""

    def __init__(self, cfg: GPTConfig, params: Dict, slots: int):
        if slots < 1:
            raise ValueError("serve_slots must be >= 1, got %d" % slots)
        if cfg.feat % cfg.n_head:
            raise ValueError("feat %d not divisible by n_head %d"
                             % (cfg.feat, cfg.n_head))
        self.cfg = cfg
        self._cfg_key = dataclasses.astuple(cfg)
        self.slots = slots
        self.dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
        # fused QKV once per server lifetime (models/gpt.py does this once
        # per decode CALL; a server amortizes it over every request)
        self._blocks = _fuse_qkv_blocks(params["blocks"])
        self._outer = {k: params[k] for k in ("emb", "pos", "lnf_g",
                                              "lnf_b", "head")}
        hd = cfg.feat // cfg.n_head
        shape = (cfg.n_layer, slots, cfg.n_head, cfg.seq_len, hd)
        self.cache_k = jnp.zeros(shape, self.dtype)
        self.cache_v = jnp.zeros(shape, self.dtype)
        # donating the caches halves peak HBM on real chips; CPU (the test
        # mesh) ignores donation with a warning, so gate on the backend
        self._donate = jax.default_backend() != "cpu"

    def lint_specs(self, n_prompt: int = 8, donate: Optional[bool] = None):
        """(label, jitted fn, abstract args, donate_argnums) rows for the
        compiled-step audit (analysis/step_audit.py): prefill at one
        representative prompt length plus the shared tick. ``donate``
        overrides the backend-gated donation choice so tests can pin the
        aliasing contract on the CPU mesh too. Pure AOT — nothing runs,
        nothing is allocated."""
        from jax import ShapeDtypeStruct as SDS
        don = self._donate if donate is None else bool(donate)
        nums = (2, 3) if don else ()
        f32, i32, key = jnp.float32, jnp.int32, SDS((2,), jnp.uint32)
        b = self.slots
        prefill_args = (self._blocks, self._outer, self.cache_k,
                        self.cache_v, SDS((1, n_prompt), i32),
                        SDS((), i32), key, SDS((), f32), SDS((), i32),
                        SDS((), f32))
        tick_args = (self._blocks, self._outer, self.cache_k, self.cache_v,
                     SDS((b,), i32), SDS((b,), i32),
                     SDS((b, 2), jnp.uint32), SDS((b,), i32),
                     SDS((b,), f32), SDS((b,), i32), SDS((b,), f32))
        return [
            ("serve_prefill", _prefill_fn(self._cfg_key, n_prompt, don),
             prefill_args, nums),
            ("serve_tick", _tick_fn(self._cfg_key, don), tick_args, nums),
        ]

    def cache_bytes(self) -> int:
        if self.cache_k is None:        # closed (metrics after shutdown)
            return 0
        return 2 * self.cache_k.size * self.cache_k.dtype.itemsize

    def close(self) -> None:
        """Drop the cache buffers (the server calls this at shutdown)."""
        self.cache_k = self.cache_v = None

    def prefill(self, slot: int, prompt: np.ndarray, key: np.ndarray,
                temperature: float, top_k: int, top_p: float) -> int:
        """Admit one request into ``slot``: full forward over ``prompt``
        (1-D int array), write its K/V row, return the first generated
        token (synchronized — the host needs it for EOS/TTFT anyway)."""
        fn = _prefill_fn(self._cfg_key, int(len(prompt)), self._donate)
        self.cache_k, self.cache_v, tok = fn(
            self._blocks, self._outer, self.cache_k, self.cache_v,
            jnp.asarray(np.asarray(prompt, np.int32))[None],
            jnp.asarray(slot, jnp.int32), jnp.asarray(key),
            jnp.asarray(temperature, jnp.float32),
            jnp.asarray(top_k, jnp.int32), jnp.asarray(top_p, jnp.float32))
        return int(tok)

    def tick(self, tok: np.ndarray, pos: np.ndarray, keys: np.ndarray,
             fold: np.ndarray, temp: np.ndarray, top_k: np.ndarray,
             top_p: np.ndarray) -> np.ndarray:
        """One batched decode step across every slot row (free rows run
        too, on dummy state — their writes land at masked positions of
        rows that prefill fully rewrites at the next admit, and their
        tokens are discarded by the scheduler). ``fold`` is each row's
        token index in ITS OWN request — the fold_in schedule that makes
        a slot row's sample stream identical to the offline path's.
        Returns the (slots,) next tokens, synchronized."""
        fn = _tick_fn(self._cfg_key, self._donate)
        self.cache_k, self.cache_v, nxt = fn(
            self._blocks, self._outer, self.cache_k, self.cache_v,
            jnp.asarray(tok), jnp.asarray(pos), jnp.asarray(keys),
            jnp.asarray(fold), jnp.asarray(temp), jnp.asarray(top_k),
            jnp.asarray(top_p))
        return np.asarray(nxt)
